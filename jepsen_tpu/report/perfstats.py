"""Windowed perf statistics on the accelerator.

The reference's ``checker/perf`` renders latency/rate graphs per run by
shelling out to gnuplot over the raw history; here the statistics are
ONE vmapped XLA dispatch over the packed ``.jtc`` row columns for a
whole batch of histories — per-window completion rates split by op
function and outcome, and per-window latency p50/p90/p99 read off
log-bucketed histograms.

Buckets are the PR-9 quantile-sketch geometry (``obs/metrics.py``
DDSketch-style, relative accuracy ``ALPHA`` = 1%): value ``x`` lands in
bucket ``k = ceil(log(x) / log(gamma))`` with
``gamma = (1+ALPHA)/(1-ALPHA)``, bucket estimate
``2 * gamma**k / (gamma + 1)``.  That makes every device histogram
MERGEABLE with the host sketches by bucket addition
(:func:`sketch_from_hist`), and pins the same accuracy bar the sketches
carry: any quantile within ~``ALPHA`` relative error (differential gate
vs ``np.percentile`` in ``tests/test_report.py``; the ≤2% acceptance
bar rides the committed ``bench.py report`` section).

Layout choices (why this fits one dispatch at north-star scale): the
per-window histogram ``[W, NB]`` is reduced to ``[W, 3]`` quantiles
*inside* the kernel, so the host receives quantiles + rates + ONE
summed ``[NB]`` histogram per history — ~30 KB/history instead of the
~200 KB/history the raw windowed histograms would cost.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from jepsen_tpu.checkers.protocol import VALID, Checker
from jepsen_tpu.history.encode import PackedHistories, pack_histories
from jepsen_tpu.history.ops import Op, OpF, OpType

#: windows per history (the reference's perf graphs are ~this dense)
N_WINDOWS = 64

#: sketch geometry — MUST match obs.metrics.QuantileSketch's default
ALPHA = 0.01
GAMMA = (1.0 + ALPHA) / (1.0 - ALPHA)
_LOG_GAMMA = math.log(GAMMA)

#: bucket 0 holds non-positive latencies (sub-ms completions round to
#: 0 ms and report as 0.0, the sketch's zero-bucket rule); buckets
#: ``1..NB-1`` hold ``k = i - 1`` up to ~1e7 ms (2.8 h), clipped above
K_MAX = math.ceil(math.log(1e7) / _LOG_GAMMA)
N_BUCKETS = K_MAX + 2

QUANTILES = (0.5, 0.9, 0.99)

#: OpF code -> rate-grid slot: 0 = produce-like (enqueue/append/txn/
#: acquire), 1 = consume-like (dequeue/read/release), 2 = drain;
#: -1 = nemesis/bookkeeping (excluded)
_F_SLOTS = np.full(max(int(f) for f in OpF) + 1, -1, np.int32)
for _f, _slot in (
    (OpF.ENQUEUE, 0), (OpF.APPEND, 0), (OpF.TXN, 0), (OpF.ACQUIRE, 0),
    (OpF.DEQUEUE, 1), (OpF.READ, 1), (OpF.RELEASE, 1),
    (OpF.DRAIN, 2),
):
    _F_SLOTS[int(_f)] = _slot
F_NAMES = ("produce", "consume", "drain")
T_NAMES = ("ok", "fail", "info")


def bucket_value(i: int) -> float:
    """The latency estimate (ms) a histogram bucket reports — bucket 0
    is the zero bucket, ``i >= 1`` is sketch bucket ``k = i - 1``."""
    if i <= 0:
        return 0.0
    return 2.0 * GAMMA ** (i - 1) / (GAMMA + 1.0)


_BUCKET_VALUES = np.array(
    [bucket_value(i) for i in range(N_BUCKETS)], np.float32
)


@jax.tree_util.register_dataclass
@dataclass
class WindowedStats:
    """Device windowed stats for a batch of histories.

    ``rates``:     [B, W, 3, 3] completions per window by (f-slot, outcome)
    ``quantiles``: [B, W, 3]    p50/p90/p99 ok-latency (ms; -1 = empty)
    ``hist``:      [B, NB]      whole-history ok-latency histogram
                                (sketch-geometry buckets, mergeable)
    ``window_ms``: [B]          window width
    ``ok_lats``:   [B]          ok completions with a measured latency
    """

    rates: jax.Array
    quantiles: jax.Array
    hist: jax.Array
    window_ms: jax.Array
    ok_lats: jax.Array


def _quantiles_from_cdf(cdf, total, uppers):
    """Sketch quantile semantics on a bucket CDF: rank ``q*(count-1)``,
    first bucket whose cumulative count exceeds the rank."""
    qs = []
    for q in QUANTILES:
        rank = q * (total[..., 0] - 1)
        idx = jnp.argmax(cdf > rank[..., None], axis=-1)
        qs.append(jnp.where(total[..., 0] > 0, uppers[idx], -1.0))
    return jnp.stack(qs, axis=-1)


def _stats_one(f, type_, time_ms, latency_ms, mask, first):
    """[L] row columns -> windowed stats for one history."""
    f = f.astype(jnp.int32)
    type_ = type_.astype(jnp.int32)
    slots = jnp.asarray(_F_SLOTS)
    fi = slots[jnp.clip(f, 0, len(_F_SLOTS) - 1)]
    is_completion = (
        mask
        & first  # one count per op, not per drain-exploded row
        & (fi >= 0)
        & (type_ >= int(OpType.OK))
        & (type_ <= int(OpType.INFO))
        & (time_ms >= 0)
    )
    t_max = jnp.max(jnp.where(is_completion, time_ms, 0))
    window_ms = jnp.maximum(t_max // N_WINDOWS + 1, 1)
    win = jnp.clip(time_ms // window_ms, 0, N_WINDOWS - 1)

    # rates: [W, 3 f-slots, 3 outcomes]
    ti = type_ - int(OpType.OK)
    flat = (win * 3 + jnp.clip(fi, 0, 2)) * 3 + jnp.clip(ti, 0, 2)
    flat = jnp.where(is_completion, flat, N_WINDOWS * 9)
    rates = jnp.zeros((N_WINDOWS * 9,), jnp.int32)
    rates = rates.at[flat].add(
        jnp.where(is_completion, 1, 0), mode="drop"
    ).reshape(N_WINDOWS, 3, 3)

    # ok-latency histogram in sketch geometry: [W, NB]
    ok_lat = is_completion & (type_ == int(OpType.OK)) & (latency_ms >= 0)
    lat = latency_ms.astype(jnp.float32)
    k = jnp.ceil(jnp.log(jnp.maximum(lat, 1e-6)) / _LOG_GAMMA)
    bucket = jnp.where(
        lat <= 0.0,
        0,
        jnp.clip(k.astype(jnp.int32) + 1, 1, N_BUCKETS - 1),
    )
    flat = win * N_BUCKETS + bucket
    flat = jnp.where(ok_lat, flat, N_WINDOWS * N_BUCKETS)
    hist = jnp.zeros((N_WINDOWS * N_BUCKETS,), jnp.int32)
    hist = hist.at[flat].add(jnp.where(ok_lat, 1, 0), mode="drop")
    hist = hist.reshape(N_WINDOWS, N_BUCKETS)

    uppers = jnp.asarray(_BUCKET_VALUES)
    cdf = jnp.cumsum(hist, axis=-1)
    quantiles = _quantiles_from_cdf(cdf, cdf[..., -1:], uppers)

    total = hist.sum(axis=0)
    return dict(
        rates=rates,
        quantiles=quantiles,
        hist=total,
        window_ms=window_ms,
        ok_lats=total.sum(),
    )


@jax.jit
def _stats_batch(f, type_, time_ms, latency_ms, mask, first) -> WindowedStats:
    r = jax.vmap(_stats_one)(f, type_, time_ms, latency_ms, mask, first)
    return WindowedStats(
        rates=r["rates"],
        quantiles=r["quantiles"],
        hist=r["hist"],
        window_ms=r["window_ms"],
        ok_lats=r["ok_lats"],
    )


def windowed_stats(packed: PackedHistories) -> WindowedStats:
    """The windowed-stats kernel over an already-packed batch — one
    dispatch for the whole batch axis."""
    return _stats_batch(
        packed.f,
        packed.type,
        packed.time_ms,
        packed.latency_ms,
        packed.mask,
        packed.first,
    )


def windowed_stats_rows(
    mats: Sequence[np.ndarray], length: int | None = None
) -> WindowedStats:
    """Windowed stats straight from ``[n, 8]`` row matrices (the
    ``.jtc`` ``SEC_QROWS`` payloads) — the zero-parse batch entry the
    ``bench.py report`` section measures."""
    from jepsen_tpu.history.encode import pack_row_matrices

    packed = pack_row_matrices(mats, length=length)
    return windowed_stats(packed)


# ---------------------------------------------------------------------------
# host-side views
# ---------------------------------------------------------------------------


def quantiles_from_hist(hist: np.ndarray, qs=QUANTILES) -> list[float]:
    """Host twin of the in-kernel CDF walk (for whole-history quantiles
    off the summed histogram); NaN on an empty histogram."""
    hist = np.asarray(hist)
    total = int(hist.sum())
    if total == 0:
        return [float("nan")] * len(qs)
    cdf = np.cumsum(hist)
    out = []
    for q in qs:
        rank = q * (total - 1)
        idx = int(np.argmax(cdf > rank))
        out.append(float(_BUCKET_VALUES[idx]))
    return out


def sketch_from_hist(hist: np.ndarray, alpha: float = ALPHA):
    """Bridge a device histogram row into a PR-9
    :class:`~jepsen_tpu.obs.metrics.QuantileSketch` — same geometry, so
    the result MERGES with live sketches by bucket addition.  The
    sketch's ``sum`` is estimated from bucket midpoints (quantiles never
    read it; documented approximation)."""
    from jepsen_tpu.obs.metrics import QuantileSketch

    if abs(alpha - ALPHA) > 1e-12:
        raise ValueError(
            f"device histograms are cut at alpha={ALPHA}; cannot bridge "
            f"to a sketch with alpha={alpha}"
        )
    hist = np.asarray(hist)
    s = QuantileSketch(alpha=alpha)
    s._zero = int(hist[0])
    s._count = int(hist.sum())
    s._sum = float((hist * _BUCKET_VALUES).sum())
    s._buckets = {
        i - 1: int(c) for i, c in enumerate(hist) if i >= 1 and c
    }
    return s


def stats_summary(t: WindowedStats, b: int = 0) -> dict[str, Any]:
    """Compact JSON-able headline for one history: overall quantiles,
    completion mix, peak windowed rate — what ``results.json`` carries
    and the index rows read."""
    rates = np.asarray(t.rates)[b]
    hist = np.asarray(t.hist)[b]
    window_s = float(np.asarray(t.window_ms)[b]) / 1e3
    q = quantiles_from_hist(hist)
    per_window = rates.sum(axis=(1, 2))
    mix = rates.sum(axis=0)  # [3 f-slots, 3 outcomes]
    by_type = mix.sum(axis=0)
    return {
        "windows": N_WINDOWS,
        "window-s": round(window_s, 3),
        "completions": int(by_type.sum()),
        "ok": int(by_type[0]),
        "fail": int(by_type[1]),
        "info": int(by_type[2]),
        "latency-ms": {
            "p50": None if q[0] != q[0] else round(q[0], 3),
            "p90": None if q[1] != q[1] else round(q[1], 3),
            "p99": None if q[2] != q[2] else round(q[2], 3),
        },
        "peak-rate-ops-per-s": round(
            float(per_window.max()) / max(window_s, 1e-9), 1
        ),
    }


#: opts key under which :class:`WindowedPerf` stashes its computed
#: tensors for the same-run report renderer (pack + dispatch happen
#: once per run, not once per consumer)
STATS_OPT = "_windowed_stats"


class WindowedPerf(Checker):
    """``checker/perf``'s statistics half as a composable checker: the
    device windowed-stats kernel over one history, always valid (it
    renders evidence, it does not judge).  Composes with the family
    checkers exactly like ``checker/compose``; the run-report renderer
    consumes the same tensors — when ``opts`` is a mutable dict the
    computed :class:`WindowedStats` is stashed under :data:`STATS_OPT`
    so the runner's default-on render reuses it instead of re-packing
    and re-dispatching the identical history."""

    name = "perf"

    def check(
        self,
        test: Mapping[str, Any],
        history: Sequence[Op],
        opts: Mapping[str, Any] | None = None,
    ) -> dict[str, Any]:
        if not len(history):
            return {VALID: True, "completions": 0}
        t = windowed_stats(pack_histories([list(history)]))
        if isinstance(opts, dict):
            opts[STATS_OPT] = t
        return {VALID: True, **stats_summary(t, 0)}
