"""Cross-run index: a store tree becomes one browsable page.

Soak and fuzz campaigns leave dozens of run directories behind;
``build_store_index`` walks ``store/``, reads (or renders) each run's
``report.json``, and emits ``store/index.html`` — one row per run with
verdict, op count, latency headline, and links to the run's report/
timeline/forensics artifacts, plus a p50-latency trend sparkline over
the runs in recorded order.  Deterministic: rows sort by run path, and
the page is a pure function of the run summaries (well-formed XML, the
``tests/test_report.py`` gate).
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any
from xml.sax.saxutils import escape, quoteattr

from jepsen_tpu.history.store import HISTORY_FILE, RESULTS_FILE, EDN_FILE
from jepsen_tpu.report.render import (
    REPORT_FILE,
    REPORT_JSON,
    _CSS,
    _verdict_class,
)

log = logging.getLogger(__name__)

INDEX_FILE = "index.html"


def _under_symlink(d: Path, root: Path) -> bool:
    cur = d
    while cur != root and cur != cur.parent:
        if cur.is_symlink():
            return True
        cur = cur.parent
    return False


def run_dirs(root: str | Path) -> list[Path]:
    """Every run directory under ``root`` (has a recorded history or a
    results.json), sorted by path — ``latest``/``current`` symlinks
    skipped and resolved-path deduped so no run indexes twice."""
    root = Path(root)
    seen: set = set()
    out = []
    for pat in (RESULTS_FILE, HISTORY_FILE, EDN_FILE):
        for p in sorted(root.rglob(pat)):
            d = p.parent
            if _under_symlink(d, root):
                continue
            r = d.resolve()
            if r in seen:
                continue
            seen.add(r)
            out.append(d)
    return sorted(out)


def run_content_refs(root: str | Path):
    """Content-addressed refs for recorded runs: yields
    ``(digest, workload, opts, verdict, rel)`` for every run directory
    under ``root`` holding BOTH a ``results.json`` verdict and a fresh
    ``.jtc`` substrate (stale/corrupt/absent substrates are skipped —
    a seed must never serve a verdict for bytes it cannot address).

    ``digest`` is the substrate's payload sha256
    (:meth:`~jepsen_tpu.history.columnar.Jtc.content_key`), ``opts``
    the default contract (recorded runs don't persist checker options;
    non-default contracts re-check rather than hit), and ``rel`` the
    root-relative run directory — the ``report_ref`` a cache hit serves
    alongside the verdict (the PR-11 ``/report/<run>`` route).

    When the substrate has been dehydrated into the content-addressed
    section store (COLUMNAR.md §Content-addressed sections) — the
    ``.jtc`` is gone but a ``<history>.casman.json`` manifest sits
    next to the verdict — the content key is reproduced straight from
    the manifest's chunk digests, so CAS'd runs keep seeding the
    verdict cache without re-materializing a byte."""
    from jepsen_tpu.history.columnar import load_jtc

    root = Path(root)
    for d in run_dirs(root):
        results_path = d / RESULTS_FILE
        if not results_path.is_file():
            continue
        src = d / HISTORY_FILE
        key = workload = None
        if src.is_file():
            try:
                jtc = load_jtc(src)
            except Exception as e:  # noqa: BLE001 — skip, don't refuse
                log.warning("unaddressable substrate under %s: %s", d, e)
                continue
            if jtc is not None and jtc.workload is not None:
                key, workload = jtc.content_key(), jtc.workload
        if key is None:
            # no loadable .jtc — the substrate may live only in the
            # section store (dehydrated run); seed from its manifest
            key, workload = _manifest_content_ref(d)
        if key is None or workload is None:
            continue
        try:
            verdict = json.loads(results_path.read_text())
        except (OSError, ValueError) as e:
            log.warning("unreadable results.json under %s: %s", d, e)
            continue
        yield (key, workload, {}, verdict, str(d.relative_to(root)))


def _manifest_content_ref(d: Path):
    """(content_key, workload) for a run whose substrate lives only in
    the section store, or (None, None).  A manifest pointing at
    missing/corrupt objects is skipped with a warning, never guessed
    at — the cache must not serve verdicts for bytes it can't prove.
    If the source history still exists on disk it must match the
    manifest's recorded stamp (same staleness rule as ``load_jtc``)."""
    from jepsen_tpu.history.cas import SectionStore, find_run_manifest

    man = find_run_manifest(d)
    if man is None:
        return None, None
    try:
        doc = json.loads(man.read_text())
        src = d / str(doc.get("src_name") or HISTORY_FILE)
        if src.is_file():
            import hashlib

            if (
                src.stat().st_size != doc.get("src_size")
                or hashlib.sha256(src.read_bytes()).hexdigest()
                != doc.get("src_sha256")
            ):
                log.warning(
                    "CAS manifest %s is stale for %s: run not seeded",
                    man, src,
                )
                return None, None
        cas = SectionStore.for_manifest(man, doc)
        return cas.content_key_from_manifest(man), doc.get("workload")
    except Exception as e:  # noqa: BLE001 — skip, don't refuse to seed
        log.warning(
            "CAS manifest %s unusable (%s): run not seeded", man, e
        )
        return None, None


def _summary_for(d: Path, render_missing: bool) -> dict[str, Any] | None:
    rj = d / REPORT_JSON
    if not rj.is_file() and render_missing:
        from jepsen_tpu.report.render import render_run_report

        try:
            render_run_report(d)
        except Exception as e:  # noqa: BLE001 — index the rest
            log.warning("report rendering failed for %s: %s", d, e)
    if rj.is_file():
        try:
            return json.loads(rj.read_text())
        except (OSError, ValueError) as e:
            log.warning("unreadable report.json under %s: %s", d, e)
    # results-only row (no history to crunch): verdict still indexes
    try:
        results = json.loads((d / RESULTS_FILE).read_text())
        return {"run": d.name, "valid?": results.get("valid?")}
    except (OSError, ValueError):
        return None


def _sparkline(p50s: list[float | None]) -> str:
    """Inline SVG sparkline of p50 latency across runs (recorded
    order); gaps where a run had no measurable latency."""
    w, h = max(16 * len(p50s), 48), 36
    vals = [v for v in p50s if v is not None and v == v]
    vmax = max(vals) if vals else 1.0
    pts = []
    for i, v in enumerate(p50s):
        if v is None or v != v:
            continue
        x = 8 + i * 16
        y = h - 6 - (h - 12) * (v / max(vmax, 1e-9))
        pts.append(f"{x:.1f},{y:.1f}")
    line = (
        f'<polyline points="{" ".join(pts)}" fill="none" '
        f'stroke="#3d405b" stroke-width="1.5"/>'
        if len(pts) > 1
        else ""
    )
    dots = "".join(
        f'<circle cx="{p.split(",")[0]}" cy="{p.split(",")[1]}" r="2" '
        f'fill="#3d405b"/>'
        for p in pts
    )
    return (
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{w}" '
        f'height="{h}" viewBox="0 0 {w} {h}">{line}{dots}</svg>'
    )


def _baseline_panel(root: Path) -> str:
    """The fleet-memory regression panel: refresh
    ``<root>/baselines.json`` (``jepsen_tpu/report/baselines.py``) and
    render its flags LOUDLY — a red banner row per regressed series —
    or a one-line all-clear.  A baselining failure costs the panel,
    never the index."""
    try:
        from jepsen_tpu.report.baselines import write_baselines

        _path, doc = write_baselines(root)
    except Exception as e:  # noqa: BLE001 — the index must still build
        log.warning("baseline pass failed for %s: %s", root, e)
        return ""
    flags = doc.get("flags") or []
    if not flags:
        return (
            f'<div class="panel"><h3>baselines</h3>'
            f'<p class="verdict-true">no regressions flagged '
            f"({doc.get('n_series', 0)} series baselined, "
            f"{doc.get('n_drifts', 0)} non-regression drifts)</p></div>"
        )
    rows = "".join(
        f'<tr class="verdict-false"><td>{escape(str(f["series"]))}</td>'
        f"<td>{f.get('baseline', '')}</td><td>{f.get('last', '')}</td>"
        f"<td>{f.get('delta_pct', '')}%</td>"
        f"<td>{escape(str(f.get('sense', '')))}</td></tr>"
        for f in flags
    )
    return (
        f'<div class="panel"><h3 class="verdict-false">'
        f"&#9888; {len(flags)} PERFORMANCE REGRESSION(S) FLAGGED</h3>"
        f"<table><tr><th>series</th><th>baseline</th><th>last</th>"
        f"<th>delta</th><th>sense</th></tr>{rows}</table>"
        f"<p>full doc: <a href={quoteattr('baselines.json')}>"
        f"baselines.json</a></p></div>"
    )


def build_store_index(
    root: str | Path, render_missing: bool = True
) -> Path | None:
    """Walk ``root``, render any missing per-run reports (unless
    ``render_missing=False``), and write ``root/index.html``.  Returns
    the index path, or None when the tree holds no runs."""
    root = Path(root)
    dirs = run_dirs(root)
    rows_html = []
    p50s: list[float | None] = []
    n_valid = n_invalid = 0
    for d in dirs:
        s = _summary_for(d, render_missing)
        if s is None:
            continue
        rel = d.relative_to(root)
        v = s.get("valid?")
        if v is True:
            n_valid += 1
        elif v is False:
            n_invalid += 1
        lat = s.get("latency-ms") or {}
        p50 = lat.get("p50")
        p50s.append(p50 if isinstance(p50, (int, float)) else None)
        # quoteattr, not escape: escape() leaves double quotes alone,
        # and a run path containing one would terminate the attribute
        # (breaking the well-formed-XML contract)
        report_link = (
            f"<a href={quoteattr(f'{rel}/{REPORT_FILE}')}>report</a>"
            if (d / REPORT_FILE).is_file()
            else ""
        )
        forensics_link = (
            f" · <a href={quoteattr(f'{rel}/forensics.html')}>"
            f"forensics</a>"
            if (d / "forensics.html").is_file()
            else ""
        )
        nem = s.get("nemesis-windows")
        p99 = lat.get("p99")
        # isinstance guards on BOTH: one malformed report.json (e.g. a
        # string "12ms" p50) must cost one cell, not the whole index
        p50_cell = "" if not isinstance(p50, (int, float)) else f"{p50:g}"
        p99_cell = "" if not isinstance(p99, (int, float)) else f"{p99:g}"
        rows_html.append(
            f"<tr><td>{escape(str(rel))}</td>"
            f'<td class="{_verdict_class(v)}">{escape(str(v))}</td>'
            f"<td>{s.get('ops', '')}</td>"
            f"<td>{p50_cell}</td>"
            f"<td>{p99_cell}</td>"
            f"<td>{len(nem) if isinstance(nem, list) else ''}</td>"
            f"<td>{report_link}{forensics_link}</td></tr>"
        )
    if not rows_html:
        return None
    baseline_panel = _baseline_panel(root)
    html = (
        f"<html><head><title>run index</title><style>{_CSS}</style>"
        f"</head><body><h2>run index — {len(rows_html)} runs "
        f'(<span class="verdict-true">{n_valid} valid</span> / '
        f'<span class="verdict-false">{n_invalid} invalid</span>)</h2>'
        f"{baseline_panel}"
        f'<div class="panel"><h3>p50 latency trend (ms, run order)'
        f"</h3>{_sparkline(p50s)}</div>"
        f'<div class="panel"><table><tr><th>run</th><th>valid?</th>'
        f"<th>ops</th><th>p50 ms</th><th>p99 ms</th><th>nemesis</th>"
        f"<th>artifacts</th></tr>{''.join(rows_html)}</table></div>"
        f"</body></html>"
    )
    from jepsen_tpu.report.render import write_artifact

    return write_artifact(root / INDEX_FILE, html)
