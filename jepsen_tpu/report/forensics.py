"""Counterexample forensics: an invalid verdict becomes a readable page.

A bare ``valid? false`` tells an operator nothing about WHICH ops broke
the model.  This module extracts the violating evidence the checkers
already computed — lost/duplicated/unexpected values for the queue
family, the refuted projection class (double-grant / token-order /
order-violation) the P-compositional mutex search names, divergent/
phantom stream reads — flags every history op that touches it, and
renders the op window around the first violation with the flagged ops
highlighted.  When the run came from a minimized fuzz repro, the page
links the repro driver (``emit.py`` passes it through).

Same determinism + well-formed-XML contract as ``report/render.py``.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Mapping, Sequence
from xml.sax.saxutils import escape, quoteattr

from jepsen_tpu.history.ops import NEMESIS_PROCESS, Op, OpType
from jepsen_tpu.report.render import (
    COLORS,
    FORENSICS_FILE,
    _CSS,
    write_artifact,
)

#: ops shown around the first violating op when the history is long
_WINDOW = 120


def _as_set(v) -> set:
    """results.json round-trips checker sets as lists; live results
    still hold sets."""
    if v is None:
        return set()
    if isinstance(v, (set, frozenset)):
        return set(v)
    if isinstance(v, (list, tuple)):
        return set(v)
    return {v}


def violating_values(results: Mapping[str, Any]) -> dict[str, set]:
    """``{reason: values}`` extracted from every invalid sub-result —
    the queue family's lost/duplicated/unexpected sets, the stream
    family's anomaly sets, the mutex family's refuted class."""
    out: dict[str, set] = {}

    def add(reason: str, values) -> None:
        vs = _as_set(values)
        if vs:
            out.setdefault(reason, set()).update(vs)

    for name in sorted(results):
        r = results.get(name)
        if not isinstance(r, dict) or r.get("valid?") is not False:
            continue
        for reason in ("lost", "unexpected", "duplicated"):
            add(reason, r.get(reason))
        for reason in (
            "divergent", "phantom", "non-monotonic", "reordered",
            "duplicated-reads",
        ):
            add(reason, r.get(reason))
        # pcomp: the refuted projection class — ('value', v) / lock key
        cls = r.get("invalid-class")
        if cls is not None:
            if isinstance(cls, (list, tuple)) and len(cls) == 2:
                add(f"refuted-class:{cls[0]}", [cls[1]])
            else:
                add("refuted-class", [cls])
        ov = r.get("order-violation")
        if ov:
            add("order-violation", ov)
    return out


def _op_values(op: Op) -> set:
    """Every scalar a history op touches (drain/read completions carry
    lists; mutex tokens ride ``[key, token]`` pairs)."""
    v = op.value
    if v is None:
        return set()
    if isinstance(v, (list, tuple)):
        out: set = set()
        for x in v:
            if isinstance(x, (list, tuple)):
                out.update(
                    y for y in x if isinstance(y, (int, str, float))
                )
            elif isinstance(x, (int, str, float)):
                out.add(x)
        return out
    if isinstance(v, (int, str, float)):
        return {v}
    return set()


def flag_ops(
    history: Sequence[Op], values_by_reason: Mapping[str, set]
) -> dict[int, list[str]]:
    """``{history position: [reasons]}`` for every op touching a
    violating value."""
    flat: dict[Any, list[str]] = {}
    for reason, vs in sorted(values_by_reason.items()):
        for v in vs:
            flat.setdefault(v, []).append(reason)
    flagged: dict[int, list[str]] = {}
    for i, op in enumerate(history):
        if op.process == NEMESIS_PROCESS:
            continue
        hit = sorted(
            {r for v in _op_values(op) for r in flat.get(v, ())}
        )
        if hit:
            flagged[i] = hit
    return flagged


def logpattern_matches(results: Mapping[str, Any]) -> list[dict]:
    """Every node-log line a ``log-file-pattern`` checker matched —
    previously invisible in reports (the matches lived only in
    results.json).  Robust to the checker's registration name: any
    sub-result carrying ``pattern`` + ``matches`` counts."""
    out: list[dict] = []
    for name in sorted(results):
        r = results.get(name)
        if (
            isinstance(r, dict)
            and "pattern" in r
            and isinstance(r.get("matches"), list)
        ):
            for m in r["matches"]:
                if isinstance(m, dict):
                    out.append({**m, "pattern": r["pattern"]})
    return out


def _cluster_window_html(
    run_dir: Path, history: Sequence[Op], flagged: Mapping[int, Any]
) -> str:
    """The cluster-telemetry answer to "which node was leader and what
    was commit lag during the violating window" — rendered only when
    the run carries a cluster.json AND ops were flagged."""
    from jepsen_tpu.obs.cluster import (
        cluster_window_summary,
        load_cluster_json,
    )

    doc = load_cluster_json(run_dir)
    if not doc or not doc.get("samples") or not flagged:
        return ""
    times = [
        history[i].time for i in flagged if history[i].time >= 0
    ]
    if not times:
        return ""
    t_lo, t_hi = min(times), max(times)
    w = cluster_window_summary(doc, t_lo, t_hi)
    leaders = ", ".join(
        f"{entry['node']} (term {entry['term']})"
        for entry in w["leaders"]
    ) or "none sampled"
    lag = (
        str(w["max-commit-lag"])
        if w["max-commit-lag"] is not None
        else "-"
    )
    return (
        f'<div class="panel"><h3>cluster during the violating window '
        f"[{t_lo / 1e9:.3f}s, {t_hi / 1e9:.3f}s]</h3>"
        f"<p>leader(s): {escape(leaders)} · max commit-index lag: "
        f"{escape(lag)} · tripwires in window: "
        f"{w['tripwires-in-window']} · {w['samples-in-window']} "
        f"telemetry samples (cluster.json)</p></div>"
    )


def _quarantine_note_html(results: Mapping[str, Any]) -> str:
    """PR-13 honesty note: when the violating verdict came out of a
    degraded/quarantine-carrying check, the forensics page must say so
    — the violating window sits NEAR evidence the checker could not
    judge (quarantined histories are explicit unknowns, not absent),
    and a reader weighing the counterexample needs that context."""
    quarantined_subs = sorted(
        name
        for name, r in results.items()
        if isinstance(r, dict) and r.get("quarantined")
    )
    deg = results.get("degraded")
    n_q = int((deg or {}).get("quarantined_histories", 0) or 0)
    if not quarantined_subs and not n_q:
        return ""
    parts = []
    if quarantined_subs:
        parts.append(
            f"sub-checker(s) {', '.join(quarantined_subs)} carry "
            f"quarantine evidence for THIS history"
        )
    if n_q:
        parts.append(
            f"{n_q} histories of the same degraded batch were "
            f"quarantined (dead/wedged workers or poison inputs)"
        )
    return (
        f'<div class="panel"><h3><span class="verdict-unknown">'
        f"quarantine nearby</span></h3><p>This violating window sits "
        f"near quarantined evidence: {escape('; '.join(parts))}. "
        f"Quarantined verdicts are explicit unknowns — the violation "
        f"shown here is real on the judged evidence, but neighboring "
        f"histories may be missing from the batch picture "
        f"(results.json → degraded / quarantined).</p></div>"
    )


def _logpattern_html(results: Mapping[str, Any]) -> str:
    matches = logpattern_matches(results)
    if not matches:
        return ""
    rows = "".join(
        f"<tr><td>{escape(str(m.get('node', '?')))}</td>"
        f"<td>{escape(str(m.get('file', '?')))}:{m.get('line', 0)}</td>"
        f"<td>{escape(str(m.get('text', ''))[:200])}</td></tr>"
        for m in matches[:50]
    )
    more = (
        f"<p>… {len(matches) - 50} more matches in results.json</p>"
        if len(matches) > 50
        else ""
    )
    return (
        f'<div class="panel"><h3>matched node-log lines '
        f"(log-file-pattern)</h3><table><tr><th>node</th>"
        f"<th>file:line</th><th>text</th></tr>{rows}</table>{more}</div>"
    )


def render_forensics(
    run_dir: str | Path,
    history: Sequence[Op] | None = None,
    results: Mapping[str, Any] | None = None,
    repro_path: str | Path | None = None,
    title: str | None = None,
    out_path: str | Path | None = None,
) -> Path | None:
    """Write ``forensics.html`` for an invalid run; returns the path, or
    None when the verdict is not invalid (a valid run has no
    counterexample to explain — refusing keeps the page an honest
    artifact, the soak/fuzz capture discipline)."""
    from jepsen_tpu.history.store import RESULTS_FILE, Store

    run_dir = Path(run_dir)
    if history is None:
        history = Store(run_dir.parent).load_history(run_dir)
    history = list(history)
    if results is None:
        try:
            results = json.loads((run_dir / RESULTS_FILE).read_text())
        except (OSError, ValueError):
            results = {}
    if results.get("valid?") is not False:
        return None
    title = title or f"{run_dir.name} forensics"

    values = violating_values(results)
    flagged = flag_ops(history, values)
    first = min(flagged) if flagged else 0
    lo = max(first - _WINDOW // 2, 0)
    hi = min(lo + _WINDOW, len(history))

    invalid_names = sorted(
        name
        for name, r in results.items()
        if isinstance(r, dict) and r.get("valid?") is False
    )

    reason_rows = "".join(
        f"<tr><td>{escape(reason)}</td>"
        f"<td>{escape(', '.join(str(v) for v in sorted(vs, key=str)))}"
        f"</td></tr>"
        for reason, vs in sorted(values.items())
    )

    op_rows = []
    for i in range(lo, hi):
        op = history[i]
        reasons = flagged.get(i)
        color = COLORS.get(op.type, "#cccccc")
        style = (
            ' style="background:#ffe0e0;font-weight:bold"'
            if reasons
            else ""
        )
        val = "" if op.value is None else str(op.value)
        if len(val) > 80:
            val = val[:77] + "..."
        op_rows.append(
            f"<tr{style}><td>{op.index}</td>"
            f"<td>{op.time / 1e9:.3f}s</td><td>{op.process}</td>"
            f"<td>{escape(op.f.name.lower())}</td>"
            f'<td><span style="color:{color}">'
            f"{escape(op.type.name.lower())}</span></td>"
            f"<td>{escape(val)}</td>"
            f"<td>{escape(', '.join(reasons)) if reasons else ''}</td>"
            f"</tr>"
        )

    repro_note = ""
    if repro_path is not None:
        repro_note = (
            f"<p>minimized fuzz repro: "
            f"<a href={quoteattr(str(repro_path))}>"
            f"{escape(Path(str(repro_path)).name)}</a></p>"
        )
    cluster_html = _cluster_window_html(run_dir, history, flagged)
    quarantine_html = _quarantine_note_html(results)
    logpattern_html = _logpattern_html(results)
    html = (
        f"<html><head><title>{escape(title)}</title>"
        f"<style>{_CSS}</style></head><body>"
        f'<h2>{escape(title)} — <span class="verdict-false">'
        f"valid? = False</span></h2>"
        f"<p>invalidating checkers: "
        f"{escape(', '.join(invalid_names) or '(none named)')} · "
        f"{len(flagged)} of {len(history)} ops touch violating values"
        f"</p>{repro_note}"
        f"{quarantine_html}{cluster_html}{logpattern_html}"
        f'<div class="panel"><h3>violating values</h3><table>'
        f"<tr><th>reason</th><th>values</th></tr>{reason_rows}"
        f"</table></div>"
        f'<div class="panel"><h3>op window [{lo}, {hi}) around the '
        f"first violation (flagged rows highlighted)</h3><table>"
        f"<tr><th>index</th><th>time</th><th>proc</th><th>f</th>"
        f"<th>type</th><th>value</th><th>flag</th></tr>"
        f"{''.join(op_rows)}</table></div>"
        f"</body></html>"
    )
    out = Path(out_path) if out_path is not None else run_dir / FORENSICS_FILE
    return write_artifact(out, html)
