"""Run reports: device-computed perf/timeline analytics, verdict
forensics, and the cross-run trend index (ISSUE 11 / OBSERVABILITY.md
§Run reports).

The reference suite composes ``checker/perf`` (latency/rate graphs) and
``jepsen.checker.timeline`` (per-process HTML op timelines) into every
test; this package is that analysis-and-evidence layer for the batched
world: the number-crunching is one vmapped XLA dispatch over the
``.jtc`` row columns (``perfstats``), the artifacts are deterministic
self-contained HTML with embedded SVG (``render``), invalid verdicts get
an op-level forensics page (``forensics``), and a store full of runs
becomes a browsable index with trend sparklines (``index``).
"""

from jepsen_tpu.report.perfstats import (  # noqa: F401
    WindowedPerf,
    WindowedStats,
    sketch_from_hist,
    windowed_stats,
    windowed_stats_rows,
)
from jepsen_tpu.report.render import render_run_report  # noqa: F401
from jepsen_tpu.report.forensics import render_forensics  # noqa: F401
from jepsen_tpu.report.index import build_store_index  # noqa: F401
