"""Per-run HTML report: perf panels, nemesis shading, op timeline.

``store/report`` parity for one run directory: ``report.html`` (latency
over time with percentile bands, throughput panel, nemesis fault
windows shaded on the SAME clock as the ops — everything keys off
``op.time`` ns-from-run-start, the clock the flight-recorder trace
shares), ``timeline.html`` (``jepsen.checker.timeline`` parity: one row
per process, one invoke→complete bar per op colored by outcome), and —
for an invalid verdict — ``forensics.html`` (``report/forensics.py``).

Determinism contract (pinned in ``tests/test_report.py``): the
artifacts are a pure function of the run directory's recorded state —
no wall clock, no dict-iteration-order leakage, fixed-precision number
formatting — so a fixed store renders byte-identical artifacts on every
invocation.  Every artifact is well-formed XML (no unclosed tags, no
HTML-only entities): the test suite parses each one with
``xml.etree.ElementTree`` as a structural gate.
"""

from __future__ import annotations

import json
import math
from pathlib import Path
from typing import Any, Mapping, Sequence
from xml.sax.saxutils import escape, quoteattr

import numpy as np

from jepsen_tpu.history.ops import NEMESIS_PROCESS, Op, OpF, OpType

REPORT_FILE = "report.html"
REPORT_JSON = "report.json"
TIMELINE_FILE = "timeline.html"
FORENSICS_FILE = "forensics.html"


def write_artifact(path: Path, text: str) -> Path:
    """Atomic artifact write (tmp → rename): the sidecar renders on
    demand from concurrent handler threads, and a reader racing a
    truncate-then-write ``write_text`` would be served a torn page
    with a clean 200."""
    import os

    path = Path(path)
    tmp = path.with_name(f"{path.name}.{os.getpid()}.tmp")
    try:
        tmp.write_text(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path

#: outcome colors (shared by every panel; timeline.py's palette)
COLORS = {
    OpType.OK: "#81b29a",
    OpType.FAIL: "#e07a5f",
    OpType.INFO: "#f2cc8f",
    None: "#cccccc",  # never completed
}
_NEMESIS_FILL = "#d7263d"
_Q_COLORS = {"p50": "#3d405b", "p90": "#5f7fbf", "p99": "#d7263d"}

_CSS = """
body { font-family: monospace; background: #fafaf8; color: #222;
       margin: 1.2em; }
h2, h3 { margin: 0.4em 0; }
.verdict-true { color: #2a7f4f; } .verdict-false { color: #c22; }
.verdict-unknown { color: #b8860b; }
table { border-collapse: collapse; font-size: 12px; }
td, th { border: 1px solid #ddd; padding: 2px 8px; text-align: left; }
.panel { margin: 1em 0; }
a { color: #3d405b; }
"""


# ---------------------------------------------------------------------------
# nemesis windows (one clock: op.time ns from run start)
# ---------------------------------------------------------------------------


def nemesis_windows(
    history: Sequence[Op],
) -> list[tuple[int, int, str]]:
    """``(t0_ns, t1_ns, label)`` fault windows from the recorded
    nemesis ops: a START completion opens a window, the next STOP
    completion closes it (the same pairing the PR-9 trace spans use);
    a window the run never healed closes at the history's end."""
    t_max = max((op.time for op in history if op.time >= 0), default=0)
    out: list[tuple[int, int, str]] = []
    open_w: tuple[int, str] | None = None
    for op in history:
        if op.process != NEMESIS_PROCESS or op.type == OpType.INVOKE:
            continue
        if op.f == OpF.START and op.time >= 0:
            label = str(op.value) if op.value is not None else "fault"
            open_w = (op.time, label)
        elif op.f == OpF.STOP and open_w is not None:
            t0, label = open_w
            open_w = None
            out.append((t0, op.time if op.time >= 0 else t_max, label))
    if open_w is not None:
        out.append((open_w[0], t_max, open_w[1]))
    return out


# ---------------------------------------------------------------------------
# SVG panels
# ---------------------------------------------------------------------------

_W, _H = 860, 240
_ML, _MR, _MT, _MB = 56, 10, 10, 28  # margins


def _xpix(t_s: float, t_max_s: float) -> float:
    return _ML + (_W - _ML - _MR) * (t_s / max(t_max_s, 1e-9))


def _fmt(x: float) -> str:
    return f"{x:.2f}"


def _svg_open(height: int = _H) -> list[str]:
    return [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{_W}" '
        f'height="{height}" viewBox="0 0 {_W} {height}" '
        f'font-family="monospace" font-size="10">',
        f'<rect x="{_ML}" y="{_MT}" width="{_W - _ML - _MR}" '
        f'height="{height - _MT - _MB}" fill="#ffffff" '
        f'stroke="#cccccc"/>',
    ]


def _svg_nemesis(parts: list[str], windows, t_max_s: float, height: int):
    for t0, t1, label in windows:
        x0 = _xpix(t0 / 1e9, t_max_s)
        x1 = _xpix(t1 / 1e9, t_max_s)
        parts.append(
            f'<rect x="{_fmt(x0)}" y="{_MT}" '
            f'width="{_fmt(max(x1 - x0, 1.0))}" '
            f'height="{height - _MT - _MB}" fill="{_NEMESIS_FILL}" '
            f'fill-opacity="0.12"><title>'
            f"{escape(label)} [{t0 / 1e9:.1f}s → {t1 / 1e9:.1f}s]"
            f"</title></rect>"
        )


def _svg_xaxis(parts: list[str], t_max_s: float, height: int):
    for frac in (0.0, 0.25, 0.5, 0.75, 1.0):
        x = _ML + (_W - _ML - _MR) * frac
        parts.append(
            f'<text x="{_fmt(x)}" y="{height - _MB + 14}" '
            f'text-anchor="middle" fill="#555555">'
            f"{t_max_s * frac:.0f}s</text>"
        )


def latency_panel_svg(
    quantiles: np.ndarray,  # [W, 3] ms, -1 = empty window
    window_s: float,
    windows_nemesis,
    t_max_s: float,
) -> str:
    """Latency-over-time with a p50..p99 percentile band and the p50/
    p90/p99 lines, log-y, nemesis windows shaded."""
    q = np.asarray(quantiles, np.float64)
    have = q[:, 0] >= 0  # non-empty windows (0 = sub-ms completions)
    vmax = float(q.max()) if q.max() > 0 else 1.0
    ymax = 10 ** math.ceil(math.log10(max(vmax, 1.0)))
    pos = q[have]
    pos = pos[pos > 0]
    ymin = max(
        10 ** math.floor(math.log10(float(pos.min()))) if pos.size else 0.1,
        0.01,
    )
    if ymin >= ymax:
        ymin = ymax / 100.0
    lo, hi = math.log10(ymin), math.log10(ymax)

    def ypix(v: float) -> float:
        v = min(max(v, ymin), ymax)
        return _MT + (_H - _MT - _MB) * (
            1.0 - (math.log10(v) - lo) / (hi - lo)
        )

    xs = [(w + 0.5) * window_s for w in range(len(q))]
    parts = _svg_open()
    _svg_nemesis(parts, windows_nemesis, t_max_s, _H)
    # y decade gridlines + labels
    d = int(math.floor(lo))
    while d <= hi:
        v = 10.0**d
        if ymin <= v <= ymax:
            y = ypix(v)
            parts.append(
                f'<line x1="{_ML}" y1="{_fmt(y)}" x2="{_W - _MR}" '
                f'y2="{_fmt(y)}" stroke="#eeeeee"/>'
            )
            parts.append(
                f'<text x="{_ML - 4}" y="{_fmt(y + 3)}" '
                f'text-anchor="end" fill="#555555">{v:g}ms</text>'
            )
        d += 1
    # percentile band p50..p99
    pts_band = []
    for i in np.nonzero(have)[0]:
        pts_band.append(
            f"{_fmt(_xpix(xs[i], t_max_s))},{_fmt(ypix(q[i, 2]))}"
        )
    for i in np.nonzero(have)[0][::-1]:
        pts_band.append(
            f"{_fmt(_xpix(xs[i], t_max_s))},{_fmt(ypix(q[i, 0]))}"
        )
    if pts_band:
        parts.append(
            f'<polygon points="{" ".join(pts_band)}" fill="#5f7fbf" '
            f'fill-opacity="0.15" stroke="none"/>'
        )
    for qi, qname in enumerate(("p50", "p90", "p99")):
        pts = [
            f"{_fmt(_xpix(xs[i], t_max_s))},{_fmt(ypix(q[i, qi]))}"
            for i in np.nonzero(have)[0]
        ]
        if pts:
            parts.append(
                f'<polyline points="{" ".join(pts)}" fill="none" '
                f'stroke="{_Q_COLORS[qname]}" stroke-width="1.2"/>'
            )
    _svg_xaxis(parts, t_max_s, _H)
    for i, qname in enumerate(("p50", "p90", "p99")):
        parts.append(
            f'<text x="{_W - _MR - 120 + i * 40}" y="{_MT + 12}" '
            f'fill="{_Q_COLORS[qname]}">{qname}</text>'
        )
    parts.append("</svg>")
    return "".join(parts)


def rate_panel_svg(
    rates: np.ndarray,  # [W, 3 f-slots, 3 outcomes]
    window_s: float,
    windows_nemesis,
    t_max_s: float,
) -> str:
    """Throughput panel: completions/s per window by outcome (ok/fail/
    info stacked as lines), nemesis windows shaded."""
    r = np.asarray(rates, np.float64).sum(axis=1)  # [W, 3 outcomes]
    per_s = r / max(window_s, 1e-9)
    vmax = max(float(per_s.max()), 1.0)
    parts = _svg_open()
    _svg_nemesis(parts, windows_nemesis, t_max_s, _H)

    def ypix(v: float) -> float:
        return _MT + (_H - _MT - _MB) * (1.0 - min(v, vmax) / vmax)

    for frac in (0.5, 1.0):
        parts.append(
            f'<text x="{_ML - 4}" y="{_fmt(ypix(vmax * frac) + 3)}" '
            f'text-anchor="end" fill="#555555">{vmax * frac:.0f}/s</text>'
        )
    xs = [(w + 0.5) * window_s for w in range(len(r))]
    for ti, tname in enumerate(("ok", "fail", "info")):
        if per_s[:, ti].sum() == 0:
            continue
        color = COLORS[OpType(int(OpType.OK) + ti)]
        pts = [
            f"{_fmt(_xpix(xs[w], t_max_s))},{_fmt(ypix(per_s[w, ti]))}"
            for w in range(len(r))
        ]
        parts.append(
            f'<polyline points="{" ".join(pts)}" fill="none" '
            f'stroke="{color}" stroke-width="1.2"><title>{tname}'
            f"</title></polyline>"
        )
    _svg_xaxis(parts, t_max_s, _H)
    parts.append("</svg>")
    return "".join(parts)


# ---------------------------------------------------------------------------
# cluster telemetry panels (cluster.json — obs/cluster.py, ISSUE 12)
# ---------------------------------------------------------------------------

#: role strip colors (cluster panel); "up" = a local-mode broker with
#: no raft block, grey = never sampled
ROLE_COLORS = {
    "leader": "#2a7f4f",
    "follower": "#5f7fbf",
    "candidate": "#f2cc8f",
    "down": "#e07a5f",
    "up": "#cccccc",
}

#: per-node line colors for the commit-lag panel (cycled)
_NODE_COLORS = (
    "#3d405b", "#81b29a", "#e07a5f", "#5f7fbf", "#b8860b", "#d7263d",
)


def _cluster_by_node(doc: Mapping[str, Any]) -> dict[str, list[dict]]:
    by_node: dict[str, list[dict]] = {}
    for s in doc.get("samples") or []:
        by_node.setdefault(s["node"], []).append(s)
    for rows in by_node.values():
        rows.sort(key=lambda s: s["t"])
    return by_node


def cluster_role_svg(
    doc: Mapping[str, Any], windows_nemesis, t_max_s: float
) -> str:
    """Leader/role timeline strip: one row per node, colored by role
    between consecutive samples, nemesis windows shaded — role flips
    inside fault windows are the panel's whole point."""
    by_node = _cluster_by_node(doc)
    nodes = sorted(by_node)
    row_h = 22
    height = _MT + row_h * max(len(nodes), 1) + _MB
    parts = _svg_open(height)
    _svg_nemesis(parts, windows_nemesis, t_max_s, height)
    for i, node in enumerate(nodes):
        y = _MT + i * row_h + 3
        parts.append(
            f'<text x="{_ML - 4}" y="{y + 11}" text-anchor="end" '
            f'fill="#555555">{escape(node[-9:])}</text>'
        )
        rows = by_node[node]
        for j, s in enumerate(rows):
            t0_s = s["t"] / 1e9
            t1_s = (
                rows[j + 1]["t"] / 1e9 if j + 1 < len(rows) else t_max_s
            )
            x0 = _xpix(min(t0_s, t_max_s), t_max_s)
            x1 = _xpix(min(max(t1_s, t0_s), t_max_s), t_max_s)
            color = ROLE_COLORS.get(s["role"], "#cccccc")
            parts.append(
                f'<rect x="{_fmt(x0)}" y="{y}" '
                f'width="{_fmt(max(x1 - x0, 0.8))}" height="{row_h - 6}" '
                f'fill="{color}"><title>{escape(node)} '
                f"{escape(str(s['role']))} term {s['term']} commit "
                f"{s['commit']} [{t0_s:.1f}s]</title></rect>"
            )
    _svg_xaxis(parts, t_max_s, height)
    legend_x = _W - _MR - 300
    for k, role in enumerate(("leader", "follower", "candidate", "down")):
        parts.append(
            f'<text x="{legend_x + k * 75}" y="{_MT - 1}" '
            f'fill="{ROLE_COLORS[role]}">{role}</text>'
        )
    parts.append("</svg>")
    return "".join(parts)


def cluster_lag_svg(
    doc: Mapping[str, Any], windows_nemesis, t_max_s: float
) -> str:
    """Term staircase (grey steps, right labels) + per-node commit-index
    lag behind the sample's max commit (colored lines, left axis)."""
    by_node = _cluster_by_node(doc)
    nodes = sorted(by_node)
    # align per poll instant: t -> {node: sample}
    by_t: dict[int, dict[str, dict]] = {}
    for node, rows in by_node.items():
        for s in rows:
            by_t.setdefault(s["t"], {})[node] = s
    ts = sorted(by_t)
    lags: dict[str, list[tuple[float, float]]] = {n: [] for n in nodes}
    terms: list[tuple[float, float]] = []
    for t in ts:
        rows = by_t[t]
        commits = [
            s["commit"] for s in rows.values() if s["role"] != "down"
        ]
        top = max(commits, default=0)
        terms.append((t / 1e9, max(
            (s["term"] for s in rows.values()), default=0
        )))
        for node, s in rows.items():
            if s["role"] != "down":
                lags[node].append((t / 1e9, top - s["commit"]))
    lag_max = max(
        (v for pts in lags.values() for _t, v in pts), default=0.0
    )
    term_max = max((v for _t, v in terms), default=0.0)
    parts = _svg_open()
    _svg_nemesis(parts, windows_nemesis, t_max_s, _H)

    def ypix(v: float, vmax: float) -> float:
        return _MT + (_H - _MT - _MB) * (1.0 - v / max(vmax, 1.0))

    # term staircase (steps between polls)
    if terms:
        pts = []
        prev = terms[0][1]
        pts.append(f"{_fmt(_xpix(terms[0][0], t_max_s))},"
                   f"{_fmt(ypix(prev, term_max))}")
        for t_s, v in terms[1:]:
            x = _fmt(_xpix(t_s, t_max_s))
            pts.append(f"{x},{_fmt(ypix(prev, term_max))}")
            pts.append(f"{x},{_fmt(ypix(v, term_max))}")
            prev = v
        parts.append(
            f'<polyline points="{" ".join(pts)}" fill="none" '
            f'stroke="#999999" stroke-width="1.0" '
            f'stroke-dasharray="4 2"><title>term (max '
            f"{term_max:g})</title></polyline>"
        )
        parts.append(
            f'<text x="{_W - _MR - 2}" y="{_MT + 12}" text-anchor="end" '
            f'fill="#999999">term ≤ {term_max:g}</text>'
        )
    for frac in (0.5, 1.0):
        parts.append(
            f'<text x="{_ML - 4}" y="{_fmt(ypix(lag_max * frac, lag_max) + 3)}" '
            f'text-anchor="end" fill="#555555">'
            f"{lag_max * frac:.0f}</text>"
        )
    for i, node in enumerate(nodes):
        pts = [
            f"{_fmt(_xpix(t_s, t_max_s))},{_fmt(ypix(v, lag_max))}"
            for t_s, v in lags[node]
        ]
        if pts:
            color = _NODE_COLORS[i % len(_NODE_COLORS)]
            parts.append(
                f'<polyline points="{" ".join(pts)}" fill="none" '
                f'stroke="{color}" stroke-width="1.2"><title>'
                f"{escape(node)} commit lag</title></polyline>"
            )
    _svg_xaxis(parts, t_max_s, _H)
    parts.append("</svg>")
    return "".join(parts)


def _cluster_node_rows(doc: Mapping[str, Any]) -> str:
    """Per-node final-state table rows (role, term, commit, elections,
    CRC rejections, wire faults, tripwires, fsync p50/p99)."""
    from jepsen_tpu.obs.metrics import QuantileSketch

    rows = []
    final = doc.get("final") or {}
    for node in sorted(final):
        snap = final[node] or {}
        raft = snap.get("raft") or {}
        broker = snap.get("broker") or {}
        counters = raft.get("counters") or {}
        fsync = raft.get("fsync_ms") or {}
        if fsync.get("count"):
            sk = QuantileSketch.from_state(fsync)
            p50, p99 = sk.quantile(0.50), sk.quantile(0.99)
            fsync_txt = f"{p50:.2f} / {p99:.2f}"
        else:
            fsync_txt = "-"
        role = raft.get("role") or ("up" if snap else "down")
        wire = (
            counters.get("wire_corrupt", 0)
            + counters.get("wire_duplicate", 0)
            + counters.get("wire_delay", 0)
        )
        rows.append(
            f"<tr><td>{escape(node)}</td>"
            f'<td><span style="color:'
            f'{ROLE_COLORS.get(role, "#cccccc")}">{escape(str(role))}'
            f"</span></td>"
            f"<td>{raft.get('term', '-')}</td>"
            f"<td>{raft.get('commit_idx', '-')}</td>"
            f"<td>{counters.get('elections_won', 0)}"
            f"/{counters.get('elections_started', 0)}</td>"
            f"<td>{counters.get('crc_rejected', 0)}</td>"
            f"<td>{wire}</td>"
            f"<td>{counters.get('safety_violations', 0)}</td>"
            f"<td>{broker.get('ready', 0)}/{broker.get('inflight', 0)}</td>"
            f"<td>{fsync_txt}</td></tr>"
        )
    return "".join(rows)


def cluster_panel_html(
    doc: Mapping[str, Any], windows_nemesis, t_max_s: float
) -> str:
    """The report's cluster section: role strip, term/commit-lag panel,
    per-node table, event count."""
    if not doc.get("samples"):
        return ""
    s = doc.get("summary") or {}
    role_svg = cluster_role_svg(doc, windows_nemesis, t_max_s)
    lag_svg = cluster_lag_svg(doc, windows_nemesis, t_max_s)
    return (
        f'<div class="panel"><h3>cluster telemetry — node roles on the '
        f"op clock (shaded = nemesis fault windows)</h3>"
        f"<p>{s.get('polls', 0)} polls · leaders "
        f"{escape(', '.join(s.get('leaders-seen', []) or ['-']))} · "
        f"{s.get('leader-changes', 0)} leader changes · "
        f"{s.get('elections-won', 0)} elections won · tripwires "
        f"{s.get('safety-violations', 0)} · "
        f"{len(doc.get('events') or [])} node events</p>{role_svg}</div>"
        f'<div class="panel"><h3>commit-index lag per node (lines) + '
        f"term staircase (dashed)</h3>{lag_svg}</div>"
        f'<div class="panel"><h3>per-node internals (end of run)</h3>'
        f"<table><tr><th>node</th><th>role</th><th>term</th>"
        f"<th>commit</th><th>elections won/started</th>"
        f"<th>crc rejected</th><th>wire faults</th><th>tripwires</th>"
        f"<th>ready/inflight</th><th>fsync p50/p99 ms</th></tr>"
        f"{_cluster_node_rows(doc)}</table></div>"
    )


# ---------------------------------------------------------------------------
# timeline.html (jepsen.checker.timeline parity, XML-well-formed)
# ---------------------------------------------------------------------------


def render_timeline(
    history: Sequence[Op], out_path: str | Path, title: str = "timeline"
) -> Path:
    """One row per process, one invoke→complete bar per op colored
    ok/fail/info (grey = never completed), hover details."""
    pairs: list[tuple[Op, Op | None]] = []
    open_by_process: dict[int, Op] = {}
    for op in history:
        if op.type == OpType.INVOKE:
            open_by_process[op.process] = op
        else:
            inv = open_by_process.pop(op.process, None)
            if inv is not None:
                pairs.append((inv, op))
    for p in sorted(open_by_process):
        pairs.append((open_by_process[p], None))

    # `or 1`: a history whose only timestamped ops sit at t=0 ns must
    # not divide by zero (default= only covers the EMPTY generator)
    t_max = max((op.time for op in history if op.time >= 0), default=1) or 1
    processes = sorted(
        {inv.process for inv, _ in pairs},
        key=lambda p: (p == NEMESIS_PROCESS, p),
    )
    rows = []
    for p in processes:
        bars = []
        for inv, comp in pairs:
            if inv.process != p:
                continue
            left = 100.0 * max(inv.time, 0) / t_max
            end_t = comp.time if comp is not None and comp.time >= 0 else t_max
            width = max(100.0 * (end_t - max(inv.time, 0)) / t_max, 0.15)
            color = COLORS[comp.type if comp is not None else None]
            value = (
                comp.value
                if comp is not None and comp.value is not None
                else inv.value
            )
            tip = quoteattr(
                f"{inv.f.name.lower()} "
                f"{value if value is not None else ''} "
                f"[{inv.time / 1e9:.3f}s → {end_t / 1e9:.3f}s] "
                f"{comp.type.name.lower() if comp else 'open'}"
                + (
                    f" {comp.error}"
                    if comp is not None and comp.error
                    else ""
                )
            )
            bars.append(
                f'<div class="op" title={tip} style='
                f'"left:{left:.3f}%;width:{width:.3f}%;'
                f'background:{color}"></div>'
            )
        label = "nemesis" if p == NEMESIS_PROCESS else f"proc {p}"
        rows.append(
            f'<div class="row"><div class="label">{label}</div>'
            f'<div class="lane">{"".join(bars)}</div></div>'
        )
    style = (
        "body { font-family: monospace; background: #fafaf8; }\n"
        ".row { position: relative; height: 22px; "
        "border-bottom: 1px solid #eee; }\n"
        ".label { position: absolute; left: 0; width: 90px; "
        "font-size: 11px; line-height: 22px; }\n"
        ".lane { position: absolute; left: 100px; right: 0; top: 0; "
        "bottom: 0; }\n"
        ".op { position: absolute; height: 16px; top: 3px; "
        "border-radius: 3px; min-width: 2px; opacity: 0.9; }\n"
        ".op:hover { outline: 2px solid #333; z-index: 10; }\n"
    )
    out = write_artifact(
        Path(out_path),
        f"<html><head><title>{escape(title)}</title>"
        f"<style>{style}</style></head>"
        f"<body><h3>{escape(title)}</h3>"
        f"<p>{len(pairs)} ops · {t_max / 1e9:.1f}s · hover for "
        f"details · green ok / red fail / yellow info / grey open</p>"
        f"{''.join(rows)}</body></html>",
    )
    return out


# ---------------------------------------------------------------------------
# the per-run report
# ---------------------------------------------------------------------------


def _verdict_class(v) -> str:
    if v is True:
        return "verdict-true"
    if v is False:
        return "verdict-false"
    return "verdict-unknown"


def _sub_verdict_rows(results: Mapping[str, Any]) -> str:
    rows = []
    for name in sorted(results):
        r = results[name]
        if not isinstance(r, dict) or "valid?" not in r:
            continue
        v = r["valid?"]
        rows.append(
            f'<tr><td>{escape(name)}</td><td class="{_verdict_class(v)}">'
            f"{escape(str(v))}</td></tr>"
        )
    return "".join(rows)


def _degraded_panel_html(degraded: Mapping[str, Any] | None) -> str:
    """The PR-13 degraded-provenance row: when a ``check --procs`` run
    completed elastically past worker deaths, results.json carries the
    machine-readable ``degraded`` dict and the report must SHOW it — a
    degraded verdict that renders like a clean one is the silent-fold
    failure mode the elastic contract forbids.  Inactive provenance
    (the no-fault elastic run) renders nothing."""
    from jepsen_tpu.parallel.distributed import degraded_active

    if not degraded_active(degraded):
        return ""
    dead = ", ".join(
        f"worker {d.get('pid')} (rc={d.get('rc')})"
        for d in degraded.get("dead_workers", ())
    ) or "none"
    req_rows = "".join(
        f"<tr><td>{int(r.get('stripe', -1))}</td>"
        f"<td>{int(r.get('from_pid', -1))} → "
        f"{escape(str(r.get('completed_by')))}</td>"
        f"<td>{int(r.get('retries', 0))}</td>"
        f"<td>{escape(str(r.get('recovery_s', '-')))}</td></tr>"
        for r in degraded.get("requeued_stripes", ())
    )
    n_q = int(degraded.get("quarantined_histories", 0) or 0)
    wedged = degraded.get("wedged_killed") or []
    return (
        f'<div class="panel"><h3><span class="verdict-unknown">DEGRADED'
        f"</span> check (elastic recovery)</h3>"
        f"<p>effective workers {degraded.get('effective_procs')} of "
        f"{degraded.get('procs')} · dead: {escape(dead)} · "
        f"wedge-killed: {escape(', '.join(str(w) for w in wedged) or 'none')}"
        f" · quarantined histories: {n_q}"
        + (
            " (their verdicts are explicit unknowns — the composed "
            "verdict can be at best unknown)"
            if n_q
            else ""
        )
        + "</p>"
        + (
            f"<table><tr><th>requeued stripe</th><th>worker</th>"
            f"<th>retries</th><th>recovery s</th></tr>{req_rows}</table>"
            if req_rows
            else ""
        )
        + "</div>"
    )


def render_run_report(
    run_dir: str | Path,
    history: Sequence[Op] | None = None,
    results: Mapping[str, Any] | None = None,
    title: str | None = None,
    trace_path: str | Path | None = None,
    stats=None,
) -> dict[str, str]:
    """Render ``report.html`` + ``timeline.html`` (+ ``forensics.html``
    on an invalid verdict) + the machine-readable ``report.json`` into
    ``run_dir``; returns ``{artifact-name: path}``.

    Pure function of the run directory's recorded state; the device
    windowed-stats kernel does the number crunching.  ``stats`` may
    carry the :class:`~jepsen_tpu.report.perfstats.WindowedStats` the
    run's ``WindowedPerf`` checker already computed for THIS history
    (the runner forwards it) — pack + dispatch then happen once per
    run.
    """
    from jepsen_tpu.history.encode import pack_histories
    from jepsen_tpu.history.store import RESULTS_FILE, Store
    from jepsen_tpu.report.perfstats import (
        stats_summary,
        windowed_stats,
    )

    run_dir = Path(run_dir)
    if history is None:
        history = Store(run_dir.parent).load_history(run_dir)
    history = list(history)
    if results is None:
        try:
            results = json.loads((run_dir / RESULTS_FILE).read_text())
        except (OSError, ValueError):
            results = {}
    title = title or run_dir.name

    paths: dict[str, str] = {}
    t_max_ns = max(
        (op.time for op in history if op.time >= 0), default=1
    ) or 1
    t_max_s = t_max_ns / 1e9
    windows = nemesis_windows(history)

    if history:
        t = stats if stats is not None else windowed_stats(
            pack_histories([history])
        )
        summary = stats_summary(t, 0)
        quant = np.asarray(t.quantiles)[0]
        rates = np.asarray(t.rates)[0]
        window_s = summary["window-s"]
    else:
        summary = {"completions": 0, "windows": 0, "window-s": 0.0}
        quant = np.full((1, 3), -1.0)
        rates = np.zeros((1, 3, 3))
        window_s = 1.0

    # cluster telemetry (obs/cluster.py): rendered when the run carries
    # a cluster.json — runs with telemetry off (or predating it) simply
    # have no cluster section
    from jepsen_tpu.obs.cluster import load_cluster_json

    cluster_doc = load_cluster_json(run_dir)
    cluster_html = (
        cluster_panel_html(cluster_doc, windows, t_max_s)
        if cluster_doc
        else ""
    )

    verdict = results.get("valid?")
    degraded_html = _degraded_panel_html(results.get("degraded"))
    summary_doc = {
        "run": run_dir.name,
        "valid?": verdict,
        "ops": len(history),
        "nemesis-windows": [
            {"t0-s": round(t0 / 1e9, 3), "t1-s": round(t1 / 1e9, 3),
             "fault": label}
            for t0, t1, label in windows
        ],
        **summary,
    }
    if cluster_doc:
        summary_doc["cluster"] = cluster_doc.get("summary")
    if degraded_html:
        deg = results["degraded"]
        summary_doc["degraded"] = {
            "procs": deg.get("procs"),
            "effective_procs": deg.get("effective_procs"),
            "dead_workers": len(deg.get("dead_workers") or ()),
            "requeued_stripes": len(deg.get("requeued_stripes") or ()),
            "quarantined_histories": deg.get("quarantined_histories", 0),
        }
    write_artifact(
        run_dir / REPORT_JSON,
        json.dumps(summary_doc, indent=1, sort_keys=True) + "\n",
    )
    paths["report-json"] = str(run_dir / REPORT_JSON)

    tl = render_timeline(
        history, run_dir / TIMELINE_FILE, title=f"{title} timeline"
    )
    paths["timeline"] = str(tl)

    forensic_link = ""
    if verdict is False:
        from jepsen_tpu.report.forensics import render_forensics

        fp = render_forensics(run_dir, history=history, results=results)
        if fp is not None:
            paths["forensics"] = str(fp)
            forensic_link = (
                f' · <a href="{FORENSICS_FILE}">forensics</a>'
            )

    lat_svg = latency_panel_svg(quant, window_s, windows, t_max_s)
    rate_svg = rate_panel_svg(rates, window_s, windows, t_max_s)
    nem_rows = "".join(
        f"<tr><td>{escape(label)}</td><td>{t0 / 1e9:.1f}s</td>"
        f"<td>{t1 / 1e9:.1f}s</td></tr>"
        for t0, t1, label in windows
    )
    lat = summary.get("latency-ms", {})

    def _ms(v) -> str:
        return "-" if v is None else f"{v:g}"

    trace_note = ""
    if trace_path is not None:
        trace_note = (
            f"<p>flight-recorder trace (same clock): "
            f"<a href={quoteattr(str(trace_path))}>"
            f"{escape(Path(str(trace_path)).name)}</a></p>"
        )
    html = (
        f"<html><head><title>{escape(title)}</title>"
        f"<style>{_CSS}</style></head><body>"
        f"<h2>{escape(title)} — <span class="
        f'"{_verdict_class(verdict)}">valid? = {escape(str(verdict))}'
        f"</span></h2>"
        f"<p>{len(history)} ops · {t_max_s:.1f}s · "
        f"ok {summary.get('ok', 0)} / fail {summary.get('fail', 0)} / "
        f"info {summary.get('info', 0)} · "
        f"latency p50 {_ms(lat.get('p50'))} / p90 {_ms(lat.get('p90'))}"
        f" / p99 {_ms(lat.get('p99'))} ms · "
        f'<a href="{TIMELINE_FILE}">timeline</a>{forensic_link}</p>'
        f"{trace_note}"
        f'<div class="panel"><h3>completion latency (percentile band '
        f"p50..p99; shaded = nemesis fault windows)</h3>{lat_svg}</div>"
        f'<div class="panel"><h3>throughput (completions/s: green ok / '
        f"red fail / yellow info)</h3>{rate_svg}</div>"
        + degraded_html
        + f'<div class="panel"><h3>sub-verdicts</h3><table>'
        f"<tr><th>checker</th><th>valid?</th></tr>"
        f"{_sub_verdict_rows(results)}</table></div>"
        + cluster_html
        + (
            f'<div class="panel"><h3>nemesis windows (one clock with '
            f"the op timeline)</h3><table><tr><th>fault</th><th>start"
            f"</th><th>heal</th></tr>{nem_rows}</table></div>"
            if nem_rows
            else ""
        )
        + "</body></html>"
    )
    write_artifact(run_dir / REPORT_FILE, html)
    paths["report"] = str(run_dir / REPORT_FILE)
    return paths
