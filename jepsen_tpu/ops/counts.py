"""Masked per-value scatter kernels.

The total-queue and per-value-linearizability checkers reduce a history to
per-value statistics over a dense value space of width ``V`` (values come
from a single incrementing counter — reference ``rabbitmq.clj:245-247`` — so
the space is dense and small).  The core primitive is a masked scatter-add /
scatter-min / scatter-max into a ``[V]`` vector; unselected rows are routed
to index ``V`` — genuinely out of bounds, so ``mode='drop'`` discards them
(note ``-1`` would *wrap* to ``V-1``, not drop) — making padded rows no-ops
by construction.  The scattered payload is additionally neutralized with
``where(select, …)`` as defense in depth.

These are plain XLA scatters: on TPU they lower to efficient sorted-scatter
loops, and under ``shard_map`` the op axis can be sharded with a ``psum``
combining step (see ``jepsen_tpu.parallel``) — the long-history analog of
sequence parallelism.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _routed(values: jax.Array, select: jax.Array, value_space: int) -> jax.Array:
    """Scatter indices: the value where selected, else ``V`` (out of bounds,
    dropped by ``mode='drop'``)."""
    return jnp.where(select, values, value_space)


def masked_value_counts(
    values: jax.Array,  # [L] int32
    select: jax.Array,  # [L] bool
    value_space: int,
    weights: jax.Array | None = None,  # [L] int32, default 1
) -> jax.Array:
    """``out[v] = sum(weights[i] for i where select[i] and values[i]==v)``."""
    # values may be narrow (i8/i16 packing); the accumulator stays i32
    w = jnp.ones(values.shape, jnp.int32) if weights is None else weights
    return (
        jnp.zeros((value_space,), jnp.int32)
        .at[_routed(values, select, value_space)]
        .add(jnp.where(select, w, 0), mode="drop")
    )


def masked_value_reduce_min(
    values: jax.Array,  # [L] int32
    select: jax.Array,  # [L] bool
    payload: jax.Array,  # [L] int32 — quantity to min-reduce per value
    value_space: int,
    init: int = 2**31 - 1,
) -> jax.Array:
    """``out[v] = min(payload[i] for i where select[i] and values[i]==v)``,
    ``init`` where no row matched."""
    payload = payload.astype(jnp.int32)  # narrow payloads must not clip init
    return (
        jnp.full((value_space,), init, jnp.int32)
        .at[_routed(values, select, value_space)]
        .min(jnp.where(select, payload, init), mode="drop")
    )


def masked_value_reduce_max(
    values: jax.Array,  # [L] int32
    select: jax.Array,  # [L] bool
    payload: jax.Array,  # [L] int32 — quantity to max-reduce per value
    value_space: int,
    init: int = -(2**31),
) -> jax.Array:
    """``out[v] = max(payload[i] for i where select[i] and values[i]==v)``,
    ``init`` where no row matched."""
    payload = payload.astype(jnp.int32)  # narrow payloads must not clip init
    return (
        jnp.full((value_space,), init, jnp.int32)
        .at[_routed(values, select, value_space)]
        .max(jnp.where(select, payload, init), mode="drop")
    )
