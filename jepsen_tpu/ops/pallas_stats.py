"""Fused per-value history statistics as a Pallas TPU kernel.

The scatter path (``jepsen_tpu.ops.counts``) evaluates the total-queue and
queue-linearizability checkers with ~9 independent XLA scatter ops.  This
kernel is the **native-kernel escape hatch** SURVEY.md §7.2 reserves for
the case where XLA's scheduling of those scatters is poor: it computes all
six per-value stat vectors in one pass over the rows by materializing a
value×row comparison tile ``eq[v, l] = (value[l] == v)`` in VMEM and
reducing it along rows under six predicates (pure VPU work, no scatters):

**Measured verdict (v5e-1, 2026-07)**: XLA's sorted-scatter lowering is
*good* here — the scatter path beats this kernel 5–10× at every probed
shape (e.g. B=4096 L=1024 V=384: 0.14 ms vs 1.2 ms; B=8 L=65536: 0.14 ms
vs 0.75 ms), because the dense comparison does O(L·V/lane) work against
the scatters' O(L).  The kernel therefore stays an *alternative verified
backend* (``fused.fused_tensor_check``, differential-tested bit-exact
against the scatter path) and a working template for future hot ops that
XLA does schedule poorly — not the default path.  Don't hand-schedule what
the compiler already does well.

    a[v] — enqueue-invoke count        (total-queue + queue-lin)
    e[v] — enqueue-ok count            (total-queue)
    x[v] — enqueue-fail count          (queue-lin)
    d[v] — ok-read count               (total-queue + queue-lin)
    s[v] — min history position of an enqueue invoke   (queue-lin)
    t[v] — min history position of an ok read          (queue-lin)

Layout (Mosaic tiling wants the last two dims ≡ (8·k, 128·k) or full-axis):
the ``[B, L]`` int32 columns are reshaped to ``[B, L/128, 128]`` so each
input block is one history with full row axes; the comparison tile puts
**value ids on sublanes** (``TILE_V = 128``) and the 128-row chunk on
lanes, so row reductions are lane reductions.  Grid = ``(B, V / TILE_V)``;
each program scans the history's ``L/128`` chunks with a ``fori_loop``.
Stat tiles land in an ``[B, 8, V]`` output (rows 6..7 padding) whose
``(8, TILE_V)`` block is exactly one native tile.

The packer guarantees ``L`` and ``V`` are multiples of 128
(``jepsen_tpu.history.encode.LANE``); padded rows carry ``mask=0`` and
``value=-1`` and fail every predicate.

``interpret=True`` (automatic off-TPU) runs the same kernel through the
Pallas interpreter, which is how the CPU test mesh exercises it.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from jepsen_tpu.history.encode import LANE, PackedHistories
from jepsen_tpu.history.ops import OpF, OpType

_INF = 2**31 - 1
TILE_V = 128  # value ids per program (sublane axis of the comparison tile)
_N_STATS = 8  # 6 used + 2 sublane-padding rows


@jax.tree_util.register_dataclass
@dataclass
class QueueStats:
    """Fused per-value stats, each ``[B, V]`` int32."""

    a: jax.Array  # enqueue invokes
    e: jax.Array  # enqueue oks
    x: jax.Array  # enqueue fails
    d: jax.Array  # ok reads
    s: jax.Array  # min enqueue-invoke position (INF if none)
    t: jax.Array  # min ok-read position (INF if none)


def _fused_kernel(f_ref, t_ref, v_ref, m_ref, out_ref):
    j = pl.program_id(1)
    n_chunks = f_ref.shape[1]
    col = (
        jax.lax.broadcasted_iota(jnp.int32, (TILE_V, LANE), 0) + j * TILE_V
    )  # value id per sublane row
    lane = jax.lax.broadcasted_iota(jnp.int32, (TILE_V, LANE), 1)

    def body(i, acc):
        a, e, x, d, s, t = acc
        sl = pl.ds(i, 1)
        fv = f_ref[0, sl, :]  # [1, 128] — broadcasts against [TILE_V, 128]
        tv = t_ref[0, sl, :]
        vv = v_ref[0, sl, :]
        mv = m_ref[0, sl, :]
        pos = lane + i * LANE  # global history position of each row

        live = (vv >= 0) & (mv != 0)
        is_enq = (fv == int(OpF.ENQUEUE)) & live
        is_read = (
            ((fv == int(OpF.DEQUEUE)) | (fv == int(OpF.DRAIN)))
            & live
            & (tv == int(OpType.OK))
        )
        enq_inv = is_enq & (tv == int(OpType.INVOKE))
        eq = vv == col  # [TILE_V, 128] comparison tile

        def cnt(sel):
            return jnp.sum((eq & sel).astype(jnp.int32), axis=1)

        def pmin(sel):
            return jnp.min(jnp.where(eq & sel, pos, _INF), axis=1)

        return (
            a + cnt(enq_inv),
            e + cnt(is_enq & (tv == int(OpType.OK))),
            x + cnt(is_enq & (tv == int(OpType.FAIL))),
            d + cnt(is_read),
            jnp.minimum(s, pmin(enq_inv)),
            jnp.minimum(t, pmin(is_read)),
        )

    zero = jnp.zeros((TILE_V,), jnp.int32)
    inf = jnp.full((TILE_V,), _INF, jnp.int32)
    a, e, x, d, s, t = jax.lax.fori_loop(
        0, n_chunks, body, (zero, zero, zero, zero, inf, inf)
    )
    out_ref[0, 0, :] = a
    out_ref[0, 1, :] = e
    out_ref[0, 2, :] = x
    out_ref[0, 3, :] = d
    out_ref[0, 4, :] = s
    out_ref[0, 5, :] = t
    out_ref[0, 6, :] = zero
    out_ref[0, 7, :] = zero


@functools.partial(jax.jit, static_argnames=("value_space", "interpret"))
def _fused_queue_stats(
    f, type_, value, mask_i32, value_space: int, interpret: bool
) -> QueueStats:
    B, L = f.shape
    if L % LANE:
        raise ValueError(f"L={L} must be a multiple of {LANE}")
    if value_space % TILE_V:
        raise ValueError(f"V={value_space} must be a multiple of {TILE_V}")
    Lr = L // LANE
    shape3 = (B, Lr, LANE)
    in_spec = pl.BlockSpec(
        (1, Lr, LANE), lambda b, j: (b, 0, 0), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        _fused_kernel,
        grid=(B, value_space // TILE_V),
        in_specs=[in_spec] * 4,
        out_specs=pl.BlockSpec(
            (1, _N_STATS, TILE_V),
            lambda b, j: (b, 0, j),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((B, _N_STATS, value_space), jnp.int32),
        interpret=interpret,
    )(
        f.reshape(shape3),
        type_.reshape(shape3),
        value.reshape(shape3),
        mask_i32.reshape(shape3),
    )
    return QueueStats(
        a=out[:, 0],
        e=out[:, 1],
        x=out[:, 2],
        d=out[:, 3],
        s=out[:, 4],
        t=out[:, 5],
    )


def fused_queue_stats(
    packed: PackedHistories, interpret: bool | None = None
) -> QueueStats:
    """One-pass fused stats for a packed batch.  ``interpret`` defaults to
    True off-TPU (Pallas interpreter) and False on TPU (Mosaic)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # The packed columns are narrow (i8/i16); this kernel's tiles are i32,
    # so the columns are widened up front — which costs an extra HBM pass
    # and is acceptable only because this path is the *differential twin*
    # of the XLA scatter path, not the hot path.  If it ever becomes
    # primary, widen per-tile inside the kernel (load narrow, cast in
    # VMEM) to keep the narrow-packing bandwidth win.
    return _fused_queue_stats(
        packed.f.astype(jnp.int32),
        packed.type.astype(jnp.int32),
        packed.value.astype(jnp.int32),
        packed.mask.astype(jnp.int32),
        packed.value_space,
        interpret,
    )
