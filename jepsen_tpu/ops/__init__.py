"""JAX kernels shared by the TPU checkers."""

from jepsen_tpu.ops.counts import (  # noqa: F401
    masked_value_counts,
    masked_value_reduce_min,
)
