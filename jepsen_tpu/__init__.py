"""jepsen_tpu — a TPU-native distributed-systems correctness-testing framework.

A brand-new framework with the capabilities of ``rabbitmq/jepsen`` (a Jepsen
test suite + the Jepsen framework surface it consumes): generator-driven
concurrent workloads against real RabbitMQ quorum-queue clusters, network
partition nemeses, an SSH control plane, per-run history recording — and a
history-analysis phase ("checkers") that is a JAX/XLA program running on TPU:
histories are packed into ``int32`` tensors, checked with ``jax.vmap`` across
histories, sharded across chips with ``jax.sharding`` meshes, anomaly counts
reduced with ``lax.psum``.

Layer map (mirrors SURVEY.md §1 for the reference):

- ``jepsen_tpu.history``   — op schema, JSONL/EDN store, int32 tensor packing
- ``jepsen_tpu.checkers``  — total-queue / linearizability / perf checkers,
  protocol + compose + cpu/tpu backend dispatch
- ``jepsen_tpu.ops``       — JAX kernels (masked scatter counts, scans, bitsets)
- ``jepsen_tpu.parallel``  — device mesh, shardings, shard_map'd checking
- ``jepsen_tpu.models``    — sequential data-type models for linearizability
- ``jepsen_tpu.generators``— generator algebra (mix, delay, phases, nemesis…)
- ``jepsen_tpu.client``    — queue client protocol + native C++ AMQP driver
- ``jepsen_tpu.control``   — SSH exec DSL, DB lifecycle, nemesis engine
- ``jepsen_tpu.cli``       — ``test`` / ``check`` / ``bench-check`` commands
"""

__version__ = "0.1.0"
