"""Device-mesh sharding for batched history checking."""

from jepsen_tpu.parallel.mesh import (  # noqa: F401
    HIST_AXIS,
    SEQ_AXIS,
    checker_mesh,
    reduced_verdict,
    shard_packed,
    sharded_check,
    sharded_elle,
    sharded_elle_mops,
    sharded_elle_mops_verdict,
    sharded_queue_lin,
    sharded_queue_verdict,
    sharded_stream_lin,
    sharded_stream_verdict,
    sharded_total_queue,
    sharded_wgl,
    sharded_wgl_pcomp,
)
