"""Sharded checking over a 2-D device mesh.

The checker plane is the part of the reference with *no* distributed story
(single-threaded, in-process — SURVEY.md §2.4); this module is its TPU-native
replacement.  Two mesh axes map the two scaling dimensions of history
checking:

- ``hist`` — data parallelism across histories.  Each history is checked
  independently (``jax.vmap``), so the batch axis shards with **zero**
  communication; this is the primary axis and rides ICI (and DCN across
  hosts via ``jax.distributed``).
- ``seq`` — sequence parallelism *within* a history, for long histories
  (the long-context analog, SURVEY.md §5).  The count-vector stage of each
  checker is linear in ops, so the op axis shards freely: every device
  scatters its op block into a full local ``[V]`` count vector, a
  ``lax.psum`` over ``seq`` combines them (one all-reduce of a few small
  int vectors — tiny on the wire), and the nonlinear classify stage runs on
  the combined counts, replicated over ``seq``.

This is the "pick a mesh, annotate shardings, let XLA insert collectives"
recipe: the only hand-placed collectives are the ``psum``/``pmin`` combines
in the ``shard_map`` bodies.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jepsen_tpu.checkers.queue_lin import (
    QueueLinTensors,
    QueueLinTensorsPacked,
    queue_lin_classify,
    queue_lin_count_vectors,
)
from jepsen_tpu.checkers.total_queue import (
    TotalQueueTensors,
    TotalQueueTensorsPacked,
    total_queue_classify,
    total_queue_count_vectors,
)
from jepsen_tpu.history.encode import PackedHistories

HIST_AXIS = "hist"
SEQ_AXIS = "seq"

try:  # jax ≥ 0.4.35 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


def checker_mesh(
    devices=None, seq: int = 1, hist: int | None = None
) -> Mesh:
    """A ``(hist, seq)`` mesh over ``devices`` (default: all devices).

    ``seq=1`` puts every device on the embarrassingly-parallel ``hist``
    axis — the right default until single histories outgrow one chip.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if hist is None:
        if n % seq:
            raise ValueError(f"{n} devices not divisible by seq={seq}")
        hist = n // seq
    from jax.experimental import mesh_utils

    arr = mesh_utils.create_device_mesh((hist, seq), devices=devices)
    return Mesh(arr, (HIST_AXIS, SEQ_AXIS))


def _row_spec() -> P:
    return P(HIST_AXIS, SEQ_AXIS)


def shard_packed(packed: PackedHistories, mesh: Mesh) -> PackedHistories:
    """Place a packed batch on the mesh: ``[B, L]`` → (hist, seq) sharded."""
    sh = NamedSharding(mesh, _row_spec())
    return jax.tree.map(lambda x: jax.device_put(x, sh), packed)


# ---------------------------------------------------------------------------
# shard_map'd checkers — jitted programs memoized per (mesh, value_space)
# so repeated batch checks hit the compile cache
# ---------------------------------------------------------------------------


def _vmap_counts(count_fn, value_space, *cols):
    return jax.vmap(lambda *row: count_fn(*row, value_space))(*cols)


@functools.lru_cache(maxsize=64)
def _total_queue_program(mesh: Mesh, value_space: int,
                         packed_out: bool = False):
    def body(f, ty, v, m):
        a, e, d = _vmap_counts(total_queue_count_vectors, value_space, f, ty, v, m)
        a, e, d = jax.lax.psum((a, e, d), SEQ_AXIS)
        return total_queue_classify(a, e, d, packed_out=packed_out)

    scalar, mask = P(HIST_AXIS), P(HIST_AXIS, None)
    if packed_out:
        out_specs = TotalQueueTensorsPacked(
            valid=scalar,
            attempt_count=scalar,
            acknowledged_count=scalar,
            ok_count=scalar,
            lost_count=scalar,
            unexpected_count=scalar,
            duplicated_count=scalar,
            recovered_count=scalar,
            lost=mask,
            unexpected=mask,
            duplicated=mask,
            recovered=mask,
            value_space=value_space,
        )
    else:
        out_specs = TotalQueueTensors(
            valid=scalar,
            attempt_count=scalar,
            acknowledged_count=scalar,
            ok_count=scalar,
            lost=mask,
            unexpected=mask,
            duplicated=mask,
            recovered=mask,
        )
    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(_row_spec(),) * 4, out_specs=out_specs
        )
    )


def sharded_total_queue(
    packed: PackedHistories, mesh: Mesh, packed_out: bool = False
) -> TotalQueueTensors:
    """total-queue over the mesh: local scatter → psum(seq) → classify."""
    fn = _total_queue_program(mesh, packed.value_space, packed_out)
    return fn(packed.f, packed.type, packed.value, packed.mask)


@functools.lru_cache(maxsize=64)
def _queue_lin_program(
    mesh: Mesh, value_space: int, exactly_once: bool = True,
    packed_out: bool = False,
):
    def body(f, ty, v, m):
        # global history position of each local row: shard offset + iota
        n_local = f.shape[-1]
        offset = jax.lax.axis_index(SEQ_AXIS).astype(jnp.int32) * n_local
        pos = jnp.broadcast_to(
            offset + jnp.arange(n_local, dtype=jnp.int32), f.shape
        )
        a, x, s, r, t = _vmap_counts(
            queue_lin_count_vectors, value_space, f, ty, v, pos, m
        )
        a, x, r = jax.lax.psum((a, x, r), SEQ_AXIS)
        s = jax.lax.pmin(s, SEQ_AXIS)
        t = jax.lax.pmin(t, SEQ_AXIS)
        return queue_lin_classify(a, x, s, r, t, exactly_once,
                                  packed_out=packed_out)

    scalar, mask = P(HIST_AXIS), P(HIST_AXIS, None)
    if packed_out:
        out_specs = QueueLinTensorsPacked(
            valid=scalar,
            duplicate=mask,
            phantom=mask,
            causality=mask,
            recovered=mask,
            read_value_count=scalar,
            value_space=value_space,
        )
    else:
        out_specs = QueueLinTensors(
            valid=scalar,
            duplicate=mask,
            phantom=mask,
            causality=mask,
            recovered=mask,
            read_value_count=scalar,
        )
    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(_row_spec(),) * 4, out_specs=out_specs
        )
    )


def sharded_queue_lin(
    packed: PackedHistories, mesh: Mesh, delivery: str = "exactly-once",
    packed_out: bool = False,
) -> QueueLinTensors:
    """queue linearizability over the mesh: psum counts, pmin positions."""
    fn = _queue_lin_program(
        mesh, packed.value_space, delivery == "exactly-once", packed_out
    )
    return fn(packed.f, packed.type, packed.value, packed.mask)


def sharded_check(
    packed: PackedHistories, mesh: Mesh, delivery: str = "exactly-once",
    packed_out: bool = False,
) -> tuple[TotalQueueTensors, QueueLinTensors]:
    """The full per-history verdict (both checkers) over the mesh.
    ``packed_out=True`` ships the per-value class masks as uint32
    presence bitplanes (the round-14 packed verdict buffers) — on a
    real mesh that is 8–32× less D2H gather traffic per batch."""
    return (
        sharded_total_queue(packed, mesh, packed_out),
        sharded_queue_lin(packed, mesh, delivery, packed_out),
    )


# ---------------------------------------------------------------------------
# Stream checker: hist × seq, like the queue family.  Phase A (segment
# reductions over the op axis) shards freely and combines with
# psum/pmin/pmax; phase B re-reads the rows against the *combined*
# per-value mins; the one structurally sequential piece — within-read-batch
# offset monotonicity between adjacent rows — needs exactly one row of
# state from the next shard, exchanged with a single ppermute.  The elle
# checker's per-history work is an MXU matmul closure, not a row scan: on
# seq meshes its adjacency matrices column-shard over `seq` and GSPMD
# partitions the matmuls (see sharded_elle below).
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def _reshard_fn(sh):
    return jax.jit(lambda v: v, out_shardings=sh)


def _global_put(x, mesh: Mesh, spec):
    """Place ``x`` under ``NamedSharding(mesh, spec)`` — multi-process
    safe.  A host-side ``device_put`` cannot retarget a global (non-
    fully-addressable) array: each process only holds its own shards.
    For those, a compiled identity with an output-sharding constraint
    does the move instead — the GSPMD partitioner lowers it to on-device
    collectives, which is exactly how the seq>1 global mesh re-shards an
    inferred adjacency's column axis across hosts."""
    sh = NamedSharding(mesh, spec)
    cur = getattr(x, "sharding", None)
    if cur is not None and not x.is_fully_addressable:
        if cur == sh:
            return x
        return _reshard_fn(sh)(x)
    return jax.device_put(x, sh)


def _hist_sharded(tree, mesh: Mesh):
    def put(x):
        spec = P(HIST_AXIS, *([None] * (x.ndim - 1)))
        return _global_put(x, mesh, spec)

    return jax.tree.map(put, tree)


@functools.lru_cache(maxsize=64)
def _stream_lin_program(mesh: Mesh, space: int, fail_definite: bool = True):
    from jepsen_tpu.checkers.stream_lin import (
        STREAM_COMBINE as _STREAM_COMBINE,
        _stream_classify,
        _stream_nonmono_local,
        _stream_phase_a,
        _stream_phase_b,
        _stream_row_masks,
    )

    n_seq = mesh.shape[SEQ_AXIS]

    def body(type_, f, value, offset, pos, mask, first, full_read):
        stats = jax.vmap(
            lambda t, ff, v, o, p, m: _stream_phase_a(t, ff, v, o, p, m, space)
        )(type_, f, value, offset, pos, mask)
        combined = {}
        for key, val in stats.items():
            kind = _STREAM_COMBINE[key]
            if kind == "sum":
                combined[key] = jax.lax.psum(val, SEQ_AXIS)
            elif kind == "min":
                combined[key] = jax.lax.pmin(val, SEQ_AXIS)
            else:
                combined[key] = jax.lax.pmax(val, SEQ_AXIS)

        s_at, e_at = jax.vmap(
            lambda t, ff, v, o, m, sv, ev: _stream_phase_b(
                t, ff, v, o, m, sv, ev, space
            )
        )(type_, f, value, offset, mask, combined["s_v"], combined["e_v"])
        s_at = jax.lax.pmax(s_at, SEQ_AXIS)
        e_at = jax.lax.pmin(e_at, SEQ_AXIS)

        nm = jax.vmap(_stream_nonmono_local)(
            type_, f, value, offset, mask, first
        )
        # the read-batch pair straddling the shard boundary: fetch the
        # next shard's first row (three scalars per history) and test it
        # against this shard's last row.  The right edge receives zeros
        # (is_read=False), which correctly disables the pair.
        _, is_read = jax.vmap(_stream_row_masks)(type_, f, value, offset, mask)
        perm = [(i + 1, i) for i in range(n_seq - 1)]
        recv_read, recv_first, recv_off = (
            jax.lax.ppermute(x, SEQ_AXIS, perm)
            for x in (is_read[:, 0], first[:, 0], offset[:, 0])
        )
        boundary = (
            is_read[:, -1] & recv_read & ~recv_first
            & (recv_off <= offset[:, -1])
        )
        nm = jax.lax.psum(nm + boundary.astype(jnp.int32), SEQ_AXIS)

        return jax.vmap(
            lambda st, sa, ea, n, fl: _stream_classify(
                st, sa, ea, n, fl, fail_definite
            )
        )(combined, s_at, e_at, nm, full_read)

    from jepsen_tpu.checkers.stream_lin import StreamLinTensors

    out_specs = StreamLinTensors(
        valid=P(HIST_AXIS),
        divergent=P(HIST_AXIS, None),
        duplicate=P(HIST_AXIS, None),
        phantom=P(HIST_AXIS, None),
        recovered=P(HIST_AXIS, None),
        reorder=P(HIST_AXIS, None),
        nonmonotonic_count=P(HIST_AXIS),
        lost=P(HIST_AXIS, None),
        attempt_count=P(HIST_AXIS),
        acknowledged_count=P(HIST_AXIS),
        read_value_count=P(HIST_AXIS),
    )
    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(_row_spec(),) * 7 + (P(HIST_AXIS),),
            out_specs=out_specs,
        )
    )


def shard_stream_batch(batch, mesh: Mesh):
    """Place a StreamBatch on the mesh, padding the op axis so it divides
    the ``seq`` shard count (pad rows are fully masked)."""
    from jepsen_tpu.checkers.stream_lin import StreamBatch

    n_seq = mesh.shape[SEQ_AXIS]
    L = batch.type.shape[-1]
    pad = (-L) % n_seq
    if pad:
        def padcol(x, fill):
            return jnp.pad(x, ((0, 0), (0, pad)), constant_values=fill)

        batch = StreamBatch(
            type=padcol(batch.type, 0),
            f=padcol(batch.f, 0),
            value=padcol(batch.value, -1),
            offset=padcol(batch.offset, -1),
            pos=padcol(batch.pos, 0),
            mask=padcol(batch.mask, False),
            first=padcol(batch.first, True),
            full_read=batch.full_read,
            space=batch.space,
        )
    rows = NamedSharding(mesh, _row_spec())
    per_hist = NamedSharding(mesh, P(HIST_AXIS))
    return StreamBatch(
        type=jax.device_put(batch.type, rows),
        f=jax.device_put(batch.f, rows),
        value=jax.device_put(batch.value, rows),
        offset=jax.device_put(batch.offset, rows),
        pos=jax.device_put(batch.pos, rows),
        mask=jax.device_put(batch.mask, rows),
        first=jax.device_put(batch.first, rows),
        full_read=jax.device_put(batch.full_read, per_hist),
        space=batch.space,
    )


def sharded_stream_lin(batch, mesh: Mesh, append_fail: str = "definite"):
    """Stream-log linearizability over the mesh.  ``seq=1`` meshes take
    the zero-communication data-parallel path; larger ``seq`` runs the
    seq-parallel program above (long histories shard across chips — the
    long-context lever, same shape as the queue family).  ``append_fail``
    scopes fail-typed-append forgiveness (see ``check_stream_lin_cpu``)."""
    if mesh.shape[SEQ_AXIS] == 1:
        from jepsen_tpu.checkers.stream_lin import stream_lin_tensor_check

        return stream_lin_tensor_check(
            _hist_sharded(batch, mesh), append_fail=append_fail
        )
    sharded = shard_stream_batch(batch, mesh)
    fn = _stream_lin_program(
        mesh, batch.space, fail_definite=append_fail == "definite"
    )
    return fn(
        sharded.type,
        sharded.f,
        sharded.value,
        sharded.offset,
        sharded.pos,
        sharded.mask,
        sharded.first,
        sharded.full_read,
    )


def sharded_wgl(batch, mesh: Mesh, model_key, capacity: int = 128):
    """General-model WGL frontier search over the mesh (the mutex/FIFO/
    CAS checker family): pure data parallelism — each history's search is
    an independent ``lax.scan``+``while_loop`` nest, so the batch axis
    shards over ``hist`` with zero communication and the ``seq`` axis
    replicates (a search frontier cannot split along the op axis; long
    mutex histories are short by construction — lock cycles, not load).
    Returns ``(linearizable[B], unknown[B])`` with the same semantics as
    ``wgl_tensor_check``: packing-time candidate truncation
    (``cand_overflow``) folds into *unknown*, never into a pass."""
    from jepsen_tpu.checkers.wgl import _wgl_program_cached

    prog = _wgl_program_cached(
        model_key, batch.n, capacity, int(batch.cands.shape[-1])
    )
    f, a0, a1, ret_op, cands = _hist_sharded(
        (batch.f, batch.a0, batch.a1, batch.ret_op, batch.cands), mesh
    )
    ok, ovf = prog(f, a0, a1, ret_op, cands)
    unknown = ovf | jnp.asarray(batch.cand_overflow)
    return ok & ~unknown, unknown


def sharded_wgl_pcomp(decomps, mesh: Mesh, capacity_cap: int | None = None):
    """P-compositional WGL over the mesh: the device batch axis is the
    SUB-HISTORY axis (``checkers/wgl_pcomp.py``), so a handful of
    histories still fans out into thousands of narrow frontiers that
    shard over ``hist`` with zero communication — the scaling unit is
    the class, not the history.  Buckets pad their sub axis to the
    mesh's hist extent (pad rows are empty sub-histories, trivially
    valid and never read back).  Returns per-HISTORY ``(ok, unknown,
    info)`` with the same semantics as ``pcomp_tensor_check``."""
    import dataclasses

    from jepsen_tpu.checkers.wgl_pcomp import (
        bucketize,
        finish_buckets,
        run_bucket,
    )
    from jepsen_tpu.obs import trace as obs_trace

    h = mesh.shape[HIST_AXIS]
    with obs_trace.span(
        "mesh.sharded_wgl_pcomp",
        args={"histories": len(decomps)} if obs_trace.is_enabled() else None,
    ):
        buckets = bucketize(
            decomps, capacity_cap=capacity_cap, pad_to=h, to_device=False
        )
        placed = []
        for b in buckets:
            if b.engine == "subset":
                # packed subset-lattice bucket: its staged arrays are
                # the op/candidate bitmasks, sharded over hist like any
                # other per-sub-history column
                enq, deq, ret_op, cands = _hist_sharded(
                    (b.batch.enq, b.batch.deq, b.batch.ret_op,
                     b.batch.cands),
                    mesh,
                )
                placed.append(
                    dataclasses.replace(
                        b,
                        batch=dataclasses.replace(
                            b.batch, enq=enq, deq=deq, ret_op=ret_op,
                            cands=cands
                        ),
                    )
                )
                continue
            f, a0, a1, ret_op, cands = _hist_sharded(
                (b.batch.f, b.batch.a0, b.batch.a1, b.batch.ret_op,
                 b.batch.cands),
                mesh,
            )
            placed.append(
                dataclasses.replace(
                    b,
                    batch=dataclasses.replace(
                        b.batch, f=f, a0=a0, a1=a1, ret_op=ret_op,
                        cands=cands
                    ),
                )
            )
        results = [run_bucket(b) for b in placed]
        return finish_buckets(
            decomps, placed, results, escalate=capacity_cap is None
        )


#: reasons already logged for dense-closure fallbacks (log once per
#: run/process; the counter keeps the cumulative tally for /metrics)
_dense_fallback_seen: set[str] = set()


def _note_dense_fallback(reason: str) -> None:
    """Account an honest dense fallback: bump the
    ``mesh.closure_dense_fallbacks`` counter on ``/metrics`` and log the
    reason the packed multi-chip path was refused — once per distinct
    reason per run, so a 10k-chunk campaign doesn't spam the log."""
    from jepsen_tpu.obs.metrics import REGISTRY

    REGISTRY.counter("mesh.closure_dense_fallbacks").inc()
    if reason not in _dense_fallback_seen:
        _dense_fallback_seen.add(reason)
        import logging

        logging.getLogger(__name__).warning(
            "elle seq-mesh closure falling back to DENSE: %s", reason
        )


def _packed_shard_refusal(batch, n_seq: int) -> str | None:
    """Why the packed multi-chip closure cannot lower for this batch on
    an ``n_seq``-way seq mesh, or ``None`` if it can.  The plane axis
    ``ceil(T/32)`` must split into whole uint32 words per device — a
    shard boundary inside a word would make the local ``pack_bits`` of a
    column block disagree with the global plane shard."""
    from jepsen_tpu.checkers.bitset import LANE_BITS

    T = int(batch.ww.shape[-1])
    if T % (LANE_BITS * n_seq):
        return (
            f"padded txn axis T={T} does not split into whole uint32 "
            f"plane words across seq={n_seq} (needs T % {LANE_BITS * n_seq}"
            " == 0); overflow buckets with odd pad widths take this path"
        )
    return None


@functools.lru_cache(maxsize=32)
def _elle_packed_sharded_program(mesh: Mesh, n_txns: int):
    """The packed multi-chip closure program: adjacency column blocks
    arrive dense ``[B/h, T, T/s]`` per device, pack to their plane
    shard locally (the refusal check guarantees the shard boundary sits
    on a word boundary, so local ``pack_bits`` IS the global column
    shard), and the warm-started three-graph closure chain runs with
    its ``ceil(T/32)`` plane axis sharded over ``seq`` — per squaring
    one ``all_gather`` of the packed left operand and a local blocked
    Four-Russians multiply, fixpoint by ``psum``'d change flags
    (``closure_on_cycle_packed_sharded``).  This is the composition the
    DENSE pin forbade: the 4.64× packed-representation win and the
    multi-chip column split now multiply instead of excluding each
    other."""
    from jepsen_tpu.checkers.bitset import (
        closure_on_cycle_packed_sharded,
        pack_bits,
    )
    from jepsen_tpu.checkers.elle import ElleTensors, n_squarings

    k = n_squarings(n_txns)

    def body(ww, wr, rw, txn_mask, host_bad):
        def one(a_ww, a_wr, a_rw, m):
            g0, g1c, g2 = closure_on_cycle_packed_sharded(
                pack_bits(a_ww > 0),
                pack_bits(a_wr > 0),
                pack_bits(a_rw > 0),
                k,
                SEQ_AXIS,
            )
            return g0 & m, g1c & m, g2 & m

        g0, g1c, g2 = jax.vmap(one)(ww, wr, rw, txn_mask)
        valid = ~(g0.any(-1) | g1c.any(-1) | g2.any(-1) | host_bad)
        return ElleTensors(valid=valid, g0=g0, g1c=g1c, g2=g2)

    col = P(HIST_AXIS, None, SEQ_AXIS)
    row = P(HIST_AXIS, None)
    out_specs = ElleTensors(valid=P(HIST_AXIS), g0=row, g1c=row, g2=row)
    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(col, col, col, row, P(HIST_AXIS)),
            out_specs=out_specs,
            check_rep=False,
        )
    )


def sharded_elle(batch, mesh: Mesh, closure: str | None = None):
    """Elle cycle search over the mesh.  Histories shard over ``hist``;
    when the mesh has a ``seq`` axis the ``[T, T]`` adjacency matrices
    additionally shard their column axis over it.  The default closure
    is the **packed multi-chip** program (see
    ``_elle_packed_sharded_program``): uint32 bitplanes column-sharded
    over ``seq`` with explicit ``all_gather``/``psum`` collectives —
    the Four-Russians representation win and the Megatron column split
    compose.  When the packed path is refused (plane axis not word-
    divisible) or a non-packed mode is forced, the bf16 MXU column-
    sharded GSPMD program runs instead; refusals are logged once per
    run and counted on ``/metrics``
    (``mesh.closure_dense_fallbacks``)."""
    import dataclasses

    from jepsen_tpu.checkers.elle import _resolve_closure, elle_tensor_check

    if mesh.shape[SEQ_AXIS] == 1:
        return elle_tensor_check(_hist_sharded(batch, mesh), closure=closure)

    n_seq = mesh.shape[SEQ_AXIS]
    if batch.n_txns % n_seq:
        raise ValueError(
            f"seq={n_seq} must divide n_txns="
            f"{batch.n_txns} (pack_txn_graphs pads to the lane width, "
            "so any power-of-two seq up to the lane size divides it)"
        )

    def put(x, spec):
        return _global_put(x, mesh, spec)

    mode = _resolve_closure(closure)
    if mode == "packed":
        refusal = _packed_shard_refusal(batch, n_seq)
        if refusal is None:
            fn = _elle_packed_sharded_program(mesh, batch.n_txns)
            return fn(
                put(batch.ww, P(HIST_AXIS, None, SEQ_AXIS)),
                put(batch.wr, P(HIST_AXIS, None, SEQ_AXIS)),
                put(batch.rw, P(HIST_AXIS, None, SEQ_AXIS)),
                put(batch.txn_mask, P(HIST_AXIS, None)),
                put(batch.host_bad, P(HIST_AXIS)),
            )
        _note_dense_fallback(refusal)

    sharded = dataclasses.replace(
        batch,
        ww=put(batch.ww, P(HIST_AXIS, None, SEQ_AXIS)),
        wr=put(batch.wr, P(HIST_AXIS, None, SEQ_AXIS)),
        rw=put(batch.rw, P(HIST_AXIS, None, SEQ_AXIS)),
        txn_mask=put(batch.txn_mask, P(HIST_AXIS, None)),
        host_bad=put(batch.host_bad, P(HIST_AXIS)),
    )
    return elle_tensor_check(
        sharded, closure="dense" if mode == "packed" else mode
    )


# ---------------------------------------------------------------------------
# Collective verdict reduction: the host receives ONE small verdict
# tensor per batch — invalid count (psum over hist) and the first
# invalid history's global batch index (pmin of a masked iota) — instead
# of gathering a [B] bool from every device.  On a real mesh this turns
# the per-batch D2H traffic from per-device gathers into two scalars.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=16)
def _verdict_reduce_program(mesh: Mesh):
    def body(valid, gidx):
        # valid: the local [B/h] hist-shard of the per-history verdict
        # (replicated over seq — every seq member computed the same
        # combined classify); gidx: the caller's per-history indices
        # (e.g. SOURCE-order ids under lane striping), so the reported
        # counterexample is the minimum over the caller's order, not
        # the batch layout's
        big = jnp.iinfo(jnp.int32).max
        n_bad = jax.lax.psum(
            jnp.sum(~valid).astype(jnp.int32), HIST_AXIS
        )
        first = jax.lax.pmin(
            jnp.min(jnp.where(valid, big, gidx), initial=big), HIST_AXIS
        )
        return n_bad, jnp.where(first == big, -1, first)

    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(HIST_AXIS), P(HIST_AXIS)),
            out_specs=(P(), P()),
            check_rep=False,
        )
    )


def reduced_verdict(valid, mesh: Mesh, gidx=None):
    """``(n_invalid, first_invalid)`` int32 device scalars from a
    ``[B]`` per-history bool verdict sharded over ``hist`` — the psum /
    index-pmin combine runs on device; ``first_invalid`` is ``-1`` when
    every history passed.  ``gidx`` (int32 ``[B]``, default iota) maps
    batch positions to caller indices — pad/sentinel positions should
    carry ``int32 max``.  ``B`` must divide by the mesh's hist extent
    (the pipeline's chunk padding guarantees it)."""
    import numpy as _np

    if gidx is None:
        gidx = _np.arange(valid.shape[0], dtype=_np.int32)
    return _verdict_reduce_program(mesh)(valid, gidx)


def sharded_queue_verdict(
    packed: PackedHistories,
    mesh: Mesh,
    delivery: str = "exactly-once",
    gidx=None,
):
    """Both queue sub-checkers over the mesh, reduced on device to the
    two-scalar batch verdict (pad histories are synthesized valid, so
    they can never surface as counterexamples)."""
    from jepsen_tpu.obs import trace as obs_trace

    with obs_trace.span("mesh.sharded_queue_verdict"):
        tq, ql = sharded_check(packed, mesh, delivery)
        return reduced_verdict(tq.valid & ql.valid, mesh, gidx)


def sharded_stream_verdict(
    batch, mesh: Mesh, append_fail: str = "definite", gidx=None
):
    from jepsen_tpu.obs import trace as obs_trace

    with obs_trace.span("mesh.sharded_stream_verdict"):
        sl = sharded_stream_lin(batch, mesh, append_fail=append_fail)
        return reduced_verdict(sl.valid, mesh, gidx)


def sharded_elle_mops_verdict(mops, mesh: Mesh, gidx=None):
    from jepsen_tpu.obs import trace as obs_trace

    with obs_trace.span("mesh.sharded_elle_mops_verdict"):
        el = sharded_elle_mops(mops, mesh)
        return reduced_verdict(el.valid, mesh, gidx)


def sharded_elle_mops(mops, mesh: Mesh):
    """Fused device-inference elle over the mesh (micro-op cell columns
    in, verdict tensors out — no host inference anywhere).  The
    inference stage is per-history scatter/sort work with no cross-
    history terms, so the ``[B, M]`` cell columns shard over ``hist``
    with zero communication; on ``seq>1`` meshes the inferred adjacency
    then re-shards its column axis over ``seq`` for the closure matmuls,
    exactly like ``sharded_elle``."""
    from jepsen_tpu.checkers.elle import (
        elle_infer_device,
        elle_mops_check,
        inferred_to_batch,
    )

    sharded = _hist_sharded(mops, mesh)
    if mesh.shape[SEQ_AXIS] == 1:
        return elle_mops_check(sharded)[0]
    inf = elle_infer_device(sharded)
    return sharded_elle(inferred_to_batch(inf, mops.n_txns), mesh)
