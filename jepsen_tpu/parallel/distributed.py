"""Multi-process checker plane: a real ``jax.distributed`` harness.

The reference's distributed story is SSH + AMQP only; its analysis phase
is single-threaded on one controller (SURVEY.md §2.4).  This module
scales the analysis plane across OS PROCESSES the JAX way: a launcher
(:func:`run_multiprocess_check`) spawns ``--procs N`` workers, process 0
hosts the ``jax.distributed`` coordination service, and every worker

1. joins the cluster (:func:`init_multihost` — process 0 is the
   coordinator),
2. takes its DETERMINISTIC file stripe (largest-first size ordering of
   the launcher-stat'ed manifest, striped round-robin — the same
   size-aware balancing rule as the in-process input lanes, so every
   process derives the identical assignment with no coordination),
3. runs the per-process bytes-to-verdict pipeline over its OWN local
   devices (``parallel/pipeline.py`` lanes + local mesh — computation
   never crosses the process boundary, which is what makes the same
   harness run on the CPU backend, where XLA has no cross-process
   programs, and on TPU pods, where the per-host pipelines feed the
   hosts' ICI domains),
4. publishes its verdicts through the coordination service's
   key-value store, where process 0 performs the final cross-process
   merge and emits one verdict set.

Fail-loud semantics match :class:`~jepsen_tpu.parallel.pipeline.
PipelineError`: a worker that dies (crash, kill, wedge) aborts the whole
run — the launcher kills the survivors and raises
:class:`DistributedCheckError` with NO partial verdicts, and the
coordinator's blocking KV reads are deadline-bounded so a silent wedge
cannot hang the merge forever.

Pod-style use (every host one process, one global mesh over ICI+DCN)
keeps the thin helpers below: ``init_multihost`` + ``global_checker_mesh``
run the ``parallel/mesh.py`` programs pod-wide unchanged.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from pathlib import Path

from jepsen_tpu.parallel.pipeline import PipelineError


class DistributedCheckError(PipelineError):
    """A worker process died or the merge timed out; no verdicts were
    emitted (the multi-process twin of the pipeline crash contract)."""


def init_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize ``jax.distributed`` for a multi-host checker fleet.

    All-``None`` arguments auto-detect (TPU pod metadata); no-op when
    already initialized so callers can run the same entrypoint single- and
    multi-host.
    """
    import jax

    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        if "already initialized" not in str(e):
            raise


def global_checker_mesh(seq: int = 1):
    """A ``(hist, seq)`` mesh over every device in the (possibly
    multi-host) runtime.  ``seq`` must divide the global device count; the
    ``seq`` axis is laid out innermost so it maps to intra-host ICI
    neighbors, keeping the per-history ``psum`` combines off DCN.

    NOTE: cross-process programs need a backend with multi-process
    execution (TPU/GPU).  The CPU backend cannot run them — that is what
    :func:`run_multiprocess_check`'s process-local pipelines are for."""
    import jax

    from jepsen_tpu.parallel.mesh import checker_mesh

    devices = jax.devices()
    if len(devices) % max(seq, 1) != 0:
        raise ValueError(
            f"seq={seq} must divide the global device count {len(devices)}"
        )
    return checker_mesh(devices, seq=seq)


def is_coordinator() -> bool:
    """True on the process that should write stores / print verdicts."""
    import jax

    return jax.process_index() == 0


# ---------------------------------------------------------------------------
# Deterministic file assignment: the same largest-first round-robin
# striping the input lanes use, over launcher-recorded sizes so every
# process computes the identical split with no coordination.
# ---------------------------------------------------------------------------


def assign_stripes(sizes: list[int], n_procs: int) -> list[list[int]]:
    """``n_procs`` lists of indices into the size list: indices sorted
    by size descending (ties by index — fully deterministic), striped
    round-robin, so every stripe holds a balanced byte mix."""
    order = sorted(range(len(sizes)), key=lambda i: (-sizes[i], i))
    return [order[p::n_procs] for p in range(n_procs)]


_KV_PREFIX = "jt/verdict"

#: env hook for the crash-contract test: the named process exits hard
#: mid-run (after joining the cluster, before any verdict is published)
_DIE_ENV = "JEPSEN_TPU_DIST_DIE_PID"


def _kv_client():
    from jax._src.distributed import global_state

    client = global_state.client
    if client is None:
        raise DistributedCheckError(
            "jax.distributed is not initialized; no coordination service"
        )
    return client


def worker_main(argv=None) -> int:
    """``python -m jepsen_tpu.parallel.distributed --worker ...`` —
    one checker process of the fleet.  The launcher provides the env
    (JAX_PLATFORMS / XLA_FLAGS device count) BEFORE the interpreter
    starts, so backend selection happens at import like any JAX
    program."""
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--worker", action="store_true", required=True)
    p.add_argument("--manifest", required=True)
    p.add_argument("--coordinator", required=True)
    p.add_argument("--process-id", type=int, required=True)
    p.add_argument("--num-processes", type=int, required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--merge-timeout-s", type=float, default=300.0)
    args = p.parse_args(argv)

    with open(args.manifest) as fh:
        man = json.load(fh)

    import jax

    init_multihost(
        args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    assert jax.process_count() == args.num_processes, jax.process_count()
    pid = args.process_id

    from jepsen_tpu.utils.jaxenv import enable_compilation_cache

    if man.get("cache_dir"):
        enable_compilation_cache(
            man["cache_dir"], backend=jax.default_backend()
        )

    if os.environ.get(_DIE_ENV) == str(pid):
        # crash-contract hook: die mid-run, after joining the cluster
        # and BEFORE publishing any verdict
        os._exit(42)

    from jepsen_tpu.parallel.pipeline import check_sources

    stripes = assign_stripes(man["sizes"], args.num_processes)
    # ascending manifest order: the reduce-mode first_invalid is the
    # minimum over the worker's LOCAL source order, so that order must
    # be monotone in manifest indices (the in-process lanes layer does
    # its own size balancing; assign_stripes already balanced bytes
    # across processes)
    mine = sorted(stripes[pid])
    my_paths = [man["paths"][i] for i in mine]

    opts = dict(man.get("opts") or {})
    if man.get("mesh"):
        from jepsen_tpu.parallel.mesh import checker_mesh

        # the PROCESS-LOCAL mesh: each process shards its batches over
        # its own devices; nothing crosses the process boundary
        opts["mesh"] = checker_mesh(jax.local_devices(), seq=1)
    reduce = bool(man.get("reduce"))
    t0 = time.perf_counter()
    results, stats = check_sources(
        man["workload"],
        my_paths,
        chunk=int(man.get("chunk") or 64),
        lanes=man.get("lanes"),
        reduce=reduce,
        **opts,
    )
    wall = time.perf_counter() - t0

    from jepsen_tpu.history.store import _json_default

    if reduce:
        # first_invalid is an index into MY stripe; lift to the global
        # manifest index before the merge
        fi = results.get("first_invalid", -1)
        results = dict(results)
        results["first_invalid"] = mine[fi] if 0 <= fi < len(mine) else -1
    payload = json.dumps(
        {
            "pid": pid,
            "indices": mine,
            "results": results,
            "stats": {
                "wall_s": stats.wall_s,
                "histories": stats.histories,
                "lanes": stats.lanes,
                "dropped": stats.dropped,
                "batches": stats.batches,
                "device_idle_frac": stats.device_idle_frac,
            },
        },
        default=_json_default,
    )
    client = _kv_client()
    client.key_value_set(f"{_KV_PREFIX}/{pid}", payload)

    if pid == 0:
        # the final cross-process verdict merge, on the coordinator:
        # deadline-bounded KV reads — a dead worker surfaces as a
        # timeout here (and as a non-zero exit at the launcher)
        shards = []
        for q in range(args.num_processes):
            raw = client.blocking_key_value_get(
                f"{_KV_PREFIX}/{q}", int(args.merge_timeout_s * 1000)
            )
            shards.append(json.loads(raw))
        merged = _merge_shards(man, shards, reduce)
        tmp = f"{args.out}.{os.getpid()}.tmp"
        with open(tmp, "w") as fh:
            json.dump(merged, fh)
        os.replace(tmp, args.out)
    print(
        json.dumps(
            {"pid": pid, "checked": len(my_paths), "wall_s": round(wall, 3)}
        ),
        flush=True,
    )
    return 0


def _merge_shards(man: dict, shards: list[dict], reduce: bool) -> dict:
    """Assemble the per-process verdict shards into one verdict set in
    ORIGINAL manifest order (plus the launcher-dropped entries)."""
    per_proc = [
        {
            "pid": s["pid"],
            "checked": len(s["indices"]),
            **{k: s["stats"][k] for k in ("wall_s", "lanes", "dropped")},
        }
        for s in shards
    ]
    if reduce:
        merged = {"histories": 0, "invalid": 0, "first_invalid": -1,
                  "dropped": 0}
        for s in shards:
            r = s["results"]
            merged["histories"] += r["histories"]
            merged["invalid"] += r["invalid"]
            merged["dropped"] += r.get("dropped", 0)
            g = r.get("first_invalid", -1)
            if g >= 0 and (
                merged["first_invalid"] < 0 or g < merged["first_invalid"]
            ):
                merged["first_invalid"] = g
        return {"reduce": True, "verdict": merged, "per_process": per_proc}
    out: list = [None] * len(man["paths"])
    for s in shards:
        for i, r in zip(s["indices"], s["results"]):
            out[i] = r
    return {"reduce": False, "results": out, "per_process": per_proc}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_multiprocess_check(
    workload: str,
    paths,
    n_procs: int,
    *,
    devices_per_proc: int = 1,
    chunk: int = 64,
    lanes: int | None = 0,
    mesh: bool = False,
    reduce: bool = False,
    timeout_s: float = 900.0,
    cache_dir: str | None = None,
    platform: str | None = None,
    **opts,
) -> tuple[list | dict, dict]:
    """The multi-process bytes-to-verdict launcher (CLI ``check --procs``).

    Spawns ``n_procs`` worker processes joined through
    ``jax.distributed`` (worker 0 hosts the coordination service),
    assigns every history file to exactly one worker by the
    deterministic size-striped rule, runs the per-process pipelines,
    and returns the coordinator's merged verdicts:

    - ``reduce=False`` → ``(results, info)`` with one JSON-normalized
      result dict per path, in order (launcher-dropped unreadable /
      zero-length files carry explicit ``unknown`` entries);
    - ``reduce=True`` → ``(verdict, info)`` with the collectively
      reduced ``{"histories", "invalid", "first_invalid"}`` scalars.

    A dead worker (non-zero exit, kill, timeout) aborts the whole run
    with :class:`DistributedCheckError` and NO partial verdicts."""
    import tempfile

    from jepsen_tpu.parallel.pipeline import _lane_census

    paths = [str(p) for p in paths]
    if n_procs < 1:
        raise ValueError(f"n_procs must be >= 1, got {n_procs}")
    # launcher census: sizes feed the deterministic assignment, so they
    # are stat'ed ONCE here and recorded in the manifest (workers must
    # never re-stat — a file changing size mid-launch would desync the
    # stripes); unreadable/zero-length files are dropped loudly — the
    # SAME census the in-process lanes run (one policy, one code path)
    kept, sizes, dropped = _lane_census(paths, workload)

    port = _free_port()
    with tempfile.TemporaryDirectory(prefix="jt_dist_") as td:
        manifest = {
            "workload": workload,
            "paths": [paths[i] for i in kept],
            "sizes": sizes,
            "chunk": chunk,
            "lanes": lanes,
            "mesh": mesh,
            "reduce": reduce,
            "cache_dir": cache_dir,
            "opts": opts,
        }
        mpath = os.path.join(td, "manifest.json")
        with open(mpath, "w") as fh:
            json.dump(manifest, fh)
        out_path = os.path.join(td, "merged.json")

        env = os.environ.copy()
        env["JAX_PLATFORMS"] = platform or "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={devices_per_proc}"
        )
        repo = str(Path(__file__).resolve().parent.parent.parent)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        logs = [os.path.join(td, f"worker{pid}.log") for pid in range(n_procs)]
        procs = []
        for pid in range(n_procs):
            # worker output goes to files, not pipes: a chatty worker
            # must never block on a full pipe while the launcher polls
            lf = open(logs[pid], "w")
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, "-m",
                        "jepsen_tpu.parallel.distributed",
                        "--worker",
                        "--manifest", mpath,
                        "--coordinator", f"localhost:{port}",
                        "--process-id", str(pid),
                        "--num-processes", str(n_procs),
                        "--out", out_path,
                        "--merge-timeout-s", str(min(timeout_s, 300.0)),
                    ],
                    stdout=lf,
                    stderr=subprocess.STDOUT,
                    cwd=repo,
                    env=env,
                )
            )
            lf.close()
        deadline = time.monotonic() + timeout_s
        failed: tuple[int, int | None] | None = None
        pending = set(range(n_procs))
        try:
            # poll loop: the moment ANY worker dies non-zero, the run
            # aborts — the survivors are killed rather than left to
            # grind toward a merge that can never complete
            while pending and failed is None:
                for pid in sorted(pending):
                    rc = procs[pid].poll()
                    if rc is None:
                        continue
                    pending.discard(pid)
                    if rc != 0:
                        failed = (pid, rc)
                        break
                if pending and failed is None:
                    if time.monotonic() > deadline:
                        failed = (min(pending), None)
                        break
                    time.sleep(0.05)
        finally:
            for pr in procs:
                if pr.poll() is None:
                    pr.kill()
            for pr in procs:
                if pr.poll() is None:
                    try:
                        pr.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        pass
        if failed is not None:
            pid, rc = failed
            try:
                with open(logs[pid]) as fh:
                    tail = fh.read()[-1500:]
            except OSError:
                tail = "<no worker log>"
            raise DistributedCheckError(
                f"worker {pid} of {n_procs} "
                f"{'timed out' if rc is None else f'died (rc={rc})'} — "
                f"aborting with no partial verdicts:\n{tail}"
            )
        try:
            with open(out_path) as fh:
                merged = json.load(fh)
        except (OSError, ValueError) as e:
            raise DistributedCheckError(
                f"coordinator produced no merged verdict file: {e}"
            )
    info = {
        "n_procs": n_procs,
        "devices_per_proc": devices_per_proc,
        "dropped": len(dropped),
        "per_process": merged["per_process"],
    }
    if reduce:
        verdict = merged["verdict"]
        verdict["dropped"] += len(dropped)
        # lift kept-space counterexample index to original path space
        if verdict["first_invalid"] >= 0:
            verdict["first_invalid"] = kept[verdict["first_invalid"]]
        return verdict, info
    results: list = [None] * len(paths)
    for j, i in enumerate(kept):
        results[i] = merged["results"][j]
    from jepsen_tpu.parallel.pipeline import _dropped_result

    for i, reason in dropped.items():
        results[i] = _dropped_result(workload, reason)
    return results, info


if __name__ == "__main__":
    sys.exit(worker_main())
