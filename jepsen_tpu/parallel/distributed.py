"""Multi-host checking: ``jax.distributed`` over DCN.

The reference's distributed story is SSH + AMQP only; its analysis phase is
single-threaded on one controller (SURVEY.md §2.4).  The TPU build scales
the analysis plane the JAX way: every host in a pod slice calls
``init_multihost`` (process 0 is the coordinator), after which
``jax.devices()`` spans the whole pod and the same ``checker_mesh`` /
``sharded_check`` programs from ``jepsen_tpu.parallel.mesh`` run
pod-wide — the ``hist`` axis shards across hosts over DCN (zero
cross-history communication, so DCN bandwidth doesn't matter) and the
``seq`` axis stays within a host's ICI domain.

Single-host (or single-process) use needs no initialization at all; these
helpers are deliberately thin so the mesh-program code has exactly one code
path for 1 chip, 8 chips, or a pod.
"""

from __future__ import annotations

import jax

from jepsen_tpu.parallel.mesh import checker_mesh


def init_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize ``jax.distributed`` for a multi-host checker fleet.

    All-``None`` arguments auto-detect (TPU pod metadata); no-op when
    already initialized so callers can run the same entrypoint single- and
    multi-host.
    """
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        if "already initialized" not in str(e):
            raise


def global_checker_mesh(seq: int = 1):
    """A ``(hist, seq)`` mesh over every device in the (possibly
    multi-host) runtime.  ``seq`` must divide the global device count; the
    ``seq`` axis is laid out innermost so it maps to intra-host ICI
    neighbors, keeping the per-history ``psum`` combines off DCN."""
    devices = jax.devices()
    if len(devices) % max(seq, 1) != 0:
        raise ValueError(
            f"seq={seq} must divide the global device count {len(devices)}"
        )
    return checker_mesh(devices, seq=seq)


def is_coordinator() -> bool:
    """True on the process that should write stores / print verdicts."""
    return jax.process_index() == 0
