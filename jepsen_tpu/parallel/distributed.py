"""Multi-process checker plane: a real ``jax.distributed`` harness.

The reference's distributed story is SSH + AMQP only; its analysis phase
is single-threaded on one controller (SURVEY.md §2.4).  This module
scales the analysis plane across OS PROCESSES the JAX way: a launcher
(:func:`run_multiprocess_check`) spawns ``--procs N`` workers, process 0
hosts the ``jax.distributed`` coordination service, and every worker

1. joins the cluster (:func:`init_multihost` — process 0 is the
   coordinator),
2. takes its DETERMINISTIC file stripe (largest-first size ordering of
   the launcher-stat'ed manifest, striped round-robin — the same
   size-aware balancing rule as the in-process input lanes, so every
   process derives the identical assignment with no coordination),
3. runs the per-process bytes-to-verdict pipeline over its OWN local
   devices (``parallel/pipeline.py`` lanes + local mesh — computation
   never crosses the process boundary, which is what makes the same
   harness run on the CPU backend, where XLA has no cross-process
   programs, and on TPU pods, where the per-host pipelines feed the
   hosts' ICI domains),
4. publishes its verdicts through the coordination service's
   key-value store, where process 0 performs the final cross-process
   merge and emits one verdict set.

Failure semantics are ELASTIC by default (PR 13, ROADMAP direction 2's
resilience half): the launcher's liveness poll no longer kills the
survivors when a worker dies.  Work moves through a spool-directory
task protocol — one task per ``assign_stripes`` stripe, claimed by
atomic rename, results written atomically per stripe — so a
dead/wedged worker's stripes RE-QUEUE onto the survivors with bounded
retry + exponential backoff, a per-stripe deadline SIGKILLs a wedged
(e.g. SIGSTOPped) claim-holder so its stripes recirculate too (an
ACTIVE worker heartbeats its claim's mtime, so the deadline measures
wedge, never honest long work), and a
stripe whose retries exhaust is QUARANTINED: its histories report
``unknown`` with the worker's death evidence while every other verdict
survives.  The merged verdict carries machine-readable ``degraded``
provenance (dead workers, requeued stripes, retry counts, reduced
worker count) instead of dying.  Elastic workers do NOT join
``jax.distributed`` — computation never crosses the process boundary,
and coupling worker liveness through the coordination service is
exactly what made the old contract kill-everything.

``fail_fast=True`` (CLI ``check --procs --fail-fast``) preserves the
PR-5 contract verbatim: ``jax.distributed`` join, KV-store merge on
process 0, and a worker that dies (crash, kill, wedge) aborts the
whole run — the launcher kills the survivors and raises
:class:`DistributedCheckError` with NO partial verdicts; the
coordinator's blocking KV reads stay deadline-bounded so a silent
wedge cannot hang the merge forever.

Pod-style use (every host one process, one global mesh over ICI+DCN)
keeps the thin helpers below: ``init_multihost`` + ``global_checker_mesh``
run the ``parallel/mesh.py`` programs pod-wide unchanged.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from pathlib import Path

from jepsen_tpu.parallel.pipeline import PipelineError


class DistributedCheckError(PipelineError):
    """A worker process died or the merge timed out; no verdicts were
    emitted (the multi-process twin of the pipeline crash contract)."""


def init_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize ``jax.distributed`` for a multi-host checker fleet.

    All-``None`` arguments auto-detect (TPU pod metadata); no-op when
    already initialized so callers can run the same entrypoint single- and
    multi-host.
    """
    import jax

    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        if "already initialized" not in str(e):
            raise


def global_checker_mesh(seq: int = 1):
    """A ``(hist, seq)`` mesh over every device in the (possibly
    multi-host) runtime.  ``seq`` must divide the global device count; the
    ``seq`` axis is laid out innermost so it maps to intra-host ICI
    neighbors, keeping the per-history ``psum`` combines off DCN.

    NOTE: cross-process programs need a backend with multi-process
    execution (TPU/GPU).  The CPU backend cannot run them — that is what
    :func:`run_multiprocess_check`'s process-local pipelines are for."""
    import jax

    from jepsen_tpu.parallel.mesh import checker_mesh

    devices = jax.devices()
    if len(devices) % max(seq, 1) != 0:
        raise ValueError(
            f"seq={seq} must divide the global device count {len(devices)}"
        )
    return checker_mesh(devices, seq=seq)


def is_coordinator() -> bool:
    """True on the process that should write stores / print verdicts."""
    import jax

    return jax.process_index() == 0


# ---------------------------------------------------------------------------
# Deterministic file assignment: the same largest-first round-robin
# striping the input lanes use, over launcher-recorded sizes so every
# process computes the identical split with no coordination.
# ---------------------------------------------------------------------------


def assign_stripes(sizes: list[int], n_procs: int) -> list[list[int]]:
    """``n_procs`` lists of indices into the size list: indices sorted
    by size descending (ties by index — fully deterministic), striped
    round-robin, so every stripe holds a balanced byte mix."""
    order = sorted(range(len(sizes)), key=lambda i: (-sizes[i], i))
    return [order[p::n_procs] for p in range(n_procs)]


_KV_PREFIX = "jt/verdict"

#: env hook for the crash-contract tests: the named process(es,
#: comma-separated) exit hard mid-run — fail-fast workers before any
#: verdict is published; elastic workers right AFTER claiming their
#: first stripe, so the requeue path is what gets exercised
_DIE_ENV = "JEPSEN_TPU_DIST_DIE_PID"

#: env hook for the stripe-deadline tests: the named elastic worker(s)
#: wedge (sleep forever) after claiming — the SIGSTOP shape
_WEDGE_ENV = "JEPSEN_TPU_DIST_WEDGE_PID"


def _hook_hit(env_name: str, pid: int) -> bool:
    raw = os.environ.get(env_name)
    if not raw:
        return False
    return str(pid) in [p.strip() for p in raw.split(",")]


def _kv_client():
    from jax._src.distributed import global_state

    client = global_state.client
    if client is None:
        raise DistributedCheckError(
            "jax.distributed is not initialized; no coordination service"
        )
    return client


def worker_main(argv=None) -> int:
    """``python -m jepsen_tpu.parallel.distributed --worker ...`` —
    one checker process of the fleet.  The launcher provides the env
    (JAX_PLATFORMS / XLA_FLAGS device count) BEFORE the interpreter
    starts, so backend selection happens at import like any JAX
    program."""
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--worker", action="store_true", required=True)
    p.add_argument("--manifest", required=True)
    p.add_argument("--elastic", action="store_true")
    p.add_argument("--global-mesh", action="store_true")
    p.add_argument("--spool")
    p.add_argument("--coordinator")
    p.add_argument("--process-id", type=int, required=True)
    p.add_argument("--num-processes", type=int, required=True)
    p.add_argument("--out")
    p.add_argument("--merge-timeout-s", type=float, default=300.0)
    args = p.parse_args(argv)
    if args.global_mesh:
        if not (args.spool and args.coordinator):
            p.error("--spool and --coordinator are required with "
                    "--global-mesh")
    elif args.elastic:
        if not args.spool:
            p.error("--spool is required with --elastic")
    elif not (args.coordinator and args.out):
        # the fail-fast worker joins jax.distributed and merges to a
        # file — both flags are load-bearing there (the elastic worker
        # needs neither, which is why they can't be required=True)
        p.error("--coordinator and --out are required without --elastic")

    with open(args.manifest) as fh:
        man = json.load(fh)

    if args.global_mesh:
        return _global_mesh_worker(args, man)
    if args.elastic:
        return _elastic_worker(args, man)

    import jax

    init_multihost(
        args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    assert jax.process_count() == args.num_processes, jax.process_count()
    pid = args.process_id

    from jepsen_tpu.utils.jaxenv import enable_compilation_cache

    if man.get("cache_dir"):
        enable_compilation_cache(
            man["cache_dir"], backend=jax.default_backend()
        )

    if _hook_hit(_DIE_ENV, pid):
        # crash-contract hook: die mid-run, after joining the cluster
        # and BEFORE publishing any verdict
        os._exit(42)

    from jepsen_tpu.parallel.pipeline import check_sources

    stripes = assign_stripes(man["sizes"], args.num_processes)
    # ascending manifest order: the reduce-mode first_invalid is the
    # minimum over the worker's LOCAL source order, so that order must
    # be monotone in manifest indices (the in-process lanes layer does
    # its own size balancing; assign_stripes already balanced bytes
    # across processes)
    mine = sorted(stripes[pid])
    my_paths = [man["paths"][i] for i in mine]

    opts = dict(man.get("opts") or {})
    if man.get("mesh"):
        from jepsen_tpu.parallel.mesh import checker_mesh

        # the PROCESS-LOCAL mesh: each process shards its batches over
        # its own devices; nothing crosses the process boundary
        opts["mesh"] = checker_mesh(jax.local_devices(), seq=1)
    reduce = bool(man.get("reduce"))
    t0 = time.perf_counter()
    # fail_fast=True: the fail-fast worker preserves the PR-5 contract
    # verbatim — any pipeline crash kills this process, which the
    # launcher turns into the abort-all DistributedCheckError
    results, stats = check_sources(
        man["workload"],
        my_paths,
        chunk=int(man.get("chunk") or 64),
        lanes=man.get("lanes"),
        reduce=reduce,
        fail_fast=True,
        **opts,
    )
    wall = time.perf_counter() - t0

    from jepsen_tpu.history.store import _json_default

    if reduce:
        # first_invalid is an index into MY stripe; lift to the global
        # manifest index before the merge
        fi = results.get("first_invalid", -1)
        results = dict(results)
        results["first_invalid"] = mine[fi] if 0 <= fi < len(mine) else -1
    payload = json.dumps(
        {
            "pid": pid,
            "indices": mine,
            "results": results,
            "stats": {
                "wall_s": stats.wall_s,
                "histories": stats.histories,
                "lanes": stats.lanes,
                "dropped": stats.dropped,
                "batches": stats.batches,
                "device_idle_frac": stats.device_idle_frac,
            },
        },
        default=_json_default,
    )
    client = _kv_client()
    client.key_value_set(f"{_KV_PREFIX}/{pid}", payload)

    if pid == 0:
        # the final cross-process verdict merge, on the coordinator:
        # deadline-bounded KV reads — a dead worker surfaces as a
        # timeout here (and as a non-zero exit at the launcher)
        shards = []
        for q in range(args.num_processes):
            raw = client.blocking_key_value_get(
                f"{_KV_PREFIX}/{q}", int(args.merge_timeout_s * 1000)
            )
            shards.append(json.loads(raw))
        merged = _merge_shards(man, shards, reduce)
        tmp = f"{args.out}.{os.getpid()}.tmp"
        with open(tmp, "w") as fh:
            json.dump(merged, fh)
        os.replace(tmp, args.out)
    print(
        json.dumps(
            {"pid": pid, "checked": len(my_paths), "wall_s": round(wall, 3)}
        ),
        flush=True,
    )
    return 0


def _merge_shards(man: dict, shards: list[dict], reduce: bool) -> dict:
    """Assemble the per-process verdict shards into one verdict set in
    ORIGINAL manifest order (plus the launcher-dropped entries)."""
    per_proc = [
        {
            "pid": s["pid"],
            "checked": len(s["indices"]),
            **{k: s["stats"][k] for k in ("wall_s", "lanes", "dropped")},
        }
        for s in shards
    ]
    if reduce:
        # "quarantined" is always 0 here — fail-fast workers abort the
        # whole run rather than quarantine — but the key stays so the
        # reduced-verdict schema is identical across both modes
        merged = {"histories": 0, "invalid": 0, "first_invalid": -1,
                  "dropped": 0, "quarantined": 0}
        for s in shards:
            r = s["results"]
            merged["histories"] += r["histories"]
            merged["invalid"] += r["invalid"]
            merged["dropped"] += r.get("dropped", 0)
            g = r.get("first_invalid", -1)
            if g >= 0 and (
                merged["first_invalid"] < 0 or g < merged["first_invalid"]
            ):
                merged["first_invalid"] = g
        return {"reduce": True, "verdict": merged, "per_process": per_proc}
    out: list = [None] * len(man["paths"])
    for s in shards:
        for i, r in zip(s["indices"], s["results"]):
            out[i] = r
    return {"reduce": False, "results": out, "per_process": per_proc}


# ---------------------------------------------------------------------------
# Elastic mode: spool-directory task protocol.  The launcher writes one
# task file per assign_stripes stripe; workers claim by atomic rename
# (tasks/t{k}.json -> claims/t{k}.json.p{pid}), write their verdict
# shard atomically (results/r{k}.json), and poll until the launcher's
# `done` sentinel.  A worker that dies mid-claim leaves its claim file
# behind — the launcher's liveness poll requeues it onto the survivors.
# ---------------------------------------------------------------------------


def _write_json_atomic(path: Path, doc: dict) -> None:
    tmp = path.with_name(path.name + f".{os.getpid()}.tmp")
    from jepsen_tpu.history.store import _json_default

    tmp.write_text(json.dumps(doc, default=_json_default))
    os.replace(tmp, path)


def _claim_task(tasks: Path, claims: Path, pid: int):
    """Claim one task by atomic rename, preferring this worker's OWN
    deterministic stripe (task id == process id initially) before
    stealing.  Tasks under a requeue backoff (``not_before``) are
    skipped.  Returns ``(task_dict, claim_path)`` or None."""
    now = time.time()
    pref = tasks / f"t{pid}.json"
    cands = [pref] if pref.exists() else []
    cands += [p for p in sorted(tasks.glob("t*.json")) if p != pref]
    for tf in cands:
        try:
            task = json.loads(tf.read_text())
        except (OSError, ValueError):
            continue  # lost a race with another claimer / mid-write
        if float(task.get("not_before", 0.0)) > now:
            continue  # backoff window: leave it for a later scan
        dst = claims / f"{tf.name}.p{pid}"
        try:
            # fresh mtime BEFORE the rename: os.rename preserves the
            # task file's write time, and the launcher's deadline scan
            # must never catch a just-claimed stripe wearing a stale
            # timestamp (it would SIGKILL a healthy holder)
            os.utime(tf)
            os.rename(tf, dst)
        except OSError:
            continue  # lost the claim race
        os.utime(dst)  # claim time — the launcher's stripe deadline
        return task, dst
    return None


def _claim_heartbeat(claim_path: Path, stop, period: float = 2.0) -> None:
    """Refresh the claim file's mtime while the stripe is actively
    being checked: the launcher's per-stripe deadline measures WEDGE
    (a SIGSTOP freezes every thread, heartbeat included — the mtime
    goes stale), never honest long work (a 10k-history stripe can
    legitimately outlive any fixed deadline).  A vanished claim file
    (requeued from under us after a presumed death) ends the beat."""
    while not stop.wait(period):
        try:
            os.utime(claim_path)
        except OSError:
            return


def _elastic_worker(args, man: dict) -> int:
    """One elastic checker process: claim stripes off the spool, run
    the per-process (elastic) pipeline over each, publish verdict
    shards as files.  No ``jax.distributed`` join — nothing crosses
    the process boundary, and nothing couples this worker's liveness
    to its siblings'."""
    import threading

    import jax

    pid = args.process_id
    spool = Path(args.spool)
    tasks, claims, resdir = (
        spool / "tasks", spool / "claims", spool / "results",
    )
    done_f = spool / "done"

    from jepsen_tpu.utils.jaxenv import enable_compilation_cache

    if man.get("cache_dir"):
        enable_compilation_cache(
            man["cache_dir"], backend=jax.default_backend()
        )
    opts = dict(man.get("opts") or {})
    if man.get("mesh"):
        from jepsen_tpu.parallel.mesh import checker_mesh

        # the PROCESS-LOCAL mesh, exactly as in fail-fast mode
        opts["mesh"] = checker_mesh(jax.local_devices(), seq=1)
    reduce = bool(man.get("reduce"))

    from jepsen_tpu.parallel.pipeline import check_sources

    checked = 0
    # the spawning launcher IS this process's parent; orphaning shows
    # as a reparent AWAY from it (to init or a subreaper) — comparing
    # against the recorded pid instead of literal 1 keeps the check
    # honest when the launcher itself runs as PID 1 (container
    # entrypoint)
    launcher_pid = os.getppid()
    while not done_f.exists():
        if os.getppid() != launcher_pid:
            return 3  # orphaned: the launcher is gone; don't linger
        got = _claim_task(tasks, claims, pid)
        if got is None:
            time.sleep(0.05)
            continue
        task, claim_path = got
        if _hook_hit(_DIE_ENV, pid):
            # crash hook: die AFTER claiming, BEFORE any result — the
            # launcher must requeue this stripe onto a survivor
            os._exit(42)
        if _hook_hit(_WEDGE_ENV, pid):
            # wedge hook BEFORE the heartbeat starts: a real SIGSTOP
            # freezes the beat thread too, and this hook must look the
            # same to the launcher's stripe deadline
            time.sleep(3600)
        k = int(task["task"])
        mine = sorted(task["indices"])
        my_paths = [man["paths"][i] for i in mine]
        hb_stop = threading.Event()
        hb = threading.Thread(
            target=_claim_heartbeat,
            args=(claim_path, hb_stop),
            name="claim-heartbeat",
            daemon=True,
        )
        hb.start()
        t0 = time.perf_counter()
        try:
            results, stats = check_sources(
                man["workload"],
                my_paths,
                chunk=int(man.get("chunk") or 64),
                lanes=man.get("lanes"),
                reduce=reduce,
                fail_fast=False,
                **opts,
            )
        finally:
            hb_stop.set()
        wall = time.perf_counter() - t0
        if reduce:
            # first_invalid is an index into THIS stripe; lift to the
            # global manifest index before the merge
            fi = results.get("first_invalid", -1)
            results = dict(results)
            results["first_invalid"] = (
                mine[fi] if 0 <= fi < len(mine) else -1
            )
        _write_json_atomic(
            resdir / f"r{k}.json",
            {
                "task": k,
                "pid": pid,
                "retries": int(task.get("retries", 0)),
                "indices": mine,
                "results": results,
                "stats": {
                    "wall_s": wall,
                    "histories": stats.histories,
                    "lanes": stats.lanes,
                    "dropped": stats.dropped,
                    "batches": stats.batches,
                    "quarantined": stats.quarantined,
                    "unit_retries": stats.unit_retries,
                    "device_idle_frac": stats.device_idle_frac,
                },
            },
        )
        claim_path.unlink(missing_ok=True)
        checked += len(mine)
    print(json.dumps({"pid": pid, "checked": checked}), flush=True)
    return 0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_multiprocess_check(
    workload: str,
    paths,
    n_procs: int,
    *,
    devices_per_proc: int = 1,
    chunk: int = 64,
    lanes: int | None = 0,
    mesh: bool = False,
    reduce: bool = False,
    timeout_s: float = 900.0,
    cache_dir: str | None = None,
    platform: str | None = None,
    fail_fast: bool = False,
    stripe_timeout_s: float | None = None,
    max_stripe_retries: int = 2,
    global_mesh: bool = False,
    seq: int = 1,
    _proc_hook=None,
    **opts,
) -> tuple[list | dict, dict]:
    """The multi-process bytes-to-verdict launcher (CLI ``check --procs``).

    Spawns ``n_procs`` worker processes, assigns every history file to
    exactly one worker by the deterministic size-striped rule
    (:func:`assign_stripes`), runs the per-process pipelines, and
    returns the merged verdicts:

    - ``reduce=False`` → ``(results, info)`` with one JSON-normalized
      result dict per path, in order (launcher-dropped unreadable /
      zero-length files carry explicit ``unknown`` entries);
    - ``reduce=True`` → ``(verdict, info)`` with the collectively
      reduced ``{"histories", "invalid", "first_invalid",
      "quarantined"}`` scalars.

    ELASTIC by default: a dead/wedged worker's stripes requeue onto the
    survivors (bounded retry + exponential backoff; ``stripe_timeout_s``
    SIGKILLs a wedged claim-holder), exhausted stripes quarantine as
    explicit ``unknown`` entries, and ``info["degraded"]`` carries the
    machine-readable provenance.  Only a run with NO surviving worker
    (or a global timeout) raises :class:`DistributedCheckError`.

    ``fail_fast=True`` preserves the PR-5 contract verbatim: one
    ``jax.distributed`` fleet, KV-store merge, and a dead worker
    (non-zero exit, kill, timeout) aborts the whole run with
    :class:`DistributedCheckError` and NO partial verdicts.

    ``global_mesh=True`` is the third mode (PR 18): the N processes
    join ONE ``jax.distributed`` fleet and run the SAME shard_map
    verdict programs over one global ``(hist, seq)`` mesh — collectives
    cross the host boundary (gloo on CPU), each process feeds its own
    input lane, failures degrade by generation restart (see
    :func:`_run_global_mesh_check`).  Requires ``reduce=True``;
    ``seq>1`` shards the packed closure's plane axis ACROSS hosts.

    ``_proc_hook`` (tools/chaos_check.py) receives the worker Popen
    list right after spawn — the handle a checker-nemesis needs to
    SIGKILL/SIGSTOP real workers mid-check."""
    # workers run with cwd=repo (PYTHONPATH root), so a caller's
    # relative source paths must be anchored to THIS process's cwd
    # before they enter the manifest
    paths = [os.path.abspath(p) for p in paths]
    if cache_dir is not None:
        cache_dir = os.path.abspath(cache_dir)
    if global_mesh:
        return _run_global_mesh_check(
            workload,
            paths,
            n_procs,
            devices_per_proc=devices_per_proc,
            chunk=chunk,
            seq=seq,
            reduce=reduce,
            timeout_s=timeout_s,
            cache_dir=cache_dir,
            platform=platform,
            stripe_timeout_s=stripe_timeout_s,
            max_stripe_retries=max_stripe_retries,
            _proc_hook=_proc_hook,
            **opts,
        )
    if not fail_fast:
        return _run_elastic_check(
            workload,
            paths,
            n_procs,
            devices_per_proc=devices_per_proc,
            chunk=chunk,
            lanes=lanes,
            mesh=mesh,
            reduce=reduce,
            timeout_s=timeout_s,
            cache_dir=cache_dir,
            platform=platform,
            stripe_timeout_s=stripe_timeout_s,
            max_stripe_retries=max_stripe_retries,
            _proc_hook=_proc_hook,
            **opts,
        )
    import tempfile

    from jepsen_tpu.parallel.pipeline import _lane_census

    paths = [str(p) for p in paths]
    if n_procs < 1:
        raise ValueError(f"n_procs must be >= 1, got {n_procs}")
    # launcher census: sizes feed the deterministic assignment, so they
    # are stat'ed ONCE here and recorded in the manifest (workers must
    # never re-stat — a file changing size mid-launch would desync the
    # stripes); unreadable/zero-length files are dropped loudly — the
    # SAME census the in-process lanes run (one policy, one code path)
    kept, sizes, dropped = _lane_census(paths, workload)

    port = _free_port()
    with tempfile.TemporaryDirectory(prefix="jt_dist_") as td:
        manifest = {
            "workload": workload,
            "paths": [paths[i] for i in kept],
            "sizes": sizes,
            "chunk": chunk,
            "lanes": lanes,
            "mesh": mesh,
            "reduce": reduce,
            "cache_dir": cache_dir,
            "opts": opts,
        }
        mpath = os.path.join(td, "manifest.json")
        with open(mpath, "w") as fh:
            json.dump(manifest, fh)
        out_path = os.path.join(td, "merged.json")

        env = _worker_env(platform, devices_per_proc)
        repo = env["PYTHONPATH"].split(os.pathsep)[0]
        logs = [os.path.join(td, f"worker{pid}.log") for pid in range(n_procs)]
        procs = []
        for pid in range(n_procs):
            # worker output goes to files, not pipes: a chatty worker
            # must never block on a full pipe while the launcher polls
            lf = open(logs[pid], "w")
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, "-m",
                        "jepsen_tpu.parallel.distributed",
                        "--worker",
                        "--manifest", mpath,
                        "--coordinator", f"localhost:{port}",
                        "--process-id", str(pid),
                        "--num-processes", str(n_procs),
                        "--out", out_path,
                        "--merge-timeout-s", str(min(timeout_s, 300.0)),
                    ],
                    stdout=lf,
                    stderr=subprocess.STDOUT,
                    cwd=repo,
                    env=env,
                )
            )
            lf.close()
        deadline = time.monotonic() + timeout_s
        failed: tuple[int, int | None] | None = None
        pending = set(range(n_procs))
        try:
            # poll loop: the moment ANY worker dies non-zero, the run
            # aborts — the survivors are killed rather than left to
            # grind toward a merge that can never complete
            while pending and failed is None:
                for pid in sorted(pending):
                    rc = procs[pid].poll()
                    if rc is None:
                        continue
                    pending.discard(pid)
                    if rc != 0:
                        failed = (pid, rc)
                        break
                if pending and failed is None:
                    if time.monotonic() > deadline:
                        failed = (min(pending), None)
                        break
                    time.sleep(0.05)
        finally:
            for pr in procs:
                if pr.poll() is None:
                    pr.kill()
            for pr in procs:
                if pr.poll() is None:
                    try:
                        pr.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        pass
        if failed is not None:
            pid, rc = failed
            tail = _log_tail(logs[pid], 1500)
            raise DistributedCheckError(
                f"worker {pid} of {n_procs} "
                f"{'timed out' if rc is None else f'died (rc={rc})'} — "
                f"aborting with no partial verdicts:\n{tail}"
            )
        try:
            with open(out_path) as fh:
                merged = json.load(fh)
        except (OSError, ValueError) as e:
            raise DistributedCheckError(
                f"coordinator produced no merged verdict file: {e}"
            )
    info = {
        "n_procs": n_procs,
        "devices_per_proc": devices_per_proc,
        "dropped": len(dropped),
        "per_process": merged["per_process"],
    }
    if reduce:
        verdict = merged["verdict"]
        verdict["dropped"] += len(dropped)
        # lift kept-space counterexample index to original path space
        if verdict["first_invalid"] >= 0:
            verdict["first_invalid"] = kept[verdict["first_invalid"]]
        return verdict, info
    results: list = [None] * len(paths)
    for j, i in enumerate(kept):
        results[i] = merged["results"][j]
    from jepsen_tpu.parallel.pipeline import _dropped_result

    for i, reason in dropped.items():
        results[i] = _dropped_result(workload, reason)
    return results, info


def _worker_env(platform: str | None, devices_per_proc: int) -> dict:
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = platform or "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices_per_proc}"
    )
    repo = str(Path(__file__).resolve().parent.parent.parent)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


def degraded_active(deg: dict | None) -> bool:
    """True when a ``degraded`` provenance dict records any actual
    degradation (deaths, requeues, quarantines, wedge kills) — the
    no-fault elastic run carries the dict with everything empty."""
    if not deg:
        return False
    return bool(
        deg.get("dead_workers")
        or deg.get("requeued_stripes")
        or deg.get("quarantined_stripes")
        or deg.get("wedged_killed")
        or deg.get("quarantined_histories")
    )


def _log_tail(path: str, limit: int = 800) -> str:
    try:
        with open(path) as fh:
            return fh.read()[-limit:]
    except OSError:
        return "<no worker log>"


def _run_elastic_check(
    workload: str,
    paths,
    n_procs: int,
    *,
    devices_per_proc: int,
    chunk: int,
    lanes: int | None,
    mesh: bool,
    reduce: bool,
    timeout_s: float,
    cache_dir: str | None,
    platform: str | None,
    stripe_timeout_s: float | None,
    max_stripe_retries: int,
    _proc_hook,
    backoff_s: float = 0.5,
    **opts,
) -> tuple[list | dict, dict]:
    """The elastic launcher: spool-directory tasks, survivor requeue,
    per-stripe deadlines, quarantine past the retry budget, and a
    merged verdict with ``degraded`` provenance.  See
    :func:`run_multiprocess_check` for the contract."""
    import signal
    import tempfile

    from jepsen_tpu.obs import metrics as obs_metrics
    from jepsen_tpu.obs import trace as obs_trace
    from jepsen_tpu.parallel.pipeline import _lane_census

    paths = [str(p) for p in paths]
    if n_procs < 1:
        raise ValueError(f"n_procs must be >= 1, got {n_procs}")
    kept, sizes, dropped = _lane_census(paths, workload)
    if stripe_timeout_s is None:
        stripe_timeout_s = min(timeout_s, 300.0)

    with tempfile.TemporaryDirectory(prefix="jt_dist_") as td:
        spool = Path(td)
        tasks_d, claims_d, res_d = (
            spool / "tasks", spool / "claims", spool / "results",
        )
        for d in (tasks_d, claims_d, res_d):
            d.mkdir()
        manifest = {
            "workload": workload,
            "paths": [paths[i] for i in kept],
            "sizes": sizes,
            "chunk": chunk,
            "lanes": lanes,
            "mesh": mesh,
            "reduce": reduce,
            "cache_dir": cache_dir,
            "opts": opts,
            "elastic": True,
        }
        mpath = spool / "manifest.json"
        _write_json_atomic(mpath, manifest)
        stripes = assign_stripes(sizes, n_procs)
        stripe_indices = {p: sorted(stripes[p]) for p in range(n_procs)}
        for p in range(n_procs):
            _write_json_atomic(
                tasks_d / f"t{p}.json",
                {
                    "task": p,
                    "indices": stripe_indices[p],
                    "retries": 0,
                    "not_before": 0.0,
                },
            )

        env = _worker_env(platform, devices_per_proc)
        repo = env["PYTHONPATH"].split(os.pathsep)[0]
        logs = [
            os.path.join(td, f"worker{pid}.log") for pid in range(n_procs)
        ]
        procs = []
        for pid in range(n_procs):
            lf = open(logs[pid], "w")
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, "-m",
                        "jepsen_tpu.parallel.distributed",
                        "--worker", "--elastic",
                        "--manifest", str(mpath),
                        "--spool", str(spool),
                        "--process-id", str(pid),
                        "--num-processes", str(n_procs),
                    ],
                    stdout=lf,
                    stderr=subprocess.STDOUT,
                    cwd=repo,
                    env=env,
                )
            )
            lf.close()
        if _proc_hook is not None:
            _proc_hook(procs)

        deaths: list[dict] = []
        requeued: list[dict] = []
        quarantined: dict[int, dict] = {}
        stripe_attempts: dict[int, list[str]] = {}
        wedged_killed: list[int] = []
        running = set(range(n_procs))
        have: set[int] = set()
        task_ids = set(range(n_procs))
        deadline = time.monotonic() + timeout_s
        gauge = obs_metrics.REGISTRY.gauge("dist.workers_alive")
        gauge.set(len(running))
        timed_out = False
        try:
            while True:
                now = time.monotonic()
                # -- liveness: any exit before `done` is a death event
                for pid in sorted(running):
                    rc = procs[pid].poll()
                    if rc is None:
                        continue
                    running.discard(pid)
                    gauge.set(len(running))
                    deaths.append(
                        {
                            "pid": pid,
                            "rc": rc,
                            "log_tail": _log_tail(logs[pid], 400),
                            "_t": now,
                        }
                    )
                    obs_metrics.REGISTRY.counter(
                        "dist.worker_deaths"
                    ).inc()
                    if obs_trace.is_enabled():
                        obs_trace.event(
                            "checker.worker_death",
                            track="dist",
                            args={"pid": pid, "rc": rc},
                        )
                    # requeue the dead worker's claimed stripes
                    for cf in sorted(claims_d.glob(f"t*.json.p{pid}")):
                        try:
                            k = int(cf.name[1:].split(".", 1)[0])
                        except ValueError:
                            continue
                        try:
                            task = json.loads(cf.read_text())
                        except (OSError, ValueError):
                            # unreadable claim content must not orphan
                            # the stripe — its id (filename) and indices
                            # (manifest) still fully identify the work
                            task = {
                                "task": k,
                                "indices": stripe_indices[k],
                                "retries": len(stripe_attempts.get(k, ())),
                                "not_before": 0.0,
                            }
                        cf.unlink(missing_ok=True)
                        if (res_d / f"r{k}.json").exists():
                            continue  # the result landed before death
                        stripe_attempts.setdefault(k, []).append(
                            f"worker {pid} rc={rc}"
                        )
                        retries = int(task.get("retries", 0)) + 1
                        if retries > max_stripe_retries:
                            quarantined[k] = {
                                "stage": "worker",
                                "attempts": list(stripe_attempts[k]),
                                "errors": [
                                    f"stripe {k} lost its worker "
                                    f"{retries} times (last: pid {pid} "
                                    f"rc={rc}); retry budget "
                                    f"({max_stripe_retries}) exhausted"
                                ],
                                "retries": retries,
                            }
                            obs_metrics.REGISTRY.counter(
                                "dist.stripe_quarantines"
                            ).inc()
                        else:
                            task["retries"] = retries
                            task["not_before"] = (
                                time.time()
                                + backoff_s * 2 ** (retries - 1)
                            )
                            _write_json_atomic(
                                tasks_d / f"t{k}.json", task
                            )
                            requeued.append(
                                {
                                    "stripe": k,
                                    "retries": retries,
                                    "from_pid": pid,
                                    "_t": now,
                                }
                            )
                            obs_metrics.REGISTRY.counter(
                                "dist.stripe_requeues"
                            ).inc()
                            if obs_trace.is_enabled():
                                obs_trace.event(
                                    "checker.stripe_requeue",
                                    track="dist",
                                    args={
                                        "stripe": k,
                                        "retries": retries,
                                        "from_pid": pid,
                                    },
                                )
                # -- per-stripe deadline: a wedged claim-holder (e.g.
                # SIGSTOPped) is killed so its stripes recirculate
                for cf in list(claims_d.glob("t*.json.p*")):
                    try:
                        age = time.time() - cf.stat().st_mtime
                    except OSError:
                        continue
                    if age <= stripe_timeout_s:
                        continue
                    try:
                        holder = int(cf.name.rsplit(".p", 1)[1])
                    except (IndexError, ValueError):
                        continue
                    if holder in running and procs[holder].poll() is None:
                        try:
                            procs[holder].send_signal(signal.SIGKILL)
                        except OSError:
                            pass
                        wedged_killed.append(holder)
                # -- results scan (+ recovery-time evidence for
                # requeued stripes, onto the PR-9 sketches)
                for rf in res_d.glob("r*.json"):
                    try:
                        k = int(rf.name[1:-5])
                    except ValueError:
                        continue
                    if k in have:
                        continue
                    have.add(k)
                    for entry in requeued:
                        if entry["stripe"] == k and "recovery_s" not in entry:
                            entry["recovery_s"] = round(now - entry["_t"], 3)
                            obs_metrics.REGISTRY.sketch(
                                "dist.stripe_recovery_s"
                            ).add(entry["recovery_s"])
                if have | set(quarantined) >= task_ids:
                    break
                if not running:
                    raise DistributedCheckError(
                        f"all {n_procs} elastic workers died with "
                        f"{len(task_ids - have - set(quarantined))} "
                        f"stripe(s) unfinished — nothing left to requeue "
                        f"onto:\n{deaths[-1]['log_tail'] if deaths else ''}"
                    )
                if now > deadline:
                    timed_out = True
                    raise DistributedCheckError(
                        f"elastic check timed out after {timeout_s:.0f}s "
                        f"with {len(task_ids - have - set(quarantined))} "
                        f"stripe(s) unfinished"
                    )
                time.sleep(0.05)
        finally:
            # completion (or failure): tell the workers, give the
            # stragglers a moment, then reap
            try:
                (spool / "done").touch()
            except OSError:
                pass
            grace = time.monotonic() + (0.0 if timed_out else 5.0)
            while (
                any(pr.poll() is None for pr in procs)
                and time.monotonic() < grace
            ):
                time.sleep(0.05)
            for pr in procs:
                if pr.poll() is None:
                    pr.kill()
            for pr in procs:
                if pr.poll() is None:
                    try:
                        pr.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        pass
            gauge.set(0)

        shard_docs: dict[int, dict] = {}
        for k in sorted(have):
            try:
                shard_docs[k] = json.loads(
                    (res_d / f"r{k}.json").read_text()
                )
            except (OSError, ValueError) as e:
                raise DistributedCheckError(
                    f"unreadable verdict shard for stripe {k}: {e}"
                )
        merged, per_process = _merge_elastic(
            manifest, shard_docs, quarantined, stripe_indices, workload,
            reduce,
        )

    for d in deaths:
        d.pop("_t", None)
    for entry in requeued:
        entry.pop("_t", None)
        entry["completed_by"] = (
            shard_docs[entry["stripe"]]["pid"]
            if entry["stripe"] in shard_docs
            else None
        )
    worker_quarantined = sum(
        int(doc["stats"].get("quarantined", 0))
        for doc in shard_docs.values()
    )
    degraded = {
        "elastic": True,
        "procs": n_procs,
        "effective_procs": n_procs - len(deaths),
        "dead_workers": deaths,
        "requeued_stripes": requeued,
        "quarantined_stripes": [
            {
                "stripe": k,
                "indices": [kept[i] for i in stripe_indices[k]],
                "evidence": ev,
            }
            for k, ev in sorted(quarantined.items())
        ],
        "wedged_killed": sorted(set(wedged_killed)),
        "quarantined_histories": worker_quarantined
        + sum(len(stripe_indices[k]) for k in quarantined),
    }
    info = {
        "n_procs": n_procs,
        "devices_per_proc": devices_per_proc,
        "dropped": len(dropped),
        "per_process": per_process,
        "elastic": True,
        "degraded": degraded,
    }
    if reduce:
        verdict = merged
        verdict["dropped"] += len(dropped)
        if verdict["first_invalid"] >= 0:
            verdict["first_invalid"] = kept[verdict["first_invalid"]]
        return verdict, info
    results: list = [None] * len(paths)
    from jepsen_tpu.parallel.pipeline import (
        _dropped_result,
        _quarantined_result,
    )

    for k, doc in shard_docs.items():
        for i, r in zip(doc["indices"], doc["results"]):
            results[kept[i]] = r
    for k, ev in quarantined.items():
        for i in stripe_indices[k]:
            results[kept[i]] = _quarantined_result(workload, ev)
    for i, reason in dropped.items():
        results[i] = _dropped_result(workload, reason)
    return results, info


def _merge_elastic(
    man: dict,
    shard_docs: dict[int, dict],
    quarantined: dict[int, dict],
    stripe_indices: dict[int, list[int]],
    workload: str,
    reduce: bool,
):
    """Assemble per-stripe verdict shards + quarantined stripes into
    one verdict set (kept-manifest index space) and the per-process
    stats rows."""
    per: dict[int, dict] = {}
    for k, doc in sorted(shard_docs.items()):
        row = per.setdefault(
            doc["pid"],
            {"pid": doc["pid"], "checked": 0, "wall_s": 0.0, "lanes": 0,
             "dropped": 0, "quarantined": 0, "stripes": []},
        )
        row["checked"] += len(doc["indices"])
        row["wall_s"] += float(doc["stats"].get("wall_s", 0.0))
        row["lanes"] = max(
            row["lanes"], int(doc["stats"].get("lanes", 0))
        )
        row["dropped"] += int(doc["stats"].get("dropped", 0))
        row["quarantined"] += int(doc["stats"].get("quarantined", 0))
        row["stripes"].append(k)
    per_process = [per[p] for p in sorted(per)]
    if not reduce:
        return None, per_process
    merged = {
        "histories": 0, "invalid": 0, "first_invalid": -1,
        "quarantined": 0, "dropped": 0,
    }
    for k, doc in sorted(shard_docs.items()):
        r = doc["results"]
        merged["histories"] += r["histories"]
        merged["invalid"] += r["invalid"]
        merged["quarantined"] += r.get("quarantined", 0)
        merged["dropped"] += r.get("dropped", 0)
        g = r.get("first_invalid", -1)
        if g >= 0 and (
            merged["first_invalid"] < 0 or g < merged["first_invalid"]
        ):
            merged["first_invalid"] = g
    for k in quarantined:
        merged["histories"] += len(stripe_indices[k])
        merged["quarantined"] += len(stripe_indices[k])
    return merged, per_process


# ---------------------------------------------------------------------------
# Global-mesh mode (PR 18, ROADMAP direction 2's collective half): N
# processes join ONE jax.distributed fleet and run the SAME shard_map
# verdict programs over one global (hist, seq) mesh — the collectives
# (the packed multi-chip closure's all_gather/psum, the verdict
# reduction's psum/pmin) cross the host boundary for real, instead of
# each process reducing privately and merging through the KV store.
# Each process owns one Podracer-style input lane (census → stripes →
# pack → stage; pipeline.gm_* helpers) and feeds exactly its contiguous
# row block of every global batch via make_array_from_process_local_data;
# one small KV exchange of raw pack maxima per chunk keeps the jitted
# program shapes identical on every host.  On the CPU backend the
# cross-process collectives run over gloo.
#
# Failure semantics are GENERATION-elastic: lockstep collectives mean a
# dead host wedges its survivors inside a psum, so the launcher's
# liveness poll kills the whole generation on the first death and
# respawns N-1 processes on a fresh coordinator — completed stripes are
# skipped (results/r{k}.json is the ledger, exactly the PR-13 spool
# shape), unfinished stripes requeue, and stripes whose generation
# retries exhaust quarantine.  The merged verdict carries the same
# machine-readable `degraded` provenance as elastic mode.
# ---------------------------------------------------------------------------

_GM_KV = "jt/gm"


def _enable_cpu_collectives() -> None:
    """The CPU backend needs a cross-process collectives implementation
    configured BEFORE ``jax.distributed.initialize`` — gloo ships with
    jaxlib and turns multi-process ``shard_map`` collectives into real
    socket traffic between the worker processes."""
    import jax

    if (os.environ.get("JAX_PLATFORMS") or "").strip().lower().startswith(
        "cpu"
    ):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")


def build_global_mesh(seq: int = 1):
    """The fleet-wide ``(hist, seq)`` mesh.

    ``seq == 1``: devices process-major down the hist axis — process p's
    devices own a contiguous block of history rows, so its input lane
    feeds its local shard directly and the cross-host collective is the
    verdict reduction's psum/pmin.

    ``seq > 1``: ``seq`` must be a multiple of the process count; each
    process contributes ``k = seq/N`` adjacent seq columns, so the
    column (plane) axis of the packed closure spans ALL processes and
    every ``all_gather`` of the packed left operand crosses the host
    boundary — the arXiv 2112.09017 block distribution, with hosts as
    the outer block grid.  Every process then holds a column slice of
    EVERY history row (one shared lane), the fat-history regime where
    device work dominates ingest."""
    import numpy as _np

    import jax

    from jepsen_tpu.parallel.mesh import HIST_AXIS, SEQ_AXIS

    devs = sorted(jax.devices(), key=lambda d: d.id)
    n = jax.process_count()
    total = len(devs)
    d_local = total // n
    for j, d in enumerate(devs):
        if d.process_index != j // d_local:
            raise DistributedCheckError(
                "device ids are not process-major; the lane-per-host row "
                "blocks would not be contiguous"
            )
    from jax.sharding import Mesh

    if seq <= 1:
        arr = _np.array(devs).reshape(total, 1)
    else:
        if seq % n:
            raise ValueError(
                f"global-mesh seq={seq} must be a multiple of the process "
                f"count {n} so the plane-axis collectives cross hosts"
            )
        k = seq // n
        if d_local % k:
            raise ValueError(
                f"each process contributes seq/N={k} seq columns, which "
                f"must divide its local device count {d_local}"
            )
        hist = d_local // k
        arr = (
            _np.array(devs)
            .reshape(n, hist, k)
            .transpose(1, 0, 2)
            .reshape(hist, seq)
        )
    return Mesh(arr, (HIST_AXIS, SEQ_AXIS))


def _process_block(sharding, shape) -> tuple:
    """The contiguous index box of the global array that THIS process's
    devices own under ``sharding`` — the block its input lane must
    produce.  Raises when the process's shards don't tile one box (a
    layout this feeding scheme can't serve)."""
    import jax

    imap = sharding.devices_indices_map(tuple(shape))
    pidx = jax.process_index()
    local = [idx for d, idx in imap.items() if d.process_index == pidx]
    if not local:
        raise DistributedCheckError(
            "process owns no shard of the global batch"
        )
    norm = {
        tuple(
            (s.start or 0, shape[a] if s.stop is None else s.stop)
            for a, s in enumerate(idx)
        )
        for idx in local
    }
    box = tuple(
        slice(min(b[a][0] for b in norm), max(b[a][1] for b in norm))
        for a in range(len(shape))
    )
    one = next(iter(norm))
    shard_vol = 1
    for a in range(len(shape)):
        shard_vol *= one[a][1] - one[a][0]
    box_vol = 1
    for s in box:
        box_vol *= s.stop - s.start
    if shard_vol * len(norm) != box_vol:
        raise DistributedCheckError(
            "process-local shards do not tile a contiguous block under "
            "this mesh layout"
        )
    return box


def _feed_global(lane_np, lane_row0: int, mesh, spec, global_shape):
    """One lane's host block -> a global sharded array.  ``lane_np``
    holds rows ``[lane_row0, lane_row0 + lane_rows)`` of the global row
    axis (axis 0) and ALL columns; the process block is cut out of it
    and handed to ``make_array_from_process_local_data`` — no host ever
    materializes another host's rows."""
    import numpy as _np

    import jax
    from jax.sharding import NamedSharding

    sh = NamedSharding(mesh, spec)
    box = _process_block(sh, global_shape)
    rel = (
        slice(box[0].start - lane_row0, box[0].stop - lane_row0),
    ) + tuple(box[1:])
    block = _np.ascontiguousarray(lane_np[rel])
    return jax.make_array_from_process_local_data(
        sh, block, tuple(global_shape)
    )


def _gm_exchange(kvp: str, pid: int, n: int, payload: dict, kv_ms: int):
    """Publish this process's chunk facts and read everyone's — the
    per-chunk shape-agreement barrier.  A sibling that died before
    publishing surfaces as a deadline timeout here, which exits this
    worker non-zero and lets the launcher restart the generation."""
    kv = _kv_client()
    kv.key_value_set(f"{kvp}/{pid}", json.dumps(payload))
    docs = []
    for q in range(n):
        raw = kv.blocking_key_value_get(f"{kvp}/{q}", kv_ms)
        docs.append(json.loads(raw))
    return docs


def _gm_queue_chunk(
    man: dict, mesh, lanes: int, quantum: int, pid: int, n: int,
    idxs: list[int], kvp: str, kv_ms: int,
) -> tuple[int, int]:
    """One queue chunk over the global mesh: stage my lane's rows, agree
    on (L, V), feed my row/column block, run the sharded verdict with
    cross-host reduction.  Returns ``(n_invalid, first_invalid)`` in
    kept-manifest gid space."""
    import dataclasses

    import numpy as _np

    from jax.sharding import PartitionSpec as P

    from jepsen_tpu.parallel.mesh import (
        HIST_AXIS,
        SEQ_AXIS,
        sharded_queue_verdict,
    )
    from jepsen_tpu.parallel.pipeline import (
        _GID_PAD,
        _pow2_bucket,
        gm_lane_plan,
        gm_pack_queue_lane,
        gm_stage_queue_lane,
    )

    B = len(idxs)
    b_l, bounds = gm_lane_plan(B, lanes, quantum)
    lane = pid if lanes > 1 else 0
    lo, hi = bounds[lane]
    mats, (n_max, vmax) = gm_stage_queue_lane(
        [man["paths"][i] for i in idxs[lo:hi]],
        use_cache=bool((man.get("opts") or {}).get("use_cache", True)),
    )
    docs = _gm_exchange(kvp, pid, n, {"n": n_max, "v": vmax}, kv_ms)
    length = _pow2_bucket(max(max(d["n"] for d in docs), 1))
    space = _pow2_bucket(max(d["v"] for d in docs) + 1)
    packed = gm_pack_queue_lane(mats, b_l, length, space)

    b_pad = lanes * b_l
    gidx = _np.full(b_l, _GID_PAD, _np.int32)
    gidx[: hi - lo] = _np.asarray(idxs[lo:hi], _np.int32)
    row0 = lane * b_l
    row = P(HIST_AXIS, SEQ_AXIS)

    def feed2(x):
        return _feed_global(_np.asarray(x), row0, mesh, row, (b_pad, length))

    packed_g = dataclasses.replace(
        packed,
        **{
            f: feed2(getattr(packed, f))
            for f in ("index", "process", "type", "f", "value", "time_ms",
                      "latency_ms", "mask", "first")
        },
    )
    gidx_g = _feed_global(gidx, row0, mesh, P(HIST_AXIS), (b_pad,))
    delivery = (man.get("opts") or {}).get("delivery", "exactly-once")
    nb, first = sharded_queue_verdict(
        packed_g, mesh, delivery=delivery, gidx=gidx_g
    )
    return int(_np.asarray(nb)), int(_np.asarray(first))


def _gm_elle_chunk(
    man: dict, mesh, lanes: int, quantum: int, pid: int, n: int,
    idxs: list[int], kvp: str, kv_ms: int,
) -> tuple[int, int]:
    """One elle chunk over the global mesh: stage my lane's micro-op
    substrates, splice degenerate rows through MY host's oracle (the
    shard-boundary fallback splice), agree on (T, M, V, K, R), feed my
    block of the live batch, and run fused device inference + the
    packed multi-chip closure with its plane axis sharded across hosts.
    Returns ``(n_invalid, first_invalid)`` in kept-manifest gid space."""
    import dataclasses
    import math

    import numpy as _np

    from jax.sharding import PartitionSpec as P

    from jepsen_tpu.checkers.elle import check_elle_cpu
    from jepsen_tpu.history.encode import LANE, _round_up
    from jepsen_tpu.history.store import read_history
    from jepsen_tpu.parallel.mesh import (
        HIST_AXIS,
        SEQ_AXIS,
        sharded_elle_mops_verdict,
    )
    from jepsen_tpu.parallel.pipeline import (
        _GID_PAD,
        gm_lane_plan,
        gm_pack_elle_lane,
        gm_stage_elle_lane,
    )

    model = (man.get("opts") or {}).get("model", "serializable")
    B = len(idxs)
    b_l, bounds = gm_lane_plan(B, lanes, quantum)
    lane = pid if lanes > 1 else 0
    lo, hi = bounds[lane]
    mm, live, degen, maxima = gm_stage_elle_lane(
        [man["paths"][i] for i in idxs[lo:hi]],
        use_cache=bool((man.get("opts") or {}).get("use_cache", True)),
    )
    # degenerate rows: host-oracle fallback on the lane that owns them
    # (the splice boundary IS the shard boundary); the per-lane fold is
    # exchanged so every process derives the identical chunk verdict
    di, df = 0, -1
    for i in degen:
        r = check_elle_cpu(read_history(man["paths"][idxs[lo + i]]),
                           model=model)
        if r["valid?"] is not True:
            di += 1
            g = idxs[lo + i]
            df = g if df < 0 else min(df, g)
    docs = _gm_exchange(
        kvp, pid, n,
        {"x": list(maxima), "live": len(live), "di": di, "df": df},
        kv_ms,
    )
    # one doc per LANE (for the shared-lane seq>1 layout every process
    # published the same facts; fold lane 0's only)
    lane_docs = docs[:lanes]
    n_invalid = sum(d["di"] for d in lane_docs)
    first = min((d["df"] for d in lane_docs if d["df"] >= 0), default=-1)

    live_max = max(d["live"] for d in lane_docs)
    t_glob = max(d["x"][0] for d in lane_docs)
    if live_max == 0 or t_glob == 0:
        return n_invalid, first
    n_seq = mesh.shape[SEQ_AXIS]
    # T granule: the lane width AND whole uint32 plane words per seq
    # shard, so the packed multi-chip closure lowers (no silent dense
    # fallback) and n_txns % seq holds
    granule = math.lcm(LANE, 32 * n_seq) if n_seq > 1 else LANE
    t_pad = _round_up(t_glob, granule)
    at_least = tuple(int(max(d["x"][j] for d in lane_docs))
                     for j in range(1, 5))
    b_live = _round_up(live_max, quantum)
    mops = gm_pack_elle_lane(mm, live, b_live, t_pad, at_least)

    b_pad = lanes * b_live
    gidx = _np.full(b_live, _GID_PAD, _np.int32)
    gidx[: len(live)] = _np.asarray(
        [idxs[lo + i] for i in live], _np.int32
    )
    row0 = lane * b_live
    m_cells = mops.txn.shape[1]

    def feed2(x):
        return _feed_global(
            _np.asarray(x), row0, mesh, P(HIST_AXIS, None),
            (b_pad, m_cells),
        )

    def feed1(x):
        return _feed_global(
            _np.asarray(x), row0, mesh, P(HIST_AXIS), (b_pad,)
        )

    mops_g = dataclasses.replace(
        mops,
        **{
            f: feed2(getattr(mops, f))
            for f in ("txn", "kind", "key", "val", "rpos", "rid", "alast",
                      "mask")
        },
        n_committed=feed1(mops.n_committed),
    )
    gidx_g = feed1(gidx)
    nb, fdev = sharded_elle_mops_verdict(mops_g, mesh, gidx=gidx_g)
    nb, fdev = int(_np.asarray(nb)), int(_np.asarray(fdev))
    n_invalid += nb
    if fdev >= 0 and (first < 0 or fdev < first):
        first = fdev
    return n_invalid, first


def _global_mesh_worker(args, man: dict) -> int:
    """One process of the global-mesh fleet.  No task claiming: every
    worker walks the SAME stripe list in the same order (skipping
    stripes whose result existed when this generation started — the
    crash-recovery ledger), because the collectives need every process
    in every program.  Process 0 writes the per-stripe verdict docs."""
    import jax

    _enable_cpu_collectives()
    init_multihost(
        args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    assert jax.process_count() == args.num_processes, jax.process_count()
    pid = args.process_id
    n = args.num_processes

    from jepsen_tpu.utils.jaxenv import enable_compilation_cache

    if man.get("cache_dir"):
        enable_compilation_cache(
            man["cache_dir"], backend=jax.default_backend()
        )

    seq = int(man.get("seq") or 1)
    mesh = build_global_mesh(seq)
    from jepsen_tpu.parallel.mesh import HIST_AXIS
    from jepsen_tpu.parallel.pipeline import _chunks

    d_local = len(jax.devices()) // n
    lanes = n if seq <= 1 else 1
    # rows-per-process granule of the global hist axis: lane heights
    # must be multiples of it so every lane block is whole device shards
    quantum = d_local if seq <= 1 else mesh.shape[HIST_AXIS]

    resdir = Path(args.spool) / "results"
    done0 = {int(f.name[1:-5]) for f in resdir.glob("r*.json")}
    stripes = [sorted(s) for s in man["stripes"]]
    chunk = int(man.get("chunk") or 64)
    kv_ms = int(man.get("kv_timeout_ms") or 120_000)
    run_chunk = (
        _gm_queue_chunk if man["workload"] == "queue" else _gm_elle_chunk
    )

    checked = 0
    first_done = False
    for k, stripe in enumerate(stripes):
        if k in done0:
            continue
        if first_done and _hook_hit(_DIE_ENV, pid):
            # crash-contract hook: die between stripes, AFTER completing
            # one — the restart generation must skip the finished stripe
            # and redo only the rest
            os._exit(42)
        if _hook_hit(_WEDGE_ENV, pid):
            time.sleep(3600)
        t0 = time.perf_counter()
        total_invalid, total_first, histories = 0, -1, 0
        for ci, cidx in enumerate(_chunks(stripe, chunk)):
            cidx = list(cidx)
            inv, first = run_chunk(
                man, mesh, lanes, quantum, pid, n, cidx,
                f"{_GM_KV}/t{k}/c{ci}", kv_ms,
            )
            total_invalid += inv
            histories += len(cidx)
            if first >= 0 and (total_first < 0 or first < total_first):
                total_first = first
        wall = time.perf_counter() - t0
        if pid == 0:
            _write_json_atomic(
                resdir / f"r{k}.json",
                {
                    "pid": pid,
                    "task": k,
                    "indices": stripe,
                    "results": {
                        "histories": histories,
                        "invalid": total_invalid,
                        "first_invalid": total_first,
                        "quarantined": 0,
                        "dropped": 0,
                    },
                    "stats": {
                        "wall_s": wall,
                        "histories": histories,
                        "lanes": lanes,
                        "dropped": 0,
                        "quarantined": 0,
                    },
                },
            )
        checked += len(stripe)
        first_done = True
    print(json.dumps({"pid": pid, "checked": checked}), flush=True)
    return 0


def _run_global_mesh_check(
    workload: str,
    paths,
    n_procs: int,
    *,
    devices_per_proc: int = 1,
    chunk: int = 64,
    seq: int = 1,
    reduce: bool = True,
    timeout_s: float = 900.0,
    cache_dir: str | None = None,
    platform: str | None = None,
    stripe_timeout_s: float | None = None,
    max_stripe_retries: int = 2,
    _proc_hook=None,
    **opts,
) -> tuple[dict, dict]:
    """Launcher for the global-mesh fleet (see the section comment):
    generation-elastic — the first worker death kills the generation
    (survivors are wedged inside collectives, not salvageable) and
    respawns N-1 on a fresh coordinator; completed stripes are skipped
    via the results ledger, exhausted stripes quarantine.  Returns the
    reduced verdict + info with ``degraded`` provenance."""
    import tempfile

    from jepsen_tpu.parallel.pipeline import _lane_census

    if workload not in ("queue", "elle"):
        raise ValueError(
            "global-mesh mode runs the queue and elle collective verdict "
            f"programs; workload {workload!r} is not wired yet"
        )
    if not reduce:
        raise ValueError(
            "global-mesh mode reduces on device (two scalars cross D2H); "
            "pass reduce=True"
        )
    if n_procs < 1:
        raise ValueError(f"n_procs must be >= 1, got {n_procs}")
    if seq > 1:
        if seq % n_procs:
            raise ValueError(
                f"seq={seq} must be a multiple of n_procs={n_procs}"
            )
        if devices_per_proc % (seq // n_procs):
            raise ValueError(
                f"seq/N={seq // n_procs} seq columns per process must "
                f"divide devices_per_proc={devices_per_proc}"
            )
    paths = [str(p) for p in paths]
    kept, sizes, dropped = _lane_census(paths, workload)
    n_tasks = max(1, min(n_procs, len(kept)))
    stripes = [sorted(s) for s in assign_stripes(sizes, n_tasks)]

    with tempfile.TemporaryDirectory(prefix="jt_gmesh_") as td:
        spool = Path(td) / "spool"
        resdir = spool / "results"
        resdir.mkdir(parents=True)
        manifest = {
            "workload": workload,
            "paths": [paths[i] for i in kept],
            "sizes": sizes,
            "chunk": chunk,
            "seq": seq,
            "reduce": True,
            "cache_dir": cache_dir,
            "opts": opts,
            "stripes": stripes,
            "global_mesh": True,
        }
        mpath = os.path.join(td, "manifest.json")
        with open(mpath, "w") as fh:
            json.dump(manifest, fh)
        env = _worker_env(platform, devices_per_proc)
        repo = env["PYTHONPATH"].split(os.pathsep)[0]

        deadline = time.monotonic() + timeout_s
        fleet = n_procs
        gen = 0
        deaths: list[int] = []
        requeued: list[int] = []
        wedged_killed = 0
        retries: dict[int, int] = {}
        quarantined: dict[int, dict] = {}
        last_log = ""

        def seq_for_fleet(n: int) -> int:
            # the widest seq axis a generation of n processes can still
            # factor: seq' = n * k with k | devices_per_proc, capped at
            # the requested seq.  A shrunken fleet keeps verifying on a
            # NARROWER mesh (seq is a layout, not a semantic: verdicts
            # are seq-invariant by the differential pins) rather than
            # dying forever on an unbuildable one.
            best = 1
            k = 1
            while n * k <= seq:
                if devices_per_proc % k == 0:
                    best = n * k
                k += 1
            return min(best, seq) if seq > 1 else seq

        man_seq = seq

        def results_done() -> set[int]:
            return {int(f.name[1:-5]) for f in resdir.glob("r*.json")}

        while True:
            done = results_done()
            todo = [
                k for k in range(n_tasks)
                if k not in done and k not in quarantined
            ]
            if not todo:
                break
            eff_seq = seq_for_fleet(fleet)
            if eff_seq != man_seq:
                man_seq = eff_seq
                manifest["seq"] = eff_seq
                with open(mpath, "w") as fh:
                    json.dump(manifest, fh)
            port = _free_port()
            logs = [
                os.path.join(td, f"g{gen}_w{i}.log") for i in range(fleet)
            ]
            procs = []
            for i in range(fleet):
                lf = open(logs[i], "w")
                procs.append(
                    subprocess.Popen(
                        [
                            sys.executable, "-m",
                            "jepsen_tpu.parallel.distributed",
                            "--worker", "--global-mesh",
                            "--manifest", mpath,
                            "--spool", str(spool),
                            "--coordinator", f"127.0.0.1:{port}",
                            "--process-id", str(i),
                            "--num-processes", str(fleet),
                        ],
                        stdout=lf,
                        stderr=subprocess.STDOUT,
                        cwd=repo,
                        env=env,
                    )
                )
                lf.close()
            if _proc_hook is not None:
                _proc_hook(procs)
            failed: tuple[int, int | None] | None = None
            wedged = False
            pending = set(range(fleet))
            n_done_seen = len(done)
            progress_t = time.monotonic()
            try:
                while pending and failed is None:
                    for i in sorted(pending):
                        rc = procs[i].poll()
                        if rc is None:
                            continue
                        pending.discard(i)
                        if rc != 0:
                            failed = (i, rc)
                            break
                    if not pending or failed is not None:
                        break
                    now = time.monotonic()
                    if now > deadline:
                        for pr in procs:
                            pr.kill()
                        raise DistributedCheckError(
                            f"global-mesh run timed out after {timeout_s}s "
                            f"(generation {gen}):\n"
                            + _log_tail(logs[0], 1500)
                        )
                    if stripe_timeout_s is not None:
                        nd = len(results_done())
                        if nd > n_done_seen:
                            n_done_seen, progress_t = nd, now
                        elif now - progress_t > stripe_timeout_s:
                            # no stripe landed for a full deadline: a
                            # wedged (e.g. SIGSTOPped) member has the
                            # fleet stuck in a collective — kill the
                            # generation and restart
                            failed = (-1, None)
                            wedged = True
                            break
                    time.sleep(0.05)
            finally:
                for pr in procs:
                    if pr.poll() is None:
                        pr.kill()
                for pr in procs:
                    try:
                        pr.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        pass
            if failed is None:
                continue  # clean generation; loop re-checks the ledger
            fpid, _rc = failed
            last_log = _log_tail(logs[max(fpid, 0)], 1500)
            done2 = results_done()
            lost = [k for k in todo if k not in done2]
            if wedged:
                wedged_killed += 1
            else:
                deaths.append(fpid)
                fleet = max(1, fleet - 1)
            for k in lost:
                retries[k] = retries.get(k, 0) + 1
                if retries[k] > max_stripe_retries:
                    quarantined[k] = {
                        "reason": "generation retries exhausted",
                        "retries": retries[k],
                    }
                else:
                    requeued.append(k)
            gen += 1
            if all(
                k in quarantined or k in done2 for k in range(n_tasks)
            ):
                break
            time.sleep(min(0.2 * gen, 1.0))

        shard_docs = {}
        for f in sorted(resdir.glob("r*.json")):
            with open(f) as fh:
                shard_docs[int(f.name[1:-5])] = json.load(fh)
        if not shard_docs and quarantined and len(quarantined) == n_tasks:
            raise DistributedCheckError(
                "global-mesh fleet never completed a stripe "
                f"({len(deaths)} deaths, {wedged_killed} wedge kills):\n"
                + last_log
            )
        stripe_indices = {k: stripes[k] for k in range(n_tasks)}
        merged, per_process = _merge_elastic(
            manifest, shard_docs, quarantined, stripe_indices, workload,
            True,
        )
        verdict = merged
        verdict["dropped"] += len(dropped)
        if verdict["first_invalid"] >= 0:
            verdict["first_invalid"] = kept[verdict["first_invalid"]]
        degraded = {
            "dead_workers": deaths,
            "requeued_stripes": sorted(set(requeued)),
            "quarantined_stripes": sorted(quarantined),
            "wedged_killed": wedged_killed,
            "quarantined_histories": sum(
                len(stripe_indices[k]) for k in quarantined
            ),
            "final_procs": fleet,
            "generations": gen + 1,
            "seq_final": man_seq,
        }
        info = {
            "n_procs": n_procs,
            "devices_per_proc": devices_per_proc,
            "dropped": len(dropped),
            "per_process": per_process,
            "global_mesh": True,
            "seq": seq,
            "degraded": degraded,
        }
        return verdict, info


if __name__ == "__main__":
    sys.exit(worker_main())
