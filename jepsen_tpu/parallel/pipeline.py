"""Pipelined bytes-to-verdict executor: overlap pack, staging, check.

BENCH_r05 measured the stream checker's end-to-end rate at ~8% of its
device-only rate (20,055 device vs 1,638 e2e hist/s; stream_10k 211 vs
19): parse → pack → transfer → check ran strictly serially on one
thread, so the device idled through every host phase and vice versa.
This module is the input-pipeline subsystem that closes that gap — the
same overlap discipline a training stack applies to data loading
(tf.data / Grain-style prefetch), applied to history verification:

    producer thread                 consumer (caller) thread
    ───────────────                 ────────────────────────
    chunk k+1: native thread-pool   chunk k:   device_put (async H2D)
               parse + host pack               dispatch checker program
               (GIL released for               block on chunk k-1's
               the whole native                verdict, convert to host
               batch)                          results

- **Host stage** (``produce``): runs on a dedicated producer thread.
  The family producers parse history bytes through the native
  thread-pool entry points (``fastpack.pack_files`` /
  ``stream_rows_files`` / ``elle_mops_files`` — ctypes releases the GIL
  for the whole multi-file call) with the digest-keyed per-file caches
  consulted first, then assemble HOST (numpy) batches with
  power-of-two shape bucketing so chunked packing reuses the jitted
  programs instead of recompiling per chunk.
- **Staging stage** (``place``): ``jax.device_put`` of the host batch —
  asynchronous H2D; with a mesh, the sharded placement from
  ``parallel.mesh``.
- **Check stage** (``check``): the family's jitted verdict program,
  optionally wrapped with ``donate_argnums=0`` so the staged input
  buffers are donated to the computation (the recycled staging slot:
  XLA reuses the donated bytes for temporaries/outputs instead of
  holding both generations live — double-buffer depth bounds peak
  footprint at 2 staged batches).  At most ``depth`` batches are in
  flight; the executor blocks on the OLDEST dispatch, so the device
  works through chunk k while the host packs chunk k+1.

Crash semantics: a stage failure on ANY chunk aborts the whole run with
:class:`PipelineError` — no verdict is returned for the failed chunk,
any later chunk, or any earlier chunk (partial results never escape, so
a caller can never mistake a crashed run's prefix for a full verdict
set).  ``tests/test_pipeline.py`` holds the differential contract
(pipelined ≡ serial for every family, including degenerate-history
host-fallback splices) and the crash-mid-pipeline proof.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable, Sequence

import numpy as np

#: histories per pipeline chunk — small enough that the first chunk
#: reaches the device quickly, large enough to amortize dispatch
DEFAULT_CHUNK = 64


class PipelineError(RuntimeError):
    """A pipeline stage crashed; no verdicts were emitted."""


@dataclass
class PipelineStats:
    """Executor timing evidence (the bench's utilization schema).

    ``stage_overlap_frac``: fraction of total stage busy time that ran
    concurrently with another stage — 0.0 for a strictly serial run,
    approaching ``1 - 1/stages`` for a perfectly overlapped one.
    ``device_idle_frac``: fraction of wall clock with no device work in
    flight (the executor's target is to drive this toward 0 once the
    first batch is staged)."""

    batches: int = 0
    histories: int = 0
    wall_s: float = 0.0
    produce_busy_s: float = 0.0
    place_busy_s: float = 0.0
    check_busy_s: float = 0.0
    stage_overlap_frac: float = 0.0
    device_idle_frac: float = 0.0

    def finalize(self) -> "PipelineStats":
        busy = self.produce_busy_s + self.place_busy_s + self.check_busy_s
        self.stage_overlap_frac = (
            max(0.0, busy - self.wall_s) / busy if busy > 0 else 0.0
        )
        self.device_idle_frac = (
            max(0.0, self.wall_s - self.check_busy_s) / self.wall_s
            if self.wall_s > 0
            else 0.0
        )
        return self


_STOP = object()


class _Crash:
    def __init__(self, index: int, exc: BaseException):
        self.index = index
        self.exc = exc


def run_pipeline(
    items: Sequence[Any],
    produce: Callable[[Any], Any],
    check: Callable[[Any], Any],
    *,
    place: Callable[[Any], Any] | None = None,
    collect: Callable[[Any], Any] | None = None,
    depth: int = 2,
) -> tuple[list[Any], PipelineStats]:
    """Run ``items`` through produce → place → check with overlap.

    ``produce(item)`` runs on the producer thread (host pack);
    ``place(host_batch)`` and ``check(placed)`` on the caller's thread —
    ``check`` must DISPATCH asynchronously (a jitted JAX program does);
    the executor blocks on the oldest in-flight result via
    ``collect(raw)`` (default: ``jax.block_until_ready`` + numpy
    conversion), keeping at most ``depth`` dispatches outstanding.

    Returns ``(results, stats)`` with one collected result per item, in
    order.  Any stage exception aborts with :class:`PipelineError` and
    NO results (see module docstring).
    """
    import jax

    if place is None:
        place = jax.device_put
    if collect is None:
        def collect(raw):
            jax.block_until_ready(raw)
            return jax.tree.map(np.asarray, raw)

    stats = PipelineStats()
    n = len(items)
    if n == 0:
        return [], stats
    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    abort = threading.Event()

    def put(obj) -> None:
        # bounded puts re-check the abort flag so a crashed consumer
        # can never wedge the producer behind a full queue
        while not abort.is_set():
            try:
                q.put(obj, timeout=0.1)
                return
            except queue.Full:
                continue

    def producer() -> None:
        i = 0
        try:
            for i, item in enumerate(items):
                if abort.is_set():
                    return
                t0 = time.perf_counter()
                host = produce(item)
                stats.produce_busy_s += time.perf_counter() - t0
                put((i, host))
            put(_STOP)
        except BaseException as e:  # noqa: BLE001 - re-raised by consumer
            put(_Crash(i, e))

    t_start = time.perf_counter()
    prod = threading.Thread(target=producer, daemon=True)
    prod.start()

    results: list[Any] = [None] * n
    in_flight: list[tuple[int, Any, float]] = []  # (index, raw, dispatch_t)
    last_ready = t_start

    def drain_one() -> None:
        nonlocal last_ready
        i, raw, t_disp = in_flight.pop(0)
        t0 = time.perf_counter()
        results[i] = collect(raw)
        t_ready = time.perf_counter()
        # device occupancy: the interval this batch actually had the
        # device, serialized against the previous batch's completion
        stats.check_busy_s += t_ready - max(t_disp, last_ready)
        last_ready = t_ready
        del t0

    try:
        while True:
            got = q.get()
            if got is _STOP:
                break
            if isinstance(got, _Crash):
                raise PipelineError(
                    f"pipeline produce stage crashed on batch "
                    f"{got.index}: {type(got.exc).__name__}: {got.exc}"
                ) from got.exc
            i, host = got
            t0 = time.perf_counter()
            placed = place(host)
            stats.place_busy_s += time.perf_counter() - t0
            t_disp = time.perf_counter()
            raw = check(placed)
            in_flight.append((i, raw, t_disp))
            del placed  # the staged slot recycles once check holds it
            while len(in_flight) >= max(1, depth):
                drain_one()
        while in_flight:
            drain_one()
    except PipelineError:
        abort.set()
        raise
    except Exception as e:
        abort.set()
        raise PipelineError(
            f"pipeline check stage crashed: {type(e).__name__}: {e}"
        ) from e
    finally:
        abort.set()
        prod.join(timeout=10.0)

    stats.batches = n
    stats.wall_s = time.perf_counter() - t_start
    return results, stats.finalize()


_DONATED_CACHE: dict = {}


def donated(
    check_fn: Callable[[Any], Any], key: tuple | None = None
) -> Callable[[Any], Any]:
    """Wrap a verdict program so the staged input batch is DONATED to
    the computation (``jax.jit(..., donate_argnums=0)``): XLA may reuse
    the staged buffers for temporaries and outputs, which is what makes
    the recycled double-buffered staging slot hold at ~2 batches of
    device memory instead of accumulating one per in-flight dispatch.

    ``key`` memoizes the wrapper: jit caches are per wrapper OBJECT, so
    a fresh ``jax.jit`` per family construction would re-trace every
    batch shape in every ``check_sources`` call (and defeat warm-up
    runs).  Families pass ``(kind, *contract_params)``; the same key
    always returns the same jitted program."""
    import jax

    if key is None:
        key = ("_fn", check_fn)
    got = _DONATED_CACHE.get(key)
    if got is None:
        got = _DONATED_CACHE[key] = jax.jit(check_fn, donate_argnums=0)
    return got


def _pow2_bucket(n: int, floor: int = 128) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


def _chunks(seq: Sequence[Any], size: int) -> list[Sequence[Any]]:
    size = max(1, size)
    return [seq[i : i + size] for i in range(0, len(seq), size)]


# ---------------------------------------------------------------------------
# Family producers: history BYTES (file paths) -> host-packed batches.
# Cache-first (digest-keyed per-file caches), then the native thread-pool
# multi-file parse, then the Python twin — identical substrate contract
# to the serial paths, differential-tested in tests/test_pipeline.py.
# ---------------------------------------------------------------------------


def _stream_substrates(paths: Sequence[Path], threads: int, use_cache: bool):
    """Per-path ``(cols, full)`` stream substrates, cache → native → Python."""
    from jepsen_tpu.checkers.stream_lin import _stream_rows
    from jepsen_tpu.history.fastpack import stream_rows_files
    from jepsen_tpu.history.store import read_history
    from jepsen_tpu.history.storecache import (
        load_stream_rows_cache,
        save_stream_rows_cache,
    )

    out: list = [None] * len(paths)
    misses = []
    if use_cache:
        for i, p in enumerate(paths):
            got = load_stream_rows_cache(p)
            if got is not None:
                out[i] = got
            else:
                misses.append(i)
    else:
        misses = list(range(len(paths)))
    if misses:
        native = stream_rows_files([paths[i] for i in misses], threads)
        for j, i in enumerate(misses):
            got = native[j] if native is not None else None
            if got is None:
                got = _stream_rows(read_history(paths[i]))
            out[i] = got
            if use_cache:
                save_stream_rows_cache(paths[i], got[0], got[1])
    return out


def _queue_substrates(paths: Sequence[Path], threads: int, use_cache: bool):
    """Per-path ``[n, 8]`` row matrices, cache → native → Python."""
    from jepsen_tpu.history.fastpack import pack_files
    from jepsen_tpu.history.rows import (
        load_rows_cache,
        rows_with_cache,
        save_rows_cache,
    )

    out: list = [None] * len(paths)
    misses = []
    if use_cache:
        for i, p in enumerate(paths):
            got = load_rows_cache(p)
            if got is not None:
                out[i] = got[1]
            else:
                misses.append(i)
    else:
        misses = list(range(len(paths)))
    if misses:
        native = pack_files([paths[i] for i in misses], threads)
        for j, i in enumerate(misses):
            got = native[j] if native is not None else None
            if got is not None:
                if use_cache:
                    save_rows_cache(paths[i], got[0], got[1])
                out[i] = got[1]
            else:
                out[i] = rows_with_cache(paths[i])[1]
    return out


def _elle_substrates(paths: Sequence[Path], threads: int, use_cache: bool):
    """Per-path ``(mat, meta)`` elle cell substrates, cache → native →
    Python (the ``elle_mops.npz`` layer)."""
    from jepsen_tpu.checkers.elle import elle_mops_for
    from jepsen_tpu.history.fastpack import elle_mops_files
    from jepsen_tpu.history.store import read_history
    from jepsen_tpu.history.storecache import (
        load_elle_mops_cache,
        save_elle_mops_cache,
    )

    out: list = [None] * len(paths)
    misses = []
    if use_cache:
        for i, p in enumerate(paths):
            got = load_elle_mops_cache(p)
            if got is not None:
                out[i] = got
            else:
                misses.append(i)
    else:
        misses = list(range(len(paths)))
    if misses:
        native = elle_mops_files([paths[i] for i in misses], threads)
        for j, i in enumerate(misses):
            got = native[j] if native is not None else None
            if got is None:
                got = elle_mops_for(read_history(paths[i]))
            out[i] = got
            if use_cache:
                save_elle_mops_cache(paths[i], got[0], got[1])
    return out


# ---------------------------------------------------------------------------
# Family pipelines: produce / place / check / convert per family.
# ---------------------------------------------------------------------------


@dataclass
class _Family:
    produce: Callable[[Any], Any]
    check: Callable[[Any], Any]
    place: Callable[[Any], Any]
    convert: Callable[[Any, Any], list[dict]]  # (chunk_item, collected)
    collect: Callable[[Any], Any] | None = None  # default: block + numpy


def _default_donate() -> bool:
    """Donate staged buffers only where the runtime can actually reuse
    them: the CPU backend leaves most donations unusable (and warns per
    compile), so donation is a chip-path behavior."""
    import jax

    return jax.default_backend() != "cpu"


def _pad_chunk(subs: list, n: int, sentinel) -> list:
    """Pad a short (tail) chunk up to ``n`` with sentinel substrates so
    every chunk shares one batch shape — the jitted program compiles
    once, not once more for the remainder chunk.  ``convert`` trims the
    pad rows by the true chunk length."""
    if len(subs) < n:
        subs = list(subs) + [sentinel] * (n - len(subs))
    return subs


#: empty-history sentinel substrate (``_stream_rows`` on no ops) — used
#: to pad tail chunks to the uniform batch shape
_STREAM_SENTINEL = (
    np.asarray([[0, 5, -1, -1, 0, 1]], np.int32),
    False,
)


def _stream_family(
    threads: int,
    use_cache: bool,
    append_fail: str,
    mesh=None,
    donate: bool | None = None,
    chunk_pad: int = 0,
) -> _Family:
    import jax

    from jepsen_tpu.checkers.stream_lin import (
        pack_stream_rows,
        stream_lin_tensor_check,
        stream_lin_tensors_to_results,
    )

    if donate is None:
        donate = _default_donate()

    def produce(chunk):
        subs = (
            _stream_substrates(chunk, threads, use_cache)
            if chunk and isinstance(chunk[0], (str, Path))
            else list(chunk)
        )
        subs = _pad_chunk(subs, chunk_pad, _STREAM_SENTINEL)
        n_max = max(m.shape[0] for m, _ in subs)
        hi = max(
            max(int(m[:, 2].max(initial=0)), int(m[:, 3].max(initial=0)))
            for m, _ in subs
        )
        batch = pack_stream_rows(
            subs,
            length=_pow2_bucket(n_max),
            space=_pow2_bucket(hi + 1),
            to_device=False,
        )
        return batch, [f for _, f in subs]

    base_check = lambda b: stream_lin_tensor_check(b, append_fail=append_fail)
    if mesh is not None:
        from jepsen_tpu.parallel.mesh import sharded_stream_lin

        check = lambda b: sharded_stream_lin(b, mesh, append_fail=append_fail)
        place = _mesh_stream_place(mesh)
    else:
        check = (
            donated(base_check, key=("stream", append_fail))
            if donate
            else base_check
        )
        place = jax.device_put

    def convert(item, collected):
        tensors, fulls = collected
        out = stream_lin_tensors_to_results(tensors, fulls)[: len(item)]
        for r in out:
            r["append-fail"] = append_fail
        return [{"stream": r} for r in out]

    def place_pair(pair):
        batch, fulls = pair
        return place(batch), fulls

    def check_pair(pair):
        batch, fulls = pair
        return check(batch), fulls

    def collect_pair(raw):
        tensors, fulls = raw
        jax.block_until_ready(tensors)
        return jax.tree.map(np.asarray, tensors), fulls

    return _Family(produce, check_pair, place_pair, convert, collect_pair)


def _mesh_stream_place(mesh):
    from jepsen_tpu.parallel.mesh import SEQ_AXIS, _hist_sharded

    def place(batch):
        if mesh.shape[SEQ_AXIS] == 1:
            return _hist_sharded(batch, mesh)
        return batch  # seq>1: sharded_stream_lin pads + places itself

    return place


def _queue_family(
    threads: int,
    use_cache: bool,
    delivery: str,
    mesh=None,
    donate: bool | None = None,
    chunk_pad: int = 0,
) -> _Family:
    import jax

    from jepsen_tpu.checkers.fused import combined_tensor_check
    from jepsen_tpu.checkers.queue_lin import queue_lin_tensors_to_results
    from jepsen_tpu.checkers.total_queue import _tensors_to_results
    from jepsen_tpu.history.encode import pack_row_matrices

    if donate is None:
        donate = _default_donate()

    def produce(chunk):
        mats = (
            _queue_substrates(chunk, threads, use_cache)
            if chunk and isinstance(chunk[0], (str, Path))
            else list(chunk)
        )
        mats = _pad_chunk(mats, chunk_pad, np.zeros((0, 8), np.int32))
        n_max = max(m.shape[0] for m in mats)
        vmax = max(
            (int(m[:, 4].max(initial=0)) for m in mats if m.shape[0]),
            default=0,
        )
        return pack_row_matrices(
            mats,
            length=_pow2_bucket(max(n_max, 1)),
            value_space=_pow2_bucket(vmax + 1),
            to_device=False,
        )

    base_check = lambda p: combined_tensor_check(p, delivery=delivery)
    if mesh is not None:
        from jepsen_tpu.parallel.mesh import shard_packed, sharded_check

        check = lambda p: sharded_check(p, mesh, delivery=delivery)
        place = lambda p: shard_packed(p, mesh)
    else:
        check = (
            donated(base_check, key=("queue", delivery))
            if donate
            else base_check
        )
        place = jax.device_put

    def convert(item, collected):
        tq, ql = collected
        tq_rows = _tensors_to_results(tq)[: len(item)]
        ql_rows = queue_lin_tensors_to_results(ql)[: len(item)]
        for b in ql_rows:
            # the serial path (check_queue_lin_batch) records the judged
            # contract level; a bare re-check inherits it from
            # results.json — dropping it would silently tighten verdicts
            b["delivery"] = delivery
        return [
            {"queue": a, "linear": b} for a, b in zip(tq_rows, ql_rows)
        ]

    return _Family(produce, check, place, convert)


def _elle_family(
    threads: int,
    use_cache: bool,
    model: str,
    mesh=None,
    donate: bool | None = None,
    chunk_pad: int = 0,
) -> _Family:
    """Elle chunks carry a degenerate-history splice: tensor-
    representable histories go through the fused device inference,
    degenerate ones through the host-inference oracle — the SAME splice
    contract as ``check_elle_batch`` (``split_elle_mops``)."""
    import jax

    from jepsen_tpu.checkers.elle import (
        ElleMopsMeta,
        _classify,
        _txn_graph_from_inferred,
        check_elle_cpu,
        elle_mops_check,
        split_elle_mops,
    )
    from jepsen_tpu.history.store import read_history

    if donate is None:
        donate = _default_donate()
    sentinel = (
        np.zeros((0, 8), np.int32),
        ElleMopsMeta(n_txns=0, txn_index=[], keys=[], degenerate=False),
    )

    if mesh is not None:
        from jepsen_tpu.parallel.mesh import HIST_AXIS

        mesh_h = mesh.shape[HIST_AXIS]
    else:
        mesh_h = 1

    def produce(chunk):
        from_paths = chunk and isinstance(chunk[0], (str, Path))
        subs = (
            _elle_substrates(chunk, threads, use_cache)
            if from_paths
            else [(m, g) for m, g in chunk]
        )
        subs = _pad_chunk(subs, chunk_pad, sentinel)
        live, mops, degen = split_elle_mops(subs)
        if mesh_h > 1 and live and len(live) % mesh_h:
            # degenerate histories shrank the LIVE batch below the
            # mesh's hist-axis divisibility: extend the sentinel pad
            # (tensor-checkable, trimmed by convert) and re-split
            subs = _pad_chunk(
                subs, len(subs) + mesh_h - len(live) % mesh_h, sentinel
            )
            live, mops, degen = split_elle_mops(subs)
        degen_results = []
        for i in degen:
            # tensor-unrepresentable history: host oracle (rare; see
            # elle_mops_for's degeneracy conditions)
            h = read_history(chunk[i]) if from_paths else None
            if h is None:
                raise PipelineError(
                    "degenerate elle history needs its ops for the host "
                    "fallback; pass file paths (or pre-check via "
                    "check_elle_batch)"
                )
            degen_results.append(check_elle_cpu(h, model=model))
        metas = [subs[i][1] for i in live]
        return mops, metas, live, degen, degen_results

    if mesh is not None:
        from jepsen_tpu.parallel.mesh import _hist_sharded

        place_mops = lambda m: _hist_sharded(m, mesh)
    else:
        place_mops = jax.device_put
    check_mops = donated(elle_mops_check) if donate and mesh is None else (
        elle_mops_check
    )

    def place(item):
        mops, metas, live, degen, degen_results = item
        if mops is not None:
            mops = place_mops(mops)
        return mops, metas, live, degen, degen_results

    def check(item):
        mops, metas, live, degen, degen_results = item
        raw = check_mops(mops) if mops is not None else None
        return raw, metas, live, degen, degen_results

    def collect(raw_tuple):
        raw, metas, live, degen, degen_results = raw_tuple
        if raw is not None:
            jax.block_until_ready(raw)
            raw = jax.tree.map(np.asarray, raw)
        return raw, metas, live, degen, degen_results

    def convert(chunk, collected):
        raw, metas, live, degen, degen_results = collected
        out: list = [None] * (len(live) + len(degen))
        for i, r in zip(degen, degen_results):
            out[i] = {"elle": r}
        if raw is not None:
            t, inf = raw
            g0, g1c, g2 = (np.asarray(x) for x in (t.g0, t.g1c, t.g2))
            g1a, g1b, bad = (
                np.asarray(x) for x in (inf.g1a, inf.g1b, inf.bad_keys)
            )
            counts = tuple(
                np.asarray(getattr(inf, f"{n}_edges"))
                for n in ("ww", "wr", "rw")
            )
            for b, i in enumerate(live):
                g = _txn_graph_from_inferred(b, metas[b], g1a, g1b, bad)
                out[i] = {
                    "elle": _classify(
                        g,
                        set(np.nonzero(g0[b])[0].tolist()),
                        set(np.nonzero(g1c[b])[0].tolist()),
                        set(np.nonzero(g2[b])[0].tolist()),
                        model=model,
                        edge_counts=tuple(int(c[b]) for c in counts),
                    )
                }
        return out[: len(chunk)]

    return _Family(produce, check, place, convert, collect)


def family_for(workload: str, **opts) -> _Family:
    common = dict(
        mesh=opts.get("mesh"),
        donate=opts.get("donate"),
        chunk_pad=opts.get("chunk_pad", 0),
    )
    if workload == "stream":
        return _stream_family(
            opts.get("threads", 0),
            opts.get("use_cache", True),
            opts.get("append_fail", "definite"),
            **common,
        )
    if workload == "queue":
        return _queue_family(
            opts.get("threads", 0),
            opts.get("use_cache", True),
            opts.get("delivery", "exactly-once"),
            **common,
        )
    if workload == "elle":
        return _elle_family(
            opts.get("threads", 0),
            opts.get("use_cache", True),
            opts.get("model", "serializable"),
            **common,
        )
    raise ValueError(
        f"no pipeline family for workload {workload!r} (the mutex "
        f"family's perf path is the classic host search — WGL_BENCH.md)"
    )


def check_sources(
    workload: str,
    sources: Sequence[Any],
    *,
    chunk: int = DEFAULT_CHUNK,
    serial: bool = False,
    depth: int = 2,
    **opts,
) -> tuple[list[dict], PipelineStats]:
    """Bytes-to-verdict over ``sources`` (file paths, or pre-exploded
    family substrates) through the pipeline executor.

    Returns ``(results, stats)``: one result dict per source, in order
    — ``{"queue": ..., "linear": ...}`` / ``{"stream": ...}`` /
    ``{"elle": ...}`` with exactly the serial checkers' content (the
    differential contract).  ``serial=True`` is the triage escape
    hatch: the same stages run strictly serially on the calling thread
    — byte-identical results, no overlap."""
    pad = chunk
    if opts.get("mesh") is not None:
        # sharded placement needs the batch axis divisible by the mesh's
        # hist extent; sentinel-pad each chunk up to the next multiple
        from jepsen_tpu.parallel.mesh import HIST_AXIS

        h = opts["mesh"].shape[HIST_AXIS]
        pad = ((chunk + h - 1) // h) * h
    opts.setdefault("chunk_pad", pad)
    fam = family_for(workload, **opts)
    items = _chunks(list(sources), chunk)
    if serial:
        import jax

        def default_collect(raw):
            jax.block_until_ready(raw)
            return jax.tree.map(np.asarray, raw)

        collect = fam.collect or default_collect
        stats = PipelineStats()
        t0 = time.perf_counter()
        collected = []
        for it in items:
            t = time.perf_counter()
            host = fam.produce(it)
            stats.produce_busy_s += time.perf_counter() - t
            t = time.perf_counter()
            placed = fam.place(host)
            stats.place_busy_s += time.perf_counter() - t
            t = time.perf_counter()
            collected.append(collect(fam.check(placed)))
            stats.check_busy_s += time.perf_counter() - t
        stats.batches = len(items)
        stats.wall_s = time.perf_counter() - t0
        stats.finalize()
    else:
        collected, stats = run_pipeline(
            items,
            fam.produce,
            fam.check,
            place=fam.place,
            collect=fam.collect,
            depth=depth,
        )
    results: list[dict] = []
    for it, col in zip(items, collected):
        results.extend(fam.convert(it, col))
    stats.histories = len(results)
    return results, stats


class PipelinedChecker:
    """Checker-protocol adapter for the CLI ``check`` path and the test
    runner: the family verdict computed from the history FILE through
    the pipeline (cache-first native substrate, device check), not from
    re-packed Op objects.  One shared run serves every sub-checker of
    the family (the queue workload surfaces as two keys).

    ``path=None`` resolves lazily from the runner's ``opts["out_dir"]``
    at check time (``run_test`` saves ``history.jsonl`` before the
    analysis phase) — the soak/test assembly wires checkers before the
    run dir exists.  When no file can be found (a storeless unit-test
    run), :meth:`_from_ops` checks the in-memory ops through the same
    convert path instead."""

    def __init__(self, workload: str, path, subkey: str, **opts):
        self.workload = workload
        self.path = path
        self.subkey = subkey
        self.name = subkey
        self._opts = dict(opts)
        self._shared = self._opts.pop("shared", None)

    def _resolve_path(self, opts):
        if self.path is not None:
            return self.path
        out_dir = (opts or {}).get("out_dir")
        if out_dir is None:
            return None
        from jepsen_tpu.history.store import HISTORY_FILE

        p = Path(out_dir) / HISTORY_FILE
        return p if p.is_file() else None

    def check(self, test, history, opts=None):
        if self._shared is not None and self.workload in self._shared:
            return self._shared[self.workload][0][self.subkey]
        path = self._resolve_path(opts)
        if path is not None:
            results, _ = check_sources(
                self.workload, [path], chunk=1, **self._opts
            )
        else:
            # no file (e.g. a storeless unit-test run): serial family
            # substrates from the in-memory ops — same convert path
            results = self._from_ops(history)
        if self._shared is not None:
            self._shared[self.workload] = results
        return results[0][self.subkey]

    def _from_ops(self, history):
        if self.workload == "stream":
            from jepsen_tpu.checkers.stream_lin import _stream_rows

            subs = [_stream_rows(history)]
        elif self.workload == "queue":
            from jepsen_tpu.history.rows import _rows_for

            subs = [_rows_for(history)]
        else:
            from jepsen_tpu.checkers.elle import elle_mops_for

            # degenerate single histories need their ops for the host
            # oracle; check_elle_batch handles the splice directly
            from jepsen_tpu.checkers.elle import check_elle_batch

            model = self._opts.get("model", "serializable")
            return [
                {"elle": check_elle_batch([history], model=model)[0]}
            ]
        results, _ = check_sources(
            self.workload, subs, chunk=1, serial=True, **self._opts
        )
        return results


def attach_pipelined_checkers(test, workload: str) -> bool:
    """Swap a built test's family checkers for pipeline-backed ones
    (``tools/soak.py`` and friends: the post-run analysis then runs
    bytes-to-verdict from the stored ``history.jsonl`` through the
    executor instead of re-packing Op objects on one thread).  Contract
    levels (delivery / append-fail / consistency model) are inherited
    from the checkers being replaced, so the verdict semantics cannot
    drift.  Returns True when the swap applied (False: family has no
    pipeline — e.g. mutex — or no composed checkers to swap)."""
    checkers = getattr(getattr(test, "checker", None), "checkers", None)
    if checkers is None:
        return False
    shared: dict = {}
    if workload == "queue" and {"queue", "linear"} <= set(checkers):
        delivery = getattr(
            checkers["linear"], "delivery", "exactly-once"
        )
        for sub in ("queue", "linear"):
            checkers[sub] = PipelinedChecker(
                "queue", None, sub, shared=shared, delivery=delivery
            )
        return True
    if workload == "stream" and "stream" in checkers:
        append_fail = getattr(
            checkers["stream"], "append_fail", "definite"
        )
        checkers["stream"] = PipelinedChecker(
            "stream", None, "stream", shared=shared,
            append_fail=append_fail,
        )
        return True
    if workload == "elle" and "elle" in checkers:
        model = getattr(checkers["elle"], "model", "serializable")
        checkers["elle"] = PipelinedChecker(
            "elle", None, "elle", shared=shared, model=model
        )
        return True
    return False
