"""Pipelined bytes-to-verdict executor: overlap pack, staging, check.

BENCH_r05 measured the stream checker's end-to-end rate at ~8% of its
device-only rate (20,055 device vs 1,638 e2e hist/s; stream_10k 211 vs
19): parse → pack → transfer → check ran strictly serially on one
thread, so the device idled through every host phase and vice versa.
This module is the input-pipeline subsystem that closes that gap — the
same overlap discipline a training stack applies to data loading
(tf.data / Grain-style prefetch), applied to history verification:

    producer thread                 consumer (caller) thread
    ───────────────                 ────────────────────────
    chunk k+1: native thread-pool   chunk k:   device_put (async H2D)
               parse + host pack               dispatch checker program
               (GIL released for               block on chunk k-1's
               the whole native                verdict, convert to host
               batch)                          results

- **Host stage** (``produce``): runs on a dedicated producer thread.
  The family producers parse history bytes through the native
  thread-pool entry points (``fastpack.pack_files`` /
  ``stream_rows_files`` / ``elle_mops_files`` — ctypes releases the GIL
  for the whole multi-file call) with the digest-keyed per-file caches
  consulted first, then assemble HOST (numpy) batches with
  power-of-two shape bucketing so chunked packing reuses the jitted
  programs instead of recompiling per chunk.
- **Staging stage** (``place``): ``jax.device_put`` of the host batch —
  asynchronous H2D; with a mesh, the sharded placement from
  ``parallel.mesh``.
- **Check stage** (``check``): the family's jitted verdict program,
  optionally wrapped with ``donate_argnums=0`` so the staged input
  buffers are donated to the computation (the recycled staging slot:
  XLA reuses the donated bytes for temporaries/outputs instead of
  holding both generations live — double-buffer depth bounds peak
  footprint at 2 staged batches).  At most ``depth`` batches are in
  flight; the executor blocks on the OLDEST dispatch, so the device
  works through chunk k while the host packs chunk k+1.

Crash semantics are ELASTIC by default (PR 13): failure isolation is
work-unit-granular, not run-granular.  A chunk whose
produce/place/dispatch/collect raises is retried once (on another lane
when one exists), then QUARANTINED — ``check_sources`` isolates the
quarantined chunk per history (each member re-runs alone, so one poison
history cannot condemn its chunk-mates) and the crasher(s) report
``unknown`` with the captured exception as evidence while every other
history's verdict survives.  A quarantine can never fold into ``valid``
(the composed verdict is at best ``unknown``; ``invalid`` still trumps
everything — the PR-8 precedence rule).  ``fail_fast=True`` restores
the PR-4 contract verbatim: a stage failure on ANY chunk aborts the
whole run with :class:`PipelineError` and NO verdicts — partial results
never escape.  ``tests/test_pipeline.py`` holds the differential
contract (pipelined ≡ serial for every family, including
degenerate-history host-fallback splices) and both crash contracts;
``tests/test_elastic.py`` holds the poison-history quarantine proofs.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
import traceback
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable, Sequence

import numpy as np

from jepsen_tpu.obs import metrics as obs_metrics
from jepsen_tpu.obs import trace as obs_trace

#: histories per pipeline chunk — small enough that the first chunk
#: reaches the device quickly, large enough to amortize dispatch
DEFAULT_CHUNK = 64


class PipelineError(RuntimeError):
    """A pipeline stage crashed; no verdicts were emitted (the
    ``fail_fast=True`` contract — elastic runs quarantine instead)."""


def _scrub_exc(e):
    """Drop frame locals from a captured exception's traceback before
    retaining it as quarantine evidence: the produce/place/check frames
    pin whole packed batches and device trees, and the evidence only
    ever formats the exception chain, never the frames.  Walks the
    whole __cause__/__context__ chain — an exception raised while
    handling another still carries the original's frames."""
    seen: set[int] = set()
    cur = e if isinstance(e, BaseException) else None
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        try:
            traceback.clear_frames(cur.__traceback__)
        except Exception:  # pragma: no cover - evidence must never raise
            pass
        cur = cur.__cause__ or cur.__context__
    return e


class Quarantined:
    """The final 'collected result' of a work unit (or single history)
    whose stage failures exhausted the retry budget: the executor keeps
    going and this object carries the captured evidence in the unit's
    result slot.  ``check_sources`` turns it into explicit
    ``unknown``-with-evidence verdict entries — a quarantine is always
    visible, never a silent drop, and can never fold into ``valid``."""

    __slots__ = ("index", "stage", "attempts", "errors")

    def __init__(self, index: int, stage: str, attempts, errors):
        self.index = index
        self.stage = stage
        self.attempts = list(attempts)
        self.errors = [_scrub_exc(e) for e in errors]

    def evidence(self) -> dict:
        return {
            "stage": self.stage,
            "attempts": self.attempts,
            "errors": [
                f"{type(e).__name__}: {e}" for e in self.errors
            ],
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"Quarantined(unit={self.index}, stage={self.stage!r}, "
            f"errors={self.evidence()['errors']})"
        )


def _counter_field(name: str, cast=int, **labels):
    """A PipelineStats attribute backed by its run-scoped registry —
    the stats object is a VIEW; the registry is the storage."""

    def get(self):
        return cast(self.metrics.value(name, **labels))

    def set(self, v):
        self.metrics.counter(name, **labels).set(v)

    return property(get, set)


class PipelineStats:
    """Executor timing evidence (the bench's utilization schema) — a
    VIEW over an obs metrics registry (``jepsen_tpu/obs/metrics.py``).
    Every field except the two derived fractions is backed by a counter
    in ``self.metrics`` (a per-run :class:`~jepsen_tpu.obs.metrics.Registry`);
    the stage busy-seconds bookkeeping that used to live separately in
    ``run_pipeline``, ``run_lanes``, and the serial path now goes
    through ONE accounting point (:meth:`add_busy`), which also mirrors
    cumulative totals into the process-global registry (the service
    ``/metrics`` endpoint reads those) and records the stage as a trace
    span when the flight recorder is on.

    ``stage_overlap_frac``: fraction of total stage busy time that ran
    concurrently with another stage — 0.0 for a strictly serial run,
    approaching ``1 - 1/stages`` for a perfectly overlapped one.
    ``device_idle_frac``: fraction of wall clock with no device work in
    flight (the executor's target is to drive this toward 0 once the
    first batch is staged).  Multi-lane runs (``lanes > 1``) report
    busy seconds SUMMED across lanes, and ``device_idle_frac`` against
    the ``lanes × wall`` device-time budget.  ``dropped`` counts
    sources excluded by the lanes path's size census (unreadable /
    zero-length files — each is logged AND counted in the global
    ``pipeline.files_dropped`` counter; never a silent truncation)."""

    def __init__(self, lanes: int = 1, dropped: int = 0):
        self.metrics = obs_metrics.Registry()
        self.lanes = lanes
        self.wall_s = 0.0
        self.stage_overlap_frac = 0.0
        self.device_idle_frac = 0.0
        if dropped:
            self.dropped = dropped

    batches = _counter_field("pipeline.batches")
    histories = _counter_field("pipeline.histories")
    dropped = _counter_field("pipeline.files_dropped")
    quarantined = _counter_field("pipeline.quarantined")
    unit_retries = _counter_field("pipeline.unit_retries")
    produce_busy_s = _counter_field(
        "pipeline.stage_busy_s", cast=float, stage="produce"
    )
    place_busy_s = _counter_field(
        "pipeline.stage_busy_s", cast=float, stage="place"
    )
    check_busy_s = _counter_field(
        "pipeline.stage_busy_s", cast=float, stage="check"
    )

    def add_busy(
        self, stage: str, t0: float, t1: float, track: str | None = None
    ) -> None:
        """THE stage accounting point (``t0``/``t1`` from
        ``time.perf_counter()``): run-scoped counter + global cumulative
        counter + per-batch check-latency sketch + trace span, in one
        call, so no executor keeps private busy-second arithmetic."""
        dt = t1 - t0
        self.metrics.counter("pipeline.stage_busy_s", stage=stage).inc(dt)
        obs_metrics.REGISTRY.counter(
            "pipeline.stage_busy_s", stage=stage
        ).inc(dt)
        if stage == "check":
            # the device-interval latency of one batch — the p50/p99
            # source for obs_overhead and the stats snapshot
            self.metrics.sketch("pipeline.check_batch_s").add(dt)
            obs_metrics.REGISTRY.sketch("pipeline.check_batch_s").add(dt)
        obs_trace.complete(f"pipeline.{stage}", t0, t1, track=track)

    def run_stage(self, stage: str, fn, arg, track: str | None = None):
        """Run ``fn(arg)`` as an accounted stage (busy time counted on
        success; a crashing stage aborts the run anyway)."""
        t0 = time.perf_counter()
        out = fn(arg)
        self.add_busy(stage, t0, time.perf_counter(), track=track)
        return out

    def note_retry(
        self, stage: str, index: int, exc: BaseException,
        lane: int | None = None,
    ) -> None:
        """One work-unit retry (elastic mode): run-scoped + global
        counters, and a flight-recorder event when the tracer is on —
        the requeue is countable after the run, never just a log line."""
        self.metrics.counter("pipeline.unit_retries").inc()
        obs_metrics.REGISTRY.counter("pipeline.unit_retries").inc()
        if obs_trace.is_enabled():
            obs_trace.event(
                "checker.unit_retry",
                args={
                    "unit": index,
                    "stage": stage,
                    "lane": lane,
                    "error": f"{type(exc).__name__}: {exc}",
                },
            )

    def note_quarantine(self, evidence: dict, histories: int = 1) -> None:
        """``histories`` final quarantined verdicts (elastic mode):
        counted per HISTORY in the run-scoped and global registries,
        plus a flight-recorder event carrying the evidence."""
        self.metrics.counter("pipeline.quarantined").inc(histories)
        obs_metrics.REGISTRY.counter("pipeline.quarantined").inc(histories)
        if obs_trace.is_enabled():
            obs_trace.event(
                "checker.quarantine",
                args={"histories": histories, **evidence},
            )

    def check_batch_quantile(self, q: float) -> float:
        return self.metrics.sketch("pipeline.check_batch_s").quantile(q)

    def finalize(self) -> "PipelineStats":
        busy = self.produce_busy_s + self.place_busy_s + self.check_busy_s
        self.stage_overlap_frac = (
            max(0.0, busy - self.wall_s) / busy if busy > 0 else 0.0
        )
        budget = self.wall_s * max(self.lanes, 1)
        self.device_idle_frac = (
            max(0.0, budget - self.check_busy_s) / budget
            if budget > 0
            else 0.0
        )
        return self


_STOP = object()
_UNSET = object()


class _Crash:
    def __init__(self, index: int, exc: BaseException):
        self.index = index
        self.exc = exc


class _Poison:
    """Producer → consumer marker (elastic mode): item ``index``'s
    produce stage failed past its retry; quarantine it and keep going."""

    def __init__(self, index: int, stage: str, errors):
        self.index = index
        self.stage = stage
        self.errors = list(errors)


def _default_collect(raw):
    """The collect contract every executor and the serial oracle share:
    block on the device tree, then convert it to host numpy."""
    import jax

    jax.block_until_ready(raw)
    return jax.tree.map(np.asarray, raw)


def run_pipeline(
    items: Sequence[Any],
    produce: Callable[[Any], Any],
    check: Callable[[Any], Any],
    *,
    place: Callable[[Any], Any] | None = None,
    collect: Callable[[Any], Any] | None = None,
    depth: int = 2,
    fail_fast: bool = False,
) -> tuple[list[Any], PipelineStats]:
    """Run ``items`` through produce → place → check with overlap.

    ``produce(item)`` runs on the producer thread (host pack);
    ``place(host_batch)`` and ``check(placed)`` on the caller's thread —
    ``check`` must DISPATCH asynchronously (a jitted JAX program does);
    the executor blocks on the oldest in-flight result via
    ``collect(raw)`` (default: ``jax.block_until_ready`` + numpy
    conversion), keeping at most ``depth`` dispatches outstanding.

    Returns ``(results, stats)`` with one collected result per item, in
    order.  Failure isolation is per work unit by default: a stage
    exception on item k is retried once, then item k's result slot
    holds a :class:`Quarantined` carrying the evidence while every
    other item completes.  ``fail_fast=True`` restores the abort-all
    contract: any stage exception raises :class:`PipelineError` and NO
    results escape (see module docstring).
    """
    import jax

    if place is None:
        place = jax.device_put
    if collect is None:
        collect = _default_collect

    stats = PipelineStats()
    n = len(items)
    if n == 0:
        return [], stats
    t_start = time.perf_counter()
    results: list[Any] = [None] * n
    if fail_fast:
        _run_pipeline_failfast(
            items, produce, check, place, collect, depth, stats, results,
            t_start,
        )
    else:
        _run_pipeline_elastic(
            items, produce, check, place, collect, depth, stats, results,
            t_start,
        )
    stats.batches = n
    stats.wall_s = time.perf_counter() - t_start
    return results, stats.finalize()


def _run_pipeline_failfast(
    items, produce, check, place, collect, depth, stats, results, t_start
) -> None:
    """The PR-4 abort-all executor: any stage exception raises
    :class:`PipelineError`, partial results never escape."""
    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    abort = threading.Event()

    def put(obj) -> None:
        # bounded puts re-check the abort flag so a crashed consumer
        # can never wedge the producer behind a full queue
        while not abort.is_set():
            try:
                q.put(obj, timeout=0.1)
                return
            except queue.Full:
                continue

    def producer() -> None:
        i = 0
        try:
            for i, item in enumerate(items):
                if abort.is_set():
                    return
                host = stats.run_stage("produce", produce, item)
                put((i, host))
            put(_STOP)
        except BaseException as e:  # noqa: BLE001 - re-raised by consumer
            put(_Crash(i, e))

    prod = threading.Thread(
        target=producer, name="pipeline-producer", daemon=True
    )
    prod.start()

    in_flight: list[tuple[int, Any, float]] = []  # (index, raw, dispatch_t)
    last_ready = t_start

    def drain_one() -> None:
        nonlocal last_ready
        i, raw, t_disp = in_flight.pop(0)
        results[i] = collect(raw)
        t_ready = time.perf_counter()
        # device occupancy: the interval this batch actually had the
        # device, serialized against the previous batch's completion
        stats.add_busy("check", max(t_disp, last_ready), t_ready)
        last_ready = t_ready

    try:
        while True:
            got = q.get()
            if got is _STOP:
                break
            if isinstance(got, _Crash):
                raise PipelineError(
                    f"pipeline produce stage crashed on batch "
                    f"{got.index}: {type(got.exc).__name__}: {got.exc}"
                ) from got.exc
            i, host = got
            placed = stats.run_stage("place", place, host)
            t_disp = time.perf_counter()
            raw = check(placed)
            in_flight.append((i, raw, t_disp))
            del placed  # the staged slot recycles once check holds it
            while len(in_flight) >= max(1, depth):
                drain_one()
        while in_flight:
            drain_one()
    except PipelineError:
        abort.set()
        raise
    except Exception as e:
        abort.set()
        raise PipelineError(
            f"pipeline check stage crashed: {type(e).__name__}: {e}"
        ) from e
    finally:
        abort.set()
        prod.join(timeout=10.0)


def _run_pipeline_elastic(
    items, produce, check, place, collect, depth, stats, results, t_start
) -> None:
    """Work-unit-granular failure isolation, single-lane shape: a
    failing stage is retried once in place (one producer, one consumer
    — there is no other lane to move to), then the item quarantines and
    every other item's verdict survives."""
    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    abort = threading.Event()

    def put(obj) -> None:
        while not abort.is_set():
            try:
                q.put(obj, timeout=0.1)
                return
            except queue.Full:
                continue

    def producer() -> None:
        i = 0
        try:
            for i, item in enumerate(items):
                if abort.is_set():
                    return
                errors: list[BaseException] = []
                host = _UNSET
                for attempt in range(2):
                    try:
                        host = stats.run_stage("produce", produce, item)
                        break
                    except Exception as e:
                        errors.append(e)
                        if attempt == 0:
                            stats.note_retry("produce", i, e)
                if host is _UNSET:
                    put(_Poison(i, "produce", errors))
                else:
                    put((i, host))
            put(_STOP)
        except BaseException as e:  # noqa: BLE001 - re-raised by consumer
            # SystemExit-class: quarantine is for failures, not for
            # cancellation — crash loud, same as the fail-fast path
            put(_Crash(i, e))

    prod = threading.Thread(
        target=producer, name="pipeline-producer", daemon=True
    )
    prod.start()

    in_flight: list[tuple[int, Any, float]] = []
    last_ready = t_start

    def drain_one() -> None:
        nonlocal last_ready
        i, raw, t_disp = in_flight.pop(0)
        errors: list[BaseException] = []
        got = _UNSET
        for attempt in range(2):
            try:
                # a dispatch error surfaces here (async programs raise
                # at block time)
                got = collect(raw)
                break
            except Exception as e:
                errors.append(e)
                if attempt == 0:
                    stats.note_retry("collect", i, e)
                    # a failed dispatch poisons the raw tree — blocking
                    # on it again just re-raises, so the one genuine
                    # retry re-runs the whole chain from items[i]
                    # (deterministic; retaining the packed host tree
                    # for this rare path would pin `depth` full chunks
                    # of host memory on the no-fault hot path)
                    try:
                        raw = check(
                            stats.run_stage(
                                "place",
                                place,
                                stats.run_stage("produce", produce, items[i]),
                            )
                        )
                    except Exception as e2:
                        errors.append(e2)
                        break
        if got is _UNSET:
            results[i] = Quarantined(i, "collect", ["main"], errors)
            last_ready = time.perf_counter()
            return
        results[i] = got
        t_ready = time.perf_counter()
        stats.add_busy("check", max(t_disp, last_ready), t_ready)
        last_ready = t_ready

    try:
        while True:
            got = q.get()
            if got is _STOP:
                break
            if isinstance(got, _Crash):
                raise PipelineError(
                    f"pipeline produce stage crashed on batch "
                    f"{got.index}: {type(got.exc).__name__}: {got.exc}"
                ) from got.exc
            if isinstance(got, _Poison):
                results[got.index] = Quarantined(
                    got.index, got.stage, ["producer"], got.errors
                )
                continue
            i, host = got
            errors = []
            raw = _UNSET
            stage = "place"
            for attempt in range(2):
                try:
                    stage = "place"
                    placed = stats.run_stage("place", place, host)
                    stage = "check"
                    t_disp = time.perf_counter()
                    raw = check(placed)
                    break
                except Exception as e:
                    errors.append(e)
                    if attempt == 0:
                        stats.note_retry(stage, i, e)
            if raw is _UNSET:
                results[i] = Quarantined(i, stage, ["main"], errors)
                continue
            in_flight.append((i, raw, t_disp))
            del placed  # the staged slot recycles once check holds it
            while len(in_flight) >= max(1, depth):
                drain_one()
        while in_flight:
            drain_one()
    finally:
        abort.set()
        prod.join(timeout=10.0)


def run_lanes(
    units: Sequence[Any],
    fams: Sequence["_Family"],
    *,
    depth: int = 2,
    fail_fast: bool = False,
) -> tuple[list[Any], PipelineStats]:
    """The N-lane generalization of :func:`run_pipeline`: one lane per
    family in ``fams`` (one per addressable device), each running the
    full produce → place → dispatch → collect loop on its own thread
    with its own double-buffered staging slot.  Lanes claim work units
    off ONE shared queue — an idle lane immediately takes the next
    (largest-remaining) unit, so no device waits on another lane's
    packing (steal-on-idle by construction).

    Failure isolation matches :func:`run_pipeline`: elastic by default
    — a unit whose stage raises is retried once on ANOTHER lane (when
    one is alive), then its result slot holds a :class:`Quarantined`
    while every other unit completes.  ``fail_fast=True`` restores the
    PR-5 contract: any lane failure aborts the whole run with
    :class:`PipelineError` and NO results."""
    n = len(units)
    results: list[Any] = [None] * n
    stats = PipelineStats(lanes=len(fams))
    if n == 0:
        return results, stats
    if not fail_fast:
        t_start = time.perf_counter()
        _run_lanes_elastic(units, fams, depth, stats, results)
        stats.wall_s = time.perf_counter() - t_start
        stats.batches = n
        return results, stats.finalize()
    return _run_lanes_failfast(units, fams, depth, stats, results)


def _run_lanes_failfast(
    units, fams, depth, stats, results
) -> tuple[list[Any], PipelineStats]:
    n = len(units)
    abort = threading.Event()
    failures: list[tuple[int, BaseException]] = []
    unit_q: queue.Queue = queue.Queue()
    for k in range(n):
        unit_q.put(k)

    def lane(i: int) -> None:
        # stage accounting goes straight through the shared stats view
        # (per-metric locks; no per-lane busy arrays to merge), with
        # each lane's spans on its own `laneN` track
        fam = fams[i]
        track = f"lane{i}"
        collect = fam.collect or _default_collect
        in_flight: list[tuple[int, Any, float]] = []
        last_ready = time.perf_counter()

        def drain_one():
            nonlocal last_ready
            k, raw, t_disp = in_flight.pop(0)
            results[k] = collect(raw)
            t_ready = time.perf_counter()
            stats.add_busy(
                "check", max(t_disp, last_ready), t_ready, track=track
            )
            last_ready = t_ready

        try:
            while not abort.is_set():
                try:
                    k = unit_q.get_nowait()
                except queue.Empty:
                    break
                host = stats.run_stage(
                    "produce", fam.produce, units[k], track=track
                )
                placed = stats.run_stage(
                    "place", fam.place, host, track=track
                )
                t_disp = time.perf_counter()
                raw = fam.check(placed)
                in_flight.append((k, raw, t_disp))
                del placed
                while len(in_flight) >= max(1, depth):
                    drain_one()
            while in_flight and not abort.is_set():
                drain_one()
        except BaseException as e:  # noqa: BLE001 - re-raised below
            abort.set()
            failures.append((i, e))

    t_start = time.perf_counter()
    threads_ = [
        threading.Thread(
            target=lane, args=(i,), name=f"lane{i}", daemon=True
        )
        for i in range(len(fams))
    ]
    for t in threads_:
        t.start()
    for t in threads_:
        t.join()
    stats.wall_s = time.perf_counter() - t_start
    if failures:
        i, e = failures[0]
        raise PipelineError(
            f"lane {i} crashed: {type(e).__name__}: {e}"
        ) from e
    stats.batches = n
    return results, stats.finalize()


def _run_lanes_elastic(units, fams, depth, stats, results) -> None:
    """The elastic N-lane executor: units carry their attempt history
    ``(k, attempts)`` through the shared queue; a unit that failed on
    lane i bounces back for a DIFFERENT live lane to retry (bounded
    bounce so the endgame cannot spin), and a second failure
    quarantines it.  Lanes run until every unit holds a final result —
    a lane never exits while a retried unit could still land on it."""
    n = len(units)
    n_lanes = len(fams)
    lock = threading.Lock()
    done = threading.Event()
    state = {"completed": 0}
    alive = set(range(n_lanes))
    errors_by_unit: dict[int, list[BaseException]] = {}
    bounce: dict[int, int] = {}
    unit_q: queue.Queue = queue.Queue()
    for k in range(n):
        unit_q.put((k, ()))

    def finalize(k: int, value) -> None:
        with lock:
            results[k] = value
            state["completed"] += 1
            if state["completed"] >= n:
                done.set()

    def fail(k: int, stage: str, attempts, e: BaseException) -> None:
        with lock:
            errors_by_unit.setdefault(k, []).append(_scrub_exc(e))
            errs = list(errors_by_unit[k])
        if len(attempts) >= 2:
            finalize(k, Quarantined(k, stage, list(attempts), errs))
        else:
            stats.note_retry(
                stage, k, e, lane=attempts[-1] if attempts else None
            )
            unit_q.put((k, tuple(attempts)))

    def lane(i: int) -> None:
        fam = fams[i]
        track = f"lane{i}"
        collect = fam.collect or _default_collect
        in_flight: list[tuple[int, Any, float, tuple]] = []
        last_ready = time.perf_counter()
        # the unit this lane holds that is in NEITHER unit_q nor
        # in_flight nor results — the lane-level crash handler must
        # return it to the pool or the run loses it and never finishes
        current: tuple[int, tuple] | None = None

        def drain_one() -> None:
            nonlocal last_ready, current
            k, raw, t_disp, attempts = in_flight.pop(0)
            current = (k, attempts)
            try:
                got = collect(raw)
            except Exception as e:
                fail(k, "collect", attempts, e)
                current = None
                last_ready = time.perf_counter()
                return
            t_ready = time.perf_counter()
            stats.add_busy(
                "check", max(t_disp, last_ready), t_ready, track=track
            )
            last_ready = t_ready
            finalize(k, got)
            current = None

        try:
            while True:
                if done.is_set() and not in_flight:
                    break
                try:
                    k, attempts = unit_q.get(timeout=0.05)
                except queue.Empty:
                    if in_flight:
                        drain_one()
                    continue
                current = (k, attempts)
                if attempts and attempts[-1] == i:
                    # retried unit, and THIS lane failed it: hand it to
                    # a different live lane when one exists (bounded
                    # bounce — after that, run it here rather than spin)
                    with lock:
                        others = len(alive) > 1
                        if others and bounce.get(k, 0) < 4 * n_lanes:
                            bounce[k] = bounce.get(k, 0) + 1
                        else:
                            others = False
                    if others:
                        unit_q.put((k, attempts))
                        current = None
                        time.sleep(0.01)
                        continue
                att = attempts + (i,)
                stage = "produce"
                try:
                    host = stats.run_stage(
                        "produce", fam.produce, units[k], track=track
                    )
                    stage = "place"
                    placed = stats.run_stage(
                        "place", fam.place, host, track=track
                    )
                    stage = "check"
                    t_disp = time.perf_counter()
                    raw = fam.check(placed)
                except Exception as e:
                    fail(k, stage, att, e)
                    current = None
                    continue
                in_flight.append((k, raw, t_disp, att))
                current = None
                del placed
                while len(in_flight) >= max(1, depth):
                    drain_one()
            with lock:
                alive.discard(i)
        except BaseException as e:  # noqa: BLE001 - executor-level crash:
            # the lane dies; its in-flight units return to the pool, and
            # the LAST lane out quarantines whatever is still queued so
            # the run always terminates with one result per unit
            if current is not None:
                ck, catt = current
                try:
                    fail(ck, "lane", tuple(catt) + (i,), e)
                except BaseException:  # noqa: BLE001 - fail() itself broke
                    finalize(
                        ck, Quarantined(ck, "lane", list(catt) + [i], [e])
                    )
            for k, _raw, _t, attempts in in_flight:
                try:
                    fail(k, "collect", attempts, e)
                except BaseException:  # noqa: BLE001 - fail() itself broke
                    finalize(
                        k, Quarantined(k, "collect", list(attempts), [e])
                    )
            with lock:
                alive.discard(i)
                last = not alive
            if last and not done.is_set():
                while True:
                    try:
                        k, attempts = unit_q.get_nowait()
                    except queue.Empty:
                        break
                    finalize(
                        k, Quarantined(k, "lane", list(attempts) + [i], [e])
                    )

    threads_ = [
        threading.Thread(
            target=lane, args=(i,), name=f"lane{i}", daemon=True
        )
        for i in range(n_lanes)
    ]
    for t in threads_:
        t.start()
    for t in threads_:
        t.join()


_DONATED_CACHE: dict = {}


def donated(
    check_fn: Callable[[Any], Any], key: tuple | None = None
) -> Callable[[Any], Any]:
    """Wrap a verdict program so the staged input batch is DONATED to
    the computation (``jax.jit(..., donate_argnums=0)``): XLA may reuse
    the staged buffers for temporaries and outputs, which is what makes
    the recycled double-buffered staging slot hold at ~2 batches of
    device memory instead of accumulating one per in-flight dispatch.

    ``key`` memoizes the wrapper: jit caches are per wrapper OBJECT, so
    a fresh ``jax.jit`` per family construction would re-trace every
    batch shape in every ``check_sources`` call (and defeat warm-up
    runs).  Families pass ``(kind, *contract_params)``; the same key
    always returns the same jitted program."""
    import jax

    if key is None:
        key = ("_fn", check_fn)
    got = _DONATED_CACHE.get(key)
    if got is None:
        got = _DONATED_CACHE[key] = jax.jit(check_fn, donate_argnums=0)
    return got


def _pow2_bucket(n: int, floor: int = 128) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


class StagingRing:
    """Donation-aware staging-buffer ring for one coalescing bucket
    (ISSUE 20): ``depth`` recycled slots of preallocated host arrays at
    the bucket's fixed ``[batch, length]`` shape, so steady-state
    dispatch allocates NOTHING on the host side — entries are copied
    into a recycled slot, staged to the device in one put per plane,
    and the device copies are donated to the batched program
    (:func:`jepsen_tpu.checkers.segmented.seg_queue_batch_program`).

    Discipline: the dispatcher ``acquire``s a slot, fills it, and
    launches; the collector ``release``s it only AFTER materializing
    the results (``np.asarray`` blocks on the computation), so a slot
    is never overwritten while a launch could still read it.  ``depth``
    is the dispatch pipelining bound — with depth 2 the next
    super-batch stages while the previous one computes."""

    def __init__(self, batch: int, length: int, depth: int = 2):
        self.batch = batch
        self.length = length
        self._free: queue.Queue = queue.Queue()
        for _ in range(max(1, depth)):
            self._free.put({
                "f": np.full((batch, length), -1, np.int32),
                "typ": np.full((batch, length), -1, np.int32),
                "val": np.zeros((batch, length), np.int32),
                "pos": np.zeros((batch, length), np.int32),
                "mask": np.zeros((batch, length), bool),
            })

    def acquire(self, timeout: float | None = None):
        try:
            return self._free.get(timeout=timeout)
        except queue.Empty:
            return None

    def release(self, slot) -> None:
        self._free.put(slot)

    def fill(self, slot, preps) -> None:
        """Copy ``len(preps)`` prepared segments into the slot's rows;
        rows past the fill are masked out (the program sees them as
        empty segments), so every launch runs at the ONE compiled
        ``[batch, length]`` shape the warmup covered."""
        n = len(preps)
        for i, p in enumerate(preps):
            slot["f"][i] = p["f"]
            slot["typ"][i] = p["typ"]
            slot["val"][i] = p["val"]
            slot["pos"][i] = p["pos"]
            slot["mask"][i] = p["mask"]
        if n < self.batch:
            slot["mask"][n:] = False


def dispatch_coalesced(slot, V: int, donate: bool | None = None):
    """Stage one filled ring slot and launch the batched queue program
    — the pre-coalesced-bucket dispatch entry the service batcher
    calls.  Returns the six ``[batch, V]`` device stat planes (async on
    real accelerators; the caller demuxes after materializing)."""
    import jax.numpy as jnp

    from jepsen_tpu.checkers.segmented import seg_queue_batch_program

    if donate is None:
        donate = _default_donate()
    return seg_queue_batch_program(
        jnp.asarray(slot["f"]), jnp.asarray(slot["typ"]),
        jnp.asarray(slot["val"]), jnp.asarray(slot["pos"]),
        jnp.asarray(slot["mask"]), int(V), donate=donate,
    )


def _chunks(seq: Sequence[Any], size: int) -> list[Sequence[Any]]:
    size = max(1, size)
    return [seq[i : i + size] for i in range(0, len(seq), size)]


# ---------------------------------------------------------------------------
# Family producers: history BYTES (file paths) -> host-packed batches.
# Cache-first — since PR 7 the cache IS the `.jtc` columnar substrate
# (COLUMNAR.md: one mmap-able CRC-checksummed file per history,
# consulted by the load_* functions below with legacy-npz fallback for
# pre-format stores) — then the native thread-pool multi-file pass
# (which itself serves stat-fresh `.jtc` blocks with zero parse, GIL
# released), then the Python twin — identical substrate contract to the
# serial paths, differential-tested in tests/test_pipeline.py and
# tests/test_columnar.py.
# ---------------------------------------------------------------------------


def _stripe_indices(n: int, part: int, n_parts: int) -> list[int]:
    return list(range(part, n, n_parts))


def _native_stripe(
    native_fn, paths, misses, stripe, threads, part, n_parts,
    use_jtc=True,
):
    """Native multi-file results aligned with ``misses`` (stripe-local
    positions).  A fully-missed stripe goes through the striped-cursor
    native entry over the SHARED full path list (no per-lane sublist,
    no shared cursor between concurrent lanes); partial misses (cache
    hits in between) fall back to a compacted per-subset call.
    ``use_jtc=False`` (a ``use_cache=False`` caller) disables the native
    ``.jtc`` substrate serve so the batch genuinely parses."""
    if n_parts > 1 and len(misses) == len(stripe):
        got = native_fn(
            paths, threads, part=part, n_parts=n_parts, use_jtc=use_jtc
        )
        if got is None:
            return None
        return [got[i] for i in stripe]
    return native_fn(
        [paths[stripe[j]] for j in misses], threads, use_jtc=use_jtc
    )


def _stream_substrates(
    paths: Sequence[Path],
    threads: int,
    use_cache: bool,
    part: int = 0,
    n_parts: int = 1,
):
    """``(cols, full)`` stream substrates for indices ``part::n_parts``
    of ``paths`` (default: all), cache → native → Python."""
    from jepsen_tpu.checkers.stream_lin import _stream_rows
    from jepsen_tpu.history.fastpack import stream_rows_files
    from jepsen_tpu.history.store import read_history
    from jepsen_tpu.history.storecache import (
        load_stream_rows_cache,
        save_stream_rows_cache,
    )

    stripe = _stripe_indices(len(paths), part, n_parts)
    out: list = [None] * len(stripe)
    misses = []
    if use_cache:
        for j, i in enumerate(stripe):
            got = load_stream_rows_cache(paths[i])
            if got is not None:
                out[j] = got
            else:
                misses.append(j)
    else:
        misses = list(range(len(stripe)))
    if misses:
        native = _native_stripe(
            stream_rows_files, paths, misses, stripe, threads, part,
            n_parts, use_jtc=use_cache,
        )
        for k, j in enumerate(misses):
            got = native[k] if native is not None else None
            if got is None:
                got = _stream_rows(read_history(paths[stripe[j]]))
            out[j] = got
            if use_cache:
                save_stream_rows_cache(paths[stripe[j]], got[0], got[1])
    return out


def _queue_substrates(
    paths: Sequence[Path],
    threads: int,
    use_cache: bool,
    part: int = 0,
    n_parts: int = 1,
):
    """``[n, 8]`` row matrices for indices ``part::n_parts`` of
    ``paths`` (default: all), cache → native → Python."""
    from jepsen_tpu.history.fastpack import pack_files
    from jepsen_tpu.history.rows import (
        load_rows_cache,
        rows_with_cache,
        save_rows_cache,
    )

    stripe = _stripe_indices(len(paths), part, n_parts)
    out: list = [None] * len(stripe)
    misses = []
    if use_cache:
        for j, i in enumerate(stripe):
            got = load_rows_cache(paths[i])
            if got is not None:
                out[j] = got[1]
            else:
                misses.append(j)
    else:
        misses = list(range(len(stripe)))
    if misses:
        native = _native_stripe(
            pack_files, paths, misses, stripe, threads, part, n_parts,
            use_jtc=use_cache,
        )
        for k, j in enumerate(misses):
            got = native[k] if native is not None else None
            if got is not None:
                if use_cache:
                    save_rows_cache(paths[stripe[j]], got[0], got[1])
                out[j] = got[1]
            elif use_cache:
                out[j] = rows_with_cache(paths[stripe[j]])[1]
            else:
                # no-cache caller: the fallback must parse too, not
                # sneak the substrate/npz in through the load-through
                from jepsen_tpu.history.rows import _rows_for
                from jepsen_tpu.history.store import read_history

                out[j] = _rows_for(read_history(paths[stripe[j]]))
    return out


def _elle_substrates(
    paths: Sequence[Path],
    threads: int,
    use_cache: bool,
    part: int = 0,
    n_parts: int = 1,
):
    """``(mat, meta)`` elle cell substrates for indices
    ``part::n_parts`` of ``paths`` (default: all), cache → native →
    Python (the ``elle_mops.npz`` layer)."""
    from jepsen_tpu.checkers.elle import elle_mops_for
    from jepsen_tpu.history.fastpack import elle_mops_files
    from jepsen_tpu.history.store import read_history
    from jepsen_tpu.history.storecache import (
        load_elle_mops_cache,
        save_elle_mops_cache,
    )

    stripe = _stripe_indices(len(paths), part, n_parts)
    out: list = [None] * len(stripe)
    misses = []
    if use_cache:
        for j, i in enumerate(stripe):
            got = load_elle_mops_cache(paths[i])
            if got is not None:
                out[j] = got
            else:
                misses.append(j)
    else:
        misses = list(range(len(stripe)))
    if misses:
        native = _native_stripe(
            elle_mops_files, paths, misses, stripe, threads, part,
            n_parts, use_jtc=use_cache,
        )
        for k, j in enumerate(misses):
            got = native[k] if native is not None else None
            if got is None:
                got = elle_mops_for(read_history(paths[stripe[j]]))
            out[j] = got
            if use_cache:
                save_elle_mops_cache(paths[stripe[j]], got[0], got[1])
    return out


def _wgl_substrates(
    paths: Sequence[Path],
    threads: int,
    use_cache: bool,
    part: int = 0,
    n_parts: int = 1,
):
    """``[n, 8]`` mutex WGL cell matrices for indices ``part::n_parts``
    of ``paths`` (default: all), cache → native → Python.  An entry may
    be None (a history with out-of-int32 fields — unrepresentable as
    cells); the family producer then derives the ops from the parsed
    history instead."""
    from jepsen_tpu.checkers.wgl_pcomp import wgl_cells_for
    from jepsen_tpu.history.fastpack import wgl_cells_files
    from jepsen_tpu.history.store import read_history
    from jepsen_tpu.history.storecache import (
        load_wgl_cells_cache,
        save_wgl_cells_cache,
    )

    stripe = _stripe_indices(len(paths), part, n_parts)
    out: list = [None] * len(stripe)
    misses = []
    if use_cache:
        for j, i in enumerate(stripe):
            got = load_wgl_cells_cache(paths[i])
            if got is not None:
                out[j] = got
            else:
                misses.append(j)
    else:
        misses = list(range(len(stripe)))
    if misses:
        native = _native_stripe(
            wgl_cells_files, paths, misses, stripe, threads, part,
            n_parts, use_jtc=use_cache,
        )
        for k, j in enumerate(misses):
            got = native[k] if native is not None else None
            if got is None:
                got = wgl_cells_for(read_history(paths[stripe[j]]))
            out[j] = got
            if use_cache and got is not None:
                save_wgl_cells_cache(paths[stripe[j]], got)
    return out


class _Stripe(Sequence):
    """A work unit of the lanes executor: the ``part``-th residue class
    (mod ``n_parts``) of one SHARED size-ordered path list.  Behaves
    like the list of its paths (the family producers index and measure
    it), while the producers' native calls stride the shared array via
    the striped-cursor entry points instead of materializing sublists.
    ``gids`` carries each stripe position's ORIGINAL source index (the
    size ordering permutes them) for reduce-mode counterexamples."""

    def __init__(
        self, paths: list, part: int, n_parts: int, gids: list | None = None
    ):
        self.paths = paths
        self.part = part
        self.n_parts = n_parts
        self._idx = _stripe_indices(len(paths), part, n_parts)
        self.gids = gids

    def indices(self) -> list[int]:
        return self._idx

    def __len__(self) -> int:
        return len(self._idx)

    def __getitem__(self, j):
        return self.paths[self._idx[j]]


class _Unit(list):
    """A plain chunk that also carries its sources' global indices
    (reduce mode: the device-side index-pmin reduces over these)."""

    def __init__(self, items, gids):
        super().__init__(items)
        self.gids = gids


#: int32 max — the gid of pad/sentinel batch positions (always valid,
#: and even if one misclassified it would lose every index-pmin)
_GID_PAD = np.iinfo(np.int32).max


def _gids_of(chunk) -> list[int]:
    gids = getattr(chunk, "gids", None)
    if gids is None:
        return list(range(len(chunk)))
    return list(gids)


# ---------------------------------------------------------------------------
# Global-mesh lane staging (PIPELINE.md §Global mesh): each cooperating
# process owns one Podracer-style input lane — census → stripes → pack →
# stage — and produces exactly its contiguous row block of every global
# device batch.  These helpers are the cross-host SHAPE CONTRACT: all
# lanes must agree on the padded lane height and on every packed static
# (L/V for queue, T/M/V/K/R for elle), or the processes would jit
# different programs and the collectives would deadlock.  Agreement
# costs one small KV exchange of raw maxima per chunk (never cell
# data); `pack_row_matrices`/`pack_elle_mop_mats` then bucket those
# maxima identically on every host.
# ---------------------------------------------------------------------------


def gm_lane_plan(
    n_rows: int, lanes: int, quantum: int
) -> tuple[int, list[tuple[int, int]]]:
    """``(b_l, bounds)`` — the common padded lane height (a multiple of
    ``quantum``, the rows-per-device granule of the global hist axis)
    and each lane's real-row interval ``[lo, hi)`` of the chunk.  Lane
    blocks are contiguous in chunk order, so one lane's parse output IS
    its process-local block of the global batch — no row shuffling
    between hosts."""
    import math

    from jepsen_tpu.history.encode import _round_up

    b_l = _round_up(math.ceil(n_rows / max(1, lanes)), quantum)
    bounds = [
        (min(p * b_l, n_rows), min((p + 1) * b_l, n_rows))
        for p in range(lanes)
    ]
    return b_l, bounds


def gm_stage_queue_lane(paths, threads: int = 0, use_cache: bool = True):
    """Stage one lane's queue rows (cache → native → Python, exactly the
    serial substrate contract) and report the raw pack maxima the lanes
    must exchange: ``(mats, (n_max, vmax))``."""
    mats = (
        _queue_substrates([Path(p) for p in paths], threads, use_cache)
        if paths
        else []
    )
    n_max = max((m.shape[0] for m in mats), default=0)
    vmax = max(
        (int(m[:, 4].max(initial=0)) for m in mats if m.shape[0]), default=0
    )
    return mats, (n_max, vmax)


def gm_pack_queue_lane(mats, b_l: int, length: int, value_space: int):
    """Pack one lane's row matrices — sentinel-padded to the agreed lane
    height ``b_l`` — into host-side ``PackedHistories`` columns with the
    fleet-agreed ``(L, V)`` statics (pad rows are all-masked, synthesized
    valid by every checker)."""
    from jepsen_tpu.history.encode import pack_row_matrices

    empty = np.zeros((0, 8), np.int32)
    mats = list(mats) + [empty] * (b_l - len(mats))
    return pack_row_matrices(
        mats, length=length, value_space=value_space, to_device=False
    )


def gm_stage_elle_lane(paths, threads: int = 0, use_cache: bool = True):
    """Stage one lane's elle micro-op substrates and split them on THE
    degeneracy contract (``split_elle_mops`` semantics): returns
    ``(mats_metas, live, degen, maxima)`` where ``live``/``degen`` are
    lane-local row positions and ``maxima`` is the raw ``(n_txns, cells,
    val, key, rpos)`` tuple the lanes exchange to agree on packed
    statics.  Degenerate rows stay on THIS lane's host for the oracle
    fallback — the splice point is the lane (= shard) boundary."""
    mm = (
        _elle_substrates([Path(p) for p in paths], threads, use_cache)
        if paths
        else []
    )
    live = [i for i, (_, g) in enumerate(mm) if not g.degenerate]
    degen = [i for i, (_, g) in enumerate(mm) if g.degenerate]
    t_max = max((mm[i][1].n_txns for i in live), default=0)
    m_max = max((mm[i][0].shape[0] for i in live), default=0)

    def col(c: int) -> int:
        return max(
            (
                int(mm[i][0][:, c].max(initial=-1))
                for i in live
                if mm[i][0].shape[0]
            ),
            default=-1,
        )

    return mm, live, degen, (t_max, m_max, col(3), col(2), col(5))


def gm_pack_elle_lane(mats_metas, live, b_live: int, n_txns: int, at_least):
    """Pack one lane's LIVE elle rows — sentinel-padded to the agreed
    live lane height — with fleet-agreed statics: ``n_txns`` (= T) plus
    the raw ``(cells, val, key, rpos)`` fleet maxima folded into the
    pow2 buckets by ``pack_elle_mop_mats(at_least=...)``."""
    from jepsen_tpu.checkers.elle import ElleMopsMeta, pack_elle_mop_mats

    mats = [mats_metas[i][0] for i in live]
    metas = [mats_metas[i][1] for i in live]
    pad = b_live - len(mats)
    mats += [np.zeros((0, 8), np.int32)] * pad
    metas += [
        ElleMopsMeta(n_txns=0, txn_index=[], keys=[], degenerate=False)
    ] * pad
    return pack_elle_mop_mats(
        mats, metas, n_txns=n_txns, to_device=False, at_least=tuple(at_least)
    )


# ---------------------------------------------------------------------------
# Family pipelines: produce / place / check / convert per family.
# ---------------------------------------------------------------------------


@dataclass
class _Family:
    produce: Callable[[Any], Any]
    check: Callable[[Any], Any]
    place: Callable[[Any], Any]
    convert: Callable[[Any, Any], list[dict]]  # (chunk_item, collected)
    collect: Callable[[Any], Any] | None = None  # default: block + numpy
    # reduce mode only: (chunk_item, collected) -> {"n_invalid",
    # "first_invalid" (chunk-local), ...} — the two-scalar batch verdict
    reduce_convert: Callable[[Any, Any], dict] | None = None


def _default_donate() -> bool:
    """Donate staged buffers only where the runtime can actually reuse
    them: the CPU backend leaves most donations unusable (and warns per
    compile), so donation is a chip-path behavior."""
    import jax

    return jax.default_backend() != "cpu"


def _pad_chunk(subs: list, n: int, sentinel) -> list:
    """Pad a short (tail) chunk up to ``n`` with sentinel substrates so
    every chunk shares one batch shape — the jitted program compiles
    once, not once more for the remainder chunk.  ``convert`` trims the
    pad rows by the true chunk length."""
    if len(subs) < n:
        subs = list(subs) + [sentinel] * (n - len(subs))
    return subs


#: empty-history sentinel substrate (``_stream_rows`` on no ops) — used
#: to pad tail chunks to the uniform batch shape
_STREAM_SENTINEL = (
    np.asarray([[0, 5, -1, -1, 0, 1]], np.int32),
    False,
)


def _stream_family(
    threads: int,
    use_cache: bool,
    append_fail: str,
    mesh=None,
    donate: bool | None = None,
    chunk_pad: int = 0,
    device=None,
    reduce: bool = False,
) -> _Family:
    import jax

    from jepsen_tpu.checkers.stream_lin import (
        pack_stream_rows,
        stream_lin_tensor_check,
        stream_lin_tensors_to_results,
    )

    if donate is None:
        donate = _default_donate()

    def produce(chunk):
        if isinstance(chunk, _Stripe):
            subs = _stream_substrates(
                chunk.paths, threads, use_cache, chunk.part, chunk.n_parts
            )
        elif chunk and isinstance(chunk[0], (str, Path)):
            subs = _stream_substrates(chunk, threads, use_cache)
        else:
            subs = list(chunk)
        subs = _pad_chunk(subs, chunk_pad, _STREAM_SENTINEL)
        n_max = max(m.shape[0] for m, _ in subs)
        hi = max(
            max(int(m[:, 2].max(initial=0)), int(m[:, 3].max(initial=0)))
            for m, _ in subs
        )
        batch = pack_stream_rows(
            subs,
            length=_pow2_bucket(n_max),
            space=_pow2_bucket(hi + 1),
            to_device=False,
        )
        return batch, [f for _, f in subs]

    base_check = lambda b: stream_lin_tensor_check(b, append_fail=append_fail)
    if mesh is not None:
        from jepsen_tpu.parallel.mesh import (
            sharded_stream_lin,
            sharded_stream_verdict,
        )

        check = lambda b: sharded_stream_lin(
            b, mesh, append_fail=append_fail
        )
        place = _mesh_stream_place(mesh)
    else:
        if reduce:
            raise ValueError("reduce mode needs a mesh")
        check = (
            donated(base_check, key=("stream", append_fail))
            if donate
            else base_check
        )
        place = _device_put_on(device)

    if reduce:
        return _reduced_family(
            lambda chunk: produce(chunk)[0],  # drop the fulls channel
            lambda batch: batch.type.shape[0],
            place,
            lambda batch, g: sharded_stream_verdict(
                batch, mesh, append_fail=append_fail, gidx=g
            ),
        )

    def convert(item, collected):
        tensors, fulls = collected
        out = stream_lin_tensors_to_results(tensors, fulls)[: len(item)]
        for r in out:
            r["append-fail"] = append_fail
        return [{"stream": r} for r in out]

    def place_pair(pair):
        batch, fulls = pair
        return place(batch), fulls

    def check_pair(pair):
        batch, fulls = pair
        return check(batch), fulls

    def collect_pair(raw):
        tensors, fulls = raw
        jax.block_until_ready(tensors)
        return jax.tree.map(np.asarray, tensors), fulls

    return _Family(
        produce, check_pair, place_pair, convert, collect_pair
    )


def _mesh_stream_place(mesh):
    from jepsen_tpu.parallel.mesh import SEQ_AXIS, _hist_sharded

    def place(batch):
        if mesh.shape[SEQ_AXIS] == 1:
            return _hist_sharded(batch, mesh)
        return batch  # seq>1: sharded_stream_lin pads + places itself

    return place


def _device_put_on(device):
    """``jax.device_put`` pinned to one lane's device (``None``: the
    default device, the classic single-lane behavior)."""
    import jax

    if device is None:
        return jax.device_put
    return lambda tree: jax.device_put(tree, device)


def _no_convert(item, collected):  # reduce-mode families have no
    raise RuntimeError(            # per-history conversion
        "reduce-mode family has no per-history convert"
    )


def _reduced_family(base_produce, batch_len, place_batch, verdict) -> _Family:
    """The reduce-mode family shape shared by stream and queue (elle
    adds the degenerate host-fallback fold and keeps its own): thread
    the chunk's global-id vector (pads carry the never-wins gid) through
    place to the family's sharded verdict, and unpack the two on-device
    scalars.  ``verdict(host_batch, gidx)`` must return
    ``(n_invalid, first_invalid)``."""

    def produce_r(chunk):
        host = base_produce(chunk)
        g = np.full((batch_len(host),), _GID_PAD, np.int32)
        gd = _gids_of(chunk)
        g[: len(gd)] = gd
        return host, g

    def place_r(pair):
        host, g = pair
        return place_batch(host), g

    def check_r(pair):
        host, g = pair
        return verdict(host, g)

    def reduce_convert(item, collected):
        n_invalid, first = collected
        return {"n_invalid": int(n_invalid), "first_invalid": int(first)}

    return _Family(
        produce_r, check_r, place_r, _no_convert,
        reduce_convert=reduce_convert,
    )


def _queue_family(
    threads: int,
    use_cache: bool,
    delivery: str,
    mesh=None,
    donate: bool | None = None,
    chunk_pad: int = 0,
    device=None,
    reduce: bool = False,
) -> _Family:
    import jax

    from jepsen_tpu.checkers.fused import combined_tensor_check
    from jepsen_tpu.checkers.queue_lin import queue_lin_tensors_to_results
    from jepsen_tpu.checkers.total_queue import _tensors_to_results
    from jepsen_tpu.history.encode import pack_row_matrices

    if donate is None:
        donate = _default_donate()

    def produce(chunk):
        if isinstance(chunk, _Stripe):
            mats = _queue_substrates(
                chunk.paths, threads, use_cache, chunk.part, chunk.n_parts
            )
        elif chunk and isinstance(chunk[0], (str, Path)):
            mats = _queue_substrates(chunk, threads, use_cache)
        else:
            mats = list(chunk)
        mats = _pad_chunk(mats, chunk_pad, np.zeros((0, 8), np.int32))
        n_max = max(m.shape[0] for m in mats)
        vmax = max(
            (int(m[:, 4].max(initial=0)) for m in mats if m.shape[0]),
            default=0,
        )
        return pack_row_matrices(
            mats,
            length=_pow2_bucket(max(n_max, 1)),
            value_space=_pow2_bucket(vmax + 1),
            to_device=False,
        )

    # packed verdict buffers (round 14): the per-value class masks ship
    # as uint32 presence bitplanes — 8–32× fewer verdict bytes per
    # batch; the *_to_results converters render identical maps
    base_check = lambda p: combined_tensor_check(
        p, delivery=delivery, packed_out=True
    )
    if mesh is not None:
        from jepsen_tpu.parallel.mesh import (
            shard_packed,
            sharded_check,
            sharded_queue_verdict,
        )

        check = lambda p: sharded_check(
            p, mesh, delivery=delivery, packed_out=True
        )
        place = lambda p: shard_packed(p, mesh)
    else:
        if reduce:
            raise ValueError("reduce mode needs a mesh")
        check = (
            donated(base_check, key=("queue", delivery, "packed"))
            if donate
            else base_check
        )
        place = _device_put_on(device)

    if reduce:
        return _reduced_family(
            produce,
            lambda packed: packed.f.shape[0],
            lambda packed: shard_packed(packed, mesh),
            lambda packed, g: sharded_queue_verdict(
                packed, mesh, delivery=delivery, gidx=g
            ),
        )

    def convert(item, collected):
        tq, ql = collected
        tq_rows = _tensors_to_results(tq)[: len(item)]
        ql_rows = queue_lin_tensors_to_results(ql)[: len(item)]
        for b in ql_rows:
            # the serial path (check_queue_lin_batch) records the judged
            # contract level; a bare re-check inherits it from
            # results.json — dropping it would silently tighten verdicts
            b["delivery"] = delivery
        return [
            {"queue": a, "linear": b} for a, b in zip(tq_rows, ql_rows)
        ]

    return _Family(produce, check, place, convert)


def _elle_family(
    threads: int,
    use_cache: bool,
    model: str,
    mesh=None,
    donate: bool | None = None,
    chunk_pad: int = 0,
    device=None,
    reduce: bool = False,
) -> _Family:
    """Elle chunks carry a degenerate-history splice: tensor-
    representable histories go through the fused device inference,
    degenerate ones through the host-inference oracle — the SAME splice
    contract as ``check_elle_batch`` (``split_elle_mops``)."""
    import jax

    from jepsen_tpu.checkers.elle import (
        ElleMopsMeta,
        _classify,
        _txn_graph_from_inferred,
        check_elle_cpu,
        elle_mops_check,
        split_elle_mops,
    )
    from jepsen_tpu.history.store import read_history

    if donate is None:
        donate = _default_donate()
    sentinel = (
        np.zeros((0, 8), np.int32),
        ElleMopsMeta(n_txns=0, txn_index=[], keys=[], degenerate=False),
    )

    if mesh is not None:
        from jepsen_tpu.parallel.mesh import HIST_AXIS

        mesh_h = mesh.shape[HIST_AXIS]
    else:
        mesh_h = 1

    def produce(chunk):
        from_paths = bool(chunk) and isinstance(chunk[0], (str, Path))
        if isinstance(chunk, _Stripe):
            subs = _elle_substrates(
                chunk.paths, threads, use_cache, chunk.part, chunk.n_parts
            )
        elif from_paths:
            subs = _elle_substrates(chunk, threads, use_cache)
        else:
            subs = [(m, g) for m, g in chunk]
        subs = _pad_chunk(subs, chunk_pad, sentinel)
        live, mops, degen = split_elle_mops(subs)
        if mesh_h > 1 and live and len(live) % mesh_h:
            # degenerate histories shrank the LIVE batch below the
            # mesh's hist-axis divisibility: extend the sentinel pad
            # (tensor-checkable, trimmed by convert) and re-split
            subs = _pad_chunk(
                subs, len(subs) + mesh_h - len(live) % mesh_h, sentinel
            )
            live, mops, degen = split_elle_mops(subs)
        degen_results = []
        for i in degen:
            # tensor-unrepresentable history: host oracle (rare; see
            # elle_mops_for's degeneracy conditions)
            h = read_history(chunk[i]) if from_paths else None
            if h is None:
                raise PipelineError(
                    "degenerate elle history needs its ops for the host "
                    "fallback; pass file paths (or pre-check via "
                    "check_elle_batch)"
                )
            degen_results.append(check_elle_cpu(h, model=model))
        metas = [subs[i][1] for i in live]
        return mops, metas, live, degen, degen_results

    if mesh is not None:
        from jepsen_tpu.parallel.mesh import (
            _hist_sharded,
            sharded_elle_mops_verdict,
        )

        place_mops = lambda m: _hist_sharded(m, mesh)
        check_mops = elle_mops_check
    else:
        if reduce:
            raise ValueError("reduce mode needs a mesh")
        place_mops = _device_put_on(device)
        check_mops = (
            donated(elle_mops_check) if donate else elle_mops_check
        )

    if reduce:
        base_produce = produce

        def produce_r(chunk):
            mops, _metas, live, degen, degen_results = base_produce(chunk)
            gd = _gids_of(chunk)
            g = None
            if mops is not None:
                # device-batch position b holds chunk position live[b];
                # sentinel pads (live[b] beyond the true chunk) carry
                # the never-wins pad gid
                g = np.asarray(
                    [gd[i] if i < len(gd) else _GID_PAD for i in live],
                    np.int32,
                )
            return mops, g, degen, degen_results, gd

        def place_r(item):
            mops, g, degen, degen_results, gd = item
            if mops is not None:
                mops = place_mops(mops)
            return mops, g, degen, degen_results, gd

        def check_r(item):
            mops, g, degen, degen_results, gd = item
            raw = (
                sharded_elle_mops_verdict(mops, mesh, gidx=g)
                if mops is not None
                else None
            )
            return raw, degen, degen_results, gd

        def collect_r(raw_tuple):
            raw, degen, degen_results, gd = raw_tuple
            if raw is not None:
                jax.block_until_ready(raw)
                raw = jax.tree.map(np.asarray, raw)
            return raw, degen, degen_results, gd

        def reduce_convert(chunk, collected):
            # fold the host-fallback (degenerate) verdicts into the
            # reduced device verdict: counts add, first-invalid takes
            # the minimum GLOBAL source index across both populations
            raw, degen, degen_results, gd = collected
            n_invalid = sum(
                1 for r in degen_results if r["valid?"] is not True
            )
            first = -1
            for i, r in zip(degen, degen_results):
                if r["valid?"] is not True and (
                    first < 0 or gd[i] < first
                ):
                    first = gd[i]
            if raw is not None:
                nb, fdev = int(raw[0]), int(raw[1])
                n_invalid += nb
                if fdev >= 0 and (first < 0 or fdev < first):
                    first = fdev
            return {"n_invalid": n_invalid, "first_invalid": first}

        return _Family(
            produce_r, check_r, place_r, _no_convert, collect_r,
            reduce_convert,
        )

    def place(item):
        mops, metas, live, degen, degen_results = item
        if mops is not None:
            mops = place_mops(mops)
        return mops, metas, live, degen, degen_results

    def check(item):
        mops, metas, live, degen, degen_results = item
        raw = check_mops(mops) if mops is not None else None
        return raw, metas, live, degen, degen_results

    def collect(raw_tuple):
        raw, metas, live, degen, degen_results = raw_tuple
        if raw is not None:
            jax.block_until_ready(raw)
            raw = jax.tree.map(np.asarray, raw)
        return raw, metas, live, degen, degen_results

    def convert(chunk, collected):
        raw, metas, live, degen, degen_results = collected
        out: list = [None] * (len(live) + len(degen))
        for i, r in zip(degen, degen_results):
            out[i] = {"elle": r}
        if raw is not None:
            t, inf = raw
            g0, g1c, g2 = (np.asarray(x) for x in (t.g0, t.g1c, t.g2))
            g1a, g1b, bad = (
                np.asarray(x) for x in (inf.g1a, inf.g1b, inf.bad_keys)
            )
            counts = tuple(
                np.asarray(getattr(inf, f"{n}_edges"))
                for n in ("ww", "wr", "rw")
            )
            for b, i in enumerate(live):
                g = _txn_graph_from_inferred(b, metas[b], g1a, g1b, bad)
                out[i] = {
                    "elle": _classify(
                        g,
                        set(np.nonzero(g0[b])[0].tolist()),
                        set(np.nonzero(g1c[b])[0].tolist()),
                        set(np.nonzero(g2[b])[0].tolist()),
                        model=model,
                        edge_counts=tuple(int(c[b]) for c in counts),
                    )
                }
        return out[: len(chunk)]

    return _Family(produce, check, place, convert, collect)


def _mutex_family(
    threads: int,
    use_cache: bool,
    mesh=None,
    donate: bool | None = None,
    chunk_pad: int = 0,
    device=None,
    reduce: bool = False,
) -> _Family:
    """The mutex/WGL family: bytes → WGL cells (``SEC_WGL`` of the
    ``.jtc`` substrate, native ``jt_wgl_cells_files`` thread pool) →
    P-compositional decomposition → shape-bucketed vmapped frontier
    searches (``checkers/wgl_pcomp.py``).  An overflowed sub-history
    surfaces as *unknown* and takes the exact CPU escape hatch inside
    ``convert`` — the same contract as the serial ``MutexWgl`` checker,
    never a silent per-piece skip.  ``chunk_pad``/``donate`` are
    accepted for interface symmetry; bucket shapes are already pinned
    by the (n_ops, capacity, cands) buckets, so chunk padding adds
    nothing — and since round 14 donation lives INSIDE ``run_bucket``
    (the staged bucket arrays are one-shot, donated by the row and
    subset programs alike on backends whose runtime can use it)."""
    import jax

    from jepsen_tpu.checkers.wgl import (
        fenced_mutex_wgl_ops,
        mutex_history_is_fenced,
        mutex_wgl_ops,
    )
    from jepsen_tpu.checkers.wgl_pcomp import (
        bucketize,
        decompose,
        finish_buckets,
        mutex_ops_from_cells,
        pcomp_check_cpu,
        pcomp_result,
        run_bucket,
    )
    from jepsen_tpu.history.store import read_history
    from jepsen_tpu.models.core import FencedMutex, OwnedMutex

    if reduce:
        raise ValueError(
            "the mutex family has no reduce mode: the device batch axis "
            "is the SUB-HISTORY axis, not the history axis, so the "
            "collective index-pmin would name a class, not a history"
        )
    if mesh is not None:
        from jepsen_tpu.parallel.mesh import HIST_AXIS, _hist_sharded

        mesh_h = mesh.shape[HIST_AXIS]
    else:
        mesh_h = 1

    def _ops_of(item):
        """→ ``(wgl_ops, model_key)`` from a cell matrix or an Op list."""
        if isinstance(item, np.ndarray):
            return mutex_ops_from_cells(item)
        if mutex_history_is_fenced(item):
            return fenced_mutex_wgl_ops(item), (FencedMutex, ())
        return mutex_wgl_ops(item), (OwnedMutex, ())

    def produce(chunk):
        if isinstance(chunk, _Stripe):
            cells = _wgl_substrates(
                chunk.paths, threads, use_cache, chunk.part, chunk.n_parts
            )
            items = [
                c if c is not None else read_history(chunk[j])
                for j, c in enumerate(cells)
            ]
        elif chunk and isinstance(chunk[0], (str, Path)):
            cells = _wgl_substrates(chunk, threads, use_cache)
            items = [
                c if c is not None else read_history(p)
                for c, p in zip(cells, chunk)
            ]
        else:
            items = list(chunk)
        pairs = [_ops_of(it) for it in items]
        decomps = [decompose(ops, mk) for ops, mk in pairs]  # per-key:
        #   always sound for the mutex family
        buckets = bucketize(decomps, pad_to=mesh_h, to_device=False)
        return decomps, buckets, pairs

    def _place_batch(b):
        # mutex classes always ride the row engine (order-dependent
        # state), but the placement stays engine-aware so a packed
        # subset bucket placed through this family would not misroute
        cols = (
            ("enq", "deq", "ret_op", "cands")
            if hasattr(b, "enq")
            else ("f", "a0", "a1", "ret_op", "cands")
        )
        vals = tuple(getattr(b, c) for c in cols)
        if mesh is not None:
            vals = _hist_sharded(vals, mesh)
        else:
            vals = _device_put_on(device)(vals)
        return dataclasses.replace(b, **dict(zip(cols, vals)))

    def place(item):
        decomps, buckets, pairs = item
        return (
            decomps,
            [
                dataclasses.replace(bk, batch=_place_batch(bk.batch))
                for bk in buckets
            ],
            pairs,
        )

    def check(item):
        decomps, buckets, pairs = item
        raws = [run_bucket(bk) for bk in buckets]  # async dispatches
        return decomps, buckets, pairs, raws

    def collect(raw_tuple):
        decomps, buckets, pairs, raws = raw_tuple
        jax.block_until_ready(raws)
        return decomps, buckets, pairs, jax.tree.map(np.asarray, raws)

    def convert(chunk, collected):
        decomps, buckets, pairs, raws = collected
        # escalation (rare) re-dispatches on the caller's thread — plain
        # vmapped programs, no collectives, safe outside the mesh gate
        ok, unknown, info = finish_buckets(decomps, buckets, raws)
        out = []
        for i, d in enumerate(decomps):
            cls, args = d.model_key
            r = pcomp_result(d, bool(ok[i]), bool(unknown[i]), info[i])
            if unknown[i]:
                # frontier overflow even escalated: the exact CPU search
                # (itself per-class) decides, the offending class stays
                # visible
                cpu = pcomp_check_cpu(pairs[i][0], d.model_key)
                cpu["pcomp-overflow-class"] = r.get("overflow-class")
                r = cpu
            r["model"] = cls.name
            out.append({"mutex": r})
        return out[: len(chunk)]

    return _Family(produce, check, place, convert, collect)


def family_for(workload: str, **opts) -> _Family:
    common = dict(
        mesh=opts.get("mesh"),
        donate=opts.get("donate"),
        chunk_pad=opts.get("chunk_pad", 0),
        device=opts.get("device"),
        reduce=opts.get("reduce", False),
    )
    if workload == "stream":
        return _stream_family(
            opts.get("threads", 0),
            opts.get("use_cache", True),
            opts.get("append_fail", "definite"),
            **common,
        )
    if workload == "queue":
        return _queue_family(
            opts.get("threads", 0),
            opts.get("use_cache", True),
            opts.get("delivery", "exactly-once"),
            **common,
        )
    if workload == "elle":
        return _elle_family(
            opts.get("threads", 0),
            opts.get("use_cache", True),
            opts.get("model", "serializable"),
            **common,
        )
    if workload == "mutex":
        return _mutex_family(
            opts.get("threads", 0),
            opts.get("use_cache", True),
            **common,
        )
    raise ValueError(f"no pipeline family for workload {workload!r}")


def _pad_for(chunk: int, opts: dict) -> int:
    pad = chunk
    if opts.get("mesh") is not None:
        # sharded placement needs the batch axis divisible by the mesh's
        # hist extent; sentinel-pad each chunk up to the next multiple
        from jepsen_tpu.parallel.mesh import HIST_AXIS

        h = opts["mesh"].shape[HIST_AXIS]
        pad = ((chunk + h - 1) // h) * h
    return pad


def _merge_reduced(fam: "_Family", items, collected) -> dict:
    """Fold per-chunk two-scalar verdicts into one batch verdict dict.
    Each chunk's ``first_invalid`` is already a GLOBAL source index
    (the device reduction pmin-ed over the chunk's gid vector).
    Quarantined members (elastic mode) are COUNTED, never silently
    folded: ``quarantined > 0`` forces the composed verdict to at best
    ``unknown`` (:func:`reduced_valid`)."""
    merged = {
        "histories": 0, "invalid": 0, "first_invalid": -1,
        "quarantined": 0,
    }
    for it, col in zip(items, collected):
        merged["histories"] += len(it)
        if isinstance(col, Quarantined):
            merged["quarantined"] += len(it)
            continue
        if isinstance(col, _SalvagedUnit):
            for sub, sub_col in col.members:
                if isinstance(sub_col, Quarantined):
                    merged["quarantined"] += 1
                    continue
                d = fam.reduce_convert(sub, sub_col)
                merged["invalid"] += d["n_invalid"]
                g = d["first_invalid"]
                if g >= 0 and (
                    merged["first_invalid"] < 0
                    or g < merged["first_invalid"]
                ):
                    merged["first_invalid"] = g
            continue
        d = fam.reduce_convert(it, col)
        merged["invalid"] += d["n_invalid"]
        g = d["first_invalid"]
        if g >= 0 and (
            merged["first_invalid"] < 0 or g < merged["first_invalid"]
        ):
            merged["first_invalid"] = g
    return merged


def reduced_valid(merged: dict):
    """The composed verdict of a reduce-mode batch dict under the PR-8
    precedence rule: ``invalid`` trumps everything; any quarantined
    history caps the verdict at ``unknown``; only a clean batch is
    ``True``.  A quarantine can never be folded into valid."""
    from jepsen_tpu.checkers.protocol import UNKNOWN

    if merged.get("invalid", 0) > 0:
        return False
    if merged.get("quarantined", 0) > 0:
        return UNKNOWN
    return True


class _SalvagedUnit:
    """A quarantined unit after per-history isolation: ``members`` is
    one ``(single_item_unit, collected_or_Quarantined)`` pair per
    member, in unit order."""

    def __init__(self, members):
        self.members = members


def _salvage_unit(fam: "_Family", unit, q: Quarantined) -> _SalvagedUnit:
    """Per-history isolation of a quarantined unit: each member re-runs
    ALONE through the same produce → place → check → collect stages
    (chunk of one — the sentinel pad keeps the compiled batch shape),
    so one poison history cannot condemn its chunk-mates.  Members that
    still crash quarantine individually, carrying both the unit-level
    and their own evidence."""
    collect = fam.collect or _default_collect
    gids = _gids_of(unit)
    members = []
    for j in range(len(unit)):
        sub = _Unit([unit[j]], [gids[j]])
        stage = "produce"
        try:
            host = fam.produce(sub)
            stage = "place"
            placed = fam.place(host)
            stage = "check"
            raw = fam.check(placed)
            stage = "collect"
            col = collect(raw)
        except Exception as e:
            members.append(
                (
                    sub,
                    Quarantined(
                        q.index, stage, q.attempts + ["salvage"],
                        q.errors + [e],
                    ),
                )
            )
            continue
        members.append((sub, col))
    return _SalvagedUnit(members)


def _resolve_quarantines(
    fam: "_Family", items, collected, stats: PipelineStats
) -> list:
    """Elastic post-pass: isolate every quarantined unit per history
    (:func:`_salvage_unit`) and count the FINAL per-history quarantines
    into the stats/obs registries."""
    out = list(collected)
    for k, col in enumerate(out):
        if not isinstance(col, Quarantined):
            continue
        salvaged = _salvage_unit(fam, items[k], col)
        n_q = sum(
            1 for _s, c in salvaged.members if isinstance(c, Quarantined)
        )
        if n_q:
            stats.note_quarantine(col.evidence(), histories=n_q)
        out[k] = salvaged
    return out


def _quarantined_result(workload: str, evidence: dict) -> dict:
    """An explicit per-history ``unknown``-with-evidence verdict for a
    quarantined history — same shape discipline as
    :func:`_dropped_result`: one entry per source, never a silent
    truncation, and ``unknown`` can never compose into ``valid``."""
    from jepsen_tpu.checkers.protocol import UNKNOWN

    errs = evidence.get("errors") or ["?"]
    row = {
        "valid?": UNKNOWN,
        "error": f"quarantined at {evidence.get('stage')}: {errs[-1]}",
        "quarantined": dict(evidence),
    }
    if workload == "queue":
        return {"queue": dict(row), "linear": dict(row)}
    return {workload: dict(row)}


def _convert_unit(
    fam: "_Family", workload: str, unit, col, stats: PipelineStats,
    fail_fast: bool,
) -> list[dict]:
    """One unit's collected result → per-history result dicts, with the
    elastic guards: a salvaged unit converts member by member, and a
    ``convert`` crash (the last stage outside the executor) quarantines
    the unit's histories instead of sinking the run."""
    if isinstance(col, _SalvagedUnit):
        out = []
        for sub, sub_col in col.members:
            if isinstance(sub_col, Quarantined):
                out.append(_quarantined_result(workload, sub_col.evidence()))
            else:
                out.extend(
                    _convert_unit(
                        fam, workload, sub, sub_col, stats, fail_fast
                    )
                )
        return out
    if fail_fast:  # the PR-4 contract: a convert crash propagates raw
        return fam.convert(unit, col)
    try:
        return fam.convert(unit, col)
    except Exception as e:
        q = Quarantined(-1, "convert", ["main"], [e])
        stats.note_quarantine(q.evidence(), histories=len(unit))
        return [
            _quarantined_result(workload, q.evidence()) for _ in unit
        ]


def _dropped_result(workload: str, reason: str) -> dict:
    """An explicit per-source verdict for a file the lane census dropped
    — the results list keeps one entry per source, never a silent
    truncation."""
    from jepsen_tpu.checkers.protocol import UNKNOWN

    row = {"valid?": UNKNOWN, "error": reason}
    if workload == "queue":
        return {"queue": dict(row), "linear": dict(row)}
    return {workload: dict(row)}


def _lane_census(sources, workload):
    """Stat every path source; split into (kept indices, sizes,
    {dropped index: reason}).  Unreadable and zero-length files cannot
    be size-balanced (and a 0-byte history carries no ops) — each drop
    is LOGGED, incremented in the global ``pipeline.files_dropped``
    obs counter (the after-the-run countable record the log line never
    was), and later counted in the run's stats."""
    import logging
    import os

    log = logging.getLogger(__name__)
    kept, sizes, dropped = [], [], {}
    for i, p in enumerate(sources):
        try:
            sz = os.stat(p).st_size
        except OSError as e:
            reason = f"unreadable history file: {e}"
            kind = "unreadable"
            log.warning(
                "lane census: dropping %s (%s) — counted in stats.dropped",
                p, e,
            )
        else:
            if sz > 0:
                kept.append(i)
                sizes.append(sz)
                continue
            reason = "zero-length history file"
            kind = "zero-length"
            log.warning(
                "lane census: dropping zero-length %s — counted in "
                "stats.dropped", p,
            )
        dropped[i] = reason
        obs_metrics.REGISTRY.counter(
            "pipeline.files_dropped", reason=kind
        ).inc()
        if obs_trace.is_enabled():
            obs_trace.event(
                "pipeline.file_dropped", args={"path": str(p), "reason": kind}
            )
    return kept, sizes, dropped


def _check_sources_lanes(
    workload: str,
    sources: list,
    *,
    chunk: int,
    depth: int,
    lanes: int,
    reduce: bool = False,
    fail_fast: bool = False,
    **opts,
):
    """N-lane bytes-to-verdict: size-aware unit balancing (largest-first
    round-robin stripes of one shared ordered path list) over per-device
    lanes claiming units off a shared queue (steal-on-idle)."""
    import jax

    devices = jax.local_devices()
    n_lanes = len(devices) if lanes <= 0 else max(1, min(lanes, len(devices)))
    paths_mode = bool(sources) and all(
        isinstance(s, (str, Path)) for s in sources
    )
    if paths_mode:
        kept, sizes, dropped = _lane_census(sources, workload)
    else:
        kept, sizes, dropped = list(range(len(sources))), [1] * len(sources), {}
    # largest-first ordering; round-robin striping over it yields
    # byte-balanced units of at most ``chunk`` files each
    order = sorted(range(len(kept)), key=lambda j: -sizes[j])
    ordered_idx = [kept[j] for j in order]
    ordered = [sources[i] for i in ordered_idx]
    if not ordered:  # nothing survived the census (or empty input)
        stats = PipelineStats(lanes=n_lanes, dropped=len(dropped))
        if reduce:
            return (
                {
                    "histories": 0,
                    "invalid": 0,
                    "first_invalid": -1,
                    "quarantined": 0,
                    "dropped": len(dropped),
                },
                stats,
            )
        out = [
            _dropped_result(workload, dropped[i])
            for i in range(len(sources))
        ]
        stats.histories = len(out)
        return out, stats
    n_units = max(1, (len(ordered) + chunk - 1) // chunk)
    unit_len = (len(ordered) + n_units - 1) // n_units
    opts = dict(opts)
    opts["reduce"] = reduce
    opts.setdefault("chunk_pad", _pad_for(max(unit_len, 1), opts))
    unit_indices = [
        _stripe_indices(len(ordered), k, n_units) for k in range(n_units)
    ]
    if paths_mode:
        units = [
            _Stripe(
                ordered, k, n_units,
                gids=[ordered_idx[i] for i in unit_indices[k]],
            )
            for k in range(n_units)
        ]
    else:
        units = [
            _Unit(
                ordered[k::n_units],
                [ordered_idx[i] for i in unit_indices[k]],
            )
            for k in range(n_units)
        ]
    mesh = opts.get("mesh")
    if mesh is not None:
        # all lanes feed the shared mesh (sharded staging/dispatch);
        # the lanes still overlap each other's host packing.  Dispatch
        # is serialized through one gate: concurrent enqueues of
        # collective programs from different threads interleave the
        # per-device queues inconsistently and deadlock the CPU
        # backend's all-reduce rendezvous (in-order in-flight programs
        # — the single-thread pipelined shape — are safe)
        base = family_for(workload, **opts)
        gate = threading.Lock()

        def locked_check(placed, _check=base.check):
            with gate:
                return _check(placed)

        fams = [
            dataclasses.replace(base, check=locked_check)
            for _ in range(n_lanes)
        ]
    else:
        fams = [
            family_for(workload, device=devices[i], **opts)
            for i in range(n_lanes)
        ]
    collected, stats = run_lanes(
        units, fams, depth=depth, fail_fast=fail_fast
    )
    if not fail_fast:
        collected = _resolve_quarantines(fams[0], units, collected, stats)
    stats.dropped = len(dropped)
    if reduce:
        merged = _merge_reduced(fams[0], units, collected)
        merged["dropped"] = len(dropped)
        stats.histories = merged["histories"]
        return merged, stats
    out: list = [None] * len(sources)
    for k, (unit, col) in enumerate(zip(units, collected)):
        conv = _convert_unit(fams[0], workload, unit, col, stats, fail_fast)
        for j, r in enumerate(conv):
            out[ordered_idx[unit_indices[k][j]]] = r
    for i, reason in dropped.items():
        out[i] = _dropped_result(workload, reason)
    stats.histories = len(out)
    return out, stats


def check_sources(
    workload: str,
    sources: Sequence[Any],
    *,
    chunk: int = DEFAULT_CHUNK,
    serial: bool = False,
    depth: int = 2,
    lanes: int | None = None,
    reduce: bool = False,
    fail_fast: bool = False,
    **opts,
) -> tuple[list[dict], PipelineStats]:
    """Bytes-to-verdict over ``sources`` (file paths, or pre-exploded
    family substrates) through the pipeline executor.

    Returns ``(results, stats)``: one result dict per source, in order
    — ``{"queue": ..., "linear": ...}`` / ``{"stream": ...}`` /
    ``{"elle": ...}`` with exactly the serial checkers' content (the
    differential contract).  ``serial=True`` is the triage escape
    hatch: the same stages run strictly serially on the calling thread
    — byte-identical results, no overlap.

    ``lanes`` opts into the scale-out executor: one input lane (producer
    + staging slot) per addressable device (``lanes=0``: all local
    devices), with size-aware largest-first unit balancing and
    steal-on-idle — see :func:`run_lanes`.  Unreadable/zero-length path
    sources are dropped from lane balancing with a logged warning, an
    explicit ``unknown`` verdict entry, and a ``stats.dropped`` count.

    ``reduce=True`` (requires ``mesh``) returns the collective-reduced
    batch verdict instead of per-history results: one dict
    ``{"histories", "invalid", "first_invalid", "quarantined"}`` whose
    scalars were combined ON DEVICE (psum / index-pmin) — the host
    never gathers the per-history verdict tensors.

    Failure isolation is ELASTIC by default: a chunk whose stage
    raises is retried once, then isolated per history — the crasher(s)
    report ``unknown`` with the exception as evidence (``quarantined``
    key in the result row / reduce-dict count) while every other
    history's verdict survives.  ``fail_fast=True`` restores the
    abort-all :class:`PipelineError` contract; the ``serial=True``
    triage path always fails fast (it exists to surface the first
    error loudly)."""
    if lanes is not None and not serial:
        return _check_sources_lanes(
            workload,
            list(sources),
            chunk=chunk,
            depth=depth,
            lanes=lanes,
            reduce=reduce,
            fail_fast=fail_fast,
            **opts,
        )
    opts = dict(opts)
    opts["reduce"] = reduce
    opts.setdefault("chunk_pad", _pad_for(chunk, opts))
    fam = family_for(workload, **opts)
    items = _chunks(list(sources), chunk)
    if reduce:
        # contiguous chunks: chunk k's gids are its source offsets
        items = [
            _Unit(it, list(range(k * chunk, k * chunk + len(it))))
            for k, it in enumerate(items)
        ]
    if serial:
        import jax

        collect = fam.collect or _default_collect
        stats = PipelineStats()
        t0 = time.perf_counter()
        collected = []
        for it in items:
            host = stats.run_stage("produce", fam.produce, it)
            placed = stats.run_stage("place", fam.place, host)
            collected.append(
                stats.run_stage(
                    "check", lambda p: collect(fam.check(p)), placed
                )
            )
        stats.batches = len(items)
        stats.wall_s = time.perf_counter() - t0
        stats.finalize()
    else:
        collected, stats = run_pipeline(
            items,
            fam.produce,
            fam.check,
            place=fam.place,
            collect=fam.collect,
            depth=depth,
            fail_fast=fail_fast,
        )
        if not fail_fast:
            collected = _resolve_quarantines(fam, items, collected, stats)
    if reduce:
        merged = _merge_reduced(fam, items, collected)
        merged["dropped"] = 0
        stats.histories = merged["histories"]
        return merged, stats
    results: list[dict] = []
    for it, col in zip(items, collected):
        results.extend(
            _convert_unit(fam, workload, it, col, stats, fail_fast or serial)
        )
    stats.histories = len(results)
    return results, stats


class PipelinedChecker:
    """Checker-protocol adapter for the CLI ``check`` path and the test
    runner: the family verdict computed from the history FILE through
    the pipeline (cache-first native substrate, device check), not from
    re-packed Op objects.  One shared run serves every sub-checker of
    the family (the queue workload surfaces as two keys).

    ``path=None`` resolves lazily from the runner's ``opts["out_dir"]``
    at check time (``run_test`` saves ``history.jsonl`` before the
    analysis phase) — the soak/test assembly wires checkers before the
    run dir exists.  When no file can be found (a storeless unit-test
    run), :meth:`_from_ops` checks the in-memory ops through the same
    convert path instead."""

    def __init__(self, workload: str, path, subkey: str, **opts):
        self.workload = workload
        self.path = path
        self.subkey = subkey
        self.name = subkey
        self._opts = dict(opts)
        self._shared = self._opts.pop("shared", None)

    def _resolve_path(self, opts):
        if self.path is not None:
            return self.path
        out_dir = (opts or {}).get("out_dir")
        if out_dir is None:
            return None
        from jepsen_tpu.history.store import HISTORY_FILE

        p = Path(out_dir) / HISTORY_FILE
        return p if p.is_file() else None

    def check(self, test, history, opts=None):
        if self._shared is not None and self.workload in self._shared:
            return self._shared[self.workload][0][self.subkey]
        path = self._resolve_path(opts)
        if path is not None:
            results, _ = check_sources(
                self.workload, [path], chunk=1, **self._resolved_opts()
            )
        else:
            # no file (e.g. a storeless unit-test run): serial family
            # substrates from the in-memory ops — same convert path
            results = self._from_ops(history)
        if self._shared is not None:
            self._shared[self.workload] = results
        return results[0][self.subkey]

    def _resolved_opts(self) -> dict:
        """``mesh=True`` resolves lazily to a device mesh at check time
        (checkers are wired before any device use; a mesh object must
        not be built at soak-assembly time).  A single long soak
        history has ONE file, so the scale-out axis is the op axis:
        queue/stream get a seq-parallel mesh over all local devices
        (the per-history count/scan programs shard their op blocks and
        psum-combine); elle's seq path needs txn-lane divisibility, so
        it keeps the plain single-device dispatch."""
        o = dict(self._opts)
        if o.get("mesh") is True:
            import jax

            from jepsen_tpu.parallel.mesh import checker_mesh

            n = len(jax.local_devices())
            if self.workload in ("queue", "stream") and n > 1:
                o["mesh"] = checker_mesh(seq=n)
            else:
                o.pop("mesh")
        return o

    def _from_ops(self, history):
        if self.workload == "stream":
            from jepsen_tpu.checkers.stream_lin import _stream_rows

            subs = [_stream_rows(history)]
        elif self.workload == "queue":
            from jepsen_tpu.history.rows import _rows_for

            subs = [_rows_for(history)]
        elif self.workload == "mutex":
            # the mutex producer takes Op lists directly (it derives the
            # model + decomposition from them, same as from cells)
            subs = [list(history)]
        else:
            from jepsen_tpu.checkers.elle import elle_mops_for

            # degenerate single histories need their ops for the host
            # oracle; check_elle_batch handles the splice directly
            from jepsen_tpu.checkers.elle import check_elle_batch

            model = self._opts.get("model", "serializable")
            return [
                {"elle": check_elle_batch([history], model=model)[0]}
            ]
        results, _ = check_sources(
            self.workload, subs, chunk=1, serial=True,
            **self._resolved_opts(),
        )
        return results


def attach_pipelined_checkers(test, workload: str, **scale_opts) -> bool:
    """Swap a built test's family checkers for pipeline-backed ones
    (``tools/soak.py`` and friends: the post-run analysis then runs
    bytes-to-verdict from the stored ``history.jsonl`` through the
    executor instead of re-packing Op objects on one thread).  Contract
    levels (delivery / append-fail / consistency model) are inherited
    from the checkers being replaced, so the verdict semantics cannot
    drift.  ``scale_opts`` forward scale-out knobs (``lanes`` — 0 = one
    lane per local device) into :func:`check_sources`.  Returns True
    when the swap applied (False: no composed checkers to swap, or an
    explicitly monolithic mutex checker)."""
    checkers = getattr(getattr(test, "checker", None), "checkers", None)
    if checkers is None:
        return False
    shared: dict = {}
    scale_opts = {k: v for k, v in scale_opts.items() if v is not None}
    if workload == "queue" and {"queue", "linear"} <= set(checkers):
        delivery = getattr(
            checkers["linear"], "delivery", "exactly-once"
        )
        for sub in ("queue", "linear"):
            checkers[sub] = PipelinedChecker(
                "queue", None, sub, shared=shared, delivery=delivery,
                **scale_opts,
            )
        return True
    if workload == "stream" and "stream" in checkers:
        append_fail = getattr(
            checkers["stream"], "append_fail", "definite"
        )
        checkers["stream"] = PipelinedChecker(
            "stream", None, "stream", shared=shared,
            append_fail=append_fail, **scale_opts,
        )
        return True
    if workload == "elle" and "elle" in checkers:
        model = getattr(checkers["elle"], "model", "serializable")
        checkers["elle"] = PipelinedChecker(
            "elle", None, "elle", shared=shared, model=model,
            **scale_opts,
        )
        return True
    if workload == "mutex" and "mutex" in checkers:
        if getattr(checkers["mutex"], "pcomp", True) is False:
            return False  # an explicitly monolithic checker stays
        opts = {k: v for k, v in scale_opts.items() if k != "reduce"}
        checkers["mutex"] = PipelinedChecker(
            "mutex", None, "mutex", shared=shared, **opts
        )
        return True
    return False


# ---------------------------------------------------------------------------
# segment-producer mode (ISSUE 15 / SEGMENTED.md)
# ---------------------------------------------------------------------------


def check_source_segmented(
    workload: str,
    src,
    *,
    segment_ops: int,
    resume: bool = False,
    carry_cap: int | None = None,
    device: bool = True,
    keep_checkpoint: bool = False,
    prefix_index=None,
    **opts,
) -> tuple[dict, "PipelineStats"]:
    """The pipeline's segment-producer mode: ONE history streamed
    through the segmented carry engine (``checkers/segmented.py``) in
    fixed-shape segments — bounded memory regardless of history
    length, durable per-segment checkpoints, ``resume=True`` to
    continue a killed check from the last one.  ``prefix_index`` (a
    directory path or :class:`~jepsen_tpu.history.prefix_index.
    PrefixCheckpointIndex`) arms fleet memory: a re-submitted history
    resumes from the deepest published anchor whose content hash
    matches its bytes (SEGMENTED.md §Prefix resume).

    The producer here is the op axis, not the file axis: per-segment
    check latency lands in the run registry's
    ``segmented.segment_check_s`` sketch (the same PR-9 substrate the
    batch executor's ``check_batch_s`` uses) and the returned
    :class:`PipelineStats` view reports segments as checked batches,
    so ``bench-check``-style consumers read one accounting surface for
    both modes.
    """
    from jepsen_tpu.checkers.segmented import segmented_check_file
    from jepsen_tpu.obs.metrics import REGISTRY

    stats = PipelineStats()
    t0 = time.perf_counter()
    before = REGISTRY.value("segmented.segments")
    result = segmented_check_file(
        src,
        workload=workload,
        segment_ops=segment_ops,
        opts={k: v for k, v in opts.items() if v is not None},
        resume=resume,
        carry_cap=carry_cap,
        device=device,
        keep_checkpoint=keep_checkpoint,
        prefix_index=prefix_index,
    )
    t1 = time.perf_counter()
    segs = int(REGISTRY.value("segmented.segments") - before)
    stats.histories = 1
    stats.batches = segs
    stats.add_busy("check", t0, t1)
    return result, stats
