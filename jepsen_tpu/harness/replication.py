"""Quorum replication for the mini broker: a compact Raft over TCP.

Round-3's local cluster ran N *independent* brokers, so partitions could
only be mapped to quorum-loss SIGSTOPs — the framework executed for real
but the SUT could not produce real distributed anomalies (VERDICT r3,
weak #6).  This module gives the mini broker the actual behavior the
reference's partitions exist to stress (RabbitMQ quorum queues are Raft —
``/root/reference/rabbitmq/resources/rabbitmq/advanced.config:3`` tunes
Ra's election timeouts):

- a publish is **confirmed only after a majority** of nodes hold it;
- a leader that loses quorum **steps down** (stops confirming);
- the majority side **elects a new leader** and keeps serving;
- a healed/restarted node **catches up** from the leader's log, and
  uncommitted entries from a deposed leader are **truncated** — exactly
  the window the ``confirm-before-quorum`` seeded bug (below) turns into
  observable lost writes.

The implementation is textbook Raft (Ongaro & Ousterhout; terms, votes
with the log-up-to-date check, AppendEntries consistency check + conflict
truncation, commit = majority match in the current term) with two
persistence modes:

- **In-memory (default)**: nodes rejoin empty after a kill, with a
  startup grace period — they neither vote nor campaign until they have
  heard from a live leader or sat out several election timeouts.  That
  grace closes the classic re-vote-after-restart hole a memory-only Raft
  would otherwise have; runs are short and the nemesis kills at most one
  node per cycle (``control/nemesis.py:130-146``), so the majority
  always retains every committed entry.
- **Durable (``data_dir=``)**: term/vote in ``meta.json`` and the log in
  an append-only ``wal.jsonl`` (truncations recorded as ``{"trunc": i}``
  markers), each fsync'd *before* the corresponding RPC answer or
  commit count — the Raft persistence contract, matching real quorum
  queues (RabbitMQ's Ra log).  A restarted node recovers its full log
  and needs no grace (its vote survived the crash), so even a
  whole-cluster power failure — SIGKILL every node, restart — loses
  nothing that was confirmed.  Leaders append a no-op entry on election
  so recovered prior-term entries commit without waiting for client
  traffic (§5.4.2's counting rule never applies to them directly).

Membership is **dynamic** (Raft §6, add-only, one change at a time):
a node started with ``bootstrap=False`` and only itself in ``peers`` is
PENDING — it neither campaigns nor commits until a ``join_request`` RPC
(sent by :meth:`RaftNode.request_join`, proxied to the leader if the
contacted node isn't it) lands an AddServer ``cfg`` entry in the log.
Config entries take effect when *appended*, not when committed; conflict
truncation reverts them; the WAL recovers them.  This is what
``rabbitmqctl join_cluster`` maps onto in ``--db local``
(``rabbitmq.clj:99-119`` choreography).

Partitions are **per-link and socket-level**: each node keeps a
``blocked`` set of peer names, mirroring an ``iptables -A INPUT -s peer``
DROP rule (``control/net.py:59-66``): an incoming RPC from a blocked peer
is dropped unanswered, and — because the *reply* to our own request would
arrive as input from that peer — responses to outgoing RPCs to a blocked
peer are discarded after the request is sent (the side effect happens on
the far side; we just never hear it — faithful one-way-drop semantics).

Replicated ops and the queue state machine live in
:class:`QueueMachine`; the broker calls :class:`RaftNode.submit` and
blocks until commit (or times out → no publisher confirm → the client
records an indeterminate op, which is always safe).

Seeded bugs (the red-run proofs that the replication mode is actually
exercised):

- ``confirm-before-quorum`` — the leader reports an ENQ as successful
  immediately after *local* append, before any replica has it.  A
  partition that isolates that leader then heals makes the new leader
  truncate the unreplicated entries: confirmed writes vanish, and
  ``total-queue`` must flag them as lost end-to-end.
- ``ack-before-fsync`` — durable mode only: log entries are buffered in
  process memory and never reach the WAL, while everything else
  (replication, commit, confirms) proceeds normally — the classic
  "fsync lies" durability bug.  Partitions can't expose it (the
  in-memory majority stays correct); a whole-cluster crash-restart
  does: every node recovers a log missing the buffered tail, confirmed
  writes vanish, and ``total-queue`` must flag them as lost.
- ``drop-unacked-on-close`` — enforced by the broker, not this module
  (``harness/broker.py``): a dying connection's un-acked deliveries are
  *discarded* instead of requeued, so messages delivered-but-unacked at
  drain time vanish from the replicated inflight map's reachable set —
  the delivery/requeue plane's loss mode, also flagged by total-queue.
- ``no-wire-checksum`` — peer RPC frames are sent WITHOUT the integrity
  CRC and received without verification, so a wire-corrupted frame that
  still parses as JSON is *processed* instead of dropped: a mutated
  entry body replicates into one replica's state machine and the
  replicas silently diverge (a client reading from the corrupted
  replica sees a phantom value; the real one is lost).  The default
  (checksummed) transport drops every mangled frame — corruption
  degrades to packet loss, which Raft is built to retry through.

Runtime fault hooks (driven by the nemeses through the broker admin
port, ``control/nemesis.py``):

- :meth:`RaftNode.set_fsync_latency` — slow-disk injection on the WAL:
  every real ``fsync`` (log append, term/vote persist) stalls
  ``mean ± jitter`` ms while holding the node lock, exactly like a
  device-mapper ``delay`` target under the store.  Fsyncgate-adjacent
  but distinct from the fail-stop path: the disk is *slow*, not lying —
  a correct node's confirms get slower (possibly timing out into
  indeterminate ops, which is always safe) and nothing confirmed may be
  lost.  Note the ``ack-before-fsync`` bug is immune to the stall by
  construction: a node that never tells storage is fast — that is the
  tell the red/green pair pins.
- :meth:`RaftNode.set_wire_faults` — wire-layer chaos on this node's
  outgoing frames (netem's corrupt/duplicate/delay, scoped to the peer
  RPC plane): corruption mutates one alphanumeric byte (JSON stays
  parseable — the nasty case; structural damage is already dropped by
  the parser), duplication re-delivers idempotent protocol RPCs
  (append_entries / request_vote — TCP dedups client_op streams, so
  non-idempotent forwards are never duplicated), and delay holds one
  frame while concurrent frames overtake (reordering).
"""

from __future__ import annotations

import base64
import json
import logging
import os
import random
import socket
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

from jepsen_tpu.obs.metrics import QuantileSketch

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

logger = logging.getLogger("jepsen_tpu.replication")


class NodeCounters:
    """Per-node telemetry counters (ISSUE 12): plain int attributes
    incremented inline on the paths they watch.  No lock — most sites
    already hold the node lock, and the rest accept the same unlocked
    read-modify-write accuracy contract as the tracer's per-track
    totals (a rare lost increment costs gauge accuracy, never
    correctness).  Read via :meth:`snapshot` (the admin ``STATS``
    command and the in-process poller, obs/cluster.py)."""

    __slots__ = (
        "elections_started", "elections_won",
        "rpc_sent", "rpc_recv", "rpc_dropped", "crc_rejected",
        "wire_corrupt", "wire_duplicate", "wire_delay",
        "safety_violations", "recoveries", "wal_bytes",
    )

    def __init__(self) -> None:
        for name in self.__slots__:
            setattr(self, name, 0)

    def snapshot(self) -> dict:
        return {name: getattr(self, name) for name in self.__slots__}


# ---------------------------------------------------------------------------
# State machine: the replicated queue/stream store
# ---------------------------------------------------------------------------


@dataclass
class _RMsg:
    mid: str
    ts_ms: float  # leader-stamped enqueue time — drives deterministic TTL
    body: bytes
    props: bytes
    #: fencing token carried by a granted (inflight) message from a
    #: fenced queue: the Raft log index of the DEQ commit that granted
    #: it.  0 = unfenced / not currently granted.  Commit indices are
    #: strictly increasing, which is the whole point — every ownership
    #: transition (grant, revocation-requeue, release) advances the
    #: queue's fence, so a stale holder's token can never validate again.
    fence: int = 0


class QueueMachine:
    """Deterministic queue/stream state machine.

    Every mutation enters through :meth:`apply` with values (including
    timestamps) taken from the committed log entry, so replicas converge
    byte-for-byte.  :meth:`counts` / :meth:`stream_snapshot` are local,
    non-mutating DIAGNOSTIC views of this replica (DEPTHS, tests) — the
    client-facing stream read path is the committed ``read_stream`` op
    (``ReplicatedBackend.stream_read``), which is linearizable.  TTL
    expiry is *simulated* in ``counts`` and *performed* inside DEQ
    application (the op carries ``now``)."""

    def __init__(self) -> None:
        self.queues: dict[str, deque[_RMsg]] = {}
        self.streams: dict[str, list[bytes]] = {}
        self.meta: dict[str, dict] = {}
        # mid -> (owner, queue, _RMsg); insertion order = requeue order
        self.inflight: dict[str, tuple[str, str, _RMsg]] = {}
        #: fenced queues only: queue -> the commit index of the latest
        #: ownership transition (grant / revocation-requeue / release).
        #: An operation bearing token T is valid iff T == fences[q];
        #: indices are monotone, so every superseded token is stale
        #: forever.  Deterministic: driven purely by committed op order.
        self.fences: dict[str, int] = {}
        self.lock = threading.RLock()

    # -- apply (mutating; called with committed entries only) --------------
    def apply(self, index: int, op: dict) -> Any:
        with self.lock:
            return self._apply_locked(index, op)

    def _apply_locked(self, index: int, op: dict) -> Any:
        k = op["k"]
        if k == "declare":
            if op.get("qtype") == "stream":
                self.streams.setdefault(op["q"], [])
            else:
                self.queues.setdefault(op["q"], deque())
                # last declare wins, like the broker's queue_meta
                self.meta[op["q"]] = {
                    "ttl_ms": op.get("ttl_ms"),
                    "dlx_key": op.get("dlx"),
                    "fenced": bool(op.get("fenced")),
                }
            return None
        if k == "enq":
            # protected (fenced) publish: the op carries the fencing token
            # of the lock it claims to hold; a token superseded by a later
            # grant/revocation/release is rejected AT APPLY TIME — every
            # replica agrees, because fences is a pure function of the
            # committed log
            if op.get("fence") is not None:
                if self.fences.get(op["fence_q"], 0) != op["fence"]:
                    return {"stale": True}
            self._enq_locked(f"m{index}", op)
            return None
        if k == "txn":
            for i, sub in enumerate(op["ops"]):
                self._enq_locked(f"m{index}.{i}", sub)
            return None
        if k == "deq":
            q = op["q"]
            self._expire_locked(q, op["now"])
            dq = self.queues.get(q)
            if not dq:
                return None
            msg = dq.popleft()
            if (self.meta.get(q) or {}).get("fenced"):
                # THE GRANT: the commit index of this DEQ is the fencing
                # token — monotonically increasing across grants by
                # construction (log indices), and recorded as the queue's
                # current fence so stale-token operations can be refused
                self.fences[q] = index
                msg = _RMsg(msg.mid, msg.ts_ms, msg.body, msg.props,
                            fence=index)
            self.inflight[msg.mid] = (op["owner"], q, msg)
            return msg
        if k == "settle":
            ent = self.inflight.get(op["mid"])
            if ent and ent[0] == op["owner"]:
                del self.inflight[op["mid"]]
            return None
        if k == "requeue_one":
            ent = self.inflight.pop(op["mid"], None)
            if ent:
                owner, q, msg = ent
                self.queues.setdefault(q, deque()).append(
                    self._revoke_locked(q, msg, index)
                )
            return None
        if k == "requeue_owner":
            self._requeue_locked(lambda o: o == op["owner"], index)
            return None
        if k == "requeue_node":
            self._requeue_locked(
                lambda o: o.startswith(op["node"] + "|"), index
            )
            return None
        if k == "fence_release":
            # fenced release: valid only while the releaser's token IS the
            # queue's current fence AND the granted entry is still
            # inflight (not already revoked by a requeue).  On success the
            # grant settles atomically with the token's return — no
            # window where two token messages can exist — and the fence
            # advances to THIS commit, making the released token stale.
            q, token = op["q"], op["token"]
            ent = next(
                (
                    (mid, e)
                    for mid, e in self.inflight.items()
                    if e[1] == q and e[2].fence == token
                ),
                None,
            )
            if self.fences.get(q) != token or ent is None:
                return {"stale": True}
            mid, _e = ent
            del self.inflight[mid]
            self.fences[q] = index
            self.queues.setdefault(q, deque()).append(
                _RMsg(
                    f"m{index}",
                    op["ts"],
                    base64.b64decode(op["body"]),
                    base64.b64decode(op.get("props", "")),
                )
            )
            return {"released": True, "mid": mid}
        if k == "purge":
            dq = self.queues.get(op["q"])
            n = len(dq) if dq else 0
            self.queues[op["q"]] = deque()
            return n
        if k == "noop":
            # leader-election marker (durable mode): commits prior-term
            # entries by the counting rule without waiting for traffic
            return None
        if k == "cfg":
            # membership change: consumed by the Raft layer on APPEND
            # (§6); nothing for the queue state machine to do at commit
            return None
        if k == "read_stream":
            # linearizable read: committing the read through the log IS
            # the linearization point — the returned snapshot reflects
            # every append committed before it, on every node, even when
            # the node that asked is a lagging follower.  Stream-ness is
            # part of the committed answer (a local marker would race
            # the declare's application on lagging replicas).
            if op["q"] not in self.streams:
                return {"_notstream": True}
            return list(self.streams[op["q"]])
        raise ValueError(f"unknown replicated op {k!r}")

    def _enq_locked(self, mid: str, op: dict) -> None:
        q = op["q"]
        body = base64.b64decode(op["body"])
        props = base64.b64decode(op.get("props", ""))
        if q in self.streams:
            self.streams[q].append(body)
        else:
            self.queues.setdefault(q, deque()).append(
                _RMsg(mid, op["ts"], body, props)
            )

    def _requeue_locked(
        self, match: Callable[[str], bool], index: int
    ) -> None:
        hits = [m for m, (o, _q, _msg) in self.inflight.items() if match(o)]
        for mid in hits:
            _o, q, msg = self.inflight.pop(mid)
            self.queues.setdefault(q, deque()).append(
                self._revoke_locked(q, msg, index)
            )

    def _revoke_locked(self, q: str, msg: _RMsg, index: int) -> _RMsg:
        """Requeueing a granted fenced message is a REVOCATION: advance
        the queue's fence to this requeue's commit index (the old
        holder's token goes stale even before the next grant) and strip
        the token from the returning message."""
        if msg.fence:
            self.fences[q] = index
            return _RMsg(msg.mid, msg.ts_ms, msg.body, msg.props)
        return msg

    def _expire_locked(self, qname: str, now_ms: float) -> None:
        """Dead-letter expired heads, timestamps from the log (never the
        local clock — replicas must agree)."""
        meta = self.meta.get(qname) or {}
        ttl = meta.get("ttl_ms")
        if ttl is None:
            return
        dq = self.queues.get(qname)
        dlx = meta.get("dlx_key")
        while dq and now_ms - dq[0].ts_ms >= ttl:
            msg = dq.popleft()
            if dlx:
                self.queues.setdefault(dlx, deque()).append(
                    _RMsg(msg.mid + "d", now_ms, msg.body, msg.props)
                )

    # -- local reads --------------------------------------------------------
    def counts(self, now_ms: float) -> dict[str, int]:
        """Per-queue depth (ready + inflight) with TTL expiry *simulated*
        against ``now_ms`` — the DEPTHS view must not mutate replicated
        state, but must also not count messages that are already past
        their TTL (advisor r3 #5).  Expiry here is head-contiguous,
        exactly like ``_expire_locked``: an old-timestamped message
        requeued behind a younger head is NOT counted as expired, or the
        view would claim dead-letters that a drain cannot find."""
        with self.lock:
            out: dict[str, int] = {}
            moved: dict[str, int] = {}
            for q, dq in self.queues.items():
                meta = self.meta.get(q) or {}
                ttl = meta.get("ttl_ms")
                n = len(dq)
                if ttl is not None:
                    expired = 0
                    for m in dq:  # heads only — mirror _expire_locked
                        if now_ms - m.ts_ms >= ttl:
                            expired += 1
                        else:
                            break
                    n -= expired
                    if meta.get("dlx_key") and expired:
                        moved[meta["dlx_key"]] = (
                            moved.get(meta["dlx_key"], 0) + expired
                        )
                out[q] = n
            for q, extra in moved.items():
                out[q] = out.get(q, 0) + extra
            for _mid, (_o, q, _msg) in self.inflight.items():
                out[q] = out.get(q, 0) + 1
            for s, log in self.streams.items():
                out[s] = len(log)
            return out

    def stream_snapshot(self, name: str) -> list[bytes]:
        with self.lock:
            return list(self.streams.get(name, ()))


# ---------------------------------------------------------------------------
# Raft node
# ---------------------------------------------------------------------------


@dataclass
class _Waiter:
    term: int
    event: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    failed: bool = False


# ---------------------------------------------------------------------------
# Wire-layer fault injection (the netem corrupt/duplicate/delay family)
# ---------------------------------------------------------------------------

#: RPC kinds that are idempotent at the protocol level — the only ones
#: wire duplication re-delivers.  A ``client_op`` forward rides a
#: TCP-like stream whose transport dedups segments, and re-submitting
#: it would fabricate an application-level duplicate no real wire can;
#: the consensus RPCs are replayed by design, so a duplicate is a legal
#: schedule Raft must already tolerate.
IDEMPOTENT_RPCS = ("append_entries", "request_vote")


@dataclass
class WireFaultSpec:
    """Per-node wire-fault rates, applied to frames this node SENDS
    (its side of the wire): each outgoing frame independently risks one
    corrupted byte, a duplicate delivery (idempotent RPCs only), and a
    pre-send delay that lets concurrent frames overtake (reordering)."""

    corrupt_p: float = 0.0
    duplicate_p: float = 0.0
    delay_p: float = 0.0
    delay_ms: float = 0.0

    def validate(self) -> "WireFaultSpec":
        for name in ("corrupt_p", "duplicate_p", "delay_p"):
            p = getattr(self, name)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"wire fault {name}={p} outside [0, 1]")
        if self.delay_p > 0.0 and self.delay_ms <= 0.0:
            raise ValueError(
                "wire delay_p > 0 with delay_ms <= 0 is a no-fault no-op"
            )
        if self.delay_ms < 0.0:
            raise ValueError(f"wire delay_ms={self.delay_ms} negative")
        return self

    def active(self) -> bool:
        return max(self.corrupt_p, self.duplicate_p, self.delay_p) > 0.0


def corrupt_frame(data: bytes, rng: random.Random) -> bytes:
    """Flip ONE digit byte of a serialized frame to a different digit.
    Digit→digit is always JSON-safe (numbers stay numbers, digits inside
    base64 strings stay string bytes), which makes this the nasty
    corruption class: the frame still parses, only its MEANING changed —
    terms, indices, commit counts, payload bytes.  Structurally broken
    frames are already rejected by the JSON parser, checksum or not.
    The trailing newline (framing) is never touched."""
    idxs = [i for i, b in enumerate(data) if 0x30 <= b <= 0x39]
    if not idxs:
        return data
    i = rng.choice(idxs)
    repl = rng.choice([d for d in b"0123456789" if d != data[i]])
    return data[:i] + bytes([repl]) + data[i + 1 :]


class RaftNode:
    """One Raft participant; RPCs are newline-delimited JSON over TCP.

    ``peers`` maps node name -> (host, replication_port) for *all* nodes
    including this one.  ``apply_fn(index, op)`` is called exactly once
    per committed entry, in log order, on every node."""

    def __init__(
        self,
        name: str,
        peers: dict[str, tuple[str, int]],
        apply_fn: Callable[[int, dict], Any],
        election_timeout: tuple[float, float] = (0.25, 0.5),
        heartbeat_s: float = 0.06,
        dead_owner_s: float = 1.5,
        seed_bug: str | None = None,
        rng_seed: int | None = None,
        data_dir: str | None = None,
        bootstrap: bool = True,
    ):
        self.name = name
        self.peers = dict(peers)
        self.others = [p for p in peers if p != name]
        #: the config this node was BORN with; the live config is the
        #: latest committed-or-appended ``cfg`` log entry, falling back
        #: to this (recomputed on append/truncate — Raft §6: membership
        #: changes take effect when written, not when committed)
        self._initial_peers = dict(peers)
        #: a node started self-only with ``bootstrap=False`` is PENDING:
        #: it neither campaigns nor serves until a join_request lands it
        #: in a leader's config and replication hands it the cfg entry
        self.bootstrap = bootstrap
        self._join_lock = threading.Lock()
        self._retired = False  # set when a cfg entry removes this node
        self.apply_fn = apply_fn
        self.eto = election_timeout
        self.heartbeat_s = heartbeat_s
        self.dead_owner_s = dead_owner_s
        self.seed_bug = seed_bug
        self.rng = random.Random(rng_seed)

        #: cluster telemetry (ISSUE 12): counters + the WAL-fsync
        #: latency sketch, read at poll granularity (never per-op) via
        #: stats_snapshot / the admin STATS command.  Maintaining them
        #: is a handful of int adds per RPC/fsync — always on, like the
        #: pipeline's metrics-view accounting.
        self.counters = NodeCounters()
        self._fsync_ms = QuantileSketch()

        # runtime fault hooks (nemesis-driven via the broker admin port)
        self._fsync_delay_ms = 0.0
        self._fsync_jitter_ms = 0.0
        self._fault_lock = threading.Lock()
        self._fault_rng = random.Random(rng_seed)
        self._wire: WireFaultSpec | None = None
        #: wire-duplication re-sends ride ONE reusable worker (started
        #: lazily on the first duplicate) — a fresh daemon thread per
        #: duplicated frame would be the advisor-r5 thread-churn
        #: anti-pattern the _hb_loop worker in this file exists to avoid
        self._dup_pending: deque[tuple[tuple[str, int], bytes]] = deque()
        self._dup_event = threading.Event()
        self._dup_worker_started = False

        self.lock = threading.RLock()
        self.state = FOLLOWER
        self.term = 0
        self.voted_for: str | None = None
        self.log: list[tuple[int, dict]] = []  # (term, op)
        self.commit_idx = 0  # 1-based count of committed entries
        self.applied_idx = 0
        self.leader_hint: str | None = None
        self.next_idx: dict[str, int] = {}
        self.match_idx: dict[str, int] = {}
        self.last_peer_ok: dict[str, float] = {}
        #: peers with a catch-up loop in flight (single-flight per peer)
        self._replicating: set[str] = set()
        self.waiters: dict[int, _Waiter] = {}
        self.blocked: set[str] = set()
        self._last_heartbeat = time.monotonic()
        self._election_deadline = self._fresh_deadline()

        self.data_dir = data_dir
        self._wal_fh = None
        if data_dir is not None:
            self._recover()  # sets term/voted_for/log from disk
            # a durable node's vote survived the crash: no re-vote hole,
            # so it participates immediately (real Raft semantics)
            self._grace_until = time.monotonic()
        else:
            # startup grace: a memory-only node must not vote/campaign
            # until it has heard from a live leader or sat out several
            # timeouts
            self._grace_until = time.monotonic() + 3 * self.eto[1]
        self._requeued_dead: dict[str, float] = {}
        #: busy-peer heartbeat dispatch: the ticker deposits peers here
        #: and one REUSABLE worker thread sends the heartbeats — a fresh
        #: daemon thread per busy peer per tick was continuous thread
        #: churn at tick rate during long catch-ups (advisor r5)
        self._hb_pending: set[str] = set()
        self._hb_event = threading.Event()

        host, port = self.peers[name]
        self._server = socket.create_server((host, port))
        self.port = self._server.getsockname()[1]
        self.peers[name] = (host, self.port)
        self._running = True
        self._threads = [
            threading.Thread(target=self._accept_loop, daemon=True),
            threading.Thread(target=self._ticker, daemon=True),
            threading.Thread(target=self._hb_loop, daemon=True),
        ]
        for t in self._threads:
            t.start()

    # -- lifecycle ----------------------------------------------------------
    def stop(self) -> None:
        self._running = False
        self._hb_event.set()  # unblock the heartbeat worker so it exits
        self._dup_event.set()  # likewise the duplicate-sender, if started
        try:
            self._server.close()
        except OSError:
            pass
        # unblock a pending accept(): on Linux, close() does not
        # interrupt a thread already blocked in accept() — the in-flight
        # syscall keeps the LISTEN socket alive, so the port would stay
        # bound (un-rebindable by an in-process restart) until the next
        # stray peer RPC happened along
        try:
            socket.create_connection(
                ("127.0.0.1", self.port), 0.2
            ).close()
        except OSError:
            pass
        with self.lock:
            if self._wal_fh is not None:
                try:
                    self._wal_fh.close()
                except OSError:
                    pass
                self._wal_fh = None

    def _fresh_deadline(self) -> float:
        return time.monotonic() + self.rng.uniform(*self.eto)

    # -- durability ---------------------------------------------------------
    # Contract (Raft §5): term/vote and log entries must be on stable
    # storage BEFORE the node answers the RPC (or, on the leader, before
    # the entry counts toward commit).  Callers hold self.lock.

    def _recover(self) -> None:
        os.makedirs(self.data_dir, exist_ok=True)
        meta_p = os.path.join(self.data_dir, "meta.json")
        try:
            with open(meta_p) as fh:
                meta = json.load(fh)
            self.term = int(meta.get("term", 0))
            self.voted_for = meta.get("voted_for")
            self.counters.recoveries += 1  # prior durable state found
        except (OSError, ValueError):
            pass
        wal_p = os.path.join(self.data_dir, "wal.jsonl")
        try:
            good = 0  # byte offset of the end of the last intact record
            with open(wal_p, "rb") as fh:
                for raw in fh:
                    line = raw.strip()
                    if line:
                        try:
                            rec = json.loads(line.decode())
                        except ValueError:
                            break  # torn tail write: gone from here on
                        if not raw.endswith(b"\n"):
                            break  # intact JSON but no newline: still torn
                        if "trunc" in rec:
                            del self.log[rec["trunc"] - 1 :]
                        else:
                            self.log.append((rec["t"], rec["op"]))
                    good += len(raw)
            # drop the torn bytes NOW: later appends reopen in "a" mode,
            # and records written after a leftover partial line would be
            # unreadable by the next recovery (fsync'd yet lost)
            if good < os.path.getsize(wal_p):
                logger.warning(
                    "raft %s WAL recovery: dropping %d torn tail bytes "
                    "(recovered %d entries)",
                    self.name, os.path.getsize(wal_p) - good, len(self.log),
                )
                with open(wal_p, "rb+") as fh:
                    fh.truncate(good)
                    fh.flush()
                    os.fsync(fh.fileno())
            self.counters.wal_bytes = good  # recovered WAL size
        except OSError:
            pass
        # recovered entries re-apply as commit_idx advances (apply is
        # deterministic, the machine starts empty — exact replay); a
        # recovered cfg entry restores the cluster membership too
        self._recompute_config_locked()

    def _persist_meta_locked(self) -> None:
        if self.data_dir is None:
            return
        try:
            tmp = os.path.join(self.data_dir, "meta.json.tmp")
            with open(tmp, "w") as fh:
                json.dump(
                    {"term": self.term, "voted_for": self.voted_for}, fh
                )
                fh.flush()
                self._timed_fsync(fh.fileno())
            os.replace(tmp, os.path.join(self.data_dir, "meta.json"))
        except OSError as e:
            self._fail_stop_locked("meta persist failed", e)

    def _wal_write_locked(self, records: list[dict]) -> None:
        """Append ``records`` to the WAL and fsync — unless the
        ``ack-before-fsync`` seeded bug is on, in which case the records
        go nowhere: the process keeps acting on its in-memory log while
        the durable log silently falls behind (lost on SIGKILL)."""
        if self.data_dir is None or not records:
            return
        if self.seed_bug == "ack-before-fsync":
            return  # THE BUG: ack/commit proceeds, storage never told
        try:
            if self._wal_fh is None:
                self._wal_fh = open(
                    os.path.join(self.data_dir, "wal.jsonl"), "a"
                )
            data = "".join(
                json.dumps(r, separators=(",", ":")) + "\n"
                for r in records
            )
            self._wal_fh.write(data)
            self._wal_fh.flush()
            self._timed_fsync(self._wal_fh.fileno())
            self.counters.wal_bytes += len(data)
        except OSError as e:
            self._fail_stop_locked("WAL write failed", e)

    def _fail_stop_locked(self, why: str, exc: OSError) -> None:
        """A node that cannot persist must stop participating — acking
        state that isn't on disk would be a silent durability lie, and a
        retry of the same entries would find them already in the
        in-memory log and ack without ever writing them (review r4
        find).  Fail-stop is what real Raft stores do on fsync failure
        (fsyncgate).  The raised OSError makes the in-flight RPC go
        unanswered and the in-flight client op fail/drop."""
        logger.error("raft %s fail-stop: %s: %s", self.name, why, exc)
        self.stop()
        raise OSError(f"raft {self.name} fail-stop: {why}") from exc

    # -- public surface -----------------------------------------------------
    def is_leader(self) -> bool:
        with self.lock:
            return self.state == LEADER

    def role(self) -> tuple[str, int, str | None]:
        with self.lock:
            return self.state, self.term, self.leader_hint

    def stats_snapshot(self) -> dict:
        """One point-in-time telemetry snapshot (obs/cluster.py's raft
        block; JSON-safe — it rides the admin ``STATS`` line).  Gauges
        are read under the node lock; counters/sketch carry the usual
        unlocked-accuracy contract."""
        with self.lock:
            state, term, hint = self.state, self.term, self.leader_hint
            commit, applied = self.commit_idx, self.applied_idx
            log_len = len(self.log)
        return {
            "name": self.name,
            "role": state,
            "term": term,
            "leader_hint": hint,
            "commit_idx": commit,
            "applied_idx": applied,
            "log_len": log_len,
            "durable": self.data_dir is not None,
            "counters": self.counters.snapshot(),
            "fsync_ms": self._fsync_ms.state(),
        }

    def block(self, peer: str) -> None:
        with self.lock:
            self.blocked.add(peer)

    def unblock_all(self) -> None:
        with self.lock:
            self.blocked.clear()

    # -- runtime fault hooks ------------------------------------------------
    def set_fsync_latency(
        self, mean_ms: float, jitter_ms: float = 0.0
    ) -> None:
        """Slow-disk injection: every subsequent real fsync (WAL append,
        term/vote persist) stalls ``mean ± jitter`` ms, like a
        device-mapper delay target under the store.  Refused on a
        memory-only node — with no WAL there is no fsync to slow, and a
        silently-absent fault would let a run claim "tolerates slow
        disks" without one (the false-green-by-absent-fault class this
        codebase refuses everywhere)."""
        if mean_ms < 0.0 or jitter_ms < 0.0:
            raise ValueError("fsync latency must be non-negative")
        if self.data_dir is None and (mean_ms or jitter_ms):
            raise ValueError(
                f"raft {self.name} is memory-only (no WAL): fsync "
                f"latency would be a no-fault no-op; use durable mode"
            )
        with self._fault_lock:
            self._fsync_delay_ms = float(mean_ms)
            self._fsync_jitter_ms = float(jitter_ms)

    def set_wire_faults(self, spec: WireFaultSpec | None) -> None:
        """Install (or with ``None`` clear) this node's outgoing wire
        fault spec — netem's corrupt/duplicate/delay on the peer RPC
        plane."""
        if spec is not None:
            spec.validate()
        with self._fault_lock:
            self._wire = spec

    def _timed_fsync(self, fileno: int) -> None:
        """One real WAL/meta fsync (stall included), timed into the
        per-node fsync latency sketch.  ``ack-before-fsync`` never
        reaches here, so under that bug the sketch stays empty while
        everything else proceeds — the telemetry-visible tell the
        differential suite pins (tests/test_cluster_obs.py)."""
        t0 = time.perf_counter()
        self._fsync_stall()
        os.fsync(fileno)
        self._fsync_ms.add((time.perf_counter() - t0) * 1e3)

    def _fsync_stall(self) -> None:
        """The slow disk itself: called immediately before each real
        ``os.fsync``.  Stalls the calling thread (which holds the node
        lock — a node waiting on its disk IS stalled, that is the
        fault).  Note ``ack-before-fsync`` never reaches here: a node
        that skips storage is fast, which is exactly the tell the
        slow-disk red/green pair pins."""
        with self._fault_lock:
            mean, jit = self._fsync_delay_ms, self._fsync_jitter_ms
            extra = self._fault_rng.uniform(-jit, jit) if jit else 0.0
        if mean > 0.0 or jit > 0.0:
            time.sleep(max(0.0, mean + extra) / 1000.0)

    # -- frame integrity + wire mangling ------------------------------------
    # Frame format: b"%08x " % crc32(body) + body + b"\n" — the CRC is
    # out-of-band so the sender serializes ONCE and the receiver
    # verifies against the raw received bytes with no re-serialization
    # (this runs on every heartbeat/append at tick rate x peers).  The
    # ``no-wire-checksum`` seeded bug sends the bare body instead.

    def _frame(self, msg: dict) -> bytes:
        body = json.dumps(msg).encode()
        if self.seed_bug == "no-wire-checksum":
            return body + b"\n"
        return b"%08x " % zlib.crc32(body) + body + b"\n"

    def _parse_frame(self, buf: bytes) -> dict | None:
        """Parse (and with checksums on, CRC-verify) one received frame;
        ``None`` means drop it.  A frame whose CRC prefix is absent or
        wrong is corrupted-in-flight: corruption degrades to packet
        loss, which the protocol already retries through.  Under the
        seeded bug nothing is verified — a mangled frame that still
        parses is PROCESSED (the bug)."""
        line = buf.rstrip(b"\n")
        if self.seed_bug == "no-wire-checksum":
            if line[:1] != b"{" and line[8:9] == b" ":
                line = line[9:]  # a checksummed peer's prefix, ignored
            try:
                msg = json.loads(line.decode())
            except (ValueError, UnicodeDecodeError):
                return None
            return msg if isinstance(msg, dict) else None
        if len(line) < 10 or line[8:9] != b" ":
            self.counters.crc_rejected += 1
            return None  # no CRC while checksums are on: corrupted
        body = line[9:]
        try:
            ok = int(line[:8], 16) == zlib.crc32(body)
        except ValueError:
            ok = False
        if not ok:
            self.counters.crc_rejected += 1
            logger.debug(
                "raft %s: dropped corrupted frame (crc mismatch)",
                self.name,
            )
            return None
        try:
            msg = json.loads(body.decode())
        except (ValueError, UnicodeDecodeError):
            return None
        return msg if isinstance(msg, dict) else None

    def _wire_mangle(
        self, data: bytes, rpc: str | None
    ) -> tuple[bytes, float, bool]:
        """Apply this node's wire spec to one outgoing frame: returns
        ``(bytes, pre-send delay seconds, send a duplicate?)``."""
        with self._fault_lock:
            spec, rng = self._wire, self._fault_rng
            if spec is None or not spec.active():
                return data, 0.0, False
            delay = (
                spec.delay_ms / 1000.0
                if spec.delay_p and rng.random() < spec.delay_p
                else 0.0
            )
            dup = bool(
                spec.duplicate_p
                and rpc in IDEMPOTENT_RPCS
                and rng.random() < spec.duplicate_p
            )
            if spec.corrupt_p and rng.random() < spec.corrupt_p:
                data = corrupt_frame(data[:-1], rng) + b"\n"
                self.counters.wire_corrupt += 1
            if dup:
                self.counters.wire_duplicate += 1
            if delay:
                self.counters.wire_delay += 1
        return data, delay, dup

    def submit(self, op: dict, timeout_s: float = 5.0) -> tuple[bool, Any]:
        """Commit ``op`` and return ``(True, result)``; ``(False, None)``
        when no commit happened within the deadline.

        Retries inside the deadline ONLY when the previous attempt is
        *known* to have left no log entry behind (no leader yet, the
        contacted node answered "not the leader", or our appended entry
        was truncated) — an attempt with an indeterminate outcome (commit
        wait or forward that timed out after the request was sent) must
        not be retried, or a slow-but-successful first attempt would
        double-enqueue."""
        deadline = time.monotonic() + timeout_s
        while True:
            if not self._running or self._retired:
                return False, None  # stopped/forgotten: never ack
            with self.lock:
                leader = self.state == LEADER
                hint = self.leader_hint
            if leader:
                status, result = self._submit_local(op, deadline)
                if status == "ok":
                    return True, result
                if status == "timeout":
                    return False, None  # indeterminate — never retry
                # "lost": entry definitively truncated — safe to retry
            elif hint is not None and hint != self.name and (
                hint in self.peers
            ):  # a mid-catch-up node may know the leader's NAME before
                # the cfg entry carrying its ADDRESS arrives
                resp = self._rpc(
                    hint,
                    {"rpc": "client_op", "op": op, "from": self.name},
                    timeout_s=max(0.05, deadline - time.monotonic()),
                )
                if resp is not None and resp.get("ok"):
                    return True, _decode_result(resp.get("result"))
                if resp is None or not resp.get("definite"):
                    return False, None  # indeterminate — never retry
                with self.lock:
                    if self.leader_hint == hint:
                        self.leader_hint = None  # stale hint — rediscover
            if time.monotonic() + 0.05 >= deadline:
                return False, None
            time.sleep(0.05)

    def _submit_local(self, op: dict, deadline: float) -> tuple[str, Any]:
        """One local-leader attempt: ``("ok", result)``, ``("timeout",
        None)`` (indeterminate), or ``("lost", None)`` (entry truncated —
        definitely not committed)."""
        with self.lock:
            if self.state != LEADER:
                return "lost", None
            self.log.append((self.term, op))
            index = len(self.log)  # 1-based
            self._wal_write_locked([{"t": self.term, "op": op}])
            if op.get("k") == "cfg":
                self._recompute_config_locked()  # effective on APPEND (§6)
            if self.seed_bug == "confirm-before-quorum" and op["k"] in (
                "enq",
                "txn",
            ):
                # THE BUG: report success on local append, before any
                # replica holds the entry (replication continues async;
                # no waiter — nobody ever looks at the real outcome)
                threading.Thread(
                    target=self._replicate_once, daemon=True
                ).start()
                return "ok", None
            w = _Waiter(term=self.term)
            self.waiters[index] = w
            if not self.others:
                self._advance_commit_locked()  # 1-node: own ack is quorum
        self._replicate_once()
        w.event.wait(max(0.0, deadline - time.monotonic()))
        with self.lock:
            self.waiters.pop(index, None)
        if not w.event.is_set():
            return "timeout", None
        if w.failed:
            return "lost", None
        return "ok", w.result

    # -- dynamic membership -------------------------------------------------
    def _recompute_config_locked(self) -> None:
        """Reset the live config to the latest ``cfg`` entry in the log
        (or the initial config when none remains — e.g. after a
        truncation removed it).  Keeps this node's actual bound address
        and seeds replication bookkeeping for newly-learned peers."""
        cfg = None
        for _t, op in reversed(self.log):
            if op.get("k") == "cfg":
                cfg = op["peers"]
                break
        if cfg is not None:
            peers = {n: (a[0], int(a[1])) for n, a in cfg.items()}
        else:
            peers = dict(self._initial_peers)
        # a cfg that excludes US means we were forgotten (RemoveServer):
        # retire — keep answering RPCs (the remover's commit may still
        # need our ack under the OLD config) but never campaign or serve
        # again.  The choreography only forgets stopped nodes, so this
        # is defense-in-depth, and it reverses if the entry truncates.
        self._retired = cfg is not None and self.name not in cfg
        peers[self.name] = self.peers[self.name]  # our true bound port
        prev_others = set(self.others)
        self.peers = peers
        self.others = [p for p in peers if p != self.name]
        now = time.monotonic()
        for p in self.others:
            if p not in prev_others:
                # newly learned — or RE-added under the same name after a
                # forget + wipe: its previous incarnation's match/next
                # bookkeeping describes a log the fresh node does not
                # have, so overwrite rather than setdefault (stale
                # match_idx would otherwise count ghost acks toward
                # commit, and stale next_idx costs a wasted AppendEntries
                # round before backoff — advisor r4)
                self.next_idx[p] = len(self.log) + 1
                self.match_idx[p] = 0
                self.last_peer_ok[p] = now
            else:
                self.next_idx.setdefault(p, len(self.log) + 1)
                self.match_idx.setdefault(p, 0)
                self.last_peer_ok.setdefault(p, now)

    def _pending_locked(self) -> bool:
        """True while this node must not campaign: not-yet-joined
        (non-bootstrap, self-only — a self-elected 1-node 'leader'
        would confirm unreplicated publishes) or forgotten
        (RemoveServer took us out of the config)."""
        if getattr(self, "_retired", False):
            return True
        return not self.bootstrap and len(self.peers) == 1

    def request_join(
        self, leader_addr: tuple[str, int], timeout_s: float = 12.0
    ) -> bool:
        """Ask the cluster at ``leader_addr`` to add us (the
        ``rabbitmqctl join_cluster`` mapping).  Retries until the leader
        commits the membership change AND the cfg entry has replicated
        back to us (so a caller that proceeds to serve traffic is a real
        member, not still pending)."""
        host, port = self.peers[self.name]
        msg = {
            "rpc": "join_request",
            "name": self.name,
            "host": host,
            "port": self.port,
            "from": self.name,
        }
        deadline = time.monotonic() + timeout_s
        accepted = False
        while time.monotonic() < deadline:
            if not accepted:
                resp = self._rpc_addr(
                    leader_addr, msg,
                    timeout_s=min(5.0, deadline - time.monotonic()),
                )
                accepted = bool(resp and resp.get("ok"))
                if not accepted:
                    time.sleep(0.2)
                    continue
            with self.lock:
                if len(self.peers) > 1:
                    return True  # the cfg entry reached us: full member
            time.sleep(0.05)
        return False

    def _uncommitted_cfg_locked(self) -> bool:
        """True while a ``cfg`` entry sits appended-but-uncommitted.
        Single-server membership changes are only safe when each change
        is anchored to the *committed* config (the known hazard: leaders
        of different terms appending conflicting cfg entries whose new
        majorities are disjoint).  The per-leader ``_join_lock`` cannot
        enforce that across a leadership change, so the Raft layer
        itself refuses to stack a second change on an uncommitted first
        (advisor r4); callers retry, and the retry succeeds once the
        earlier entry commits."""
        for idx in range(len(self.log), self.commit_idx, -1):
            if self.log[idx - 1][1].get("k") == "cfg":
                return True
        return False

    def _on_join_request(self, msg: dict) -> dict:
        with self.lock:
            leader = self.state == LEADER
            hint = self.leader_hint
            hint_addr = self.peers.get(hint) if hint else None
            already = msg["name"] in self.peers
        if not leader:
            if already:
                # a member asking again (idempotent re-join): fine
                return {"ok": True}
            if hint_addr is not None and hint != self.name:
                # proxy to the leader (the choreography talks to the
                # PRIMARY, which is usually but not necessarily leader)
                resp = self._rpc_addr(hint_addr, msg, timeout_s=8.0)
                return resp if resp is not None else {"ok": False}
            return {"ok": False}
        with self._join_lock:  # serialize concurrent joins (§6: one at
            with self.lock:    # a time, each from the committed config)
                if msg["name"] in self.peers:
                    return {"ok": True}
                if self._uncommitted_cfg_locked():
                    return {"ok": False}  # retried by request_join
                peers = {n: [a[0], a[1]] for n, a in self.peers.items()}
            peers[msg["name"]] = [msg["host"], int(msg["port"])]
            ok, _ = self.submit({"k": "cfg", "peers": peers}, timeout_s=8.0)
        return {"ok": bool(ok)}

    def request_forget(self, target: str, timeout_s: float = 12.0) -> bool:
        """Remove ``target`` from the cluster (``rabbitmqctl
        forget_cluster_node`` — RemoveServer, §6).  Called on any
        surviving member; forwarded to the leader.  The choreography
        only forgets STOPPED nodes (as real RabbitMQ requires — a dead
        node cannot campaign, which is what makes single-server removal
        safe without pre-vote machinery)."""
        msg = {"rpc": "forget_request", "name": target, "from": self.name}
        deadline = time.monotonic() + timeout_s
        accepted = False
        while time.monotonic() < deadline:
            if not accepted:
                # _on_forget_request handles both roles: submits when we
                # are the leader, proxies to the hint when we are not
                accepted = bool(self._on_forget_request(msg).get("ok"))
                if not accepted:
                    time.sleep(0.2)
                    continue
            with self.lock:
                if target not in self.peers:
                    return True  # the removal replicated back to us too
            time.sleep(0.05)  # committed at the leader; our copy lags
        return accepted  # committed cluster-wide even if our view lags

    def _on_forget_request(self, msg: dict) -> dict:
        target = msg["name"]
        with self.lock:
            leader = self.state == LEADER
            hint = self.leader_hint
            hint_addr = self.peers.get(hint) if hint else None
        if not leader:
            if hint_addr is not None and hint != self.name:
                resp = self._rpc_addr(hint_addr, msg, timeout_s=8.0)
                return resp if resp is not None else {"ok": False}
            return {"ok": False}
        if target == self.name:
            # real rabbitmqctl refuses too: run it from another node
            return {"ok": False, "error": "cannot forget myself"}
        with self._join_lock:  # same one-change-at-a-time rule as joins
            with self.lock:
                if target not in self.peers:
                    return {"ok": True}  # idempotent
                if self._uncommitted_cfg_locked():
                    return {"ok": False}  # retried by request_forget
                peers = {
                    n: [a[0], a[1]]
                    for n, a in self.peers.items()
                    if n != target
                }
            ok, _ = self.submit({"k": "cfg", "peers": peers}, timeout_s=8.0)
        return {"ok": bool(ok)}

    # -- RPC plumbing -------------------------------------------------------
    def _rpc(
        self, peer: str, msg: dict, timeout_s: float = 0.5
    ) -> dict | None:
        """One request/response to ``peer``.  If we block input from the
        peer, the request still goes out but the response is discarded —
        iptables INPUT-drop semantics (see module docstring)."""
        addr = self.peers.get(peer)
        if addr is None:
            return None  # peer left the config between check and call
        return self._rpc_addr(addr, msg, timeout_s=timeout_s,
                              blocked_peer=peer)

    def _rpc_addr(
        self,
        addr: tuple[str, int],
        msg: dict,
        timeout_s: float = 0.5,
        blocked_peer: str | None = None,
    ) -> dict | None:
        host, port = addr
        if not self._running:
            # a stopped node is silent on the wire: lingering daemon
            # threads (a replication loop mid-batch, a late heartbeat)
            # must not keep speaking for a "dead" node — in-process
            # restarts reuse the ports, and a ghost leader's appends
            # would resurrect state a real SIGKILL would have destroyed
            return None
        data, delay, dup = self._wire_mangle(
            self._frame(msg), msg.get("rpc")
        )
        if delay:
            # held frame: concurrent RPCs from other threads overtake
            # (the wire's reordering), then this one goes out late
            time.sleep(delay)
        if dup:
            self._enqueue_duplicate(addr, data)
        try:
            with socket.create_connection(
                (host, port), timeout=min(0.25, timeout_s)
            ) as s:
                s.sendall(data)
                self.counters.rpc_sent += 1
                if blocked_peer is not None:
                    with self.lock:
                        drop_reply = blocked_peer in self.blocked
                    if drop_reply:
                        self.counters.rpc_dropped += 1
                        return None
                s.settimeout(timeout_s)
                buf = b""
                while not buf.endswith(b"\n"):
                    chunk = s.recv(65536)
                    if not chunk:
                        return None
                    buf += chunk
                # a corrupted reply drops like a lost one (crc mismatch)
                resp = self._parse_frame(buf)
                if resp is None:
                    self.counters.rpc_dropped += 1
                else:
                    # replies count as received frames too — sent and
                    # recv stay symmetric on a healthy cluster
                    self.counters.rpc_recv += 1
                return resp
        except (OSError, ValueError):
            return None

    def _enqueue_duplicate(
        self, addr: tuple[str, int], data: bytes
    ) -> None:
        """Hand a frame to the reusable duplicate-sender worker.  The
        queue is bounded: under backlog a duplicate is simply not
        re-delivered, which is a legal wire schedule (duplication is
        best-effort chaos, never a protocol obligation)."""
        with self._fault_lock:
            if not self._dup_worker_started:
                self._dup_worker_started = True
                threading.Thread(
                    target=self._dup_loop, daemon=True
                ).start()
            if len(self._dup_pending) < 64:
                self._dup_pending.append((addr, data))
        self._dup_event.set()

    def _dup_loop(self) -> None:
        """Fire-and-forget re-delivery of idempotent RPC frames (the
        wire's duplication); responses are discarded."""
        while self._running:
            if not self._dup_event.wait(timeout=0.5):
                continue
            self._dup_event.clear()
            while True:
                with self._fault_lock:
                    if not self._dup_pending:
                        break
                    addr, data = self._dup_pending.popleft()
                try:
                    with socket.create_connection(addr, timeout=0.25) as s:
                        s.sendall(data)
                        self.counters.rpc_sent += 1
                except OSError:
                    pass

    def _accept_loop(self) -> None:
        while self._running:
            try:
                sock, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_one, args=(sock,), daemon=True
            ).start()

    def _serve_one(self, sock: socket.socket) -> None:
        try:
            sock.settimeout(10.0)
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = sock.recv(65536)
                if not chunk:
                    return
                buf += chunk
            msg = self._parse_frame(buf)
            if msg is None:
                self.counters.rpc_dropped += 1
                return  # corrupted in flight: dropped, like packet loss
            sender = msg.get("from")
            with self.lock:
                if sender in self.blocked:
                    self.counters.rpc_dropped += 1
                    return  # INPUT DROP: never processed, never answered
            self.counters.rpc_recv += 1
            resp = self._dispatch(msg)
            if resp is not None:
                # responses ride the same wire: corrupt/delay apply
                # (duplication on the same socket would be a no-op —
                # the caller reads one line)
                data, delay, _dup = self._wire_mangle(
                    self._frame(resp), None
                )
                if delay:
                    time.sleep(delay)
                sock.sendall(data)
                self.counters.rpc_sent += 1
        except (OSError, ValueError):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _dispatch(self, msg: dict) -> dict | None:
        rpc = msg.get("rpc")
        if rpc == "request_vote":
            return self._on_request_vote(msg)
        if rpc == "append_entries":
            return self._on_append_entries(msg)
        if rpc == "client_op":
            return self._on_client_op(msg)
        if rpc == "join_request":
            return self._on_join_request(msg)
        if rpc == "forget_request":
            return self._on_forget_request(msg)
        return {"ok": False, "error": f"unknown rpc {rpc!r}"}

    def _on_client_op(self, msg: dict) -> dict:
        with self.lock:
            if self.state != LEADER:
                # no entry appended: the forwarder may safely retry
                return {"ok": False, "definite": True}
        status, result = self._submit_local(msg["op"], time.monotonic() + 4.5)
        return {
            "ok": status == "ok",
            "definite": status == "lost",
            "result": _encode_result(result) if status == "ok" else None,
        }

    # -- Raft: votes --------------------------------------------------------
    def _on_request_vote(self, msg: dict) -> dict:
        with self.lock:
            if time.monotonic() < self._grace_until:
                # startup grace: an amnesiac node must not influence
                # elections until it has observed the living cluster
                return {"term": self.term, "granted": False}
            if msg["term"] > self.term:
                self._become_follower(msg["term"])
            granted = False
            if msg["term"] == self.term and self.voted_for in (
                None,
                msg["from"],
            ):
                last_term = self.log[-1][0] if self.log else 0
                up_to_date = (msg["last_log_term"], msg["last_log_idx"]) >= (
                    last_term,
                    len(self.log),
                )
                if up_to_date:
                    granted = True
                    self.voted_for = msg["from"]
                    self._persist_meta_locked()  # vote durable before reply
                    self._election_deadline = self._fresh_deadline()
            return {"term": self.term, "granted": granted}

    # -- Raft: replication --------------------------------------------------
    def _on_append_entries(self, msg: dict) -> dict:
        with self.lock:
            if msg["term"] < self.term:
                return {"term": self.term, "ok": False}
            if msg["term"] > self.term or self.state != FOLLOWER:
                self._become_follower(msg["term"])
            self.leader_hint = msg["from"]
            self._last_heartbeat = time.monotonic()
            self._election_deadline = self._fresh_deadline()
            # hearing a live leader ends startup grace early
            self._grace_until = min(self._grace_until, time.monotonic())

            prev = msg["prev_idx"]
            if prev > len(self.log):
                return {"term": self.term, "ok": False, "have": len(self.log)}
            if prev > 0 and self.log[prev - 1][0] != msg["prev_term"]:
                return {"term": self.term, "ok": False, "have": prev - 1}
            entries = [(t, op) for t, op in msg["entries"]]
            wal: list[dict] = []
            cfg_touched = False
            for i, (t, op) in enumerate(entries):
                idx = prev + i + 1  # 1-based
                if idx <= len(self.log):
                    if self.log[idx - 1][0] != t:
                        # conflict: truncate ours from idx on (losing any
                        # uncommitted divergence — the seeded bug's window)
                        if idx <= self.commit_idx:
                            # tripwire: this must be impossible (Raft
                            # safety — committed entries never truncate);
                            # if it ever fires, a confirmed-write loss is
                            # in progress and THIS is the smoking gun
                            self.counters.safety_violations += 1
                            logger.critical(
                                "raft %s SAFETY VIOLATION: truncating "
                                "COMMITTED entries [%d..%d] (commit_idx="
                                "%d) on append from %s term %d",
                                self.name, idx, len(self.log),
                                self.commit_idx, msg["from"], msg["term"],
                            )
                        del self.log[idx - 1 :]
                        self._fail_waiters_from(idx)
                        self.log.append((t, op))
                        wal.append({"trunc": idx})
                        wal.append({"t": t, "op": op})
                        cfg_touched = True  # truncation may drop a cfg
                else:
                    self.log.append((t, op))
                    wal.append({"t": t, "op": op})
                    if op.get("k") == "cfg":
                        cfg_touched = True
            self._wal_write_locked(wal)  # durable before the ok reply
            if cfg_touched:
                self._recompute_config_locked()  # §6: effective on append
            if msg["leader_commit"] > self.commit_idx:
                # Raft §5.3: commit advances at most to the index of the
                # last entry THIS RPC proved matching (prev + entries) —
                # never to leader_commit ∩ len(log) alone.  A heartbeat at
                # prev_idx=match_idx (0 right after election) reaching a
                # follower that still holds an uncommitted divergent
                # suffix from an older term must not commit that suffix:
                # applied entries never revert, so the un-capped form
                # turns a transient divergence into permanent
                # state-machine divergence (advisor r5, high).
                self.commit_idx = max(
                    self.commit_idx,
                    min(
                        msg["leader_commit"],
                        prev + len(entries),
                        len(self.log),
                    ),
                )
            self._apply_ready_locked()
            return {"term": self.term, "ok": True, "have": len(self.log)}

    def _fail_waiters_from(self, idx: int) -> None:
        for i, w in list(self.waiters.items()):
            if i >= idx:
                w.failed = True
                w.event.set()
                del self.waiters[i]

    def _apply_ready_locked(self) -> None:
        while self.applied_idx < self.commit_idx:
            self.applied_idx += 1
            term, op = self.log[self.applied_idx - 1]
            result = self.apply_fn(self.applied_idx, op)
            w = self.waiters.get(self.applied_idx)
            if w is not None:
                if w.term == term:
                    w.result = result
                else:
                    w.failed = True
                w.event.set()

    # -- Raft: roles --------------------------------------------------------
    def _become_follower(self, term: int) -> None:
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._persist_meta_locked()
        self.state = FOLLOWER

    def _become_leader_locked(self) -> None:
        self.state = LEADER
        self.leader_hint = self.name
        self.counters.elections_won += 1
        if self.data_dir is not None:
            # no-op entry (§8 / §5.4.2): recovered prior-term entries can
            # only commit via a committed current-term entry; after a
            # whole-cluster restart there may be no client traffic to
            # provide one, so the leader supplies it
            self.log.append((self.term, {"k": "noop"}))
            self._wal_write_locked([{"t": self.term, "op": {"k": "noop"}}])
        self.next_idx = {p: len(self.log) + 1 for p in self.others}
        self.match_idx = {p: 0 for p in self.others}
        now = time.monotonic()
        self.last_peer_ok = {p: now for p in self.others}
        if not self.others:
            self._advance_commit_locked()  # 1-node: leader alone is quorum

    def _start_election(self) -> None:
        with self.lock:
            if time.monotonic() < self._grace_until:
                self._election_deadline = self._fresh_deadline()
                return
            if self._pending_locked():
                # not yet a member of any cluster: self-electing would
                # make a 1-node "quorum" that confirms unreplicated
                self._election_deadline = self._fresh_deadline()
                return
            self.state = CANDIDATE
            self.term += 1
            self.voted_for = self.name
            self.counters.elections_started += 1
            self._persist_meta_locked()  # durable before soliciting votes
            term = self.term
            last_term = self.log[-1][0] if self.log else 0
            req = {
                "rpc": "request_vote",
                "term": term,
                "from": self.name,
                "last_log_idx": len(self.log),
                "last_log_term": last_term,
            }
            self._election_deadline = self._fresh_deadline()
        votes = [1]  # self
        done = threading.Event()
        with self.lock:
            # a single-node cluster is its own majority — there are no
            # peer-reply threads to run the count below
            if (
                self.state == CANDIDATE
                and self.term == term
                and votes[0] * 2 > len(self.peers)
            ):
                self._become_leader_locked()
                done.set()

        def ask(peer: str) -> None:
            resp = self._rpc(peer, req, timeout_s=self.eto[0])
            if resp is None:
                return
            with self.lock:
                if resp["term"] > self.term:
                    self._become_follower(resp["term"])
                    done.set()
                    return
                if (
                    self.state == CANDIDATE
                    and self.term == term
                    and resp.get("granted")
                ):
                    votes[0] += 1
                    if votes[0] * 2 > len(self.peers):
                        self._become_leader_locked()
                        done.set()

        threads = [
            threading.Thread(target=ask, args=(p,), daemon=True)
            for p in self.others
        ]
        for t in threads:
            t.start()
        done.wait(self.eto[0])
        with self.lock:
            if self.state == LEADER:
                pass  # heartbeats start on the next tick (immediately)
            elif self.state == CANDIDATE:
                self.state = FOLLOWER  # re-candidate on next deadline

    def _replicate_once(self) -> None:
        """One replication round to every peer (called from the ticker and
        immediately after a local submit).  Per-peer single-flight: a
        peer already mid-catch-up gets a lightweight HEARTBEAT instead —
        a batched catch-up RPC can outlast the follower's election
        timeout, and a follower whose replies are slow/lost must still
        see appends at tick rate or it starts disruptive elections the
        one-RPC-at-a-time loop alone would cause (review r5)."""
        with self.lock:
            if self.state != LEADER:
                return
            term = self.term
            fresh = [p for p in self.others if p not in self._replicating]
            busy = [p for p in self.others if p in self._replicating]
            self._replicating.update(fresh)
        for peer in fresh:
            threading.Thread(
                target=self._replicate_peer_loop,
                args=(peer, term),
                daemon=True,
            ).start()
        if busy:
            # hand busy peers to the single reusable heartbeat worker —
            # the set dedups, so a worker mid-send coalesces repeat ticks
            # instead of queueing one heartbeat per tick per peer
            with self.lock:
                self._hb_pending.update(busy)
            self._hb_event.set()

    def _hb_loop(self) -> None:
        """The reusable busy-peer heartbeat worker (see _replicate_once).
        Serial sends are fine at this fan-in: only peers mid-catch-up
        land here, an unreachable peer costs at most the 250 ms connect
        clip, and a reachable one answers in microseconds locally."""
        while self._running:
            if not self._hb_event.wait(timeout=0.5):
                continue
            self._hb_event.clear()
            while True:
                with self.lock:
                    if self.state != LEADER or not self._hb_pending:
                        self._hb_pending.clear()
                        break
                    peer = self._hb_pending.pop()
                    term = self.term
                self._heartbeat_peer(peer, term)

    def _heartbeat_peer(self, peer: str, term: int) -> None:
        """Empty AppendEntries at a known-matching point: feeds the
        follower's election timer (its deadline resets on receipt,
        before any log checks) without touching the catch-up loop's
        next/match bookkeeping."""
        with self.lock:
            if self.state != LEADER or self.term != term:
                return
            prev = min(self.match_idx.get(peer, 0), len(self.log))
            prev_term = self.log[prev - 1][0] if prev > 0 else 0
            msg = {
                "rpc": "append_entries",
                "term": term,
                "from": self.name,
                "prev_idx": prev,
                "prev_term": prev_term,
                "entries": [],
                "leader_commit": self.commit_idx,
            }
        resp = self._rpc(peer, msg, timeout_s=min(0.2, self.eto[0]))
        if resp is None:
            return
        with self.lock:
            if resp["term"] > self.term:
                self._become_follower(resp["term"])
            elif self.state == LEADER and self.term == term:
                self.last_peer_ok[peer] = time.monotonic()

    def _replicate_peer_loop(self, peer: str, term: int) -> None:
        """Batches back-to-back until the peer is caught up (or stops
        answering).  One batch per ticker tick was the round-5 burn-in's
        failed-rejoin cause: a fresh joiner replaying a long run's log
        (60k+ entries at 256/batch) needed hundreds of ticks — minutes —
        while ``request_join`` waits seconds.  The loop bound is a
        runaway backstop, not a contract; the next tick re-engages."""
        try:
            for _ in range(4096):
                if not self._replicate_peer(peer, term):
                    return
        finally:
            with self.lock:
                self._replicating.discard(peer)
                # closed race (review r5): a submit that arrived while
                # this loop was deciding to exit had its replication
                # kick swallowed by the single-flight skip — re-engage
                # rather than waiting out a full tick
                behind = (
                    self.state == LEADER
                    and self.term == term
                    and self.match_idx.get(peer, 0) < len(self.log)
                )
            if behind:
                self._replicate_once()

    def _replicate_peer(self, peer: str, term: int) -> bool:
        """One AppendEntries batch; True iff the peer acked AND remains
        behind (the caller should continue immediately)."""
        with self.lock:
            if self.state != LEADER or self.term != term:
                return False
            nxt = self.next_idx.get(peer, len(self.log) + 1)
            prev = nxt - 1
            prev_term = self.log[prev - 1][0] if prev > 0 else 0
            entries = self.log[prev : prev + 256]
            msg = {
                "rpc": "append_entries",
                "term": term,
                "from": self.name,
                "prev_idx": prev,
                "prev_term": prev_term,
                "entries": entries,
                "leader_commit": self.commit_idx,
            }
        resp = self._rpc(peer, msg, timeout_s=self.eto[0])
        if resp is None:
            return False  # unreachable: the next tick retries
        with self.lock:
            if resp["term"] > self.term:
                self._become_follower(resp["term"])
                return False
            if self.state != LEADER or self.term != term:
                return False
            self.last_peer_ok[peer] = time.monotonic()
            if resp.get("ok"):
                self.match_idx[peer] = prev + len(entries)
                self.next_idx[peer] = self.match_idx[peer] + 1
                self._advance_commit_locked()
                return self.match_idx[peer] < len(self.log)
            # follower is behind/diverged: back off (its hint if given)
            # and immediately probe again — convergence must not wait a
            # tick per backoff step either
            self.next_idx[peer] = max(
                1, min(resp.get("have", prev - 1) + 1, nxt - 1)
            )
            return True

    def _advance_commit_locked(self) -> None:
        for idx in range(len(self.log), self.commit_idx, -1):
            if self.log[idx - 1][0] != self.term:
                break  # only current-term entries commit by counting (§5.4.2)
            acks = 1 + sum(
                1 for p in self.others if self.match_idx.get(p, 0) >= idx
            )
            if acks * 2 > len(self.peers):
                self.commit_idx = idx
                self._apply_ready_locked()
                break

    # -- ticker -------------------------------------------------------------
    def _ticker(self) -> None:
        while self._running:
            time.sleep(self.heartbeat_s)
            with self.lock:
                state = self.state
                deadline = self._election_deadline
            if state == LEADER:
                self._replicate_once()
                self._leader_health_checks()
            elif time.monotonic() >= deadline:
                self._start_election()

    def _leader_health_checks(self) -> None:
        now = time.monotonic()
        with self.lock:
            if self.state != LEADER:
                return
            # step down when a majority has been silent for a full
            # election timeout: we cannot commit, so we must not pretend
            # to lead (clients would wait on confirms that can't happen)
            silent = sum(
                1
                for p in self.others
                if now - self.last_peer_ok.get(p, now) > self.eto[1]
            )
            if (len(self.others) - silent + 1) * 2 <= len(self.peers):
                self._become_follower(self.term)
                self._election_deadline = self._fresh_deadline()
                return
            # requeue inflight deliveries owned by nodes that have been
            # unreachable long enough to be presumed dead (at-least-once:
            # a paused-not-dead node's consumer sees a redelivery later)
            dead = [
                p
                for p in self.others
                if now - self.last_peer_ok.get(p, now) > self.dead_owner_s
            ]
        if self.seed_bug == "drop-unacked-on-close":
            # the seeded fault is "the requeue machinery is broken":
            # every resurrection path stays off, or a later reap would
            # quietly heal the injected loss before the checker sees it
            return
        for node in dead:
            if now - self._requeued_dead.get(node, 0) < self.dead_owner_s:
                continue
            self._requeued_dead[node] = now
            # off-thread: a commit wait must never stall the heartbeat loop
            threading.Thread(
                target=self.submit,
                args=({"k": "requeue_node", "node": node},),
                kwargs={"timeout_s": 1.0},
                daemon=True,
            ).start()


def _encode_result(result: Any) -> Any:
    if isinstance(result, _RMsg):
        return {
            "_rmsg": True,
            "mid": result.mid,
            "ts": result.ts_ms,
            "body": base64.b64encode(result.body).decode(),
            "props": base64.b64encode(result.props).decode(),
            "fence": result.fence,
        }
    if isinstance(result, list) and all(
        isinstance(x, bytes) for x in result
    ):
        return {
            "_blist": [base64.b64encode(x).decode() for x in result]
        }
    return result


def _decode_result(result: Any) -> Any:
    if isinstance(result, dict) and result.get("_rmsg"):
        return _RMsg(
            result["mid"],
            result["ts"],
            base64.b64decode(result["body"]),
            base64.b64decode(result["props"]),
            fence=int(result.get("fence", 0)),
        )
    if isinstance(result, dict) and "_blist" in result:
        return [base64.b64decode(x) for x in result["_blist"]]
    return result


# ---------------------------------------------------------------------------
# Broker-facing facade
# ---------------------------------------------------------------------------


class ReplicatedBackend:
    """What the broker holds in replicated mode: one Raft node + the local
    replica of the queue state machine, with queue-shaped methods."""

    def __init__(
        self,
        name: str,
        peers: dict[str, tuple[str, int]],
        election_timeout: tuple[float, float] = (0.25, 0.5),
        heartbeat_s: float = 0.06,
        dead_owner_s: float = 1.5,
        seed_bug: str | None = None,
        submit_timeout_s: float = 5.0,
        rng_seed: int | None = None,
        data_dir: str | None = None,
        bootstrap: bool = True,
    ):
        self.machine = QueueMachine()
        self.submit_timeout_s = submit_timeout_s
        #: wall-clock skew injected by the clock nemesis (ms added to
        #: this node's view of "now").  Deliberately touches ONLY the
        #: timestamps this node stamps into ops (TTL enqueue times, DEQ
        #: expiry "now", the DEPTHS diagnostic view) — Raft election/
        #: heartbeat timers run on time.monotonic(), which real clock
        #: skew does not move either.  A correct quorum system tolerates
        #: wall-clock skew; this is the knob that proves it.
        self.clock_offset_ms: float = 0.0
        #: called (from the apply path, any thread, possibly holding raft
        #: locks — so implementations must only signal, never re-enter)
        #: whenever an applied entry may have made messages deliverable
        self.on_visible: Callable[[], None] | None = None
        self.raft = RaftNode(
            name,
            peers,
            self._apply,
            election_timeout=election_timeout,
            heartbeat_s=heartbeat_s,
            dead_owner_s=dead_owner_s,
            seed_bug=seed_bug,
            rng_seed=rng_seed,
            data_dir=data_dir,
            bootstrap=bootstrap,
        )

    def stop(self) -> None:
        self.raft.stop()

    def _apply(self, index: int, op: dict) -> Any:
        result = self.machine.apply(index, op)
        if self.on_visible is not None and op["k"] in (
            "enq",
            "txn",
            "requeue_one",
            "requeue_owner",
            "requeue_node",
            "fence_release",
        ):
            self.on_visible()
        return result

    def _now_ms(self) -> float:
        return time.time() * 1000.0 + self.clock_offset_ms

    # -- queue ops ----------------------------------------------------------
    def declare(self, q, qtype=None, ttl_ms=None, dlx=None,
                fenced=False) -> None:
        self.raft.submit(
            {"k": "declare", "q": q, "qtype": qtype, "ttl_ms": ttl_ms,
             "dlx": dlx, "fenced": bool(fenced)},
            timeout_s=self.submit_timeout_s,
        )

    def enqueue(self, q: str, body: bytes, props: bytes) -> bool:
        ok, _ = self.raft.submit(
            {
                "k": "enq",
                "q": q,
                "body": base64.b64encode(body).decode(),
                "props": base64.b64encode(props).decode(),
                "ts": self._now_ms(),
            },
            timeout_s=self.submit_timeout_s,
        )
        return ok

    def enqueue_fenced(
        self, q: str, body: bytes, props: bytes, fence: int, fence_q: str
    ) -> str:
        """Protected publish carrying a fencing token: ``"ok"`` when the
        publish committed with a current token, ``"stale"`` when it
        committed but the token had been superseded (the publish was
        REJECTED deterministically on every replica), ``"noquorum"``
        when no commit happened (the caller withholds the confirm —
        indeterminate, the safe verdict)."""
        ok, result = self.raft.submit(
            {
                "k": "enq",
                "q": q,
                "body": base64.b64encode(body).decode(),
                "props": base64.b64encode(props).decode(),
                "ts": self._now_ms(),
                "fence": int(fence),
                "fence_q": fence_q,
            },
            timeout_s=self.submit_timeout_s,
        )
        if not ok:
            return "noquorum"
        if isinstance(result, dict) and result.get("stale"):
            return "stale"
        return "ok"

    def fence_release(
        self, q: str, token: int, body: bytes, props: bytes = b""
    ) -> tuple[str, str | None]:
        """Fenced lock release: atomically settle the grant bearing
        ``token`` and return the token message to ``q`` — iff ``token``
        is still the queue's current fence.  Returns ``("released",
        mid)``, ``("stale", None)`` (committed, but the token was
        superseded — the caller is no longer the holder), or
        ``("noquorum", None)`` (no commit; outcome unknown)."""
        ok, result = self.raft.submit(
            {
                "k": "fence_release",
                "q": q,
                "token": int(token),
                "body": base64.b64encode(body).decode(),
                "props": base64.b64encode(props).decode(),
                "ts": self._now_ms(),
            },
            timeout_s=self.submit_timeout_s,
        )
        if not ok:
            return "noquorum", None
        if isinstance(result, dict) and result.get("released"):
            return "released", result.get("mid")
        return "stale", None

    def enqueue_txn(self, items: list[tuple[str, bytes, bytes]]) -> bool:
        now = self._now_ms()
        ok, _ = self.raft.submit(
            {
                "k": "txn",
                "ops": [
                    {
                        "k": "enq",
                        "q": q,
                        "body": base64.b64encode(body).decode(),
                        "props": base64.b64encode(props).decode(),
                        "ts": now,
                    }
                    for q, body, props in items
                ],
            },
            timeout_s=self.submit_timeout_s,
        )
        return ok

    def dequeue(self, q: str, owner: str) -> _RMsg | None:
        """Pop one message (committed DEQ).  ``None`` conflates
        committed-empty with no-quorum — fine for the push loops (a miss
        is retried on the next kick), NOT for ``basic.get``'s wire
        answer: use :meth:`dequeue_get` where the caller must
        distinguish (the r7 drain loss rode exactly that conflation)."""
        return self.dequeue_get(q, owner)[1]

    def dequeue_get(self, q: str, owner: str) -> tuple[str, _RMsg | None]:
        """``("ok", msg)``, ``("empty", None)`` — a COMMITTED DEQ found
        the queue empty: the authoritative get-empty answer — or
        ``("noquorum", None)``: no commit happened (no leader, lost
        quorum, timeout); the queue's true state is UNKNOWN and the
        caller must not report empty."""
        ok, msg = self.raft.submit(
            {
                "k": "deq",
                "q": q,
                "owner": owner,
                "now": self._now_ms(),
            },
            timeout_s=self.submit_timeout_s,
        )
        if not ok:
            return "noquorum", None
        return ("ok", msg) if msg is not None else ("empty", None)

    def settle(self, owner: str, mid: str) -> None:
        self.raft.submit(
            {"k": "settle", "owner": owner, "mid": mid},
            timeout_s=self.submit_timeout_s,
        )

    def requeue_one(self, owner: str, mid: str) -> None:
        self.raft.submit(
            {"k": "requeue_one", "owner": owner, "mid": mid},
            timeout_s=self.submit_timeout_s,
        )

    def requeue_owner(self, owner: str) -> None:
        self.raft.submit(
            {"k": "requeue_owner", "owner": owner},
            timeout_s=self.submit_timeout_s,
        )

    def purge(self, q: str) -> int:
        ok, n = self.raft.submit(
            {"k": "purge", "q": q}, timeout_s=self.submit_timeout_s
        )
        return int(n or 0) if ok else 0

    def stream_read(
        self, name: str
    ) -> tuple[str, list[bytes] | None]:
        """LINEARIZABLE stream read: the read commits through the log
        (its commit is the linearization point), so it reflects every
        confirmed append cluster-wide even from a lagging follower, and
        the committed state — not any local marker — answers whether
        ``name`` is a stream at all.

        Returns ``("stream", log)``, ``("notstream", None)`` (the name is
        a classic queue / undeclared), or ``("noquorum", None)`` when the
        read cannot commit — the caller must surface *failure*, never a
        stale local view.

        Cost trade-off, deliberately simple: each read appends one log
        entry (no compaction; runs are minutes) and every replica
        materializes the snapshot on apply even though only the
        submitter's waiter consumes it.  A ReadIndex-style lease read
        would avoid both at the price of leader-lease machinery; at
        harness scale the log entry per *actual stream read* is cheap,
        and the broker caches committed "notstream" answers so classic
        queue consumes never pay it."""
        ok, result = self.raft.submit(
            {"k": "read_stream", "q": name},
            timeout_s=self.submit_timeout_s,
        )
        if not ok:
            return "noquorum", None
        if isinstance(result, dict) and result.get("_notstream"):
            return "notstream", None
        return "stream", result if isinstance(result, list) else []

    # -- local reads (diagnostics only — NOT the client read path) ----------
    def counts(self) -> dict[str, int]:
        return self.machine.counts(self._now_ms())

    def stats_snapshot(self) -> dict:
        """Cluster-telemetry snapshot for an in-process backend (the
        DirectStatsSource path, obs/cluster.py): the raft block plus
        this replica's ready/inflight depths from the local machine."""
        m = self.machine
        with m.lock:
            ready = sum(len(dq) for dq in m.queues.values()) + sum(
                len(log) for log in m.streams.values()
            )
            inflight = len(m.inflight)
        return {
            "broker": {"ready": ready, "inflight": inflight},
            "raft": self.raft.stats_snapshot(),
        }
