"""A minimal in-memory AMQP 0-9-1 broker for driver tests.

The reference tests its Java driver against a *real* broker on localhost
(``UtilsTest.java:50``); this image has no RabbitMQ, so the framework
ships a protocol-level stand-in: a threaded TCP server speaking the AMQP
subset the native driver uses (handshake, channel, queue declare/purge,
publisher confirms, basic publish/get/consume/ack/reject, tx
select/commit/rollback, per-queue ``x-message-ttl`` expiry with
``x-dead-letter-routing-key`` routing, stream queues with offset reads,
heartbeat).  It is an *independent* implementation of the wire grammar
(Python ``struct`` vs the driver's C++ codec), so framing bugs on either
side surface as protocol errors rather than silently agreeing — and the
broker itself is conformance-checked against rabbitmq-c
(``native/interop_probe.c``).

Fault injection mirrors what the checker must catch end-to-end:

- ``drop_confirms``      — accept publishes but never confirm (client
  publish-confirm timeouts → indeterminate ops);
- ``lose_acked_every=k`` — confirm every k-th publish but drop the message
  (data loss: ``total-queue`` must report ``lost``);
- ``duplicate_every=k``  — deliver every k-th message twice (at-least-once
  duplicates).
"""

from __future__ import annotations

import json as _json
import socket
import struct
import random as _random
import threading
import time as _time
from collections import deque
from dataclasses import dataclass, field


FRAME_METHOD, FRAME_HEADER, FRAME_BODY, FRAME_HEARTBEAT = 1, 2, 3, 8
FRAME_END = 0xCE


def _shortstr(s: str) -> bytes:
    b = s.encode()
    return bytes([len(b)]) + b


def _longstr(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def u8(self):
        v = self.data[self.off]
        self.off += 1
        return v

    def u16(self):
        v = struct.unpack_from(">H", self.data, self.off)[0]
        self.off += 2
        return v

    def u32(self):
        v = struct.unpack_from(">I", self.data, self.off)[0]
        self.off += 4
        return v

    def u64(self):
        v = struct.unpack_from(">Q", self.data, self.off)[0]
        self.off += 8
        return v

    def shortstr(self):
        n = self.u8()
        v = self.data[self.off : self.off + n].decode()
        self.off += n
        return v

    def table(self) -> dict:
        """Parse a field table into a dict (the subset of types the driver
        emits; unknown types abort parsing by skipping to the end)."""
        n = self.u32()
        end = self.off + n
        out: dict = {}
        try:
            while self.off < end:
                key = self.shortstr()
                t = bytes([self.u8()])
                if t == b"S":
                    ln = self.u32()
                    out[key] = self.data[self.off : self.off + ln].decode()
                    self.off += ln
                elif t == b"I":
                    out[key] = struct.unpack(
                        ">i", self.data[self.off : self.off + 4]
                    )[0]
                    self.off += 4
                elif t == b"l":
                    out[key] = struct.unpack(
                        ">q", self.data[self.off : self.off + 8]
                    )[0]
                    self.off += 8
                elif t == b"t":
                    out[key] = bool(self.u8())
                else:
                    break  # unknown type: stop parsing, skip the rest
        finally:
            self.off = end
        return out

    def rest(self):
        return self.data[self.off :]


@dataclass
class _Message:
    value: bytes
    ts: float = 0.0  # publish time (monotonic) — drives x-message-ttl
    # raw content-header properties (property-flags onward) as the
    # publisher sent them; replayed VERBATIM on deliver/get so arbitrary
    # header tables pass through byte-identical (the codec-fuzz chain
    # publishes through here and decodes on the far side)
    props: bytes = b""
    # fencing token attached while this message is a granted (un-acked)
    # delivery from a fenced queue; 0 otherwise (local mode only — the
    # replicated twin lives on replication._RMsg)
    fence: int = 0


def _props_headers(props: bytes) -> dict:
    """Parse the headers table out of raw content-header properties
    (property-flags onward); {} when absent/malformed.  The fencing
    extension rides message headers (``x-fence-token`` /
    ``x-fence-release`` / ``x-fence-lock``), like RabbitMQ's own
    ``x-stream-offset``."""
    try:
        r = _Reader(props)
        flags = r.u16()
        if flags & 0x8000:
            r.shortstr()  # content-type
        if flags & 0x4000:
            r.shortstr()  # content-encoding
        if not (flags & 0x2000):
            return {}
        return r.table()
    except (IndexError, struct.error, UnicodeDecodeError):
        return {}


def _fence_props(token: int) -> bytes:
    """Content-header properties (flags onward) carrying ONLY the
    ``x-fence-token`` header — attached to fenced grant deliveries."""
    table = _shortstr("x-fence-token") + b"l" + struct.pack(">q", token)
    return struct.pack(">H", 0x2000) + struct.pack(">I", len(table)) + table


@dataclass
class _ConnState:
    sock: socket.socket
    lock: threading.Lock = field(default_factory=threading.Lock)
    publish_seq: dict = field(default_factory=dict)  # channel -> seq
    next_tag: int = 1
    # tag -> (queue, _Message) locally; (queue, mid:str) in replicated mode
    unacked: dict = field(default_factory=dict)
    consuming_queue: str | None = None
    consuming_ch: int = 1  # the channel Basic.Consume arrived on
    consuming_noack: bool = False
    # delivery serialization: pushes for one conn may be triggered from
    # its serve thread AND the kick loop; frames of two deliveries must
    # never interleave on the wire
    deliver_lock: threading.Lock = field(default_factory=threading.Lock)
    deliver_again: bool = False
    confirm_channels: set = field(default_factory=set)
    tx_channels: set = field(default_factory=set)  # tx.select per channel
    tx_buffer: dict = field(default_factory=dict)  # ch -> [(queue, body)]
    owner: str = ""  # replicated-mode delivery owner id ("node|cN")
    open: bool = True


class MiniAmqpBroker:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        drop_confirms: bool = False,
        lose_acked_every: int = 0,
        duplicate_every: int = 0,
        lose_appended_every: int = 0,
        duplicate_append_every: int = 0,
        dirty_tx_reads: bool = False,
        fragment_max: int = 0,
        replication=None,
    ):
        self.host = host
        # replicated mode: a harness.replication.ReplicatedBackend owns
        # ALL queue/stream state (this broker becomes one cluster node);
        # the single-broker fault-injection knobs (lose_acked_every, …)
        # are local-state faults and do not apply — the replicated-mode
        # seeded fault is the Raft layer's seed_bug instead
        self.replication = replication
        # fragment_max > 0: every outgoing byte stream is sent in random
        # 1..fragment_max-byte chunks — clients' frame reassembly must
        # survive arbitrarily split TCP reads (codec-fuzz surface)
        self.fragment_max = fragment_max
        self._frag_rng = _random.Random(1234)
        self._server = socket.create_server((host, port))
        self.port = self._server.getsockname()[1]
        self.queues: dict[str, deque] = {}
        self.streams: dict[str, list] = {}  # x-queue-type=stream → log
        # per-queue declare args: x-message-ttl / x-dead-letter-routing-key
        self.queue_meta: dict[str, dict] = {}
        self.state_lock = threading.Lock()
        self.drop_confirms = drop_confirms
        self.lose_acked_every = lose_acked_every
        self.duplicate_every = duplicate_every
        self.lose_appended_every = lose_appended_every
        self.duplicate_append_every = duplicate_append_every
        self.dirty_tx_reads = dirty_tx_reads
        self._published = 0
        self._delivered = 0
        self._appended = 0
        self._conn_seq = 0
        # cluster telemetry (ISSUE 12): loud channel-close counters —
        # 540 = fenced-consume refusal, 541 = lost-quorum internal-error
        # — read at poll granularity via stats_snapshot / admin STATS
        self._chan_close_540 = 0
        self._chan_close_541 = 0
        # local-mode fencing state (replicated mode keeps the replicated
        # twin in QueueMachine.fences, driven by commit indices): per-
        # queue current fence + the monotonic token mint
        self.fences: dict[str, int] = {}
        self._fence_seq = 0
        self._owner_salt = f"{_random.Random().getrandbits(32):08x}-"
        # names a committed read answered "notstream" for (replicated
        # mode): later consumes of these classic queues skip the
        # committed stream-ness probe
        self._known_queues: set[str] = set()
        # queues declared with x-fencing: push delivery would advance
        # the fence without handing the grantee its token, so consume
        # on these is rejected (tokens ride basic.get replies only)
        self._fenced_queues: set[str] = set()
        self._conns: list[_ConnState] = []
        self._accept_thread: threading.Thread | None = None
        self._kick = threading.Event()
        self._running = False
        self._stopped = False
        if replication is not None:
            # replicated applies (on any node) may make messages
            # deliverable HERE; the apply path holds raft locks, so it
            # only signals — this thread does the actual push delivery
            replication.on_visible = self._kick.set
            threading.Thread(
                target=self._kick_loop, daemon=True
            ).start()

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "MiniAmqpBroker":
        self._running = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()
        if self.replication is not None:
            # sweep any inflight deliveries a previous incarnation of
            # this node left behind: a fast restart (< dead_owner_s)
            # never trips the leader's dead-node reaper
            threading.Thread(
                target=self._requeue_own_ghosts, daemon=True
            ).start()
            # continuous orphan sweep: the close handler's requeue_owner
            # submit is fire-and-forget, and one lost to a partition/
            # election window would otherwise strand the connection's
            # inflight deliveries FOREVER (round-4 matrix find: a
            # consumer died mid-partition, its requeue submit timed out
            # uncommitted, and the message sat inflight through the
            # whole drain — depth 1 on every replica, total-queue
            # `lost`).  The invariant lives here instead: any inflight
            # entry owned by one of THIS node's connections that no
            # longer exists is re-proposed until it commits.
            threading.Thread(
                target=self._orphan_sweep_loop,
                daemon=True,
                name="orphan-sweep",  # tests distinguish sweep-thread
            ).start()  # submits from close-path submits by this name
        return self

    def _requeue_own_ghosts(self) -> None:
        if self.replication.raft.seed_bug == "drop-unacked-on-close":
            return  # seeded: the requeue machinery is broken everywhere
        name = self.replication.raft.name
        for _ in range(10):
            if not self._running:
                return
            ok, _r = self.replication.raft.submit(
                {"k": "requeue_node", "node": name}, timeout_s=2.0
            )
            if ok:
                return
            _time.sleep(0.5)

    ORPHAN_SWEEP_S = 0.4

    def _orphan_sweep_loop(self) -> None:
        if self.replication.raft.seed_bug == "drop-unacked-on-close":
            return  # seeded: the requeue machinery is broken everywhere
        raft = self.replication.raft
        prefix = raft.name + "|"
        machine = self.replication.machine
        suspects: set[str] = set()  # orphaned on the previous tick too
        while not self._stopped:
            _time.sleep(self.ORPHAN_SWEEP_S)
            if not self._running:
                continue
            with machine.lock:
                all_owners = {
                    o for o, _q, _m in machine.inflight.values()
                }
            owners = {o for o in all_owners if o.startswith(prefix)}
            with self.state_lock:
                live = {c.owner for c in self._conns}
            orphaned = owners - live
            # departed-member sweep (r5 burn-in find, lost value 16943):
            # inflight owned by a node that is NO LONGER IN the cluster
            # config is nobody's responsibility — the forgotten node's
            # own sweep cannot submit (it restarts outside the cluster,
            # or never restarts), and the leader's dead-NODE reaper only
            # watches CURRENT members.  Every member therefore also
            # re-proposes requeues for departed owners (salted owner ids
            # make this safe across fresh rejoins under the same name;
            # requeue_owner is idempotent, so N members proposing is
            # redundancy, not a hazard).  Skipped while this node is
            # OUTSIDE a cluster — pending joiner or retired — whose
            # self-only view would mark the whole world departed; a
            # legitimately shrunk cluster (even 1-node) still sweeps.
            with raft.lock:
                outside = raft._pending_locked()
                members = set(raft.peers)
            if not outside:
                orphaned |= {
                    o
                    for o in all_owners
                    if o.split("|", 1)[0] not in members
                }
            # two-strike grace: don't race the close handler's own sweep
            # (a double requeue is idempotent, this just avoids spurious
            # submits); re-proposing every tick until the entry leaves
            # the inflight map is the point — a submit lost to an
            # election window gets retried on the next one
            for owner in orphaned & suspects:
                try:
                    self.replication.requeue_owner(owner)
                except Exception:  # noqa: BLE001 - retried next tick
                    pass
            suspects = orphaned

    def _kick_loop(self) -> None:
        while not self._stopped:
            if self._kick.wait(timeout=0.5):
                self._kick.clear()
                if self._running:
                    self._deliver_all()

    def stop(self) -> None:
        self._running = False
        self._stopped = True
        self._kick.set()  # unblock the kick loop so it can exit
        if self.replication is not None:
            self.replication.stop()
        try:
            self._server.close()
        except OSError:
            pass
        # unblock a pending accept(): on Linux, close() does not
        # interrupt a thread already blocked in accept() — the in-flight
        # syscall keeps the LISTEN socket alive, so the port would stay
        # bound (un-rebindable by an in-process restart) until the next
        # stray connection happened along
        try:
            socket.create_connection(("127.0.0.1", self.port), 0.2).close()
        except OSError:
            pass
        with self.state_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.sock.close()
            except OSError:
                pass

    def queue_depth(self, name: str = "jepsen.queue") -> int:
        if self.replication is not None:
            return self.replication.counts().get(name, 0)
        with self.state_lock:
            return len(self.queues.get(name, ()))

    def stream_depth(self, name: str = "jepsen.stream") -> int:
        """LOCAL-replica depth (tests/diagnostics; may lag the cluster —
        the client read path is the linearizable committed read)."""
        if self.replication is not None:
            return len(self.replication.machine.stream_snapshot(name))
        with self.state_lock:
            return len(self.streams.get(name, ()))

    def stats_snapshot(self) -> dict:
        """Cluster-telemetry snapshot (ISSUE 12): this node's broker
        plane (connections, ready/inflight depths, throughput counters,
        loud 540/541 channel closes) plus — in replicated mode — the
        Raft node's telemetry block.  JSON-safe: the admin ``STATS``
        command ships it verbatim, and the in-process poller consumes
        the same shape (obs/cluster.py)."""
        with self.state_lock:
            conns = list(self._conns)
            local_ready = (
                0
                if self.replication is not None  # shadowed below; don't
                else sum(  # walk every queue under the contended lock
                    len(dq) for dq in self.queues.values()
                ) + sum(len(log) for log in self.streams.values())
            )
        inflight = sum(len(c.unacked) for c in conns)
        if self.replication is not None:
            # ready = this replica's applied view; inflight = replicated
            # deliveries OWNED by this node's connections (owner ids are
            # "node|salt-cN" — the per-node slice of the cluster map)
            prefix = self.replication.raft.name + "|"
            m = self.replication.machine
            with m.lock:
                ready = sum(len(dq) for dq in m.queues.values()) + sum(
                    len(log) for log in m.streams.values()
                )
                inflight = sum(
                    1
                    for owner, _q, _m in m.inflight.values()
                    if owner.startswith(prefix)
                )
        else:
            ready = local_ready
        return {
            "broker": {
                "connections": len(conns),
                "ready": ready,
                "inflight": inflight,
                "published": self._published,
                "delivered": self._delivered,
                "appended": self._appended,
                "chan_close_540": self._chan_close_540,
                "chan_close_541": self._chan_close_541,
            },
            "raft": (
                self.replication.raft.stats_snapshot()
                if self.replication is not None
                else None
            ),
        }

    # ---- internals -------------------------------------------------------
    def _accept_loop(self):
        while self._running:
            try:
                sock, _ = self._server.accept()
            except OSError:
                break
            conn = _ConnState(sock=sock)
            with self.state_lock:
                self._conn_seq += 1
                node = (
                    self.replication.raft.name
                    if self.replication is not None
                    else "local"
                )
                # salted: a restarted process must never mint owner ids
                # that collide with its previous incarnation's replicated
                # inflight entries (requeue_node prefix-matching on
                # "node|" still covers every incarnation)
                conn.owner = f"{node}|{self._owner_salt}c{self._conn_seq}"
                self._conns.append(conn)
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _send_frame(self, conn: _ConnState, ftype: int, ch: int, payload: bytes):
        data = (
            struct.pack(">BHI", ftype, ch, len(payload))
            + payload
            + bytes([FRAME_END])
        )
        with conn.lock:
            try:
                if self.fragment_max:
                    i = 0
                    while i < len(data):
                        k = self._frag_rng.randint(1, self.fragment_max)
                        conn.sock.sendall(data[i : i + k])
                        i += k
                else:
                    conn.sock.sendall(data)
            except OSError:
                conn.open = False

    def _send_method(self, conn, ch, cls, mth, args: bytes = b""):
        self._send_frame(
            conn, FRAME_METHOD, ch, struct.pack(">HH", cls, mth) + args
        )

    def _recv_exact(self, sock, n):
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf += chunk
        return buf

    def _read_frame(self, sock):
        hdr = self._recv_exact(sock, 7)
        ftype, ch, size = struct.unpack(">BHI", hdr)
        payload = self._recv_exact(sock, size) if size else b""
        end = self._recv_exact(sock, 1)
        if end[0] != FRAME_END:
            raise ConnectionError("bad frame end")
        return ftype, ch, payload

    def _serve(self, conn: _ConnState):
        sock = conn.sock
        try:
            proto = self._recv_exact(sock, 8)
            if not proto.startswith(b"AMQP"):
                return
            # Start
            args = (
                bytes([0, 9])
                + _longstr(b"")  # server properties (empty table)
                + _longstr(b"PLAIN")
                + _longstr(b"en_US")
            )
            self._send_method(conn, 0, 10, 10, args)
            self._expect(sock, 10, 11)  # Start-Ok
            self._send_method(
                conn, 0, 10, 30, struct.pack(">HIH", 2047, 131072, 0)
            )  # Tune
            self._expect(sock, 10, 31)  # Tune-Ok
            self._expect(sock, 10, 40)  # Open
            self._send_method(conn, 0, 10, 41, _shortstr(""))  # Open-Ok

            # in-flight publish content, keyed by channel: method, header,
            # and body frames of one publish share a channel, and two
            # channels may interleave their publishes on one connection
            pending: dict = {}  # ch -> [queue, size, body]

            while conn.open:
                ftype, ch, payload = self._read_frame(sock)
                if ftype == FRAME_HEARTBEAT:
                    self._send_frame(conn, FRAME_HEARTBEAT, 0, b"")
                    continue
                if ftype == FRAME_HEADER:
                    r = _Reader(payload)
                    r.u16()
                    r.u16()
                    p = pending.get(ch)
                    if p is not None:
                        p[1] = r.u64()
                        p[2] = b""
                        p[3] = r.rest()  # property-flags onward, verbatim
                        if p[1] == 0:
                            self._finish_publish(conn, ch, p[0], b"", p[3])
                            del pending[ch]
                    continue
                if ftype == FRAME_BODY:
                    p = pending.get(ch)
                    if p is not None:
                        p[2] += payload
                        if len(p[2]) >= p[1]:
                            self._finish_publish(conn, ch, p[0], p[2], p[3])
                            del pending[ch]
                    continue
                r = _Reader(payload)
                cls, mth = r.u16(), r.u16()
                if cls == 20 and mth == 10:  # Channel.Open
                    self._send_method(conn, ch, 20, 11, _longstr(b""))
                elif cls == 50 and mth == 10:  # Queue.Declare
                    r.u16()
                    qname = r.shortstr()
                    r.u8()  # durable/exclusive/... bit flags
                    qargs = r.table()
                    with self.state_lock:
                        if qargs.get("x-fencing"):
                            self._fenced_queues.add(qname)
                        else:
                            # last declare wins (like queue_meta / the
                            # machine's meta): a redeclare without
                            # x-fencing must not leave this node
                            # treating the queue as fenced forever
                            self._fenced_queues.discard(qname)
                    if self.replication is not None:
                        self.replication.declare(
                            qname,
                            qtype=qargs.get("x-queue-type"),
                            ttl_ms=qargs.get("x-message-ttl"),
                            dlx=qargs.get("x-dead-letter-routing-key"),
                            fenced=bool(qargs.get("x-fencing")),
                        )
                        # remember stream-ness locally for consume routing
                        if qargs.get("x-queue-type") == "stream":
                            with self.state_lock:
                                self.streams.setdefault(qname, [])
                    else:
                        with self.state_lock:
                            if qargs.get("x-queue-type") == "stream":
                                self.streams.setdefault(qname, [])
                            else:
                                self.queues.setdefault(qname, deque())
                                self.queue_meta[qname] = {
                                    "ttl_ms": qargs.get("x-message-ttl"),
                                    "dlx_key": qargs.get(
                                        "x-dead-letter-routing-key"
                                    ),
                                    "fenced": bool(qargs.get("x-fencing")),
                                }
                    self._send_method(
                        conn,
                        ch,
                        50,
                        11,
                        _shortstr(qname) + struct.pack(">II", 0, 0),
                    )
                elif cls == 50 and mth == 30:  # Queue.Purge
                    r.u16()
                    qname = r.shortstr()
                    if self.replication is not None:
                        n = self.replication.purge(qname)
                    else:
                        with self.state_lock:
                            n = len(self.queues.get(qname, ()))
                            self.queues[qname] = deque()
                    self._send_method(conn, ch, 50, 31, struct.pack(">I", n))
                elif cls == 85 and mth == 10:  # Confirm.Select
                    conn.confirm_channels.add(ch)  # per-channel (spec)
                    self._send_method(conn, ch, 85, 11)
                elif cls == 60 and mth == 10:  # Basic.Qos
                    self._send_method(conn, ch, 60, 11)
                elif cls == 60 and mth == 40:  # Basic.Publish
                    r.u16()
                    r.shortstr()  # exchange
                    routing_key = r.shortstr()
                    pending[ch] = [routing_key, 0, b"", b""]
                elif cls == 60 and mth == 70:  # Basic.Get
                    r.u16()
                    qname = r.shortstr()
                    no_ack = bool(r.u8() & 1)
                    self._handle_get(conn, ch, qname, no_ack)
                elif cls == 60 and mth == 20:  # Basic.Consume
                    r.u16()
                    qname = r.shortstr()
                    ctag = r.shortstr() or "ctag-1"
                    cbits = r.u8()  # no-local/no-ack/exclusive/no-wait
                    # the ack mode is committed only on SUCCESSFUL
                    # registration (with consuming_ch/consuming_queue,
                    # below): a rejected fenced consume keeps the prior
                    # subscription alive and must not clobber its mode
                    noack_req = bool(cbits & 2)
                    cargs = r.table()
                    self._send_method(conn, ch, 60, 21, _shortstr(ctag))
                    # stream-ness + snapshot come from ONE read.  In
                    # replicated mode that read COMMITS through the log:
                    # it is linearizable (a lagging follower still
                    # returns every confirmed append) and its committed
                    # answer — not any local marker, which would race
                    # the declare's application — decides whether the
                    # name is a stream at all.
                    if self.replication is not None:
                        with self.state_lock:
                            known_queue = qname in self._known_queues
                        if known_queue:
                            # committed-answered classic queue: consumes
                            # need no linearizable snapshot, and skipping
                            # the read op keeps the uncompacted log from
                            # growing once per queue consume
                            kind, log = "notstream", None
                        else:
                            kind, log = self.replication.stream_read(qname)
                            if kind == "notstream":
                                with self.state_lock:
                                    self._known_queues.add(qname)
                    else:
                        with self.state_lock:
                            if qname in self.streams:
                                kind, log = "stream", list(
                                    self.streams[qname]
                                )
                            else:
                                kind, log = "notstream", None
                    if kind == "noquorum":
                        # the read cannot commit.  Pure silence here
                        # would be indistinguishable from a committed
                        # empty log (a false-loss verdict downstream) —
                        # close the channel so the client's read FAILS
                        # (reads are safe to fail) instead of concluding
                        # end-of-log on nothing
                        self._chan_close_541 += 1
                        self._send_method(
                            conn,
                            ch,
                            20,
                            40,
                            struct.pack(">H", 541)  # internal-error
                            + _shortstr("stream read lost quorum")
                            + struct.pack(">HH", 60, 20),
                        )
                    elif kind == "stream":
                        # offset spec: an absolute int64, or the string
                        # specs "first" (0) / "last" (the final chunk ≡
                        # the final record here) / "next" (past the
                        # current end; this broker's stream consumers are
                        # one-shot snapshots, so "next" delivers nothing —
                        # unlike real RabbitMQ, which would push appends
                        # committed after the subscribe)
                        spec = cargs.get("x-stream-offset", 0)
                        if spec == "first":
                            offset = 0
                        elif spec in ("last", "next"):
                            n = len(log)
                            offset = n - 1 if spec == "last" and n else n
                        else:
                            offset = int(spec)
                        self._stream_deliver(
                            conn, ch, qname, log, offset, ctag
                        )
                    elif self._is_fenced_queue(qname):
                        # push delivery carries no fencing token (only
                        # _get mints/attaches one), and in replicated
                        # mode the DEQ apply would still advance the
                        # fence — the grantee would hold the lock with
                        # no token to release it.  Reject rather than
                        # silently diverge from the basic.get path.
                        self._reject_fenced_consume(
                            conn, ch, clear_subscription=False
                        )
                    else:
                        # ch first: a concurrent kick-loop delivery keys
                        # off consuming_queue and must never observe the
                        # default channel (advisor r3 #1) — nor a stale
                        # ack mode, so noack commits before the queue
                        conn.consuming_noack = noack_req
                        conn.consuming_ch = ch
                        conn.consuming_queue = qname
                        self._try_deliver(conn)
                elif cls == 60 and mth == 30:  # Basic.Cancel
                    ctag = r.shortstr()
                    self._send_method(conn, ch, 60, 31, _shortstr(ctag))
                elif cls == 60 and mth == 80:  # Basic.Ack (client)
                    tag = r.u64()
                    with self.state_lock:
                        item = conn.unacked.pop(tag, None)
                    if self.replication is not None and item:
                        self.replication.settle(conn.owner, item[1])
                    self._try_deliver(conn)
                elif cls == 60 and mth == 90:  # Basic.Reject
                    tag = r.u64()
                    requeue = r.u8()
                    with self.state_lock:
                        item = conn.unacked.pop(tag, None)
                    if self.replication is not None and item:
                        if requeue:
                            self.replication.requeue_one(conn.owner, item[1])
                        else:
                            self.replication.settle(conn.owner, item[1])
                    elif item and requeue:
                        with self.state_lock:
                            qname, msg = item
                            self._revoke_fence_locked(qname, msg)
                            self.queues.setdefault(qname, deque()).append(msg)
                    self._deliver_all()
                elif cls == 90 and mth == 10:  # Tx.Select (per channel)
                    conn.tx_channels.add(ch)
                    self._send_method(conn, ch, 90, 11)
                elif cls == 90 and mth == 20:  # Tx.Commit
                    buffered = conn.tx_buffer.pop(ch, [])
                    if self.replication is not None:
                        committed = (
                            self.replication.enqueue_txn(buffered)
                            if buffered
                            else True
                        )
                        # commit-ok IS the acknowledgement: withhold it
                        # when quorum was not reached (client times out →
                        # indeterminate, the safe verdict)
                        if committed:
                            self._send_method(conn, ch, 90, 21)
                    else:
                        for qname, body, props in buffered:
                            self._apply_publish(qname, body, props)
                        self._send_method(conn, ch, 90, 21)
                        self._deliver_all()
                elif cls == 90 and mth == 30:  # Tx.Rollback
                    conn.tx_buffer.pop(ch, None)
                    self._send_method(conn, ch, 90, 31)
                elif cls == 10 and mth == 50:  # Connection.Close
                    self._send_method(conn, 0, 10, 51)
                    break
                elif cls == 20 and mth == 40:  # Channel.Close
                    # per-channel state dies with the channel: confirm
                    # mode, the delivery-tag sequence, tx mode + staged
                    # publishes, and any half-received publish content
                    conn.confirm_channels.discard(ch)
                    conn.publish_seq.pop(ch, None)
                    conn.tx_channels.discard(ch)
                    conn.tx_buffer.pop(ch, None)
                    pending.pop(ch, None)
                    self._send_method(conn, ch, 20, 41)
                else:
                    pass  # ignore anything else
        except (ConnectionError, OSError):
            pass
        finally:
            conn.open = False
            # requeue un-acked deliveries (broker semantics on conn loss)
            if self.replication is not None:
                with self.state_lock:
                    conn.unacked.clear()
                    if conn in self._conns:
                        self._conns.remove(conn)
                if (
                    self._running
                    and self.replication.raft.seed_bug
                    != "drop-unacked-on-close"
                ):
                    # unconditional: a deq can commit cluster-wide while
                    # the local submit timed out (nothing in conn.unacked
                    # to witness it) — only the replicated inflight map
                    # knows, so always sweep this owner.  (The seeded
                    # drop-unacked-on-close bug SKIPS this sweep: the
                    # delivered-but-unacked messages strand in inflight —
                    # the delivery plane's loss mode, which the drain +
                    # total-queue must catch.)
                    self.replication.requeue_owner(conn.owner)
            else:
                with self.state_lock:
                    for qname, msg in conn.unacked.values():
                        self._revoke_fence_locked(qname, msg)
                        self.queues.setdefault(qname, deque()).append(msg)
                    conn.unacked.clear()
                    if conn in self._conns:
                        self._conns.remove(conn)
            try:
                sock.close()
            except OSError:
                pass
            self._deliver_all()

    def _expect(self, sock, cls, mth):
        while True:
            ftype, _ch, payload = self._read_frame(sock)
            if ftype != FRAME_METHOD:
                continue
            r = _Reader(payload)
            c, m = r.u16(), r.u16()
            if (c, m) == (cls, mth):
                return payload
            raise ConnectionError(f"expected {cls}.{mth}, got {c}.{m}")

    def _finish_publish(
        self, conn: _ConnState, ch: int, queue: str, body: bytes,
        props: bytes = b"",
    ):
        if props:
            headers = _props_headers(props)
            if "x-fence-release" in headers:
                self._fenced_release(
                    conn, ch, queue, int(headers["x-fence-release"]),
                    body,
                )
                return
            if "x-fence-token" in headers and "x-fence-lock" in headers:
                self._fenced_publish(
                    conn, ch, queue,
                    int(headers["x-fence-token"]),
                    str(headers["x-fence-lock"]),
                    body, props,
                )
                return
        if ch in conn.tx_channels:
            # tx publishes stay invisible until tx.commit (no confirms in
            # tx mode — the commit-ok is the acknowledgement) ... unless
            # the dirty-visibility fault is injected, which applies them
            # immediately (read-uncommitted isolation: Elle must flag the
            # resulting G1a/G1b/G1c anomalies)
            if self.dirty_tx_reads:
                self._apply_publish(queue, body, props)
                self._deliver_all()
            else:
                conn.tx_buffer.setdefault(ch, []).append((queue, body, props))
            return
        seq = self._next_publish_seq(conn, ch)
        if self.replication is not None:
            # quorum-commit before confirm: the whole point of the
            # replicated mode (a seed_bug leader lies here — that's the
            # injected fault the checker must catch downstream)
            committed = self.replication.enqueue(queue, body, props)
            if (
                committed
                and ch in conn.confirm_channels
                and not self.drop_confirms
            ):
                self._send_method(
                    conn, ch, 60, 80, struct.pack(">QB", seq, 0)
                )
            return  # push deliveries ride the on_visible kick
        self._apply_publish(queue, body, props)
        # confirm mode and delivery-tag sequence are per channel, and the
        # ack rides the publishing channel (AMQP 0-9-1 confirm semantics)
        if ch in conn.confirm_channels and not self.drop_confirms:
            self._send_method(conn, ch, 60, 80, struct.pack(">QB", seq, 0))
        self._deliver_all()

    def _next_publish_seq(self, conn: _ConnState, ch: int) -> int:
        """Advance the channel's publisher-confirm sequence.  Every
        received publish consumes one sequence number whether or not a
        confirm goes out — the client's own counter advances on send,
        and a skipped number here would desynchronize every later
        ack/nack tag on the channel."""
        seq = conn.publish_seq.get(ch, 0) + 1
        conn.publish_seq[ch] = seq
        return seq

    def _confirm_fenced(
        self, conn: _ConnState, ch: int, seq: int, ok: bool
    ) -> None:
        """Answer a fenced publish: basic.ack when the token was current,
        basic.nack when it was stale (the operation was REJECTED) — the
        stale verdict must reach the client as a definite failure, never
        a silent drop (which would read as indeterminate)."""
        if ch not in conn.confirm_channels or self.drop_confirms:
            return
        self._send_method(
            conn, ch, 60, 80 if ok else 120, struct.pack(">QB", seq, 0)
        )

    def _fenced_release(
        self, conn: _ConnState, ch: int, queue: str, token: int,
        body: bytes,
    ) -> None:
        """Fenced lock release: publish of the token back to the lock
        queue bearing ``x-fence-release: <token>``.  Valid only while
        the token is the queue's current fence — a holder whose grant
        was revoked (connection loss, dead-owner reap) gets a nack, not
        a silent no-op the driver would report as released."""
        seq = self._next_publish_seq(conn, ch)
        if self.replication is not None:
            status, mid = self.replication.fence_release(
                queue, token, body, b""
            )
            if status == "noquorum":
                return  # no confirm: the outcome is genuinely unknown
            if status == "released" and mid is not None:
                # scrub the settled grant from whichever local conn held
                # it un-acked, so that conn's later death cannot requeue
                # an already-released token (double-token hazard)
                with self.state_lock:
                    for c in self._conns:
                        for tag, item in list(c.unacked.items()):
                            if item == (queue, mid):
                                del c.unacked[tag]
            self._confirm_fenced(conn, ch, seq, status == "released")
            return
        with self.state_lock:
            ok = self.fences.get(queue) == token
            holder = None
            if ok:
                for c in self._conns:
                    for tag, (qn, msg) in c.unacked.items():
                        if qn == queue and msg.fence == token:
                            holder = (c, tag)
                            break
                    if holder:
                        break
                ok = holder is not None
            if ok:
                hc, htag = holder
                del hc.unacked[htag]
                self._fence_seq += 1
                self.fences[queue] = self._fence_seq
                self.queues.setdefault(queue, deque()).append(
                    _Message(body, ts=_time.monotonic())
                )
        self._confirm_fenced(conn, ch, seq, ok)
        if ok:
            self._deliver_all()

    def _fenced_publish(
        self, conn: _ConnState, ch: int, queue: str, token: int,
        lockq: str, body: bytes, props: bytes,
    ) -> None:
        """Protected operation: a publish claiming to hold the lock at
        ``lockq`` with fencing token ``token``.  A stale token (the lock
        was revoked/re-granted since) is rejected with a nack — the
        end-to-end fencing property: no stale-token operation ever
        succeeds."""
        seq = self._next_publish_seq(conn, ch)
        if self.replication is not None:
            status = self.replication.enqueue_fenced(
                queue, body, props, token, lockq
            )
            if status == "noquorum":
                return
            self._confirm_fenced(conn, ch, seq, status == "ok")
            return
        with self.state_lock:
            # check + apply in ONE critical section: a revocation landing
            # between them (holder's connection dying on another thread)
            # must not let a just-staled token's publish slip through —
            # the replicated twin gets this atomicity from apply-time
            # evaluation of the committed op
            ok = self.fences.get(lockq) == token
            if ok:
                self._apply_publish_locked(queue, body, props)
        self._confirm_fenced(conn, ch, seq, ok)
        if ok:
            self._deliver_all()

    def _revoke_fence_locked(self, qname: str, msg: _Message) -> None:
        """Local-mode revocation: requeueing a granted fenced message
        advances the queue's fence past the holder's token (the
        replicated twin does this at requeue-apply time).  Caller holds
        ``state_lock``."""
        if msg.fence:
            self._fence_seq += 1
            self.fences[qname] = self._fence_seq
            msg.fence = 0

    def _expire_locked(self, qname: str) -> None:
        """Dead-letter expired messages (x-message-ttl + DLX routing, the
        reference's dead-letter mode — Utils.java:55, MESSAGE_TTL 1 s).
        Caller holds ``state_lock``."""
        meta = self.queue_meta.get(qname) or {}
        ttl_ms = meta.get("ttl_ms")
        if ttl_ms is None:  # 0 is a real TTL: expire immediately
            return
        q = self.queues.get(qname)
        if not q:
            return
        now = _time.monotonic()
        dlx = meta.get("dlx_key")
        while q and (now - q[0].ts) * 1000.0 >= ttl_ms:
            msg = q.popleft()
            if dlx:  # at-least-once: re-stamped into the dead-letter queue
                self.queues.setdefault(dlx, deque()).append(
                    _Message(msg.value, ts=now, props=msg.props)
                )

    def _apply_publish(self, queue: str, body: bytes, props: bytes = b""):
        """Make a publish visible (fault injection applies here)."""
        with self.state_lock:
            self._apply_publish_locked(queue, body, props)

    def _apply_publish_locked(
        self, queue: str, body: bytes, props: bytes = b""
    ):
        """Body of :meth:`_apply_publish`; caller holds ``state_lock``
        (the fenced-publish path must decide token validity and apply in
        ONE critical section)."""
        if queue in self.streams:
            self._appended += 1
            lose = (
                self.lose_appended_every
                and self._appended % self.lose_appended_every == 0
            )
            if not lose:
                self.streams[queue].append(body)
                if (
                    self.duplicate_append_every
                    and self._appended % self.duplicate_append_every == 0
                ):
                    self.streams[queue].append(body)
        else:
            self._published += 1
            lose = (
                self.lose_acked_every
                and self._published % self.lose_acked_every == 0
            )
            if not lose:  # confirm-but-drop = injected data loss
                self.queues.setdefault(queue, deque()).append(
                    _Message(body, ts=_time.monotonic(), props=props)
                )

    def _content_frames(self, conn, ch, body: bytes, method: bytes,
                        props: bytes = b""):
        self._send_frame(conn, FRAME_METHOD, ch, method)
        # publisher properties (flags onward) replay verbatim; otherwise a
        # minimal no-properties header
        header = struct.pack(">HHQ", 60, 0, len(body)) + (
            props or struct.pack(">H", 0)
        )
        self._send_frame(conn, FRAME_HEADER, ch, header)
        if body:
            self._send_frame(conn, FRAME_BODY, ch, body)

    def _handle_get(self, conn: _ConnState, ch: int, qname: str,
                    no_ack: bool = False):
        if self.replication is not None:
            status, rmsg = self.replication.dequeue_get(qname, conn.owner)
            if status == "noquorum":
                # the DEQ never committed: the queue's true state is
                # UNKNOWN.  Answering Basic.Get-Empty here LIED — the r7
                # soak's acked-loss signature was the final drain running
                # through an election/partition window, every get
                # answered "empty" without quorum, and hundreds of
                # committed-ready messages counted lost.  Close the
                # channel loudly instead (the native client marks the
                # connection broken; the drain marks the pass dirty and
                # retries after the settle sleep).
                self._chan_close_541 += 1
                self._send_method(
                    conn,
                    ch,
                    20,
                    40,
                    struct.pack(">H", 541)  # internal-error
                    + _shortstr("basic.get lost quorum (state unknown)")
                    + struct.pack(">HH", 60, 70),
                )
                return
            if rmsg is None:
                self._send_method(conn, ch, 60, 72, _shortstr(""))
                return
            with self.state_lock:
                tag = conn.next_tag
                conn.next_tag += 1
                if no_ack:
                    pass  # auto-acked: settle below, nothing to track
                else:
                    conn.unacked[tag] = (qname, rmsg.mid)
            if no_ack:
                self.replication.settle(conn.owner, rmsg.mid)
            method = (
                struct.pack(">HH", 60, 71)
                + struct.pack(">QB", tag, 0)
                + _shortstr("")
                + _shortstr(qname)
                + struct.pack(">I", 0)
            )
            # fenced grant: the delivery carries its fencing token (the
            # Raft commit index of the DEQ) in the x-fence-token header
            props = (
                _fence_props(rmsg.fence) if rmsg.fence else rmsg.props
            )
            self._content_frames(conn, ch, rmsg.body, method, props)
            return
        with self.state_lock:
            self._expire_locked(qname)
            q = self.queues.setdefault(qname, deque())
            if not q:
                msg = None
                fence = 0
            else:
                msg = q.popleft()
                self._delivered += 1
                if (
                    self.duplicate_every
                    and self._delivered % self.duplicate_every == 0
                ):
                    q.append(
                        _Message(
                            msg.value,
                            ts=_time.monotonic(),
                            props=msg.props,
                        )
                    )
                fence = 0
                if (self.queue_meta.get(qname) or {}).get("fenced"):
                    # local-mode grant: mint the next token and make it
                    # the queue's current fence (mirrors the replicated
                    # twin, where the DEQ commit index plays this role)
                    self._fence_seq += 1
                    fence = self._fence_seq
                    self.fences[qname] = fence
                    msg.fence = fence
                tag = conn.next_tag
                conn.next_tag += 1
                if not no_ack:  # no-ack gets are auto-acknowledged
                    conn.unacked[tag] = (qname, msg)
        if msg is None:
            self._send_method(conn, ch, 60, 72, _shortstr(""))
            return
        method = (
            struct.pack(">HH", 60, 71)
            + struct.pack(">QB", tag, 0)
            + _shortstr("")
            + _shortstr(qname)
            + struct.pack(">I", 0)
        )
        self._content_frames(
            conn, ch, msg.value, method,
            _fence_props(fence) if fence else msg.props,
        )

    def _reject_fenced_consume(
        self,
        conn: _ConnState,
        ch: int,
        *,
        clear_subscription: bool = True,
    ) -> None:
        """Loud refusal of push consumption on a fenced queue (540
        channel close), shared by the consume-registration rejection and
        the delivery-time re-check (a consume that raced the fenced
        declare); the delivery paths also clear ``consuming_queue`` (the
        fenced queue IS the subscription there) so the dead subscription
        stops eating kicks — the registration-time rejection must NOT
        (``consuming_queue`` still holds any pre-existing subscription
        to a different, unfenced queue, which stays live)."""
        if clear_subscription:
            with self.state_lock:
                if conn.consuming_queue is not None:
                    conn.consuming_queue = None
        self._chan_close_540 += 1
        self._send_method(
            conn,
            ch,
            20,
            40,
            struct.pack(">H", 540)  # not-implemented
            + _shortstr(
                "consume on a fenced queue "
                "(fencing tokens ride basic.get)"
            )
            + struct.pack(">HH", 60, 20),
        )

    def _is_fenced_queue(self, qname: str) -> bool:
        """Committed fenced-ness of ``qname``: the declare-time flag in
        the authoritative queue meta — the replicated machine's (which
        survives node restarts via WAL recovery and is populated by
        declares issued through ANY node, once applied locally), or the
        local broker's.  When the meta has an entry it WINS in both
        directions: a plain redeclare committed via a DIFFERENT node
        must clear fenced-ness here even though this node's shadow set
        still carries the stale fenced entry from the original declare.
        Only when the meta has no entry yet (a locally-served declare
        not applied on this replica) does the shadow set decide — and
        never nothing: the shadow alone is empty on the nodes that
        didn't serve the declare and after every restart, which would
        fail open."""
        if self.replication is not None:
            m = self.replication.machine
            with m.lock:
                meta = m.meta.get(qname)
            return self._fenced_given_meta(qname, meta)
        with self.state_lock:
            return self._is_fenced_queue_locked(qname)

    def _fenced_given_meta(self, qname: str, meta: dict | None) -> bool:
        """The meta-wins rule shared by every replicated-mode fenced
        check (callers fetch ``meta`` under the machine lock they
        already hold for other reads): a committed entry decides in
        both directions; only a queue with no committed entry yet falls
        back to this node's shadow declare observations."""
        if meta is not None:
            return bool(meta.get("fenced"))
        with self.state_lock:
            return qname in self._fenced_queues

    def _is_fenced_queue_locked(self, qname: str) -> bool:
        """Non-replicated fenced-ness under an already-held
        ``state_lock`` — for the local delivery path, which must decide
        atomically with the pop (meta entry wins; shadow set covers
        only a queue never declared on this broker)."""
        meta = self.queue_meta.get(qname)
        if meta is not None:
            return bool(meta.get("fenced"))
        return qname in self._fenced_queues

    def _try_deliver(self, conn: _ConnState):
        """Push deliveries: QoS-1 (one in flight) for acking consumers;
        no-ack consumers are auto-acknowledged and drain the queue.
        Deliveries ride the channel the consumer subscribed on
        (``conn.consuming_ch`` — consumers on channel ≠ 1 must not get
        their pushes on channel 1, advisor r3 #1).

        One delivering thread per conn: a second caller (serve thread vs
        kick loop) sets ``deliver_again`` and leaves; the holder re-runs
        after releasing, so no wake-up is lost and no two deliveries can
        interleave frames."""
        while True:
            if not conn.deliver_lock.acquire(blocking=False):
                conn.deliver_again = True
                return
            try:
                conn.deliver_again = False
                self._deliver_pass(conn)
            finally:
                conn.deliver_lock.release()
            if not conn.deliver_again:
                return

    def _deliver_pass(self, conn: _ConnState):
        ch = conn.consuming_ch
        if self.replication is not None:
            self._try_deliver_replicated(conn, ch)
            return
        while conn.consuming_queue is not None and conn.open:
            with self.state_lock:
                # a consume registered before the queue's fenced
                # declare slipped past the registration-time rejection
                # — refuse as loudly as registration would have, never
                # push a grant without its token.  Decided under the
                # SAME lock acquisition as the pop: checked outside it,
                # a fenced declare landing between check and pop would
                # slip a tokenless grant out anyway
                fenced = self._is_fenced_queue_locked(
                    conn.consuming_queue
                )
                if not fenced:
                    if conn.unacked and not conn.consuming_noack:
                        return
                    self._expire_locked(conn.consuming_queue)
                    q = self.queues.setdefault(
                        conn.consuming_queue, deque()
                    )
                    if not q:
                        return
                    msg = q.popleft()
                    self._delivered += 1
                    if (
                        self.duplicate_every
                        and self._delivered % self.duplicate_every == 0
                    ):
                        q.append(
                            _Message(
                                msg.value,
                                ts=_time.monotonic(),
                                props=msg.props,
                            )
                        )
                    tag = conn.next_tag
                    conn.next_tag += 1
                    noack = conn.consuming_noack
                    if not noack:  # no-ack consumers are auto-acked
                        conn.unacked[tag] = (conn.consuming_queue, msg)
            if fenced:
                self._reject_fenced_consume(conn, ch)
                return
            method = (
                struct.pack(">HH", 60, 60)
                + _shortstr("ctag-1")
                + struct.pack(">QB", tag, 0)
                + _shortstr("")
                + _shortstr(conn.consuming_queue)
            )
            self._content_frames(conn, ch, msg.value, method, msg.props)
            if not noack:
                return  # QoS-1: wait for the ack before the next push

    def _try_deliver_replicated(self, conn: _ConnState, ch: int) -> None:
        """Replicated push path: each delivery is a committed DEQ (moving
        the message to the replicated inflight map under this conn's
        owner id); acks settle, conn loss requeues — so leader failover
        inherits delivery state instead of losing it."""
        while conn.consuming_queue is not None and conn.open:
            # fenced re-check FIRST (before the QoS-1 unacked return,
            # like the local path): a consumer sitting on an unacked
            # message from before the queue went fenced must get the
            # loud 540 close, not a silent stall.  It rides the same
            # machine-lock round as the local ready-check (one
            # acquisition per kick on the hot push path); the ready
            # probe itself avoids paying a quorum round trip for an
            # empty-queue DEQ, which would still commit a no-op log
            # entry on every replica (benign races both ways — a miss
            # is repaired by the next kick).  The consume may have been
            # registered before the fenced declare applied on this
            # replica (cross-node declare, or a restart-recovered
            # machine) — the registration-time rejection can't see it
            m = self.replication.machine
            with m.lock:
                meta = m.meta.get(conn.consuming_queue)
                ready = len(m.queues.get(conn.consuming_queue, ()))
            if self._fenced_given_meta(conn.consuming_queue, meta):
                self._reject_fenced_consume(conn, ch)
                return
            with self.state_lock:
                if conn.unacked and not conn.consuming_noack:
                    return  # QoS-1: one in flight
            if ready == 0:
                return
            rmsg = self.replication.dequeue(
                conn.consuming_queue, conn.owner
            )
            if rmsg is None:
                return
            if rmsg.fence:
                # the DEQ applied on the leader's up-to-date meta and
                # minted a grant token even though this replica's meta
                # lagged past the check above: revoke (requeue; the
                # fence already advanced, so the next basic.get mints a
                # fresh higher token) rather than deliver the lock with
                # no token attached — and close the subscription loudly
                self.replication.requeue_one(conn.owner, rmsg.mid)
                self._reject_fenced_consume(conn, ch)
                return
            with self.state_lock:
                tag = conn.next_tag
                conn.next_tag += 1
                noack = conn.consuming_noack
                if not noack:
                    conn.unacked[tag] = (conn.consuming_queue, rmsg.mid)
            if noack:
                self.replication.settle(conn.owner, rmsg.mid)
            method = (
                struct.pack(">HH", 60, 60)
                + _shortstr("ctag-1")
                + struct.pack(">QB", tag, 0)
                + _shortstr("")
                + _shortstr(conn.consuming_queue)
            )
            self._content_frames(conn, ch, rmsg.body, method, rmsg.props)
            if not noack:
                return

    def _stream_deliver(
        self,
        conn: _ConnState,
        ch: int,
        qname: str,
        log: list,
        offset: int,
        ctag: str,
    ):
        """Non-destructive snapshot delivery from ``offset`` over the
        caller-provided snapshot; each record carries its log offset in
        the x-stream-offset message header."""
        snapshot = list(enumerate(log))[offset:]
        for off, body in snapshot:
            with self.state_lock:
                tag = conn.next_tag
                conn.next_tag += 1  # stream acks are credit-only: untracked
            method = (
                struct.pack(">HH", 60, 60)
                + _shortstr(ctag)
                + struct.pack(">QB", tag, 0)
                + _shortstr("")
                + _shortstr(qname)
            )
            self._send_frame(conn, FRAME_METHOD, ch, method)
            table = (
                _shortstr("x-stream-offset") + b"l" + struct.pack(">q", off)
            )
            header = (
                struct.pack(">HHQH", 60, 0, len(body), 0x2000)
                + struct.pack(">I", len(table))
                + table
            )
            self._send_frame(conn, FRAME_HEADER, ch, header)
            if body:
                self._send_frame(conn, FRAME_BODY, ch, body)

    def _deliver_all(self):
        with self.state_lock:
            conns = list(self._conns)
        for c in conns:
            self._try_deliver(c)


# ---------------------------------------------------------------------------
# Standalone node process — the local dev cluster's "rabbitmq-server".
#
# `python -m jepsen_tpu.harness.broker --port P --admin-port A` runs one
# broker as its own OS process with real TCP, so the control plane can
# SIGKILL / SIGSTOP / SIGCONT it like a broker VM (the dress-rehearsal
# stand-in for the reference's per-node rabbitmq-server — the closest a
# zero-egress image gets to docker-compose.yml:24-35).  The admin port
# answers one-line queries ("DEPTHS\n" → "<queue> <count>" per queue —
# the `rabbitmqctl list_queues` stand-in); state is in-memory only, so a
# SIGKILL genuinely loses whatever only this node held (the checker is
# expected to notice — that is the point of the harness).
# ---------------------------------------------------------------------------


def _admin_depths(broker: MiniAmqpBroker) -> str:
    if broker.replication is not None:
        ready = broker.replication.counts()
    else:
        with broker.state_lock:
            # expire first: TTL-dead messages must not count as queued,
            # or the drained-to-zero cross-check misreads dead-letter
            # configs (advisor r3 #5)
            for q in list(broker.queues):
                broker._expire_locked(q)
            ready = {q: len(v) for q, v in broker.queues.items()}
            for conn in broker._conns:
                for qname, _m in conn.unacked.values():
                    ready[qname] = ready.get(qname, 0) + 1
            for s, log in broker.streams.items():
                ready[s] = len(log)
    return "".join(f"{q} {n}\n" for q, n in sorted(ready.items()))


def _serve_admin(broker: MiniAmqpBroker, server: "socket.socket") -> None:
    """One-line admin queries: DEPTHS (rabbitmqctl list_queues stand-in),
    and in replicated mode the per-link partition surface the control
    plane maps iptables rules onto — BLOCK <peer> / UNBLOCK_ALL — plus
    ROLE for failover observability.

    Each accepted connection is served on its own daemon thread: JOIN
    blocks inside ``request_join``'s retry loop for up to 12–20 s, and a
    serial accept loop would stall BLOCK/UNBLOCK partition enforcement,
    DEPTHS drain cross-checks, and ROLE queries behind a mid-run
    membership rejoin (advisor r4).  The handlers themselves are safe to
    run concurrently — every broker/raft mutation they reach is
    lock-protected."""
    import threading as _threading

    while True:
        try:
            sock, _ = server.accept()
        except OSError:
            return
        _threading.Thread(
            target=_serve_admin_conn, args=(broker, sock), daemon=True
        ).start()


def _serve_admin_conn(broker: MiniAmqpBroker, sock: "socket.socket") -> None:
    try:
        req = sock.makefile("r").readline().strip()
        if req == "DEPTHS":
            sock.sendall(_admin_depths(broker).encode() or b"\n")
        elif req.startswith("BLOCK ") and broker.replication is not None:
            broker.replication.raft.block(req[len("BLOCK "):].strip())
            sock.sendall(b"OK\n")
        elif req == "UNBLOCK_ALL" and broker.replication is not None:
            broker.replication.raft.unblock_all()
            sock.sendall(b"OK\n")
        elif req.startswith("JOIN ") and broker.replication is not None:
            # rabbitmqctl join_cluster mapping: ask the cluster at
            # host:port to add this node (a real Raft AddServer
            # committed through the log — blocks until the cfg
            # entry replicates back, so an OK means full member)
            host, _, port = req[len("JOIN "):].strip().rpartition(":")
            if not host or not port.isdigit():
                sock.sendall(b"ERR bad JOIN address\n")
            else:
                ok = broker.replication.raft.request_join(
                    (host, int(port))
                )
                sock.sendall(b"OK\n" if ok else b"ERR join failed\n")
        elif req == "ROLE" and broker.replication is not None:
            state, term, hint = broker.replication.raft.role()
            sock.sendall(f"{state} {term} {hint or '-'}\n".encode())
        elif req == "STATS":
            # cluster telemetry pull (ISSUE 12): one JSON line with the
            # node's full telemetry snapshot — role/term/commit gauges,
            # RPC/election/wire counters, the WAL-fsync latency sketch
            # state, broker depths.  Works in local mode too (raft block
            # null); the runner's poller consumes it at ~1 Hz.
            sock.sendall(
                (_json.dumps(broker.stats_snapshot()) + "\n").encode()
            )
        elif req.startswith("CLOCK_SET ") and (
            broker.replication is not None
        ):
            # clock nemesis: "this node's wall clock now reads T"
            # (epoch ms).  Only the timestamps this node stamps into
            # replicated ops move — like real skew, monotonic timers
            # are untouched.
            target = float(req[len("CLOCK_SET "):])
            broker.replication.clock_offset_ms = (
                target - _time.time() * 1000.0
            )
            sock.sendall(b"OK\n")
        elif req == "CLOCK_GET" and broker.replication is not None:
            off = broker.replication.clock_offset_ms
            sock.sendall(f"{off:.3f}\n".encode())
        elif req.startswith("FSYNC_LAT ") and (
            broker.replication is not None
        ):
            # slow-disk nemesis: "this node's WAL device now takes
            # mean±jitter ms per fsync".  "FSYNC_LAT 0 0" heals.
            # Refused (ERR) on a memory-only node — no WAL, no fault.
            parts = req.split()
            try:
                broker.replication.raft.set_fsync_latency(
                    float(parts[1]),
                    float(parts[2]) if len(parts) > 2 else 0.0,
                )
                sock.sendall(b"OK\n")
            except (ValueError, IndexError) as e:
                sock.sendall(f"ERR {e}\n".encode())
        elif req.startswith("WIRE ") and broker.replication is not None:
            # wire-chaos nemesis: netem-shaped corrupt/duplicate/delay
            # on this node's outgoing peer RPC frames.
            # "WIRE <corrupt_p> <dup_p> <delay_p> <delay_ms>"; "WIRE off"
            # heals.
            from jepsen_tpu.harness.replication import WireFaultSpec

            arg = req[len("WIRE "):].strip()
            try:
                if arg == "off":
                    broker.replication.raft.set_wire_faults(None)
                else:
                    c, d, dp, dms = (float(x) for x in arg.split())
                    broker.replication.raft.set_wire_faults(
                        WireFaultSpec(
                            corrupt_p=c, duplicate_p=d,
                            delay_p=dp, delay_ms=dms,
                        )
                    )
                sock.sendall(b"OK\n")
            except ValueError as e:
                sock.sendall(f"ERR {e}\n".encode())
        elif req.startswith("FORGET ") and (
            broker.replication is not None
        ):
            # rabbitmqctl forget_cluster_node mapping: remove a
            # (stopped) node from the cluster — RemoveServer via a
            # cfg entry committed through the log, forwarded to the
            # leader by any surviving member
            target = req[len("FORGET "):].strip()
            ok = broker.replication.raft.request_forget(target)
            sock.sendall(b"OK\n" if ok else b"ERR forget failed\n")
        else:
            sock.sendall(b"ERR unknown\n")
    except (OSError, ValueError):
        # one bad request must never kill its handler thread loudly;
        # the accept loop itself is untouched either way — this port
        # carries the drain cross-check AND the partition enforcement
        # (BLOCK) for the rest of the run
        pass
    finally:
        try:
            sock.close()
        except OSError:
            pass


def main(argv=None) -> None:
    import argparse
    import signal

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--admin-port", type=int, required=True)
    # replicated-cluster mode: this process is one Raft node.  --peer is
    # repeated once per cluster member as NAME=HOST:REPLPORT (NAME itself
    # may contain ':'; the last '=' -separated field is split on its last
    # ':'); --node-id must match one --peer NAME.
    p.add_argument("--node-id", default=None)
    p.add_argument("--peer", action="append", default=[])
    p.add_argument("--seed-bug", default=None)
    p.add_argument("--data-dir", default=None,
                   help="durable Raft state (WAL + term/vote) directory; "
                        "survives SIGKILL-and-restart")
    p.add_argument("--pending-join", action="store_true",
                   help="boot outside any cluster (self-only, no self-"
                        "election); membership arrives via the admin "
                        "JOIN command (rabbitmqctl join_cluster)")
    p.add_argument("--election-ms", type=int, nargs=2, default=(250, 500))
    p.add_argument("--heartbeat-ms", type=int, default=60)
    p.add_argument("--dead-owner-ms", type=int, default=1500)
    p.add_argument("--submit-timeout-ms", type=int, default=5000)
    args = p.parse_args(argv)

    replication = None
    if args.peer:
        from jepsen_tpu.harness.replication import ReplicatedBackend

        peers: dict[str, tuple[str, int]] = {}
        for spec in args.peer:
            name, addr = spec.rsplit("=", 1)
            host, rport = addr.rsplit(":", 1)
            peers[name] = (host, int(rport))
        if args.node_id not in peers:
            p.error(f"--node-id {args.node_id!r} is not among --peer names")
        replication = ReplicatedBackend(
            args.node_id,
            peers,
            election_timeout=(
                args.election_ms[0] / 1000.0,
                args.election_ms[1] / 1000.0,
            ),
            heartbeat_s=args.heartbeat_ms / 1000.0,
            dead_owner_s=args.dead_owner_ms / 1000.0,
            seed_bug=args.seed_bug,
            submit_timeout_s=args.submit_timeout_ms / 1000.0,
            data_dir=args.data_dir,
            bootstrap=not args.pending_join,
        )

    broker = MiniAmqpBroker(port=args.port, replication=replication).start()
    admin = socket.create_server(("127.0.0.1", args.admin_port))
    threading.Thread(
        target=_serve_admin, args=(broker, admin), daemon=True
    ).start()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    admin.close()
    broker.stop()


if __name__ == "__main__":
    main()
