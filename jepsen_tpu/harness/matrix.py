"""The CI test matrix and its retry/triage semantics.

Equivalent of ``/root/reference/ci/jepsen-test.sh:92-197``: 14 named
configurations (partition strategy × duration × consumer type × dead-letter
× quorum group size), each run with ≤3 attempts, and the reference's triage
rules:

- run valid → done;
- run invalid with a genuine consistency violation ("Analysis invalid") →
  the config FAILS, no retry;
- analysis undecided ("Analysis unknown", e.g. a capped search) → retry,
  like a run that could not attest either way;
- run crashed / final read never happened ("Set was never read") → retry,
  up to the attempt cap;
- plus the out-of-band invariant: after drain, every queue on every node
  must be empty (``rabbitmqctl list_queues`` cross-check,
  ``jepsen-test.sh:144-155``).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping, Sequence

from jepsen_tpu.checkers.protocol import UNKNOWN

logger = logging.getLogger("jepsen_tpu.harness")


def _cfg(**kw: Any) -> dict[str, Any]:
    base = {
        "time-limit": 180.0,
        "time-before-partition": 20.0,
        "net-ticktime": 15,
        "consumer-type": "mixed",
    }
    base.update(kw)
    return base


#: the reference's 14-config matrix (ci/jepsen-test.sh:92-107), flag
#: values in the reference's OWN spellings ("random-partition-halves" —
#: an operator diffing these rows against the CI file sees them match
#: textually; the nemesis accepts both spellings)
CI_MATRIX: list[dict[str, Any]] = [
    _cfg(partition="random-partition-halves", duration=30.0),
    _cfg(partition="partition-halves", duration=30.0),
    _cfg(partition="partition-majorities-ring", duration=30.0),
    _cfg(partition="partition-random-node", duration=30.0),
    _cfg(partition="random-partition-halves", duration=10.0),
    _cfg(
        partition="random-partition-halves",
        duration=10.0,
        **{"quorum-initial-group-size": 3},
    ),
    _cfg(partition="partition-halves", duration=10.0),
    _cfg(partition="partition-majorities-ring", duration=10.0),
    _cfg(partition="partition-random-node", duration=10.0),
    _cfg(
        partition="partition-random-node",
        duration=10.0,
        **{"consumer-type": "asynchronous"},
    ),
    _cfg(
        partition="partition-random-node",
        duration=10.0,
        **{"consumer-type": "asynchronous", "quorum-initial-group-size": 3},
    ),
    _cfg(
        partition="partition-random-node",
        duration=10.0,
        **{"consumer-type": "polling"},
    ),
    _cfg(
        partition="random-partition-halves",
        duration=30.0,
        **{"dead-letter": True},
    ),
    _cfg(partition="partition-halves", duration=30.0, **{"dead-letter": True}),
]

#: extended configs beyond the reference's matrix: the process-fault
#: nemeses (kill = durable-state recovery + Raft rejoin; pause = a silent
#: node, the failure-detector stress).  Opt-in via ``matrix --extended``
#: so the default stays reference-parity.
EXTENDED_MATRIX: list[dict[str, Any]] = [
    _cfg(duration=30.0, nemesis="kill-random-node"),
    _cfg(duration=10.0, nemesis="pause-random-node"),
    _cfg(
        duration=30.0,
        nemesis="kill-random-node",
        **{"consumer-type": "asynchronous"},
    ),
    _cfg(
        duration=10.0,
        nemesis="pause-random-node",
        **{"dead-letter": True},
    ),
]

#: extended configs that need fault surfaces the sim cannot honestly
#: provide (no wall clocks to skew, no real membership to churn, no
#: per-node durable state for a power failure to threaten — the sim's
#: state is cluster-global, so crash-restart would recover vacuously) —
#: run only with ``matrix --db local --extended`` (or a real cluster)
LOCAL_EXTENDED_MATRIX: list[dict[str, Any]] = [
    # clock skew × dead-letter: the skew-sensitive config (1 s TTL) —
    # a correct cluster's TTL rides the replicated log, so nothing
    # acknowledged may go missing however the clocks move
    _cfg(duration=10.0, nemesis="clock-skew", **{"dead-letter": True}),
    # membership churn: kill → forget_cluster_node (real RemoveServer;
    # the cluster serves at 2/2) → fresh rejoin + catch-up, under load
    _cfg(duration=10.0, nemesis="membership-churn"),
    # the power-failure config: whole-cluster SIGKILL + restart against
    # a DURABLE cluster (WAL-recovered Raft) — nothing confirmed may be
    # lost.  `durable` is consumed by the --db local assembly.
    _cfg(duration=10.0, nemesis="crash-restart-cluster", durable=True),
    # the compose soak: partitions, kills, pauses, and power failures
    # randomly interleaved over one durable run (jepsen.nemesis/compose)
    _cfg(
        duration=10.0,
        nemesis="mixed",
        durable=True,
        partition="random-partition-halves",
    ),
    # slow-disk: fsync latency on the WAL (fsyncgate-adjacent) — a
    # correct durable cluster confirms slower and loses nothing
    _cfg(duration=10.0, nemesis="slow-disk", durable=True),
    # wire chaos: corrupt/duplicate/reorder peer frames — a correct
    # transport drops corrupted frames on checksum (degrades to
    # retried loss) and shrugs off dup/reorder by idempotency
    _cfg(duration=10.0, nemesis="wire-chaos"),
    # asymmetric one-way partition: nobody hears the victim while it
    # hears everyone — the deposed-leader truncation window without a
    # full link cut ever happening
    _cfg(
        duration=10.0,
        partition="partition-one-way-out",
    ),
]


def matrix_opts(cfg: Mapping[str, Any]) -> dict[str, Any]:
    """Translate a matrix row into test opts.  Process-fault rows carry no
    partition strategy (their nemesis never reads one)."""
    o = dict(cfg)
    if "partition" in o:
        o["network-partition"] = o.pop("partition")
    o["partition-duration"] = o.pop("duration")
    return o


def matrix_cli_flags(
    matrix: Sequence[Mapping[str, Any]] = CI_MATRIX,
) -> list[str]:
    """Each matrix config as one line of ``test`` subcommand flags — the
    single source of truth the CI shell layer consumes (the reference
    hardcodes the same 14 lines in ``ci/jepsen-test.sh:92-107``)."""
    lines = []
    for cfg in matrix:
        opts = matrix_opts(cfg)
        parts = []
        for key in sorted(opts):
            val = opts[key]
            if isinstance(val, bool):
                if val:
                    parts.append(f"--{key}")
            elif isinstance(val, float) and val == int(val):
                parts.append(f"--{key} {int(val)}")
            else:
                parts.append(f"--{key} {val}")
        lines.append(" ".join(parts))
    return lines


@dataclass
class TestOutcome:
    config_index: int
    opts: dict[str, Any]
    status: str  # "valid" | "invalid" | "error"
    attempts: int
    results: dict[str, Any] | None = None
    notes: list[str] = field(default_factory=list)


class MatrixRunner:
    """Runs a matrix of configs through a ``run_fn`` with the reference's
    retry/triage rules.

    ``run_fn(opts) -> (results_map, queue_lengths)`` where ``results_map``
    is the composed checker output (or raises on crash) and
    ``queue_lengths`` maps queue → outstanding messages after drain.
    """

    def __init__(
        self,
        run_fn: Callable[[dict[str, Any]], tuple[dict[str, Any], Mapping[str, int]]],
        matrix: Sequence[Mapping[str, Any]] = CI_MATRIX,
        max_attempts: int = 3,
    ):
        self.run_fn = run_fn
        self.matrix = list(matrix)
        self.max_attempts = max_attempts

    def run(self) -> list[TestOutcome]:
        outcomes = []
        for i, cfg in enumerate(self.matrix):
            outcomes.append(self._run_config(i, matrix_opts(cfg)))
        return outcomes

    def _run_config(self, index: int, opts: dict[str, Any]) -> TestOutcome:
        out = TestOutcome(config_index=index, opts=opts, status="error",
                          attempts=0)
        for attempt in range(1, self.max_attempts + 1):
            out.attempts = attempt
            logger.info(
                "matrix config %d/%d attempt %d: %s",
                index + 1, len(self.matrix), attempt, opts,
            )
            try:
                results, queue_lengths = self.run_fn(opts)
            except Exception as e:  # noqa: BLE001 — crash ⇒ retry
                out.notes.append(f"attempt {attempt}: crashed: {e}")
                logger.exception("run crashed; retrying")
                continue
            out.results = results

            if self._final_read_missing(results):
                # "Set was never read": the drain never observed anything,
                # so the run can't attest loss either way — invalid run,
                # retry.  Checked before the verdict because such a run
                # typically *also* reports lost>0/valid?=false, which must
                # not be triaged as a genuine violation.
                out.notes.append(
                    f"attempt {attempt}: final read missing; retrying"
                )
                continue

            leftover = {q: n for q, n in queue_lengths.items() if n != 0}
            if leftover:
                # after a completed drain, queues must be empty
                # (ci/jepsen-test.sh:144-155); checked only when the final
                # read actually happened — an aborted drain retries above.
                if results.get("valid?") is True:
                    # clean verdict + leftover = late-committing
                    # indeterminate publishes: the client timed out (mid-
                    # election) but its entry was already in the Raft log
                    # and committed after the drain.  Real brokers have
                    # the same unbounded window — the reference never
                    # trips it only because its 20 s recovery sleeps
                    # dwarf it, while scaled-down runs don't.  Not a
                    # violation (the checker saw no loss); retry.
                    out.notes.append(
                        f"attempt {attempt}: not drained but verdict "
                        f"valid (late indeterminate commits): "
                        f"{leftover}; retrying"
                    )
                    continue
                out.notes.append(f"attempt {attempt}: not drained: {leftover}")
                out.status = "invalid"
                return out

            if results.get("valid?") is True:
                out.status = "valid"
                return out

            if results.get("valid?") == UNKNOWN:
                # undecided analysis: like a run that can't attest either
                # way — retry rather than report a violation
                out.notes.append(f"attempt {attempt}: analysis unknown; retrying")
                continue

            # invalid verdict = genuine violation ("Analysis invalid"):
            # no retry — this is the signal the whole harness exists for
            out.status = "invalid"
            out.notes.append(f"attempt {attempt}: analysis invalid")
            return out
        if out.status == "error":
            out.notes.append("all attempts exhausted")
        return out

    @staticmethod
    def _final_read_missing(results: Mapping[str, Any]) -> bool:
        """A run whose drain never read anything can't attest loss — the
        reference's "Set was never read" retry case."""
        q = results.get("queue", {})
        return (
            q.get("attempt-count", 0) > 0 and q.get("ok-count", 0) == 0
        )
