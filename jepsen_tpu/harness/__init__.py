"""Test-matrix harness: the CI driver layer."""

from jepsen_tpu.harness.matrix import (  # noqa: F401
    CI_MATRIX,
    MatrixRunner,
    TestOutcome,
)
