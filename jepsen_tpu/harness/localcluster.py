"""Local process cluster: the dress-rehearsal control plane.

The reference's bar for "the pieces work together" is a real local
cluster — docker-compose boots three broker containers and the whole
test runs against them (``docker/docker-compose.yml:24-35``).  This
image has no docker and no egress, so the closest honest equivalent is
**mini-broker OS processes as nodes**: each "node" is a
``python -m jepsen_tpu.harness.broker`` process with real TCP (AMQP +
admin ports), and :class:`LocalProcTransport` maps the exact command
strings the SSH control plane would run on a broker VM
(``control/db_rabbitmq.py``, ``control/net.py``) onto actions on those
processes:

- ``rabbitmq-server -detached``      → spawn the node's broker process
- ``killall -9 beam.smp``            → SIGKILL it.  Default clusters are
  in-memory: the node's state dies with it (amnesiac rejoin + catch-up
  from the leader, under a startup grace).  With ``durable=True`` each
  node persists its Raft log + term/vote to a per-node data dir that
  survives the kill, so a restarted node — or the WHOLE restarted
  cluster, the power-failure case — recovers everything confirmed,
  matching real quorum queues' durability contract
- ``killall -STOP/-CONT beam.smp``   → SIGSTOP / SIGCONT (the pause
  nemesis: sockets held, zero progress)
- ``rabbitmqctl list_queues``        → the admin-port DEPTHS query (the
  CI drained-to-zero cross-check, ``ci/jepsen-test.sh:144-155``)
- ``iptables -A INPUT -s X`` / ``-F``→ **per-link socket-level blocks**
  on the replicated cluster (the default): the rule is forwarded to the
  node's admin port as ``BLOCK X`` / ``UNBLOCK_ALL`` and enforced inside
  its Raft RPC layer with INPUT-drop semantics (requests from X dropped
  unprocessed; replies from X discarded) — so the 4 partition topologies
  exercise real quorum behavior: leader step-down, majority failover,
  heal/catch-up, per-link asymmetries (majorities-ring).  In the legacy
  non-replicated mode (``replicated=False``) the old *quorum-loss
  mapping* applies instead: a node that can no longer see a majority is
  SIGSTOPped (the client-visible effect of a minority partition, without
  any real consensus underneath).

- ``rabbitmqctl join_cluster rabbit@P`` → a REAL membership change on
  the replicated cluster: nodes first-boot self-only (the primary
  bootstraps a 1-node cluster, secondaries boot pending — no
  self-election), and the join maps to the node's admin ``JOIN`` →
  a Raft AddServer config entry committed through the log (effective
  on append, §6), serialized one join at a time.  The cluster the
  partitions later stress was *formed* by the same choreography the
  reference runs.  Restarts (the kill nemesis) boot with the full
  known config — membership is durable metadata in RabbitMQ even
  when messages are not.  ``rm -rf`` of the install dir ("cleaning
  previous install") forgets membership and wipes durable state.

Everything else (wget, tar, config upload, feature flags, status-dump
eval) succeeds vacuously, recorded in ``log`` like
:class:`~jepsen_tpu.control.ssh.FakeTransport` — the choreography is
asserted by the FakeTransport unit tests; this transport's job is making
the *live* pieces (runner, native TCP clients, nemesis, drain, checker)
execute together for real.
"""

from __future__ import annotations

import os
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

from jepsen_tpu.control.ssh import RunResult, Transport

REPO_ROOT = str(Path(__file__).resolve().parent.parent.parent)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _Node:
    def __init__(self, name: str, port: int, admin_port: int,
                 repl_port: int = 0):
        self.name = name
        self.port = port
        self.admin_port = admin_port
        self.repl_port = repl_port
        self.proc: subprocess.Popen | None = None
        self.stderr_path: str | None = None
        #: True once this node has been a cluster member: restarts (the
        #: kill nemesis) boot with the full peer config — membership is
        #: durable metadata in RabbitMQ even when messages are not —
        #: while FIRST boots start self-only and join for real
        self.booted_once = False


class LocalProcTransport(Transport):
    """A :class:`Transport` whose "nodes" are local mini-broker processes.

    ``replicated=True`` (default for multi-node clusters) boots each
    broker as one Raft node (``harness/replication.py``): publishes
    quorum-commit before confirming, and iptables rules become real
    per-link blocks.  ``seed_bug`` is forwarded to every node (the
    ``confirm-before-quorum`` red-run fault)."""

    def __init__(
        self,
        n_nodes: int = 3,
        spawn_timeout_s: float = 30.0,
        replicated: bool | None = None,
        seed_bug: str | None = None,
        durable: bool = False,
    ):
        self.spawn_timeout_s = spawn_timeout_s
        # a 1-node "cluster" needs no consensus; multi-node defaults on
        self.replicated = (
            n_nodes > 1 if replicated is None else replicated
        )
        if seed_bug and not self.replicated:
            # a silently-dropped fault would make the red-run proof a
            # false green: the user would credit the checker for a bug
            # that was never injected
            raise ValueError(
                f"seed_bug={seed_bug!r} needs a replicated cluster "
                f"(n_nodes>1, replicated not disabled)"
            )
        if durable and not self.replicated:
            raise ValueError("durable=True needs a replicated cluster")
        if seed_bug == "ack-before-fsync" and not durable:
            # without a WAL there is nothing to skip fsyncing — the
            # fault would silently not exist (false-green red run)
            raise ValueError("seed_bug='ack-before-fsync' needs durable=True")
        self.seed_bug = seed_bug
        self.durable = durable
        self._data_root: str | None = None
        if durable:
            import tempfile

            self._data_root = tempfile.mkdtemp(prefix="jt-cluster-data-")
        self._nodes: dict[str, _Node] = {}
        for _ in range(n_nodes):
            port, admin = _free_port(), _free_port()
            repl = _free_port() if self.replicated else 0
            name = f"127.0.0.1:{port}"
            self._nodes[name] = _Node(name, port, admin, repl)
        self.log: list[tuple[str, str]] = []
        self.files: dict[tuple[str, str], bytes] = {}
        self.lock = threading.Lock()
        self._blocked: set[frozenset[str]] = set()
        self._stopped_by_net: set[str] = set()
        self._stopped_by_cmd: set[str] = set()

    # ---- the cluster surface ---------------------------------------------
    @property
    def nodes(self) -> list[str]:
        return list(self._nodes)

    def alive(self, node: str) -> bool:
        p = self._nodes[node].proc
        return p is not None and p.poll() is None

    # ---- Transport -------------------------------------------------------
    def run(self, node: str, cmd: str, timeout: float | None = None) -> RunResult:
        with self.lock:
            self.log.append((node, cmd))
        inner = self._unwrap(cmd)
        if "rabbitmq-server -detached" in inner:
            self._start(node)
            return RunResult(0, "", "")
        if "killall" in inner and "-9" in inner:
            self._kill(node)
            return RunResult(0, "", "")
        if "killall" in inner and "-STOP" in inner:
            with self.lock:
                self._stopped_by_cmd.add(node)
            self._signal(node, signal.SIGSTOP)
            return RunResult(0, "", "")
        if "killall" in inner and "-CONT" in inner:
            with self.lock:
                self._stopped_by_cmd.discard(node)
                resume = node not in self._stopped_by_net
            if resume:
                self._signal(node, signal.SIGCONT)
            return RunResult(0, "", "")
        if "iptables" in inner:
            self._iptables(node, inner)
            return RunResult(0, "", "")
        if "list_queues" in inner:
            return self._list_queues(node)
        if "join_cluster" in inner and self.replicated:
            return self._join_cluster(node, inner)
        if "forget_cluster_node" in inner and self.replicated:
            return self._forget_cluster_node(node, inner)
        if "date -u -s @" in inner and not self.replicated:
            # non-replicated mini brokers time TTL on time.monotonic():
            # a wall-clock bump genuinely cannot reach them, so a green
            # "tolerates skew" verdict would be a no-fault false green —
            # same refusal rule as seed_bug on non-replicated clusters
            return RunResult(
                1, "",
                "clock-skew needs a replicated cluster (non-replicated "
                "mini brokers run TTL on the monotonic clock)",
            )
        if "date -u -s @" in inner and self.replicated:
            # clock nemesis: "set this VM's wall clock to EPOCH" → the
            # node's admin CLOCK_SET (offset applied to the timestamps
            # it stamps into replicated ops).  Succeeds vacuously on a
            # dead node, like iptables — a real VM's clock is settable
            # whether or not the broker process is up (though HERE a
            # restarted broker forgets its skew; a real VM would not)
            epoch_s = float(inner.split("@", 1)[1].split()[0])
            r = self._admin(node, f"CLOCK_SET {epoch_s * 1000.0:.3f}")
            if r.rc != 0:
                return RunResult(0, "", f"(node down: {r.err})")
            return RunResult(0, "", "")
        if "dmsetup message jt-wal-delay" in inner:
            # slow-disk nemesis: the dm-delay table reload an operator
            # would run → the node's admin FSYNC_LAT (fsync latency
            # applied inside its WAL path).  Fails loudly on a dead or
            # memory-only node: OUR delay lives in the broker process,
            # so "installed but inert" is impossible to honor — and a
            # silent no-op would mint tolerates-slow-disk verdicts with
            # no fault (the TransportDisks refusal contract).
            mean, jitter = inner.split(" delay ", 1)[1].split()[:2]
            r = self._admin(node, f"FSYNC_LAT {mean} {jitter}")
            if r.rc != 0 or not r.out.startswith("OK"):
                return RunResult(1, r.out, r.err or "FSYNC_LAT refused")
            return RunResult(0, "", "")
        if "tc qdisc" in inner and "netem" in inner:
            # wire-chaos nemesis: the real netem line → the node's admin
            # WIRE (rates applied to its outgoing peer RPC frames).
            if inner.startswith("tc qdisc del") or " del " in inner:
                r = self._admin(node, "WIRE off")
            else:
                toks = inner.split()

                def pct(key: str) -> float:
                    v = toks[toks.index(key) + 1]
                    return float(v.rstrip("%")) / 100.0

                delay_ms = float(
                    toks[toks.index("delay") + 1].rstrip("ms")
                )
                r = self._admin(
                    node,
                    f"WIRE {pct('corrupt'):g} {pct('duplicate'):g} "
                    f"{pct('reorder'):g} {delay_ms:g}",
                )
            if r.rc != 0 or not r.out.startswith("OK"):
                return RunResult(1, r.out, r.err or "WIRE refused")
            return RunResult(0, "", "")
        if "rabbitmqctl" in inner and " eval " in inner:
            return RunResult(0, "no_local_member", "")
        if inner.startswith("rm -rf ") and "rabbitmq-server" in inner:
            # "cleaning previous install": a re-setup must re-form the
            # cluster from scratch — forget membership and durable state
            n = self._nodes[node]
            n.booted_once = False
            if self._data_root is not None:
                import shutil

                shutil.rmtree(
                    os.path.join(self._data_root, f"n{n.port}"),
                    ignore_errors=True,
                )
            return RunResult(0, "", "")
        # choreography commands with no process-level meaning here:
        # wget/tar/mkdir/chmod/mv/echo/test -e/feature flags/stop_app
        return RunResult(0, "", "")

    def put(self, node, content, remote_path):
        with self.lock:
            self.log.append((node, f"PUT {remote_path}"))
            self.files[(node, remote_path)] = content

    def get(self, node, remote_path, local_path):
        return False  # broker processes keep no on-disk logs

    def close(self) -> None:
        for n in self._nodes.values():
            self._drop_stderr(n)
            if n.proc is not None and n.proc.poll() is None:
                # a SIGSTOPped child ignores SIGTERM until resumed
                try:
                    n.proc.send_signal(signal.SIGCONT)
                    n.proc.kill()
                    n.proc.wait(timeout=5)
                except (OSError, subprocess.TimeoutExpired):
                    pass
            n.proc = None
        if self._data_root is not None:
            import shutil

            shutil.rmtree(self._data_root, ignore_errors=True)
            self._data_root = None

    # ---- command implementations -----------------------------------------
    @staticmethod
    def _unwrap(cmd: str) -> str:
        """Strip the ``sudo sh -c '…'`` envelope Control.su() adds."""
        if cmd.startswith("sudo sh -c "):
            try:
                return shlex.split(cmd)[3]
            except (ValueError, IndexError):
                return cmd
        return cmd

    def _start(self, node: str) -> None:
        import tempfile

        n = self._nodes[node]
        if n.proc is not None and n.proc.poll() is None:
            return  # already up (idempotent, like -detached)
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        fd, n.stderr_path = tempfile.mkstemp(
            prefix=f"jt-broker-{n.port}-", suffix=".log"
        )
        err_fh = os.fdopen(fd, "wb")
        cmd = [
            sys.executable, "-m", "jepsen_tpu.harness.broker",
            "--port", str(n.port), "--admin-port", str(n.admin_port),
        ]
        if self.replicated:
            cmd += ["--node-id", n.name]
            if n.booted_once:
                # restart (kill nemesis): the node was a member, and
                # cluster membership survives broker restarts (it is
                # durable metadata in RabbitMQ even for transient
                # messages) — boot with the full known config
                for peer in self._nodes.values():
                    cmd += [
                        "--peer",
                        f"{peer.name}=127.0.0.1:{peer.repl_port}",
                    ]
            else:
                # FIRST boot: self-only.  The primary bootstraps a
                # 1-node cluster; everyone else boots pending and is
                # added by a real join_cluster → Raft AddServer commit
                cmd += ["--peer", f"{n.name}=127.0.0.1:{n.repl_port}"]
                if node != next(iter(self._nodes)):
                    cmd += ["--pending-join"]
            # snappy failover relative to the suite's (possibly
            # time-scaled) partition windows.  dead-owner is deliberately
            # NOT snappy: it revokes inflight deliveries (for the mutex
            # family, the lock token — an unfenced-lock revocation), and
            # on a loaded 1-core host heartbeat gaps near 1 s are routine
            # scheduling noise, not death
            cmd += ["--election-ms", "150", "300", "--heartbeat-ms", "40",
                    "--dead-owner-ms", "2000"]
            if self.seed_bug:
                cmd += ["--seed-bug", self.seed_bug]
            if self._data_root is not None:
                # per-node dir keyed by port — SURVIVES kill/restart, so a
                # rebooted node recovers its Raft log (durable SUT)
                cmd += ["--data-dir",
                        os.path.join(self._data_root, f"n{n.port}")]
        try:
            n.proc = subprocess.Popen(
                cmd,
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=err_fh,
            )
        finally:
            err_fh.close()
        deadline = time.monotonic() + self.spawn_timeout_s
        while time.monotonic() < deadline:
            if n.proc.poll() is not None:  # died during startup
                break
            try:
                socket.create_connection(("127.0.0.1", n.port), 0.25).close()
                self._drop_stderr(n)  # only failure paths need the tail
                if node == next(iter(self._nodes)) or not self.replicated:
                    n.booted_once = True  # primary: member from birth
                return
            except OSError:
                time.sleep(0.05)
        tail = ""
        try:
            with open(n.stderr_path, "rb") as fh:
                tail = fh.read()[-500:].decode(errors="replace")
        except OSError:
            pass
        state = (
            f"exited rc={n.proc.returncode}"
            if n.proc.poll() is not None
            else f"still starting after {self.spawn_timeout_s:.0f}s"
        )
        raise RuntimeError(
            f"broker process for {node} never listened ({state})"
            + (f"; stderr tail: {tail}" if tail.strip() else "")
        )

    @staticmethod
    def _drop_stderr(n: _Node) -> None:
        if n.stderr_path is not None:
            try:
                os.unlink(n.stderr_path)
            except OSError:
                pass
            n.stderr_path = None

    def _kill(self, node: str) -> None:
        n = self._nodes[node]
        self._drop_stderr(n)
        if n.proc is not None and n.proc.poll() is None:
            try:
                n.proc.send_signal(signal.SIGCONT)  # SIGKILL beats STOP, but
                n.proc.kill()  # reap deterministically
                n.proc.wait(timeout=5)
            except (OSError, subprocess.TimeoutExpired):
                pass
        n.proc = None

    def _signal(self, node: str, sig: int) -> None:
        n = self._nodes[node]
        if n.proc is not None and n.proc.poll() is None:
            try:
                n.proc.send_signal(sig)
            except OSError:
                pass

    def _iptables(self, node: str, inner: str) -> None:
        parts = shlex.split(inner)
        if self.replicated:
            # real per-link enforcement inside the node's Raft RPC layer
            if "-F" in parts or "-X" in parts:
                self._admin(node, "UNBLOCK_ALL")
            elif "-A" in parts and "-s" in parts:
                peer = parts[parts.index("-s") + 1]
                self._admin(node, f"BLOCK {peer}")
            return
        if "-F" in parts or "-X" in parts:
            with self.lock:
                self._blocked = {
                    link for link in self._blocked if node not in link
                }
        elif "-A" in parts and "-s" in parts:
            peer = parts[parts.index("-s") + 1]
            with self.lock:
                self._blocked.add(frozenset((node, peer)))
        self._apply_stops()

    def _apply_stops(self) -> None:
        """Quorum-loss mapping: SIGSTOP every node whose visible set is a
        minority; resume nodes stopped for no remaining reason."""
        names = list(self._nodes)
        majority = len(names) // 2 + 1
        with self.lock:
            blocked = set(self._blocked)
            want_stopped = set()
            for a in names:
                visible = 1 + sum(
                    1
                    for b in names
                    if b != a and frozenset((a, b)) not in blocked
                )
                if visible < majority:
                    want_stopped.add(a)
            newly_stopped = want_stopped - self._stopped_by_net
            resumable = self._stopped_by_net - want_stopped
            self._stopped_by_net = want_stopped
            keep_stopped = self._stopped_by_cmd | self._stopped_by_net
        for a in newly_stopped:
            self._signal(a, signal.SIGSTOP)
        for a in resumable:
            if a not in keep_stopped:
                self._signal(a, signal.SIGCONT)

    def _join_cluster(self, node: str, inner: str) -> RunResult:
        """``rabbitmqctl join_cluster rabbit@<primary>`` → the node's
        admin JOIN: a real Raft AddServer committed through the log.
        Fails loudly (rc=1) — a vacuous join would leave the node
        serving as its own 1-node cluster."""
        target = inner.split("join_cluster", 1)[1].strip().split()[0]
        pname = target[len("rabbit@"):] if target.startswith("rabbit@") \
            else target
        pn = self._nodes.get(pname)
        if pn is None:
            return RunResult(1, "", f"unknown primary {pname!r}")
        r = self._admin(
            node, f"JOIN 127.0.0.1:{pn.repl_port}", timeout_s=20.0
        )
        if r.rc == 0 and r.out.startswith("OK"):
            self._nodes[node].booted_once = True  # member now
            return RunResult(0, "", "")
        return RunResult(1, r.out, r.err or "join_cluster failed")

    def _forget_cluster_node(self, node: str, inner: str) -> RunResult:
        """``rabbitmqctl forget_cluster_node rabbit@X`` run on a
        SURVIVING node → its admin FORGET (RemoveServer through the
        leader).  Like real rabbitmqctl, the target must be stopped —
        forgetting a running node is refused (an alive removed server
        would disrupt elections; dead ones can't).  On success the
        target's slate is wiped: a later restart boots OUTSIDE the
        cluster and must join_cluster afresh."""
        target = inner.split("forget_cluster_node", 1)[1].strip().split()[0]
        tname = target[len("rabbit@"):] if target.startswith("rabbit@") \
            else target
        tn = self._nodes.get(tname)
        if tn is None:
            return RunResult(1, "", f"unknown node {tname!r}")
        if self.alive(tname):
            return RunResult(
                1, "", f"{tname} is running; stop it first "
                "(rabbitmqctl refuses to forget a running node)"
            )
        r = self._admin(node, f"FORGET {tname}", timeout_s=20.0)
        if r.rc == 0 and r.out.startswith("OK"):
            tn.booted_once = False  # restart = fresh pending boot
            if self._data_root is not None:
                import shutil

                shutil.rmtree(
                    os.path.join(self._data_root, f"n{tn.port}"),
                    ignore_errors=True,
                )
            return RunResult(0, "", "")
        return RunResult(1, r.out, r.err or "forget_cluster_node failed")

    def _admin(
        self, node: str, line: str, timeout_s: float = 2.0
    ) -> RunResult:
        """One-line admin query to a node; a dead node answers rc=1 —
        except for iptables mappings, which succeed vacuously (a real
        iptables rule installs fine on a host whose broker is down)."""
        n = self._nodes[node]
        try:
            with socket.create_connection(
                ("127.0.0.1", n.admin_port), timeout_s
            ) as s:
                s.settimeout(timeout_s)
                s.sendall(line.encode() + b"\n")
                out = b""
                while chunk := s.recv(4096):
                    out += chunk
            return RunResult(0, out.decode(), "")
        except OSError as e:
            if line.startswith(("BLOCK", "UNBLOCK")):
                return RunResult(0, "", f"(node down: {e})")
            return RunResult(1, "", f"admin query failed: {e}")

    def _list_queues(self, node: str) -> RunResult:
        return self._admin(node, "DEPTHS")

    def node_stats(self, node: str, timeout_s: float = 0.5) -> dict | None:
        """One cluster-telemetry snapshot off the node's admin ``STATS``
        command; ``None`` when the node is dead/unreachable (a SIGSTOPped
        node times out inside ``timeout_s`` — the poller records it as
        down rather than stalling the sampling loop)."""
        import json

        r = self._admin(node, "STATS", timeout_s=timeout_s)
        if r.rc != 0 or not r.out.strip():
            return None
        try:
            got = json.loads(r.out)
        except ValueError:
            return None
        return got if isinstance(got, dict) else None

    def leader(self) -> str | None:
        """The current Raft leader's node name, per the nodes' admin ROLE
        answers (None when no node claims leadership — mid-election, or a
        non-replicated cluster).  The targeted ``partition-leader``
        nemesis keys off this."""
        if not self.replicated:
            return None
        for name in self._nodes:
            r = self._admin(name, "ROLE")
            if r.rc == 0 and r.out.startswith("leader"):
                return name
        return None

    def commands(self, node: str | None = None) -> list[str]:
        with self.lock:
            return [c for n, c in self.log if node is None or n == node]


def build_local_test(
    opts,
    n_nodes: int = 3,
    concurrency: int = 5,
    checker_backend: str = "tpu",
    store_root: str = "store",
    workload: str = "queue",
    replicated: bool | None = None,
    seed_bug: str | None = None,
    durable: bool = False,
    nemesis_factory=None,
):
    """The dress-rehearsal assembly in one call: ``build_rabbitmq_test``
    over a fresh :class:`LocalProcTransport` with the fast-boot
    ``RabbitMQDB`` waits.  Returns ``(test, transport)`` — the caller owns
    ``transport.close()``."""
    from jepsen_tpu.control.db_rabbitmq import RabbitMQDB
    from jepsen_tpu.suite import build_rabbitmq_test

    t = LocalProcTransport(
        n_nodes=n_nodes, replicated=replicated, seed_bug=seed_bug,
        durable=durable,
    )
    try:
        nodes = t.nodes
        test = build_rabbitmq_test(
            opts=opts,
            nodes=nodes,
            transport=t,
            db=RabbitMQDB(
                t, nodes, primary_wait_s=0.3, secondary_wait_s=0.3,
                join_stagger_max_s=0.2,
            ),
            concurrency=concurrency,
            checker_backend=checker_backend,
            store_root=store_root,
            workload=workload,
            nemesis_factory=nemesis_factory,
        )
    except BaseException:
        t.close()
        raise
    return test, t
