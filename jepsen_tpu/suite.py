"""Test assembly: the quorum-queue partition test.

Equivalent of the reference's ``rabbit-test`` (``rabbitmq.clj:250-286``):
compose client, nemesis, checkers, and the four-phase generator program —

1. a rate-limited mix of enqueues (values from one incrementing counter)
   and dequeues, with the nemesis cycling sleep→start→sleep→stop, bounded
   by ``time_limit``;
2. a final nemesis ``stop`` (heal);
3. a logged recovery sleep;
4. one ``drain`` per client thread (the final read the verdict hinges on).

``build_sim_test`` wires it to the in-process simulator (no cluster
needed); ``build_rabbitmq_test`` (control-plane milestone) wires the same
program to a real RabbitMQ cluster over SSH + AMQP.
"""

from __future__ import annotations

import itertools
from typing import Any, Mapping

from jepsen_tpu.checkers.perf import Perf
from jepsen_tpu.checkers.protocol import compose
from jepsen_tpu.checkers.queue_lin import QueueLinearizability
from jepsen_tpu.checkers.stats import Stats, UnhandledExceptions
from jepsen_tpu.checkers.total_queue import TotalQueue
from jepsen_tpu.client.protocol import QueueClient
from jepsen_tpu.client.sim import SimCluster, sim_driver_factory
from jepsen_tpu.control.net import (
    SimNet,
    SimProcs,
    TransportClocks,
    TransportDisks,
    TransportMembership,
    TransportWire,
)
from jepsen_tpu.control.nemesis import make_nemesis
from jepsen_tpu.control.runner import DB, Test
from jepsen_tpu.generators.core import (
    Clients,
    Cycle,
    Delay,
    EachThread,
    FnGen,
    Log,
    Mix,
    NemesisOnly,
    NemesisRoute,
    Once,
    OpGen,
    Phases,
    Sleep,
    TimeLimit,
)
from jepsen_tpu.history.ops import FULL_READ, Op, OpF, OpType

DEFAULT_ARCHIVE_URL = (
    "https://github.com/rabbitmq/rabbitmq-server/releases/download/"
    "v4.2.1/rabbitmq-server-generic-unix-4.2.1.tar.xz"
)

DEFAULT_OPTS: dict[str, Any] = {
    # the reference's CLI defaults (rabbitmq.clj:288-327)
    "rate": 50.0,  # ops/sec
    "time-limit": 30.0,  # seconds of phase-1 load
    "time-before-partition": 10.0,
    "partition-duration": 10.0,
    "network-partition": "partition-random-halves",
    "nemesis": "partition",  # or kill/pause-random-node, crash-restart-cluster
    "publish-confirm-timeout": 5.0,  # seconds (5000 ms in the reference)
    # stream final read: extra empty batches confirming end-of-log when no
    # offset proof is available (the x-stream-offset="last" probe is the
    # primary mechanism; this is the fallback heuristic's strictness)
    "full-read-confirm-empties": 1,
    # stream cursor reads: how long a read waits for records (a live AMQP
    # read at the log tail holds its consumer open this long when nothing
    # arrives — size it to the workload's append cadence, not the 5 s
    # publish deadline, or read-heavy mixes stall on the empty tail)
    "read-timeout": 5.0,
    "recovery-sleep": 20.0,  # gen/sleep 20 before drain
    "consumer-type": "polling",
    "net-ticktime": 15,
    "quorum-initial-group-size": 0,
    "dead-letter": False,
    "fenced": False,  # mutex family: fencing-token mode (--fenced)
    "durable": False,  # --db local: WAL-backed Raft logs (survive SIGKILL)
    "message-ttl": 1.0,  # dead-letter mode TTL (MESSAGE_TTL, Utils.java:55)
    "archive-url": DEFAULT_ARCHIVE_URL,
}


def _four_phase(opts: Mapping[str, Any], load, final_read_factory):
    """The shared four-phase choreography (``rabbitmq.clj:267-284``):
    rate-limited load under the nemesis cycle → heal → recovery sleep →
    one final read per thread.  ``load`` is the client op generator;
    ``final_read_factory()`` builds each thread's phase-4 generator.

    The nemesis side is the uniform start/sleep/stop cycle by default;
    an explicit ``nemesis-schedule`` opt (a list of ``[at_s, dur_s]``
    windows, produced by the matrix fuzzer) replaces it with start/stop
    pairs at exactly those offsets — the delta-debuggable form: dropping
    a window from the list drops exactly one fault injection."""
    schedule = opts.get("nemesis-schedule")
    if schedule is not None:
        from jepsen_tpu.fuzz.schedule import schedule_generator

        nemesis_cycle = schedule_generator(schedule)
    else:
        nemesis_cycle = Cycle(
            lambda: [
                Sleep(opts["time-before-partition"]),
                Once(OpGen(OpF.START, OpType.INFO)),
                Sleep(opts["partition-duration"]),
                Once(OpGen(OpF.STOP, OpType.INFO)),
            ]
        )
    phase_load = TimeLimit(
        NemesisRoute(nemesis_cycle, Delay(load, 1.0 / opts["rate"])),
        opts["time-limit"],
    )
    return Phases(
        [
            phase_load,
            NemesisOnly(Once(OpGen(OpF.STOP, OpType.INFO))),
            Log("waiting for recovery"),
            Sleep(opts["recovery-sleep"]),
            Clients(EachThread(lambda: Once(final_read_factory()))),
        ]
    )


def queue_generator(opts: Mapping[str, Any]):
    """The four-phase generator program (``rabbitmq.clj:267-284``)."""
    counter = itertools.count()
    enqueue = FnGen(
        lambda ctx: Op.invoke(OpF.ENQUEUE, ctx.process, next(counter))
    )
    dequeue = FnGen(lambda ctx: Op.invoke(OpF.DEQUEUE, ctx.process))
    return _four_phase(
        opts, Mix([enqueue, dequeue]), lambda: OpGen(OpF.DRAIN)
    )


def queue_checker(
    backend: str = "tpu",
    with_perf: bool = True,
    with_timeline: bool = True,
    delivery: str = "exactly-once",
):
    """``delivery`` is the SUT's contract (like the elle checker picking
    the claimed isolation level, r3): the sim broker dedups, so it is
    held to exactly-once; live RabbitMQ (and the replicated local
    cluster) redeliver after consumer/node failure — at-least-once —
    where duplicates are reported but only loss/phantoms/causality
    invalidate."""
    from jepsen_tpu.checkers.timeline import Timeline

    checkers = {
        "queue": TotalQueue(backend=backend),
        "linear": QueueLinearizability(backend=backend, delivery=delivery),
    }
    if with_timeline:
        checkers["timeline"] = Timeline()
    return _compose_with_defaults(checkers, with_perf)


def _compose_with_defaults(checkers: dict, with_perf: bool = True):
    """Compose a workload's checkers with the defaults jepsen's runner
    adds to every test (``stats`` + ``unhandled-exceptions``, plus
    ``perf`` unless disabled) — one place, so a new workload family
    cannot silently ship without them.

    ``perf`` is the reference-parity PNG renderer; ``perf-windowed`` is
    the ISSUE-11 device windowed-stats kernel (``report/perfstats.py``)
    whose summary lands in every run's ``results.json`` and whose
    tensors back the default-on run report."""
    from jepsen_tpu.report.perfstats import WindowedPerf

    checkers["stats"] = Stats()
    checkers["exceptions"] = UnhandledExceptions()
    if with_perf:
        checkers["perf"] = Perf()
        checkers["perf-windowed"] = WindowedPerf()
    return compose(checkers)


def stream_generator(opts: Mapping[str, Any]):
    """Stream workload program: rate-limited append/read mix under the
    nemesis cycle, heal, recovery sleep, then one full read per thread
    (the stream drain analog)."""
    counter = itertools.count()
    append = FnGen(
        lambda ctx: Op.invoke(OpF.APPEND, ctx.process, next(counter))
    )
    read = FnGen(lambda ctx: Op.invoke(OpF.READ, ctx.process))
    return _four_phase(
        opts,
        Mix([append, append, read]),
        lambda: FnGen(
            lambda ctx: Op.invoke(OpF.READ, ctx.process, FULL_READ)
        ),
    )


def stream_checker(
    backend: str = "tpu",
    with_perf: bool = True,
    append_fail: str = "definite",
):
    from jepsen_tpu.checkers.stream_lin import StreamLinearizability

    checkers = {
        "stream": StreamLinearizability(
            backend=backend, append_fail=append_fail
        )
    }
    return _compose_with_defaults(checkers, with_perf)


def elle_generator(opts: Mapping[str, Any], n_keys: int = 8, seed: int = 0):
    """Transactional workload program: rate-limited random list-append
    transactions (1–4 micro-ops over ``n_keys`` keys, globally unique
    append values) under the nemesis cycle, then heal + a final read-only
    txn per thread so every key's final order is observed."""
    import random as _random

    from jepsen_tpu.checkers.elle import APPEND, READ

    counter = itertools.count()
    rng = _random.Random(seed)

    def gen_txn(ctx):
        mops = []
        for _ in range(rng.randint(1, 4)):
            k = rng.randrange(n_keys)
            if rng.random() < 0.5:
                mops.append([APPEND, k, next(counter)])
            else:
                mops.append([READ, k, None])
        return Op.invoke(OpF.TXN, ctx.process, mops)

    def gen_final_read(ctx):
        return Op.invoke(
            OpF.TXN, ctx.process, [[READ, k, None] for k in range(n_keys)]
        )

    return _four_phase(
        opts, FnGen(gen_txn), lambda: FnGen(gen_final_read)
    )


def mutex_generator(opts: Mapping[str, Any]):
    """Mutex workload program (the reference's legacy commented variant,
    ``rabbitmq_test.clj:18-44``): random acquire/release mix under the
    nemesis cycle — busy-lock failures are normal history, timeouts are
    indeterminate — then heal + one final release per thread."""
    acquire = FnGen(lambda ctx: Op.invoke(OpF.ACQUIRE, ctx.process))
    release = FnGen(lambda ctx: Op.invoke(OpF.RELEASE, ctx.process))
    return _four_phase(
        opts,
        Mix([acquire, release]),
        lambda: FnGen(lambda ctx: Op.invoke(OpF.RELEASE, ctx.process)),
    )


def mutex_checker(
    backend: str = "tpu", with_perf: bool = True,
    fenced: bool | None = None,
):
    """``fenced`` pins the model the run is checked under (None =
    auto-detect from the history): unfenced → ``OwnedMutex`` mutual
    exclusion, fenced → ``FencedMutex`` token order (overlapping
    revoked/current holds are legal; stale-token success is not)."""
    from jepsen_tpu.checkers.wgl import MutexWgl

    checkers = {"mutex": MutexWgl(backend=backend, fenced=fenced)}
    return _compose_with_defaults(checkers, with_perf)


def elle_checker(
    backend: str = "tpu",
    with_perf: bool = True,
    model: str = "serializable",
):
    from jepsen_tpu.checkers.elle import ElleListAppend

    checkers = {"elle": ElleListAppend(backend=backend, model=model)}
    return _compose_with_defaults(checkers, with_perf)


def build_sim_test(
    opts: Mapping[str, Any] | None = None,
    nodes=("n1", "n2", "n3"),
    concurrency: int = 5,
    checker_backend: str = "tpu",
    sim_seed: int = 0,
    drop_acked_every: int = 0,
    duplicate_every: int = 0,
    drop_appended_every: int = 0,
    duplicate_append_every: int = 0,
    double_grant_every: int = 0,
    stale_token_every: int = 0,
    store_root: str = "store",
    workload: str = "queue",
    nemesis_factory=None,
) -> tuple[Test, SimCluster]:
    """The reference test wired to the in-process simulator.  ``workload``
    selects the queue (reference active path), stream (config #4), or
    elle transactional (config #5) program.  ``nemesis_factory`` (same
    keyword signature as :func:`make_nemesis`) swaps the nemesis
    assembly — the matrix fuzzer passes its scheduled-event nemesis
    through here."""
    from jepsen_tpu.client.protocol import StreamClient, TxnClient
    from jepsen_tpu.client.sim import (
        sim_stream_driver_factory,
        sim_txn_driver_factory,
    )

    o = {**DEFAULT_OPTS, **(opts or {})}
    cluster = SimCluster(
        nodes,
        seed=sim_seed,
        drop_acked_every=drop_acked_every,
        duplicate_every=duplicate_every,
        drop_appended_every=drop_appended_every,
        duplicate_append_every=duplicate_append_every,
        double_grant_every=double_grant_every,
        fenced=bool(o.get("fenced")),
        stale_token_every=stale_token_every,
        dead_letter=bool(o.get("dead-letter")),
        message_ttl_s=o.get("message-ttl", 1.0),
    )
    nemesis = (nemesis_factory or make_nemesis)(
        o, SimNet(cluster), SimProcs(cluster), nodes, seed=sim_seed
    )
    if workload == "stream":
        client = StreamClient(
            sim_stream_driver_factory(cluster),
            publish_confirm_timeout_s=o["publish-confirm-timeout"],
            read_timeout_s=o["read-timeout"],
            full_read_confirm_empties=o["full-read-confirm-empties"],
        )
        generator = stream_generator(o)
        checker = stream_checker(checker_backend)
        name = "rabbitmq-stream-partition-sim"
    elif workload == "elle":
        client = TxnClient(
            sim_txn_driver_factory(cluster),
            txn_timeout_s=o["publish-confirm-timeout"],
        )
        generator = elle_generator(o, seed=sim_seed)
        # the sim's txns apply under a global lock — strictly serializable
        checker = elle_checker(
            checker_backend,
            model=o.get("consistency-model", "serializable"),
        )
        name = "rabbitmq-elle-txn-sim"
    elif workload == "mutex":
        from jepsen_tpu.client.protocol import MutexClient
        from jepsen_tpu.client.sim import sim_mutex_driver_factory

        fenced = bool(o.get("fenced"))
        client = MutexClient(
            sim_mutex_driver_factory(cluster),
            op_timeout_s=o["publish-confirm-timeout"],
            fenced=fenced,
        )
        generator = mutex_generator(o)
        checker = mutex_checker(checker_backend, fenced=fenced)
        name = "rabbitmq-fenced-mutex-sim" if fenced else "rabbitmq-mutex-sim"
    elif workload == "queue":
        client = QueueClient(
            sim_driver_factory(cluster),
            publish_confirm_timeout_s=o["publish-confirm-timeout"],
        )
        generator = queue_generator(o)
        checker = queue_checker(checker_backend)
        name = "rabbitmq-simple-partition-sim"
    else:
        raise ValueError(f"unknown workload {workload!r}")
    test = Test(
        name=name,
        nodes=list(nodes),
        client=client,
        generator=generator,
        checker=checker,
        db=DB(),
        nemesis=nemesis,
        concurrency=concurrency,
        store_root=store_root,
        opts=o,
    )
    return test, cluster


def build_rabbitmq_test(
    opts: Mapping[str, Any] | None = None,
    nodes=("n1", "n2", "n3"),
    concurrency: int = 5,
    checker_backend: str = "tpu",
    store_root: str = "store",
    ssh_user: str = "root",
    ssh_private_key: str | None = None,
    transport=None,
    workload: str = "queue",
    db=None,
    nemesis_factory=None,
) -> Test:
    """The reference test against a real RabbitMQ cluster: SSH DB
    lifecycle, iptables partitions, native C++ AMQP clients.

    ``db`` overrides the DB lifecycle (default: ``RabbitMQDB`` with the
    reference's boot waits) — the local-process dress rehearsal passes a
    fast-boot ``RabbitMQDB`` over a :class:`LocalProcTransport`."""
    from jepsen_tpu.client.native import (
        native_driver_factory,
        native_stream_driver_factory,
        native_txn_driver_factory,
    )
    from jepsen_tpu.client.protocol import StreamClient, TxnClient
    from jepsen_tpu.control.db_rabbitmq import RabbitMQDB, RabbitMQProcs
    from jepsen_tpu.control.net import IptablesNet
    from jepsen_tpu.control.ssh import SshTransport

    o = {**DEFAULT_OPTS, **(opts or {})}
    transport = transport or SshTransport(
        user=ssh_user, private_key=ssh_private_key
    )
    db = db or RabbitMQDB(transport, nodes)
    nemesis = (nemesis_factory or make_nemesis)(
        o,
        IptablesNet(transport, nodes),
        RabbitMQProcs(transport, nodes),
        nodes,
        # the local process cluster can name its Raft leader (admin ROLE);
        # an SSH transport has no hook and partition-leader stays refused
        leader_fn=getattr(transport, "leader", None),
        # reproducible fault schedules when the run pins a seed (mixed-
        # nemesis family picks, partition victim choices)
        seed=(int(o["seed"]) if o.get("seed") is not None else None),
        # wall-clock fault surface (jepsen.nemesis.time): date-over-
        # transport; the local cluster maps it to admin CLOCK_SET.
        # A non-replicated local cluster gets NO clocks surface — its
        # brokers time TTL monotonically, so a skew "fault" would be a
        # silent noop and any green verdict a false one (make_nemesis
        # then refuses clock-skew, and mixed omits the member)
        clocks=(
            TransportClocks(transport, nodes)
            if getattr(transport, "replicated", True)
            else None
        ),
        # membership shrink/grow (forget_cluster_node / join_cluster):
        # same gate — only meaningful where joins are real
        membership=(
            TransportMembership(transport, nodes)
            if getattr(transport, "replicated", True)
            else None
        ),
        # slow-disk (WAL fsync latency): only where there IS a WAL —
        # a durable replicated cluster; elsewhere the surface is absent
        # and make_nemesis refuses the family rather than no-opping it
        disks=(
            TransportDisks(transport, nodes)
            if getattr(transport, "replicated", True)
            and bool(o.get("durable"))
            else None
        ),
        # wire chaos (peer-frame corrupt/duplicate/reorder): any
        # replicated cluster's RPC plane
        wire=(
            TransportWire(transport, nodes)
            if getattr(transport, "replicated", True)
            else None
        ),
    )
    if workload == "stream":
        client = StreamClient(
            native_stream_driver_factory(),
            publish_confirm_timeout_s=o["publish-confirm-timeout"],
            read_timeout_s=o["read-timeout"],
            full_read_confirm_empties=o["full-read-confirm-empties"],
        )
        generator = stream_generator(o)
        # real sockets: a ConnectionError on append is the CLIENT's
        # verdict, not the broker's (the reference's own :fail mapping,
        # rabbitmq.clj:211-213) — a materialized all-fail value is
        # `recovered`, like the queue checker's bucket (r5 burn-in find)
        checker = stream_checker(
            checker_backend, append_fail="indeterminate"
        )
        name = "rabbitmq-stream-partition"
    elif workload == "elle":
        client = TxnClient(
            native_txn_driver_factory(),
            txn_timeout_s=o["publish-confirm-timeout"],
        )
        # seedable micro-op mix ("seed" opt): distinct trials must not
        # replay byte-identical txn programs (tools/measure_g2.py)
        generator = elle_generator(o, seed=int(o.get("seed", 0) or 0))
        # AMQP tx promises atomic commit visibility, NOT read isolation
        # across keys: a live broker produces genuine G2 anti-dependency
        # cycles under concurrency, so the honest default level for this
        # SUT is read-committed (elle practice: check what the system
        # claims); --consistency-model serializable tightens it
        checker = elle_checker(
            checker_backend,
            model=o.get("consistency-model", "read-committed"),
        )
        name = "rabbitmq-elle-txn"
    elif workload == "queue":
        client = QueueClient(
            native_driver_factory(list(nodes)),
            publish_confirm_timeout_s=o["publish-confirm-timeout"],
        )
        generator = queue_generator(o)
        # RabbitMQ's queue contract is at-least-once: redelivery after
        # consumer/conn/node failure is documented behavior, not a bug —
        # hold the SUT to the level it claims (duplicates reported, only
        # loss/phantom/causality invalidate)
        checker = queue_checker(checker_backend, delivery="at-least-once")
        name = "rabbitmq-simple-partition"
    elif workload == "mutex":
        # the reference's legacy linearizable-lock variant
        # (rabbitmq_test.clj:18-44), live: a single-token quorum-queue lock
        # (acquire = hold the token un-acked, release = reject/requeue; a
        # dropped connection revokes the grant broker-side — the unfenced-
        # lock hazard the checker must see).  --fenced turns on the
        # fencing-token mode: grants carry the Raft commit index as a
        # monotonically increasing token, releases/protected ops carry it
        # back, the broker rejects stale tokens — the same revocation
        # schedule that double-grants unfenced then soaks green.
        from jepsen_tpu.client.protocol import MutexClient
        from jepsen_tpu.client.native import native_mutex_driver_factory

        fenced = bool(o.get("fenced"))
        client = MutexClient(
            native_mutex_driver_factory(),
            op_timeout_s=o["publish-confirm-timeout"],
            fenced=fenced,
        )
        generator = mutex_generator(o)
        checker = mutex_checker(checker_backend, fenced=fenced)
        name = "rabbitmq-fenced-mutex" if fenced else "rabbitmq-mutex"
    else:
        raise ValueError(f"unknown workload {workload!r}")
    # cluster telemetry plane (ISSUE 12): any transport that can answer
    # the admin STATS pull (LocalProcTransport) gets the ~1 Hz poller;
    # SSH transports have no STATS surface and stay logs-only (the
    # reference's own blindness — PARITY.md names this as exceeded)
    cluster_source = None
    if hasattr(transport, "node_stats"):
        from jepsen_tpu.obs.cluster import TransportStatsSource

        cluster_source = TransportStatsSource(transport)
    return Test(
        name=name,
        nodes=list(nodes),
        client=client,
        generator=generator,
        checker=checker,
        db=db,
        nemesis=nemesis,
        concurrency=concurrency,
        store_root=store_root,
        opts=o,
        cluster_source=cluster_source,
    )
