"""Nemeses: fault injectors driven by ``{:f :start}`` / ``{:f :stop}`` ops.

The four network-partition strategies the reference selects by flag
(``rabbitmq.clj:219-243``), rebuilt over the :class:`~jepsen_tpu.control.net.Net`
interface so one implementation drives both the simulator and real nodes
(iptables over SSH):

- ``partition-random-halves``  — shuffle nodes, cut into two halves
- ``partition-halves``         — first half vs rest, deterministic
- ``partition-majorities-ring``— each node keeps links only to its ring
  neighbors: every node still *sees* a majority, but no two nodes agree on
  which majority (the nastiest case for leader election)
- ``partition-random-node``    — isolate one random node
"""

from __future__ import annotations

import logging
import random
from typing import Any, Callable, Mapping, Sequence

from jepsen_tpu.control.net import Net, complete_grudges
from jepsen_tpu.history.ops import Op, OpF, OpType

logger = logging.getLogger("jepsen_tpu.nemesis")


def random_halves(nodes: Sequence[str], rng: random.Random):
    ns = list(nodes)
    rng.shuffle(ns)
    mid = (len(ns) + 1) // 2
    return complete_grudges([ns[:mid], ns[mid:]])


def halves(nodes: Sequence[str], rng: random.Random):
    mid = (len(nodes) + 1) // 2
    return complete_grudges([nodes[:mid], nodes[mid:]])


def majorities_ring(nodes: Sequence[str], rng: random.Random):
    """Node i keeps links only to its nearest ring neighbors (enough that
    its local view is a majority); everything further is cut.  With ≤3
    nodes every pair is ring-adjacent, so no link is cut — the interesting
    regime (as in the reference's CI) is 5 nodes, where each node sees a
    different 3-node majority."""
    ns = list(nodes)
    rng.shuffle(ns)
    n = len(ns)
    keep = (n // 2 + 1) // 2  # ring neighbors kept per side
    grudges: dict[str, set[str]] = {m: set() for m in ns}
    for i, a in enumerate(ns):
        for j, b in enumerate(ns):
            if i == j:
                continue
            dist = min((i - j) % n, (j - i) % n)
            if dist > keep:
                grudges[a].add(b)
    return grudges


def random_node(nodes: Sequence[str], rng: random.Random):
    lone = rng.choice(list(nodes))
    rest = [m for m in nodes if m != lone]
    return complete_grudges([[lone], rest])


STRATEGIES: dict[str, Callable] = {
    "partition-random-halves": random_halves,
    # the reference's OWN spelling for the same strategy
    # (rabbitmq.clj:221 "random-partition-halves", used 5x in
    # ci/jepsen-test.sh:93-107) — both are first-class so a pasted
    # reference command line parses verbatim (VERDICT r3 missing #3)
    "random-partition-halves": random_halves,
    "partition-halves": halves,
    "partition-majorities-ring": majorities_ring,
    "partition-random-node": random_node,
}

#: targeted strategy (beyond the reference's four): isolate the CURRENT
#: consensus leader — jepsen's own nemesis library grew leader-targeting
#: partitioners because random victims rarely hit the interesting window
#: (a leader's uncommitted tail).  Requires a ``leader_fn`` (the local
#: process cluster answers via its nodes' admin ROLE query); falls back
#: to a random victim when no leader is discoverable.
PARTITION_LEADER = "partition-leader"


class PartitionNemesis:
    """Applies a partition strategy on ``start``, heals on ``stop``."""

    def __init__(self, strategy: str, net: Net, nodes: Sequence[str],
                 seed: int | None = None,
                 leader_fn: Callable[[], str | None] | None = None):
        if strategy not in STRATEGIES and strategy != PARTITION_LEADER:
            raise ValueError(
                f"unknown partition {strategy!r}; one of "
                f"{sorted([*STRATEGIES, PARTITION_LEADER])}"
            )
        if strategy == PARTITION_LEADER and leader_fn is None:
            raise ValueError(
                "partition-leader needs a leader-discovery hook; this "
                "cluster's transport does not provide one"
            )
        self.strategy = strategy
        self.net = net
        self.nodes = list(nodes)
        self.rng = random.Random(seed)
        self.leader_fn = leader_fn

    def setup(self, test: Mapping[str, Any]) -> None:
        self.net.heal()

    def _grudges(self):
        if self.strategy == PARTITION_LEADER:
            victim = None
            try:
                victim = self.leader_fn()
            except Exception:  # noqa: BLE001 - discovery is best-effort
                pass
            if victim is None or victim not in self.nodes:
                victim = self.rng.choice(self.nodes)
                logger.info(
                    "nemesis: no discoverable leader; isolating %s", victim
                )
            rest = [m for m in self.nodes if m != victim]
            return complete_grudges([[victim], rest])
        return STRATEGIES[self.strategy](self.nodes, self.rng)

    def invoke(self, test: Mapping[str, Any], op: Op) -> Op:
        if op.f == OpF.START:
            grudges = self._grudges()
            self.net.partition(grudges)
            desc = {a: sorted(bs) for a, bs in grudges.items() if bs}
            logger.info("nemesis: cut links %s", desc)
            return op.complete(OpType.INFO, value=str(desc))
        if op.f == OpF.STOP:
            self.net.heal()
            logger.info("nemesis: healed")
            return op.complete(OpType.INFO, value="healed")
        raise ValueError(f"nemesis got unexpected op {op}")

    def teardown(self, test: Mapping[str, Any]) -> None:
        self.net.heal()


class ProcessNemesis:
    """Kill or pause a random node's DB process on ``start``; restart or
    resume every victim on ``stop``.  Jepsen's classic process nemeses,
    beyond the reference's partition-only set: a SIGKILLed node tests
    durable-state recovery and Raft re-join, a SIGSTOPped one tests the
    failure detector (the process holds its sockets but goes silent —
    exactly what ``net_ticktime``/aten tuning is about)."""

    def __init__(self, mode: str, procs, nodes: Sequence[str],
                 seed: int | None = None):
        if mode not in ("kill", "pause"):
            raise ValueError(f"unknown process-nemesis mode {mode!r}")
        self.mode = mode
        self.procs = procs
        self.nodes = list(nodes)
        self.rng = random.Random(seed)
        self.victims: list[str] = []

    def setup(self, test: Mapping[str, Any]) -> None:
        pass

    def invoke(self, test: Mapping[str, Any], op: Op) -> Op:
        if op.f == OpF.START:
            # pick among nodes still up: consecutive starts must inject a
            # new fault, and the history must never claim "kill n" for a
            # node that was already down
            up = [n for n in self.nodes if n not in self.victims]
            if not up:
                logger.info("nemesis: all nodes already %sed", self.mode)
                return op.complete(
                    OpType.INFO, value=f"already-down {self.victims}"
                )
            victim = self.rng.choice(up)
            (self.procs.kill if self.mode == "kill"
             else self.procs.pause)(victim)
            self.victims.append(victim)
            logger.info("nemesis: %s %s", self.mode, victim)
            return op.complete(OpType.INFO, value=f"{self.mode} {victim}")
        if op.f == OpF.STOP:
            restored, self.victims = self.victims, []
            for v in restored:
                (self.procs.restart if self.mode == "kill"
                 else self.procs.resume)(v)
            logger.info("nemesis: restored %s", restored)
            return op.complete(OpType.INFO, value=f"restored {restored}")
        raise ValueError(f"nemesis got unexpected op {op}")

    def teardown(self, test: Mapping[str, Any]) -> None:
        for v in self.victims:
            (self.procs.restart if self.mode == "kill"
             else self.procs.resume)(v)
        self.victims = []


class CrashRestartNemesis:
    """Power failure: SIGKILL **every** node on ``start``, restart them
    all on ``stop``.  The strictest durability test there is — nothing
    survives except what reached stable storage, so it only makes sense
    against a durable SUT (a memory-only cluster correctly loses
    everything and the checker correctly goes red).  Exposes write-path
    durability bugs (ack-before-fsync) that no partition can, because a
    partition always leaves a correct in-memory majority running."""

    def __init__(self, procs, nodes: Sequence[str]):
        self.procs = procs
        self.nodes = list(nodes)
        self.down = False

    def setup(self, test: Mapping[str, Any]) -> None:
        pass

    def invoke(self, test: Mapping[str, Any], op: Op) -> Op:
        if op.f == OpF.START:
            for n in self.nodes:
                self.procs.kill(n)
            self.down = True
            logger.info("nemesis: crash-restart killed all of %s", self.nodes)
            return op.complete(OpType.INFO, value=f"crashed {self.nodes}")
        if op.f == OpF.STOP:
            if self.down:
                for n in self.nodes:
                    self.procs.restart(n)
                self.down = False
            logger.info("nemesis: cluster restarted")
            return op.complete(OpType.INFO, value=f"restarted {self.nodes}")
        raise ValueError(f"nemesis got unexpected op {op}")

    def teardown(self, test: Mapping[str, Any]) -> None:
        if self.down:
            for n in self.nodes:
                self.procs.restart(n)
            self.down = False


class ClockSkewNemesis:
    """Bump a random node's wall clock off true on ``start`` (±0.1–3 s,
    seeded); set every bumped clock back on ``stop``.  The
    ``jepsen.nemesis.time`` family.  A correct quorum SUT shrugs: Raft
    timers are monotonic, and TTL timestamps ride inside the replicated
    log, so skew moves *when* a message expires, never *whether* the
    drain can account for it."""

    def __init__(self, clocks, nodes: Sequence[str],
                 seed: int | None = None):
        self.clocks = clocks
        self.nodes = list(nodes)
        self.rng = random.Random(seed)
        self.skewed: list[str] = []

    def setup(self, test: Mapping[str, Any]) -> None:
        pass

    def invoke(self, test: Mapping[str, Any], op: Op) -> Op:
        if op.f == OpF.START:
            victim = self.rng.choice(self.nodes)
            delta = self.rng.choice((-1, 1)) * self.rng.uniform(0.1, 3.0)
            self.clocks.bump(victim, delta)
            self.skewed.append(victim)
            logger.info("nemesis: clock-bump %s %+.0fms", victim,
                        delta * 1000)
            return op.complete(
                OpType.INFO, value=f"clock-bump {victim} {delta * 1000:+.0f}ms"
            )
        if op.f == OpF.STOP:
            reset, self.skewed = self.skewed, []
            for node in reset:
                self.clocks.reset(node)
            logger.info("nemesis: clocks reset %s", reset)
            return op.complete(OpType.INFO, value=f"clocks-reset {reset}")
        raise ValueError(f"nemesis got unexpected op {op}")

    def teardown(self, test: Mapping[str, Any]) -> None:
        for node in self.skewed:
            self.clocks.reset(node)
        self.skewed = []


class MembershipNemesis:
    """Membership churn: on ``start``, SIGKILL a random node and have a
    survivor ``forget_cluster_node`` it (a real RemoveServer commit —
    the cluster genuinely shrinks, e.g. 3→2 with a 2/2 majority); on
    ``stop``, restart the node fresh and ``join_cluster`` it back
    (AddServer + catch-up).  The operator's shrink/grow lifecycle,
    exercised under load — membership change mid-traffic is a classic
    distributed-systems bug surface the static-cluster nemeses never
    touch.  The target is always stopped before it is forgotten, as
    real rabbitmqctl requires (a dead node cannot disrupt elections)."""

    def __init__(self, procs, membership, nodes: Sequence[str],
                 seed: int | None = None):
        self.procs = procs
        self.membership = membership
        self.nodes = list(nodes)
        self.rng = random.Random(seed)
        self.out: str | None = None  # the currently-removed node
        self.forgotten = False

    def setup(self, test: Mapping[str, Any]) -> None:
        pass

    def _survivor(self, not_node: str) -> str:
        return next(n for n in self.nodes if n != not_node)

    def invoke(self, test: Mapping[str, Any], op: Op) -> Op:
        if op.f == OpF.START:
            if self.out is not None:
                return op.complete(
                    OpType.INFO, value=f"still churning {self.out}"
                )
            victim = self.rng.choice(self.nodes)
            self.procs.kill(victim)
            self.forgotten = self.membership.forget(
                self._survivor(victim), victim
            )
            self.out = victim
            what = "removed" if self.forgotten else "killed (forget failed)"
            logger.info("nemesis: membership %s %s", what, victim)
            return op.complete(OpType.INFO, value=f"{what} {victim}")
        if op.f == OpF.STOP:
            if self.out is None:
                return op.complete(OpType.INFO, value="nothing removed")
            node, self.out = self.out, None
            self.procs.restart(node)
            joined = self.membership.join(node, self._survivor(node))
            logger.info("nemesis: membership rejoined %s (join ok=%s)",
                        node, joined)
            return op.complete(
                OpType.INFO,
                value=f"rejoined {node}" if joined
                else f"restarted {node} (join failed)",
            )
        raise ValueError(f"nemesis got unexpected op {op}")

    def teardown(self, test: Mapping[str, Any]) -> None:
        if self.out is not None:
            node, self.out = self.out, None
            self.procs.restart(node)
            self.membership.join(node, self._survivor(node))


class MixedNemesis:
    """``jepsen.nemesis/compose``'s role: one nemesis that interleaves
    several fault families over the run — each ``start`` picks one
    member (seeded RNG) and injects its fault; the paired ``stop`` heals
    that same member.  The reference suite only ever selects a single
    partition strategy per run, but the jepsen *framework* composes
    nemeses, and a soak that mixes partitions with process faults
    stresses recovery paths no single-family run reaches (e.g. a kill
    landing on a cluster still healing from a partition)."""

    def __init__(self, members: Mapping[str, Any], seed: int | None = None):
        if not members:
            raise ValueError("mixed nemesis needs at least one member")
        self.members = dict(members)
        self.rng = random.Random(seed)
        self.active: Any | None = None

    def setup(self, test: Mapping[str, Any]) -> None:
        for m in self.members.values():
            m.setup(test)

    def invoke(self, test: Mapping[str, Any], op: Op) -> Op:
        if op.f == OpF.START:
            import dataclasses

            name = self.rng.choice(sorted(self.members))
            self.active = self.members[name]
            done = self.active.invoke(test, op)
            return dataclasses.replace(done, value=f"{name}: {done.value}")
        if op.f == OpF.STOP:
            if self.active is None:
                return op.complete(OpType.INFO, value="nothing active")
            member, self.active = self.active, None
            return member.invoke(test, op)
        raise ValueError(f"nemesis got unexpected op {op}")

    def teardown(self, test: Mapping[str, Any]) -> None:
        for m in self.members.values():
            m.teardown(test)


NEMESES = (
    "partition", "kill-random-node", "pause-random-node",
    "crash-restart-cluster", "clock-skew", "membership-churn", "mixed",
)


def make_nemesis(opts: Mapping[str, Any], net: Net, procs,
                 nodes: Sequence[str], seed: int | None = None,
                 leader_fn=None, clocks=None, membership=None):
    """Build the nemesis the test opts select: ``partition`` (the
    reference's four strategies via ``network-partition``, plus the
    targeted ``partition-leader``), the process faults
    ``kill-random-node`` / ``pause-random-node``, the whole-cluster
    power failure ``crash-restart-cluster``, ``clock-skew`` (needs a
    ``clocks`` surface), ``membership-churn`` (kill→forget→fresh
    rejoin; needs a ``membership`` surface), or ``mixed`` (the compose
    soak interleaving the families above)."""
    kind = opts.get("nemesis", "partition")
    if kind == "partition":
        return PartitionNemesis(
            opts["network-partition"], net, nodes, seed=seed,
            leader_fn=leader_fn,
        )
    if kind == "kill-random-node":
        return ProcessNemesis("kill", procs, nodes, seed=seed)
    if kind == "pause-random-node":
        return ProcessNemesis("pause", procs, nodes, seed=seed)
    if kind == "crash-restart-cluster":
        from jepsen_tpu.control.net import SimProcs

        if isinstance(procs, SimProcs):
            raise ValueError(
                "crash-restart-cluster needs real per-node durable state "
                "(the sim's state is cluster-global, so a whole-cluster "
                "power failure recovers vacuously — a no-op fault that "
                "would pass the durability test without testing it); "
                "use --db local --durable or --db rabbitmq"
            )
        return CrashRestartNemesis(procs, nodes)
    if kind == "clock-skew":
        if clocks is None:
            raise ValueError(
                "clock-skew needs a clocks surface (the sim models no "
                "wall clocks; use --db local or --db rabbitmq)"
            )
        return ClockSkewNemesis(clocks, nodes, seed=seed)
    if kind == "membership-churn":
        if membership is None:
            raise ValueError(
                "membership-churn needs a membership surface (a "
                "replicated cluster with forget/join — use --db local "
                "multi-node or --db rabbitmq)"
            )
        if len(nodes) < 3:
            raise ValueError(
                "membership-churn needs >=3 nodes (removing one from a "
                "2-node cluster leaves no majority to serve)"
            )
        return MembershipNemesis(procs, membership, nodes, seed=seed)
    if kind == "mixed":
        # the soak composition: partitions + process faults interleaved.
        # crash-restart joins only when the SUT is durable (a memory-only
        # cluster correctly loses everything on a full-cluster crash, so
        # mixing it in would red a bug-free run)
        # derived per-member seeds: reproducible under a pinned --seed
        # WITHOUT lockstep-correlated victim streams (identical seeds
        # would make kill and pause pick the same node sequence)
        sub = (
            None
            if seed is None
            else [seed * 8 + i + 1 for i in range(5)]
        )
        members: dict[str, Any] = {
            "partition": PartitionNemesis(
                opts["network-partition"], net, nodes,
                seed=sub and sub[0], leader_fn=leader_fn,
            ),
            "kill": ProcessNemesis(
                "kill", procs, nodes, seed=sub and sub[1]
            ),
            "pause": ProcessNemesis(
                "pause", procs, nodes, seed=sub and sub[2]
            ),
        }
        if clocks is not None:
            members["clock-skew"] = ClockSkewNemesis(
                clocks, nodes, seed=sub and sub[3]
            )
        if membership is not None and len(nodes) >= 3:
            members["membership"] = MembershipNemesis(
                procs, membership, nodes, seed=sub and sub[4]
            )
        from jepsen_tpu.control.net import SimProcs

        if opts.get("durable") and not isinstance(procs, SimProcs):
            # a sim cluster's state is cluster-global: its crash-restart
            # recovers vacuously, so the member joins only on real procs
            members["crash-restart"] = CrashRestartNemesis(procs, nodes)
        return MixedNemesis(members, seed=seed)
    raise ValueError(f"unknown nemesis {kind!r}; one of {NEMESES}")


class NoopNemesis:
    def setup(self, test):
        pass

    def invoke(self, test, op):
        return op.complete(OpType.INFO, value="noop")

    def teardown(self, test):
        pass
