"""Nemeses: fault injectors driven by ``{:f :start}`` / ``{:f :stop}`` ops.

The four network-partition strategies the reference selects by flag
(``rabbitmq.clj:219-243``), rebuilt over the :class:`~jepsen_tpu.control.net.Net`
interface so one implementation drives both the simulator and real nodes
(iptables over SSH):

- ``partition-random-halves``  — shuffle nodes, cut into two halves
- ``partition-halves``         — first half vs rest, deterministic
- ``partition-majorities-ring``— each node keeps links only to its ring
  neighbors: every node still *sees* a majority, but no two nodes agree on
  which majority (the nastiest case for leader election)
- ``partition-random-node``    — isolate one random node
"""

from __future__ import annotations

import logging
import random
from typing import Any, Callable, Mapping, Sequence

from jepsen_tpu.control.net import Net, complete_grudges
from jepsen_tpu.history.ops import Op, OpF, OpType

logger = logging.getLogger("jepsen_tpu.nemesis")


def random_halves(nodes: Sequence[str], rng: random.Random):
    ns = list(nodes)
    rng.shuffle(ns)
    mid = (len(ns) + 1) // 2
    return complete_grudges([ns[:mid], ns[mid:]])


def halves(nodes: Sequence[str], rng: random.Random):
    mid = (len(nodes) + 1) // 2
    return complete_grudges([nodes[:mid], nodes[mid:]])


def majorities_ring(nodes: Sequence[str], rng: random.Random):
    """Node i keeps links only to its nearest ring neighbors (enough that
    its local view is a majority); everything further is cut.  With ≤3
    nodes every pair is ring-adjacent, so no link is cut — the interesting
    regime (as in the reference's CI) is 5 nodes, where each node sees a
    different 3-node majority."""
    ns = list(nodes)
    rng.shuffle(ns)
    n = len(ns)
    keep = (n // 2 + 1) // 2  # ring neighbors kept per side
    grudges: dict[str, set[str]] = {m: set() for m in ns}
    for i, a in enumerate(ns):
        for j, b in enumerate(ns):
            if i == j:
                continue
            dist = min((i - j) % n, (j - i) % n)
            if dist > keep:
                grudges[a].add(b)
    return grudges


def random_node(nodes: Sequence[str], rng: random.Random):
    lone = rng.choice(list(nodes))
    rest = [m for m in nodes if m != lone]
    return complete_grudges([[lone], rest])


def one_way_in(nodes: Sequence[str], rng: random.Random):
    """Asymmetric: a random victim hears NOBODY (drops all input) while
    everyone still hears it — its requests go out, every reply dies.  A
    leader hit this way keeps suppressing elections with heartbeats the
    followers receive, while it can never commit (no acks arrive): the
    failure detector, not the election, has to notice."""
    victim = rng.choice(list(nodes))
    return {victim: {m for m in nodes if m != victim}}


def one_way_out(nodes: Sequence[str], rng: random.Random):
    """Asymmetric: NOBODY hears a random victim (everyone drops input
    from it) while the victim still hears everyone.  A leader hit this
    way sees the cluster move on without it — a new election it can
    observe but not veto — and must truncate any unreplicated tail when
    the new leader's appends arrive (the confirm-before-quorum seeded
    bug's loss window, reachable without ever cutting a full link)."""
    victim = rng.choice(list(nodes))
    return {m: {victim} for m in nodes if m != victim}


STRATEGIES: dict[str, Callable] = {
    "partition-random-halves": random_halves,
    # the reference's OWN spelling for the same strategy
    # (rabbitmq.clj:221 "random-partition-halves", used 5x in
    # ci/jepsen-test.sh:93-107) — both are first-class so a pasted
    # reference command line parses verbatim (VERDICT r3 missing #3)
    "random-partition-halves": random_halves,
    "partition-halves": halves,
    "partition-majorities-ring": majorities_ring,
    "partition-random-node": random_node,
    "partition-one-way-in": one_way_in,
    "partition-one-way-out": one_way_out,
}

#: strategies whose grudges are deliberately DIRECTED: they need a net
#: that honors grudge direction (iptables INPUT-drop per node — the
#: replicated local cluster and real SSH nets).  On a net that would
#: symmetrize (the simulator's link model) they are refused: silently
#: running the two-way version would attach this schedule's name to a
#: different fault.
ASYMMETRIC_STRATEGIES = frozenset(
    {"partition-one-way-in", "partition-one-way-out"}
)

#: targeted strategy (beyond the reference's four): isolate the CURRENT
#: consensus leader — jepsen's own nemesis library grew leader-targeting
#: partitioners because random victims rarely hit the interesting window
#: (a leader's uncommitted tail).  Requires a ``leader_fn`` (the local
#: process cluster answers via its nodes' admin ROLE query); falls back
#: to a random victim when no leader is discoverable.
PARTITION_LEADER = "partition-leader"


class PartitionNemesis:
    """Applies a partition strategy on ``start``, heals on ``stop``."""

    def __init__(self, strategy: str, net: Net, nodes: Sequence[str],
                 seed: int | None = None,
                 leader_fn: Callable[[], str | None] | None = None):
        if strategy not in STRATEGIES and strategy != PARTITION_LEADER:
            raise ValueError(
                f"unknown partition {strategy!r}; one of "
                f"{sorted([*STRATEGIES, PARTITION_LEADER])}"
            )
        if strategy == PARTITION_LEADER and leader_fn is None:
            raise ValueError(
                "partition-leader needs a leader-discovery hook; this "
                "cluster's transport does not provide one"
            )
        if strategy in ASYMMETRIC_STRATEGIES and not getattr(
            net, "one_way", False
        ):
            raise ValueError(
                f"{strategy} is a one-way partition and this net "
                f"({type(net).__name__}) symmetrizes grudges — running "
                f"it two-way would be a different fault; use a "
                f"direction-honoring net (--db local / rabbitmq)"
            )
        self.strategy = strategy
        self.net = net
        self.nodes = list(nodes)
        self.rng = random.Random(seed)
        self.leader_fn = leader_fn

    def setup(self, test: Mapping[str, Any]) -> None:
        self.net.heal()

    def _grudges(self):
        if self.strategy == PARTITION_LEADER:
            victim = None
            try:
                victim = self.leader_fn()
            except Exception:  # noqa: BLE001 - discovery is best-effort
                pass
            if victim is None or victim not in self.nodes:
                victim = self.rng.choice(self.nodes)
                logger.info(
                    "nemesis: no discoverable leader; isolating %s", victim
                )
            rest = [m for m in self.nodes if m != victim]
            return complete_grudges([[victim], rest])
        return STRATEGIES[self.strategy](self.nodes, self.rng)

    def invoke(self, test: Mapping[str, Any], op: Op) -> Op:
        if op.f == OpF.START:
            grudges = self._grudges()
            self.net.partition(grudges)
            desc = {a: sorted(bs) for a, bs in grudges.items() if bs}
            logger.info("nemesis: cut links %s", desc)
            return op.complete(OpType.INFO, value=str(desc))
        if op.f == OpF.STOP:
            self.net.heal()
            logger.info("nemesis: healed")
            return op.complete(OpType.INFO, value="healed")
        raise ValueError(f"nemesis got unexpected op {op}")

    def teardown(self, test: Mapping[str, Any]) -> None:
        self.net.heal()


class ProcessNemesis:
    """Kill or pause a random node's DB process on ``start``; restart or
    resume every victim on ``stop``.  Jepsen's classic process nemeses,
    beyond the reference's partition-only set: a SIGKILLed node tests
    durable-state recovery and Raft re-join, a SIGSTOPped one tests the
    failure detector (the process holds its sockets but goes silent —
    exactly what ``net_ticktime``/aten tuning is about)."""

    def __init__(self, mode: str, procs, nodes: Sequence[str],
                 seed: int | None = None):
        if mode not in ("kill", "pause"):
            raise ValueError(f"unknown process-nemesis mode {mode!r}")
        self.mode = mode
        self.procs = procs
        self.nodes = list(nodes)
        self.rng = random.Random(seed)
        self.victims: list[str] = []

    def setup(self, test: Mapping[str, Any]) -> None:
        pass

    def invoke(self, test: Mapping[str, Any], op: Op) -> Op:
        if op.f == OpF.START:
            # pick among nodes still up: consecutive starts must inject a
            # new fault, and the history must never claim "kill n" for a
            # node that was already down
            up = [n for n in self.nodes if n not in self.victims]
            if not up:
                logger.info("nemesis: all nodes already %sed", self.mode)
                return op.complete(
                    OpType.INFO, value=f"already-down {self.victims}"
                )
            victim = self.rng.choice(up)
            (self.procs.kill if self.mode == "kill"
             else self.procs.pause)(victim)
            self.victims.append(victim)
            logger.info("nemesis: %s %s", self.mode, victim)
            return op.complete(OpType.INFO, value=f"{self.mode} {victim}")
        if op.f == OpF.STOP:
            restored, self.victims = self.victims, []
            for v in restored:
                (self.procs.restart if self.mode == "kill"
                 else self.procs.resume)(v)
            logger.info("nemesis: restored %s", restored)
            return op.complete(OpType.INFO, value=f"restored {restored}")
        raise ValueError(f"nemesis got unexpected op {op}")

    def teardown(self, test: Mapping[str, Any]) -> None:
        for v in self.victims:
            (self.procs.restart if self.mode == "kill"
             else self.procs.resume)(v)
        self.victims = []


class CrashRestartNemesis:
    """Power failure: SIGKILL **every** node on ``start``, restart them
    all on ``stop``.  The strictest durability test there is — nothing
    survives except what reached stable storage, so it only makes sense
    against a durable SUT (a memory-only cluster correctly loses
    everything and the checker correctly goes red).  Exposes write-path
    durability bugs (ack-before-fsync) that no partition can, because a
    partition always leaves a correct in-memory majority running."""

    def __init__(self, procs, nodes: Sequence[str]):
        self.procs = procs
        self.nodes = list(nodes)
        self.down = False

    def setup(self, test: Mapping[str, Any]) -> None:
        pass

    def invoke(self, test: Mapping[str, Any], op: Op) -> Op:
        if op.f == OpF.START:
            for n in self.nodes:
                self.procs.kill(n)
            self.down = True
            logger.info("nemesis: crash-restart killed all of %s", self.nodes)
            return op.complete(OpType.INFO, value=f"crashed {self.nodes}")
        if op.f == OpF.STOP:
            if self.down:
                for n in self.nodes:
                    self.procs.restart(n)
                self.down = False
            logger.info("nemesis: cluster restarted")
            return op.complete(OpType.INFO, value=f"restarted {self.nodes}")
        raise ValueError(f"nemesis got unexpected op {op}")

    def teardown(self, test: Mapping[str, Any]) -> None:
        if self.down:
            for n in self.nodes:
                self.procs.restart(n)
            self.down = False


class ClockSkewNemesis:
    """Bump a random node's wall clock off true on ``start`` (±0.1–3 s,
    seeded); set every bumped clock back on ``stop``.  The
    ``jepsen.nemesis.time`` family.  A correct quorum SUT shrugs: Raft
    timers are monotonic, and TTL timestamps ride inside the replicated
    log, so skew moves *when* a message expires, never *whether* the
    drain can account for it."""

    def __init__(self, clocks, nodes: Sequence[str],
                 seed: int | None = None):
        self.clocks = clocks
        self.nodes = list(nodes)
        self.rng = random.Random(seed)
        self.skewed: list[str] = []

    def setup(self, test: Mapping[str, Any]) -> None:
        pass

    def invoke(self, test: Mapping[str, Any], op: Op) -> Op:
        if op.f == OpF.START:
            victim = self.rng.choice(self.nodes)
            delta = self.rng.choice((-1, 1)) * self.rng.uniform(0.1, 3.0)
            self.clocks.bump(victim, delta)
            self.skewed.append(victim)
            logger.info("nemesis: clock-bump %s %+.0fms", victim,
                        delta * 1000)
            return op.complete(
                OpType.INFO, value=f"clock-bump {victim} {delta * 1000:+.0f}ms"
            )
        if op.f == OpF.STOP:
            reset, self.skewed = self.skewed, []
            for node in reset:
                self.clocks.reset(node)
            logger.info("nemesis: clocks reset %s", reset)
            return op.complete(OpType.INFO, value=f"clocks-reset {reset}")
        raise ValueError(f"nemesis got unexpected op {op}")

    def teardown(self, test: Mapping[str, Any]) -> None:
        for node in self.skewed:
            self.clocks.reset(node)
        self.skewed = []


class MembershipNemesis:
    """Membership churn: on ``start``, SIGKILL a random node and have a
    survivor ``forget_cluster_node`` it (a real RemoveServer commit —
    the cluster genuinely shrinks, e.g. 3→2 with a 2/2 majority); on
    ``stop``, restart the node fresh and ``join_cluster`` it back
    (AddServer + catch-up).  The operator's shrink/grow lifecycle,
    exercised under load — membership change mid-traffic is a classic
    distributed-systems bug surface the static-cluster nemeses never
    touch.  The target is always stopped before it is forgotten, as
    real rabbitmqctl requires (a dead node cannot disrupt elections)."""

    def __init__(self, procs, membership, nodes: Sequence[str],
                 seed: int | None = None):
        self.procs = procs
        self.membership = membership
        self.nodes = list(nodes)
        self.rng = random.Random(seed)
        self.out: str | None = None  # the currently-removed node
        self.forgotten = False

    def setup(self, test: Mapping[str, Any]) -> None:
        pass

    def _survivor(self, not_node: str) -> str:
        return next(n for n in self.nodes if n != not_node)

    def invoke(self, test: Mapping[str, Any], op: Op) -> Op:
        if op.f == OpF.START:
            if self.out is not None:
                return op.complete(
                    OpType.INFO, value=f"still churning {self.out}"
                )
            victim = self.rng.choice(self.nodes)
            self.procs.kill(victim)
            self.forgotten = self.membership.forget(
                self._survivor(victim), victim
            )
            self.out = victim
            what = "removed" if self.forgotten else "killed (forget failed)"
            logger.info("nemesis: membership %s %s", what, victim)
            return op.complete(OpType.INFO, value=f"{what} {victim}")
        if op.f == OpF.STOP:
            if self.out is None:
                return op.complete(OpType.INFO, value="nothing removed")
            node, self.out = self.out, None
            self.procs.restart(node)
            joined = self.membership.join(node, self._survivor(node))
            logger.info("nemesis: membership rejoined %s (join ok=%s)",
                        node, joined)
            return op.complete(
                OpType.INFO,
                value=f"rejoined {node}" if joined
                else f"restarted {node} (join failed)",
            )
        raise ValueError(f"nemesis got unexpected op {op}")

    def teardown(self, test: Mapping[str, Any]) -> None:
        if self.out is not None:
            node, self.out = self.out, None
            self.procs.restart(node)
            self.membership.join(node, self._survivor(node))


class SlowDiskNemesis:
    """Slow-disk / fsync-latency injection (fsyncgate-adjacent, distinct
    from fail-stop): on ``start``, a random node's WAL device begins
    taking mean±jitter ms per fsync; on ``stop`` every slowed disk is
    restored.  A correct durable SUT under a slow disk confirms slower —
    possibly timing out into indeterminate ops, which is always safe —
    and loses nothing; the node that stays FAST under this nemesis is
    the one lying about fsync (``ack-before-fsync``), which is exactly
    the red/green pair's tell."""

    def __init__(self, disks, nodes: Sequence[str],
                 seed: int | None = None,
                 mean_ms: float = 120.0, jitter_ms: float = 80.0):
        if mean_ms <= 0.0 and jitter_ms <= 0.0:
            raise ValueError(
                "slow-disk with zero latency is a no-fault no-op"
            )
        self.disks = disks
        self.nodes = list(nodes)
        self.rng = random.Random(seed)
        self.mean_ms = mean_ms
        self.jitter_ms = jitter_ms
        self.slowed: list[str] = []

    def setup(self, test: Mapping[str, Any]) -> None:
        pass

    def invoke(self, test: Mapping[str, Any], op: Op) -> Op:
        if op.f == OpF.START:
            victim = self.rng.choice(self.nodes)
            self.disks.slow(victim, self.mean_ms, self.jitter_ms)
            if victim not in self.slowed:
                self.slowed.append(victim)
            logger.info(
                "nemesis: slow-disk %s (%g±%gms/fsync)",
                victim, self.mean_ms, self.jitter_ms,
            )
            return op.complete(
                OpType.INFO,
                value=f"slow-disk {victim} {self.mean_ms:g}ms",
            )
        if op.f == OpF.STOP:
            restored, self.slowed = self.slowed, []
            for v in restored:
                self.disks.reset(v)
            logger.info("nemesis: disks restored %s", restored)
            return op.complete(OpType.INFO, value=f"disks-ok {restored}")
        raise ValueError(f"nemesis got unexpected op {op}")

    def teardown(self, test: Mapping[str, Any]) -> None:
        for v in self.slowed:
            try:
                self.disks.reset(v)
            except Exception:  # noqa: BLE001 — node may be gone at teardown
                pass
        self.slowed = []


class WireChaosNemesis:
    """Wire-layer corruption/duplication/reordering between broker
    peers (netem's fault family): on ``start``, a random node's outgoing
    peer frames begin taking the configured fault rates; on ``stop``
    every chaotic wire is calmed.  A correct SUT's transport DROPS
    corrupted frames on checksum (corruption degrades to retried loss)
    and shrugs off duplicated/reordered protocol frames by idempotency;
    the ``no-wire-checksum`` seeded bug processes mangled frames instead
    and the replicas diverge — the checker must surface the resulting
    phantom/lost values."""

    def __init__(self, wire, nodes: Sequence[str],
                 seed: int | None = None,
                 corrupt_p: float = 0.25, duplicate_p: float = 0.15,
                 delay_p: float = 0.15, delay_ms: float = 40.0):
        if max(corrupt_p, duplicate_p, delay_p) <= 0.0:
            raise ValueError(
                "wire-chaos with all rates zero is a no-fault no-op"
            )
        for name, p in (("corrupt", corrupt_p),
                        ("duplicate", duplicate_p), ("delay", delay_p)):
            if not 0.0 <= p <= 1.0:
                raise ValueError(
                    f"wire-chaos {name} rate {p} outside [0, 1]"
                )
        if delay_p > 0.0 and delay_ms <= 0.0:
            raise ValueError(
                "wire-chaos delay rate without delay_ms is a no-op"
            )
        self.wire = wire
        self.nodes = list(nodes)
        self.rng = random.Random(seed)
        self.spec = (corrupt_p, duplicate_p, delay_p, delay_ms)
        self.chaotic: list[str] = []

    def setup(self, test: Mapping[str, Any]) -> None:
        pass

    def invoke(self, test: Mapping[str, Any], op: Op) -> Op:
        if op.f == OpF.START:
            victim = self.rng.choice(self.nodes)
            self.wire.chaos(victim, *self.spec)
            if victim not in self.chaotic:
                self.chaotic.append(victim)
            logger.info(
                "nemesis: wire-chaos %s (corrupt=%g dup=%g delay=%g@%gms)",
                victim, *self.spec,
            )
            return op.complete(
                OpType.INFO, value=f"wire-chaos {victim}"
            )
        if op.f == OpF.STOP:
            calmed, self.chaotic = self.chaotic, []
            for v in calmed:
                self.wire.calm(v)
            logger.info("nemesis: wires calmed %s", calmed)
            return op.complete(OpType.INFO, value=f"wires-ok {calmed}")
        raise ValueError(f"nemesis got unexpected op {op}")

    def teardown(self, test: Mapping[str, Any]) -> None:
        for v in self.chaotic:
            try:
                self.wire.calm(v)
            except Exception:  # noqa: BLE001 — node may be gone at teardown
                pass
        self.chaotic = []


class MixedNemesis:
    """``jepsen.nemesis/compose``'s role: one nemesis that interleaves
    several fault families over the run — each ``start`` picks one
    member (seeded RNG) and injects its fault; the paired ``stop`` heals
    that same member.  The reference suite only ever selects a single
    partition strategy per run, but the jepsen *framework* composes
    nemeses, and a soak that mixes partitions with process faults
    stresses recovery paths no single-family run reaches (e.g. a kill
    landing on a cluster still healing from a partition)."""

    def __init__(self, members: Mapping[str, Any], seed: int | None = None):
        if not members:
            raise ValueError("mixed nemesis needs at least one member")
        self.members = dict(members)
        self.rng = random.Random(seed)
        self.active: Any | None = None

    def setup(self, test: Mapping[str, Any]) -> None:
        for m in self.members.values():
            m.setup(test)

    def invoke(self, test: Mapping[str, Any], op: Op) -> Op:
        if op.f == OpF.START:
            import dataclasses

            name = self.rng.choice(sorted(self.members))
            self.active = self.members[name]
            done = self.active.invoke(test, op)
            return dataclasses.replace(done, value=f"{name}: {done.value}")
        if op.f == OpF.STOP:
            if self.active is None:
                return op.complete(OpType.INFO, value="nothing active")
            member, self.active = self.active, None
            return member.invoke(test, op)
        raise ValueError(f"nemesis got unexpected op {op}")

    def teardown(self, test: Mapping[str, Any]) -> None:
        for m in self.members.values():
            m.teardown(test)


NEMESES = (
    "partition", "kill-random-node", "pause-random-node",
    "crash-restart-cluster", "clock-skew", "membership-churn",
    "slow-disk", "wire-chaos", "mixed",
)

#: the nemesis-shaped option keys ``make_nemesis`` consumes.  Anything
#: ELSE in the fault namespaces (``wire-*``, ``slow-disk-*``) is
#: rejected loudly: a typo'd tunable must not run the schedule with the
#: default it meant to change (the silent-no-op class).
_NEMESIS_OPT_KEYS = frozenset({
    "nemesis", "network-partition", "mixed-extended",
    "nemesis-schedule",  # dedicated rejection below (fuzz-runner-only)
    "slow-disk-mean-ms", "slow-disk-jitter-ms",
    "wire-corrupt", "wire-duplicate", "wire-delay", "wire-delay-ms",
})


def _validate_nemesis_opts(opts: Mapping[str, Any], kind: str) -> None:
    unknown = sorted(
        k for k in opts
        if (k.startswith("wire-") or k.startswith("slow-disk-")
            or k.startswith("nemesis-"))
        and k not in _NEMESIS_OPT_KEYS
    )
    if unknown:
        raise ValueError(
            f"unknown nemesis option(s) {unknown}; known fault tunables: "
            f"{sorted(k for k in _NEMESIS_OPT_KEYS if k != 'nemesis')}"
        )
    if opts.get("nemesis-schedule") is not None:
        raise ValueError(
            "nemesis-schedule (an explicit event timeline) requires the "
            "scheduled nemesis — build the test with the fuzz runner's "
            "nemesis_factory; the uniform-cycle nemeses here would pair "
            "the schedule's start/stop ops with the wrong faults"
        )
    if kind in ("partition", "mixed") and not opts.get("network-partition"):
        raise ValueError(
            f"nemesis {kind!r} needs a partition strategy "
            f"(network-partition); one of {sorted(STRATEGIES)}"
        )


def _slow_disk_params(opts: Mapping[str, Any]) -> tuple[float, float]:
    return (
        float(opts.get("slow-disk-mean-ms", 120.0)),
        float(opts.get("slow-disk-jitter-ms", 80.0)),
    )


def _wire_params(opts: Mapping[str, Any]) -> dict[str, float]:
    return {
        "corrupt_p": float(opts.get("wire-corrupt", 0.25)),
        "duplicate_p": float(opts.get("wire-duplicate", 0.15)),
        "delay_p": float(opts.get("wire-delay", 0.15)),
        "delay_ms": float(opts.get("wire-delay-ms", 40.0)),
    }


def make_nemesis(opts: Mapping[str, Any], net: Net, procs,
                 nodes: Sequence[str], seed: int | None = None,
                 leader_fn=None, clocks=None, membership=None,
                 disks=None, wire=None):
    """Build the nemesis the test opts select: ``partition`` (the
    reference's four strategies via ``network-partition``, the one-way
    asymmetric pair, plus the targeted ``partition-leader``), the
    process faults ``kill-random-node`` / ``pause-random-node``, the
    whole-cluster power failure ``crash-restart-cluster``,
    ``clock-skew`` (needs a ``clocks`` surface), ``membership-churn``
    (kill→forget→fresh rejoin; needs a ``membership`` surface),
    ``slow-disk`` (fsync latency on the WAL; needs a ``disks`` surface
    — durable clusters only), ``wire-chaos`` (frame corruption/
    duplication/reordering between peers; needs a ``wire`` surface), or
    ``mixed`` (the compose soak interleaving the families above; the
    ``mixed-extended`` opt adds the two new families to the draw).

    Unknown nemesis kinds and unknown/contradictory fault tunables
    raise — a schedule must never silently run without the fault (or
    with a different fault than) its name claims."""
    kind = opts.get("nemesis", "partition")
    if kind not in NEMESES:
        raise ValueError(f"unknown nemesis {kind!r}; one of {NEMESES}")
    _validate_nemesis_opts(opts, kind)
    if kind == "partition":
        return PartitionNemesis(
            opts["network-partition"], net, nodes, seed=seed,
            leader_fn=leader_fn,
        )
    if kind == "kill-random-node":
        return ProcessNemesis("kill", procs, nodes, seed=seed)
    if kind == "pause-random-node":
        return ProcessNemesis("pause", procs, nodes, seed=seed)
    if kind == "crash-restart-cluster":
        from jepsen_tpu.control.net import SimProcs

        if isinstance(procs, SimProcs):
            raise ValueError(
                "crash-restart-cluster needs real per-node durable state "
                "(the sim's state is cluster-global, so a whole-cluster "
                "power failure recovers vacuously — a no-op fault that "
                "would pass the durability test without testing it); "
                "use --db local --durable or --db rabbitmq"
            )
        return CrashRestartNemesis(procs, nodes)
    if kind == "clock-skew":
        if clocks is None:
            raise ValueError(
                "clock-skew needs a clocks surface (the sim models no "
                "wall clocks; use --db local or --db rabbitmq)"
            )
        return ClockSkewNemesis(clocks, nodes, seed=seed)
    if kind == "membership-churn":
        if membership is None:
            raise ValueError(
                "membership-churn needs a membership surface (a "
                "replicated cluster with forget/join — use --db local "
                "multi-node or --db rabbitmq)"
            )
        if len(nodes) < 3:
            raise ValueError(
                "membership-churn needs >=3 nodes (removing one from a "
                "2-node cluster leaves no majority to serve)"
            )
        return MembershipNemesis(procs, membership, nodes, seed=seed)
    if kind == "slow-disk":
        if disks is None:
            raise ValueError(
                "slow-disk needs a disks surface (a durable replicated "
                "cluster whose WAL the delay can reach — use --db local "
                "--durable or --db rabbitmq)"
            )
        if not opts.get("durable"):
            raise ValueError(
                "slow-disk needs durable=True: a memory-only cluster "
                "has no fsync to slow, so the 'fault' would be a no-op "
                "and any green verdict a false one"
            )
        mean, jitter = _slow_disk_params(opts)
        return SlowDiskNemesis(
            disks, nodes, seed=seed, mean_ms=mean, jitter_ms=jitter
        )
    if kind == "wire-chaos":
        if wire is None:
            raise ValueError(
                "wire-chaos needs a wire surface (a replicated cluster "
                "whose peer RPC frames the faults can reach — use "
                "--db local or --db rabbitmq)"
            )
        return WireChaosNemesis(
            wire, nodes, seed=seed, **_wire_params(opts)
        )
    if kind == "mixed":
        # the soak composition: partitions + process faults interleaved.
        # crash-restart joins only when the SUT is durable (a memory-only
        # cluster correctly loses everything on a full-cluster crash, so
        # mixing it in would red a bug-free run)
        # derived per-member seeds: reproducible under a pinned --seed
        # WITHOUT lockstep-correlated victim streams (identical seeds
        # would make kill and pause pick the same node sequence)
        sub = (
            None
            if seed is None
            else [seed * 8 + i + 1 for i in range(8)]
        )
        members: dict[str, Any] = {
            "partition": PartitionNemesis(
                opts["network-partition"], net, nodes,
                seed=sub and sub[0], leader_fn=leader_fn,
            ),
            "kill": ProcessNemesis(
                "kill", procs, nodes, seed=sub and sub[1]
            ),
            "pause": ProcessNemesis(
                "pause", procs, nodes, seed=sub and sub[2]
            ),
        }
        if clocks is not None:
            members["clock-skew"] = ClockSkewNemesis(
                clocks, nodes, seed=sub and sub[3]
            )
        if membership is not None and len(nodes) >= 3:
            members["membership"] = MembershipNemesis(
                procs, membership, nodes, seed=sub and sub[4]
            )
        if opts.get("mixed-extended"):
            # the two new families join the draw only on request: the
            # default mixed schedule stays comparable with the committed
            # soak evidence (same members, same seeded family sequence)
            if disks is not None and opts.get("durable"):
                mean, jitter = _slow_disk_params(opts)
                members["slow-disk"] = SlowDiskNemesis(
                    disks, nodes, seed=sub and sub[5],
                    mean_ms=mean, jitter_ms=jitter,
                )
            if wire is not None:
                members["wire-chaos"] = WireChaosNemesis(
                    wire, nodes, seed=sub and sub[6], **_wire_params(opts)
                )
        from jepsen_tpu.control.net import SimProcs

        if opts.get("durable") and not isinstance(procs, SimProcs):
            # a sim cluster's state is cluster-global: its crash-restart
            # recovers vacuously, so the member joins only on real procs
            members["crash-restart"] = CrashRestartNemesis(procs, nodes)
        return MixedNemesis(members, seed=seed)
    raise ValueError(f"unknown nemesis {kind!r}; one of {NEMESES}")


class NoopNemesis:
    def setup(self, test):
        pass

    def invoke(self, test, op):
        return op.complete(OpType.INFO, value="noop")

    def teardown(self, test):
        pass
