"""SSH control plane: remote execution DSL.

Equivalent of ``jepsen.control`` (+ ``.util``) as the reference's DB and
nemesis layers use it (``rabbitmq.clj:32-141``): an exec DSL with ``su``
semantics, plus the helpers ``wget!``, ``install-archive!``, ``exists?``
and config-file upload with ``$VAR`` substitution (``rabbitmq.clj:48-72``).

Transports:

- :class:`SshTransport` — drives the system ``ssh``/``scp`` binaries (no
  extra Python deps in the image), BatchMode, host-key checking off, and a
  persistent ControlMaster socket per node so each command doesn't pay a
  new handshake.
- :class:`FakeTransport` — records the command stream and replays scripted
  outputs; the unit-test double for DB/nemesis choreography (the reference
  has no equivalent — its control logic is only tested against live
  clusters).
"""

from __future__ import annotations

import abc
import shlex
import subprocess
import threading
from dataclasses import dataclass, field
from pathlib import Path
from string import Template
from typing import Any, Mapping, Sequence


class RemoteError(RuntimeError):
    def __init__(self, node: str, cmd: str, rc: int, out: str, err: str):
        super().__init__(
            f"[{node}] `{cmd}` exited {rc}\nstdout: {out[-500:]}\n"
            f"stderr: {err[-500:]}"
        )
        self.node, self.cmd, self.rc, self.out, self.err = node, cmd, rc, out, err


@dataclass
class RunResult:
    rc: int
    out: str
    err: str


class Transport(abc.ABC):
    @abc.abstractmethod
    def run(self, node: str, cmd: str, timeout: float | None = None) -> RunResult:
        """Run a shell command string on ``node``."""

    @abc.abstractmethod
    def put(self, node: str, content: bytes, remote_path: str) -> None:
        """Write ``content`` to ``remote_path`` on ``node``."""

    def get(self, node: str, remote_path: str, local_path: str | Path) -> bool:
        """Stream ``remote_path`` from ``node`` into ``local_path`` (binary-
        safe).  Returns False if the file is absent/unreadable."""
        return False

    def close(self) -> None: ...


class SshTransport(Transport):
    def __init__(
        self,
        user: str = "root",
        private_key: str | None = None,
        port: int = 22,
        connect_timeout: int = 10,
        control_persist: bool = True,
    ):
        self.user = user
        self.private_key = private_key
        self.port = port
        self.connect_timeout = connect_timeout
        self.control_persist = control_persist

    def _ssh_args(self, node: str) -> list[str]:
        args = [
            "ssh",
            "-o", "BatchMode=yes",
            "-o", "StrictHostKeyChecking=no",
            "-o", "UserKnownHostsFile=/dev/null",
            "-o", "LogLevel=ERROR",
            "-o", f"ConnectTimeout={self.connect_timeout}",
            "-p", str(self.port),
        ]
        if self.control_persist:
            args += [
                "-o", "ControlMaster=auto",
                "-o", f"ControlPath=/tmp/jepsen-tpu-ssh-{self.user}-%h-%p",
                "-o", "ControlPersist=60",
            ]
        if self.private_key:
            args += ["-i", self.private_key]
        args.append(f"{self.user}@{node}")
        return args

    def run(self, node, cmd, timeout=None):
        try:
            p = subprocess.run(
                self._ssh_args(node) + [cmd],
                capture_output=True,
                text=True,
                timeout=timeout or 300,
            )
        except subprocess.TimeoutExpired as e:
            # callers treat RemoteError as the sole failure envelope; a hung
            # remote command (e.g. rabbitmqctl across a partition) must not
            # crash teardown/log-collection with an unexpected exception type
            raise RemoteError(
                node, cmd, -1, "", f"timed out after {e.timeout}s"
            ) from e
        return RunResult(p.returncode, p.stdout, p.stderr)

    def put(self, node, content, remote_path):
        try:
            p = subprocess.run(
                self._ssh_args(node)
                + [f"cat > {shlex.quote(remote_path)}"],
                input=content,
                capture_output=True,
                timeout=60,
            )
        except subprocess.TimeoutExpired as e:
            raise RemoteError(
                node, f"put {remote_path}", -1, "", f"timed out after {e.timeout}s"
            ) from e
        if p.returncode != 0:
            raise RemoteError(
                node, f"put {remote_path}", p.returncode, "", p.stderr.decode()
            )

    def get(self, node, remote_path, local_path):
        # binary-safe streaming straight to disk (broker logs can be large
        # at debug level and may contain non-UTF-8 bytes)
        try:
            with open(local_path, "wb") as fh:
                p = subprocess.run(
                    self._ssh_args(node) + [f"cat {shlex.quote(remote_path)}"],
                    stdout=fh,
                    stderr=subprocess.DEVNULL,
                    timeout=300,
                )
        except subprocess.TimeoutExpired:
            Path(local_path).unlink(missing_ok=True)
            return False
        if p.returncode != 0:
            Path(local_path).unlink(missing_ok=True)
            return False
        return True


@dataclass
class FakeTransport(Transport):
    """Scripted transport for choreography tests: ``responses`` maps a
    substring of the command to its scripted result; everything else
    succeeds with empty output.  All calls are recorded in ``log``."""

    responses: dict[str, RunResult] = field(default_factory=dict)
    log: list[tuple[str, str]] = field(default_factory=list)
    files: dict[tuple[str, str], bytes] = field(default_factory=dict)
    lock: threading.Lock = field(default_factory=threading.Lock)

    def run(self, node, cmd, timeout=None):
        with self.lock:
            self.log.append((node, cmd))
        for key, result in self.responses.items():
            if key in cmd:
                return result
        return RunResult(0, "", "")

    def put(self, node, content, remote_path):
        with self.lock:
            self.log.append((node, f"PUT {remote_path}"))
            self.files[(node, remote_path)] = content

    def get(self, node, remote_path, local_path):
        with self.lock:
            self.log.append((node, f"GET {remote_path}"))
            content = self.files.get((node, remote_path))
        if content is None:
            return False
        Path(local_path).write_bytes(content)
        return True

    def commands(self, node: str | None = None) -> list[str]:
        with self.lock:
            return [c for n, c in self.log if node is None or n == node]


class Control:
    """The per-node exec DSL (``c/exec``, ``c/su``, ``wget!`` …)."""

    def __init__(self, transport: Transport, node: str, sudo: bool = False):
        self.transport = transport
        self.node = node
        self.sudo = sudo

    def su(self) -> "Control":
        return Control(self.transport, self.node, sudo=True)

    def exec(
        self,
        *argv: Any,
        check: bool = True,
        timeout: float | None = None,
        shell: str | None = None,
    ) -> str:
        """Run a command (args are shell-quoted) or a raw ``shell`` string;
        returns trimmed stdout, raising :class:`RemoteError` on failure."""
        cmd = shell if shell is not None else " ".join(
            shlex.quote(str(a)) for a in argv
        )
        if self.sudo:
            cmd = f"sudo sh -c {shlex.quote(cmd)}"
        r = self.transport.run(self.node, cmd, timeout=timeout)
        if check and r.rc != 0:
            raise RemoteError(self.node, cmd, r.rc, r.out, r.err)
        return r.out.strip()

    def exists(self, path: str) -> bool:
        # goes through exec() so su() privileges apply
        try:
            self.exec("test", "-e", path)
            return True
        except RemoteError:
            return False

    def wget(self, url: str, dest_dir: str = "/tmp") -> str:
        """Download ``url`` into ``dest_dir`` unless present; returns the
        local path (= ``cu/wget!``)."""
        name = url.rstrip("/").rsplit("/", 1)[-1]
        dest = f"{dest_dir}/{name}"
        if not self.exists(dest):
            # download to a temp name and mv into place on success only —
            # `wget -O dest` creates dest even on failure, which would
            # poison the existence-based cache for every retry
            tmp = f"{dest}.part"
            try:
                self.exec("wget", "-q", "-O", tmp, url, timeout=600)
            except RemoteError:
                self.exec("rm", "-f", tmp, check=False)
                raise
            self.exec("mv", tmp, dest)
        return dest

    def install_archive(self, url: str, dest: str) -> None:
        """Download + unpack a tarball into ``dest`` with the leading path
        component stripped (= ``cu/install-archive!``)."""
        archive = self.wget(url)
        self.exec("rm", "-rf", dest)
        self.exec("mkdir", "-p", dest)
        self.exec(
            "tar", "xf", archive, "-C", dest, "--strip-components=1",
            timeout=300,
        )

    def write_file(
        self,
        content: str,
        remote_path: str,
        substitutions: Mapping[str, Any] | None = None,
    ) -> None:
        """Upload a config file, applying ``$VAR`` template substitution
        (the reference's pattern at ``rabbitmq.clj:48-52,64-72``).  Under
        ``su()`` the upload lands in /tmp first and is moved with sudo, so
        root-owned destinations work for non-root SSH users."""
        if substitutions:
            content = Template(content).substitute(
                {k: str(v) for k, v in substitutions.items()}
            )
        if self.sudo:
            staging = f"/tmp/.jepsen-upload-{abs(hash(remote_path))}"
            self.transport.put(self.node, content.encode(), staging)
            self.exec("mv", staging, remote_path)
        else:
            self.transport.put(self.node, content.encode(), remote_path)
