"""The test runner: setup → concurrent run → drain → analysis → store.

Equivalent of ``jepsen.core/run!`` as the reference drives it (call stack in
SURVEY.md §3.1): build a test map, set up the DB on every node, open one
client per worker, interpret the generator with worker threads + a nemesis
thread while recording every invocation and completion into an immutable
history, tear down, then hand the history to the composed checker and
persist everything in the store.

Worker semantics (matching Jepsen's process model):

- each worker thread owns a logical *process*; ops are recorded with that
  process id;
- an ``info`` (indeterminate) completion poisons the process — its op stays
  logically open forever, so the thread retires the process id and continues
  as ``process + concurrency`` with a fresh client (Jepsen's rule; without
  it a linearizability checker would wrongly close the op's interval);
- the nemesis runs as pseudo-process ``-1`` and never retires.

History timestamps are monotonic ns since test start (Jepsen convention).
"""

from __future__ import annotations

import concurrent.futures
import logging
import threading
import time as _time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Mapping, Sequence

from jepsen_tpu.checkers.protocol import UNKNOWN, VALID, Checker
from jepsen_tpu.generators.core import Generator, Pending, Scheduler
from jepsen_tpu.history.ops import NEMESIS_PROCESS, Op, OpF, OpType
from jepsen_tpu.history.store import Store

logger = logging.getLogger("jepsen_tpu.runner")


class DB:
    """Per-node database lifecycle (= ``jepsen.db/DB`` + ``LogFiles``)."""

    def setup(self, test: Mapping[str, Any], node: str) -> None: ...

    def teardown(self, test: Mapping[str, Any], node: str) -> None: ...

    def log_files(self, test: Mapping[str, Any], node: str) -> list[str]:
        return []

    def collect_log(
        self, test: Mapping[str, Any], node: str, path: str, dest: Path
    ) -> bool:
        """Stream ``path`` on ``node`` into local ``dest``; False if
        absent."""
        return False


@dataclass
class Test:
    """The test map (= the reference's ``rabbit-test`` merge,
    ``rabbitmq.clj:250-286``)."""

    name: str
    nodes: Sequence[str]
    client: Any  # Client prototype (open() per worker)
    generator: Generator
    checker: Checker
    db: DB = field(default_factory=DB)
    nemesis: Any = None
    concurrency: int = 5
    store_root: str = "store"
    opts: dict[str, Any] = field(default_factory=dict)
    #: live observers: each gets ``observe(op)`` for every recorded op
    #: (invocations AND completions, in history order) — the hook behind
    #: mid-run anomaly monitoring (checkers/live.py)
    observers: list = field(default_factory=list)
    #: render the per-run HTML report (report.html / timeline.html /
    #: forensics.html on invalid) into the run dir after analysis —
    #: default ON like jepsen's store/report; ``--no-report`` disables
    report: bool = True
    #: cluster telemetry source (obs/cluster.py): an object with
    #: ``poll() -> {node: snapshot | None}`` — wired by the builders
    #: when the transport can answer the admin ``STATS`` pull; None
    #: (e.g. SSH transports, the sim) means no telemetry plane
    cluster_source: Any = None
    #: sample the cluster source ~1 Hz during the run and harvest
    #: ``cluster.json`` beside ``results.json``; ``--no-cluster-
    #: telemetry`` disables.  With no source this is free — no poller
    #: thread is ever built
    cluster_telemetry: bool = True

    def as_map(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "nodes": list(self.nodes),
            "concurrency": self.concurrency,
            **self.opts,
        }


@dataclass
class TestRun:
    test: Test
    history: list[Op]
    results: dict[str, Any]
    run_dir: Path | None

    @property
    def valid(self) -> bool:
        return self.results.get(VALID) is True

    @property
    def verdict(self):
        """jepsen tri-state: True, False, or "unknown"."""
        return self.results.get(VALID)


class _Recorder:
    """Appends ops to the history with sequential indices + timestamps,
    then notifies observers (in recording order; a failing observer is
    logged and dropped rather than poisoning the run)."""

    def __init__(self, start_ns: int, observers: Sequence[Any] = ()):
        self.lock = threading.Lock()
        self.history: list[Op] = []
        self.start_ns = start_ns
        self.observers = list(observers)

    def record(self, op: Op) -> Op:
        with self.lock:
            op.index = len(self.history)
            op.time = _time.monotonic_ns() - self.start_ns
            self.history.append(op)
            for obs in list(self.observers):
                try:
                    obs.observe(op)
                except Exception:  # noqa: BLE001 - observer must not kill runs
                    logger.exception(
                        "observer %r failed; detaching it", obs
                    )
                    self.observers.remove(obs)
        return op


class _DeadClient:
    """Stand-in when a client can't connect: fails every op (rather than
    deadlocking the run — phase barriers and ``EachThread`` need every
    thread alive)."""

    def __init__(self, error: str):
        self.error = error

    def invoke(self, test, op: Op) -> Op:
        return op.complete(OpType.FAIL, error=f"client-dead: {self.error}")

    def close(self, test):
        pass


_BARRIER_TIMEOUT_S = 120.0
_MAX_SLEEP_S = 0.25  # cap single sleeps so threads notice aborts promptly


def _worker(
    test: Test,
    test_map: Mapping[str, Any],
    scheduler: Scheduler,
    recorder: _Recorder,
    thread_id: int,
    barrier: threading.Barrier,
):
    """One client worker thread: ask → invoke → record, until exhausted."""
    process = thread_id
    node = test.nodes[thread_id % len(test.nodes)]

    def fresh_client():
        try:
            c = test.client.open(test_map, node)
            c.setup(test_map)
            return c
        except Exception as e:  # noqa: BLE001 — keep the thread alive
            logger.exception("client open/setup failed on %s", node)
            return _DeadClient(str(e))

    client = fresh_client()
    try:
        barrier.wait(_BARRIER_TIMEOUT_S)
        while True:
            got = scheduler.next_op(thread_id, process)
            if got is None:
                break
            if isinstance(got, Pending):
                _time.sleep(
                    min(
                        max((got.wake - scheduler.now()) / 1e9, 0.0005),
                        _MAX_SLEEP_S,
                    )
                )
                continue
            got.process = process
            invoke = recorder.record(got)
            try:
                completion = client.invoke(test_map, invoke)
            except Exception as e:  # noqa: BLE001 — client bug: indeterminate
                logger.exception("client.invoke crashed")
                completion = invoke.complete(
                    OpType.INFO, error=f"client-crash: {e}"
                )
            recorder.record(completion)
            if completion.type == OpType.INFO:
                # indeterminate op: retire this process id (Jepsen rule)
                process += test.concurrency
                try:
                    client.close(test_map)
                except Exception:  # noqa: BLE001
                    pass
                client = fresh_client()
    except Exception:  # noqa: BLE001 — never leave peers waiting on us
        logger.exception("worker %d aborting the run", thread_id)
        scheduler.abort()
    finally:
        try:
            client.close(test_map)
        except Exception:  # noqa: BLE001
            pass


def _nemesis_worker(
    test: Test,
    test_map: Mapping[str, Any],
    scheduler: Scheduler,
    recorder: _Recorder,
    barrier: threading.Barrier,
):
    from jepsen_tpu.obs import trace as obs_trace

    nemesis = test.nemesis
    # open fault window (flight recorder): a START completion opens it,
    # the paired STOP closes it as one span on the "nemesis" track —
    # the trace overlays fault windows on the checker/pipeline work
    window: tuple[float, str] | None = None  # (t_start, label)
    try:
        if nemesis is not None:
            nemesis.setup(test_map)
        barrier.wait(_BARRIER_TIMEOUT_S)
        while True:
            got = scheduler.next_op(NEMESIS_PROCESS, NEMESIS_PROCESS)
            if got is None:
                break
            if isinstance(got, Pending):
                _time.sleep(
                    min(
                        max((got.wake - scheduler.now()) / 1e9, 0.0005),
                        _MAX_SLEEP_S,
                    )
                )
                continue
            got.process = NEMESIS_PROCESS
            invoke = recorder.record(got)
            if nemesis is None:
                recorder.record(
                    invoke.complete(OpType.INFO, value="no-nemesis")
                )
                continue
            try:
                completion = nemesis.invoke(test_map, invoke)
            except Exception as e:  # noqa: BLE001
                logger.exception("nemesis.invoke crashed")
                completion = invoke.complete(OpType.INFO, error=str(e))
            recorder.record(completion)
            if obs_trace.is_enabled():
                if invoke.f == OpF.START:
                    window = (
                        _time.perf_counter(),
                        str(completion.value)[:120],
                    )
                elif invoke.f == OpF.STOP and window is not None:
                    t_start, label = window
                    window = None
                    obs_trace.complete(
                        f"nemesis:{label}",
                        t_start,
                        _time.perf_counter(),
                        track="nemesis",
                        args={"heal": str(completion.value)[:120]},
                    )
        if window is not None:
            # a window the schedule never closed (run end mid-fault)
            t_start, label = window
            obs_trace.complete(
                f"nemesis:{label}",
                t_start,
                _time.perf_counter(),
                track="nemesis",
                args={"heal": "unclosed at run end"},
            )
    except Exception:  # noqa: BLE001 — never leave clients waiting on us
        logger.exception("nemesis thread aborting the run")
        scheduler.abort()


def run_test(test: Test, store: Store | None = None) -> TestRun:
    """The full lifecycle.  Returns the run (history + analysis results)."""
    test_map = test.as_map()
    st = store or Store(test.store_root)
    run_dir = st.run_dir(test.name)

    # everything the framework logs during the run lands in
    # <run_dir>/jepsen.log — the artifact the reference's CI triage greps
    # for its verdict lines (ci/jepsen-test.sh:157-195)
    log_handler = logging.FileHandler(run_dir / "jepsen.log")
    log_handler.setFormatter(
        logging.Formatter("%(asctime)s %(name)s %(levelname)s %(message)s")
    )
    pkg_logger = logging.getLogger("jepsen_tpu")
    prev_level = pkg_logger.level
    pkg_logger.addHandler(log_handler)
    if pkg_logger.level > logging.INFO or pkg_logger.level == logging.NOTSET:
        pkg_logger.setLevel(logging.INFO)
    try:
        return _run_test_logged(test, test_map, st, run_dir)
    finally:
        pkg_logger.removeHandler(log_handler)
        pkg_logger.setLevel(prev_level)
        log_handler.close()


def _run_test_logged(
    test: Test, test_map: dict[str, Any], st: Store, run_dir: Path
) -> TestRun:
    from jepsen_tpu.obs import trace as obs_trace

    logger.info("setup: %d nodes", len(test.nodes))
    with obs_trace.span("run.setup", track="run"):
        with concurrent.futures.ThreadPoolExecutor(len(test.nodes)) as pool:
            list(
                pool.map(lambda n: test.db.setup(test_map, n), test.nodes)
            )

    start_ns = _time.monotonic_ns()
    scheduler = Scheduler(
        test.generator, n_threads=test.concurrency, start_ns=start_ns
    )
    recorder = _Recorder(start_ns, observers=test.observers)
    barrier = threading.Barrier(test.concurrency + 1)

    threads = [
        threading.Thread(
            target=_worker,
            args=(test, test_map, scheduler, recorder, t, barrier),
            name=f"worker-{t}",
            daemon=True,
        )
        for t in range(test.concurrency)
    ]
    threads.append(
        threading.Thread(
            target=_nemesis_worker,
            args=(test, test_map, scheduler, recorder, barrier),
            name="nemesis",
            daemon=True,
        )
    )
    # cluster telemetry plane (obs/cluster.py): sample per-node Raft/
    # broker internals at ~1 Hz onto the run's op clock while the load
    # runs.  Best-effort by construction — a telemetry bug must never
    # change a verdict or kill a run.
    poller = None
    if test.cluster_telemetry and test.cluster_source is not None:
        try:
            from jepsen_tpu.obs.cluster import ClusterPoller

            poller = ClusterPoller(
                test.cluster_source, start_ns=start_ns
            ).start()
        except Exception:  # noqa: BLE001
            logger.exception("cluster telemetry failed to start")
            poller = None

    logger.info("run: %d workers + nemesis", test.concurrency)
    with obs_trace.span(
        "run.load",
        track="run",
        args=(
            {"workers": test.concurrency, "nodes": len(test.nodes)}
            if obs_trace.is_enabled()
            else None
        ),
    ):
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    # harvest telemetry BEFORE teardown: the final poll must still see
    # live nodes (end-of-run snapshots are part of the contract)
    if poller is not None:
        try:
            from jepsen_tpu.obs.cluster import (
                summary_line,
                write_cluster_json,
            )

            cluster_doc = poller.stop()
            write_cluster_json(run_dir, cluster_doc)
            logger.info("cluster telemetry: %s", summary_line(cluster_doc))
        except Exception:  # noqa: BLE001
            logger.exception(
                "cluster telemetry harvest failed (verdict unaffected)"
            )

    logger.info("teardown")
    with obs_trace.span("run.teardown", track="run"):
        if test.nemesis is not None:
            test.nemesis.teardown(test_map)
        with concurrent.futures.ThreadPoolExecutor(len(test.nodes)) as pool:
            list(
                pool.map(
                    lambda n: test.db.teardown(test_map, n), test.nodes
                )
            )

    history = recorder.history
    with obs_trace.span("run.save_history", track="run"):
        st.save_history(run_dir, history)

    # collect node logs into the store (= jepsen's db/LogFiles scp)
    for node in test.nodes:
        for path in test.db.log_files(test_map, node):
            dest = run_dir / "nodes" / node / Path(path).name
            dest.parent.mkdir(parents=True, exist_ok=True)
            try:
                test.db.collect_log(test_map, node, path, dest)
            except Exception:  # noqa: BLE001 — log collection best-effort
                logger.exception("fetching %s from %s failed", path, node)

    logger.info("analysis: %d history entries", len(history))
    with obs_trace.span(
        "run.analysis",
        track="run",
        args=(
            {"history_ops": len(history)}
            if obs_trace.is_enabled()
            else None
        ),
    ):
        check_opts: dict[str, Any] = {"out_dir": run_dir}
        results = test.checker.check(test_map, history, check_opts)
    st.save_results(run_dir, results)
    if test.report:
        # default-on like jepsen's store/report; best-effort — a report
        # renderer bug must never change a run's verdict or lose its
        # recorded history (the failure is LOUD in the run log).  The
        # WindowedPerf checker stashed its tensors into check_opts, so
        # the render reuses them instead of re-packing the history.
        with obs_trace.span("run.report", track="run"):
            try:
                from jepsen_tpu.report.perfstats import STATS_OPT
                from jepsen_tpu.report.render import render_run_report

                render_run_report(
                    run_dir,
                    history=history,
                    results=results,
                    stats=check_opts.get(STATS_OPT),
                )
            except Exception:  # noqa: BLE001
                logger.exception(
                    "run report rendering failed (verdict unaffected)"
                )
    verdict = results.get(VALID)
    if verdict is True:
        logger.info("Everything looks good! (%d ops)", len(history))
    elif verdict == UNKNOWN:
        # undecided (e.g. a capped search) — distinct from a violation
        logger.info("Analysis unknown (%d ops)", len(history))
    else:
        # the verdict line the reference's CI triage greps for
        logger.info("Analysis invalid! (%d ops)", len(history))
    return TestRun(test=test, history=history, results=results, run_dir=run_dir)
