"""Network manipulation interface.

The nemesis strategies compute *which links to cut* (grudges); a ``Net``
applies them to an actual network: :class:`SimNet` flips the simulator's
blocked-link set, and the SSH net (``jepsen_tpu.control.ssh``) installs
iptables DROP rules on real nodes the way ``jepsen.nemesis``'s partitioners
do ``[dep: jepsen 0.3.12]``.
"""

from __future__ import annotations

import abc
from typing import Iterable, Sequence


def undirected(grudges: dict[str, set[str]]) -> set[frozenset[str]]:
    """Collapse directed grudges to undirected blocked links."""
    out: set[frozenset[str]] = set()
    for a, peers in grudges.items():
        for b in peers:
            if a != b:
                out.add(frozenset((a, b)))
    return out


class Net(abc.ABC):
    #: True when this net honors the DIRECTION of a grudge (``a`` drops
    #: input from ``b`` while ``b`` still hears ``a``).  The asymmetric
    #: one-way partition strategies require it: a net that symmetrizes
    #: would silently run a DIFFERENT (two-way) fault and any verdict
    #: would describe a schedule nobody asked for.
    one_way = False

    @abc.abstractmethod
    def partition(self, grudges: dict[str, set[str]]) -> None:
        """Apply blocked links (``grudges[a] ∋ b`` = a drops traffic from b)."""

    @abc.abstractmethod
    def heal(self) -> None:
        """Remove all blocks."""


class SimNet(Net):
    """Collapses grudges to undirected links (the simulator models links,
    not directions) — hence ``one_way = False``: asymmetric strategies
    are refused rather than silently symmetrized."""

    def __init__(self, cluster):
        self.cluster = cluster

    def partition(self, grudges):
        self.cluster.set_blocked(undirected(grudges))

    def heal(self):
        self.cluster.heal()


class IptablesNet(Net):
    """Real-cluster partitions: per-node iptables DROP rules over SSH (the
    mechanism behind ``jepsen.nemesis``'s partitioners; the docker topology
    grants NET_ADMIN exactly for this, ``docker-compose.yml:9-10``).

    Rules are installed per grudge DIRECTION (``-A INPUT -s peer`` only on
    the node holding the grudge), so one-way partitions are first-class:
    the local process cluster forwards each rule to that node's Raft RPC
    layer with the same INPUT-drop semantics (``replication.py``)."""

    one_way = True

    def __init__(self, transport, nodes):
        from jepsen_tpu.control.ssh import Control

        self._controls = {
            n: Control(transport, n).su() for n in nodes
        }

    def partition(self, grudges: dict[str, set[str]]) -> None:
        for node, blocked in grudges.items():
            c = self._controls[node]
            for peer in blocked:
                c.exec(
                    "iptables", "-A", "INPUT", "-s", peer, "-j", "DROP",
                    "-w",
                )

    def heal(self) -> None:
        for c in self._controls.values():
            c.exec("iptables", "-F", "-w")
            c.exec("iptables", "-X", "-w", check=False)


class Procs(abc.ABC):
    """Process-level fault surface: where :class:`Net` acts on links,
    this acts on the DB process itself (the mechanism behind jepsen's
    kill/pause nemeses — beyond the reference's partition-only set)."""

    @abc.abstractmethod
    def kill(self, node: str) -> None:
        """SIGKILL the DB process (durable state survives; Raft rejoins
        on restart)."""

    @abc.abstractmethod
    def restart(self, node: str) -> None:
        """Start a killed DB process."""

    @abc.abstractmethod
    def pause(self, node: str) -> None:
        """SIGSTOP the DB process (it holds state and sockets but stops
        responding — a 'slow node', nastier than a clean death for
        failure detectors)."""

    @abc.abstractmethod
    def resume(self, node: str) -> None:
        """SIGCONT a paused DB process."""


class Clocks(abc.ABC):
    """Wall-clock fault surface (``jepsen.nemesis.time``'s role): bump a
    node's clock off true, and set it back.  A correct quorum system
    tolerates skew — its election timers are monotonic and its TTL
    timestamps travel inside the replicated log — which is exactly what
    the clock nemesis exists to demonstrate (or disprove)."""

    @abc.abstractmethod
    def bump(self, node: str, delta_s: float) -> None:
        """Set ``node``'s wall clock to controller-now + ``delta_s``."""

    @abc.abstractmethod
    def reset(self, node: str) -> None:
        """Set ``node``'s wall clock back to controller-now."""


class TransportClocks(Clocks):
    """Clock bumps over the command transport: ``date -u -s @EPOCH``
    (the portable way to set a VM's clock; the local process cluster
    maps the same command string onto its admin ``CLOCK_SET``)."""

    def __init__(self, transport, nodes):
        self.transport = transport
        self.nodes = list(nodes)

    def _set(self, node: str, epoch_s: float) -> None:
        r = self.transport.run(node, f"sudo date -u -s @{epoch_s:.3f}")
        if r.rc != 0:
            # a failed clock set (no sudo, protected clock) must never
            # silently no-op: the run would then claim "tolerates clock
            # skew" with no skew ever applied — the false-green-by-
            # absent-fault class this codebase refuses elsewhere
            # (advisor r4)
            raise RuntimeError(
                f"clock set on {node} failed (rc={r.rc}): "
                f"{(r.err or r.out).strip()[:200] or 'no output'} — "
                f"refusing to run a skew test with no actual skew"
            )

    def bump(self, node, delta_s):
        import time as _t

        self._set(node, _t.time() + delta_s)

    def reset(self, node):
        import time as _t

        self._set(node, _t.time())


class Disks(abc.ABC):
    """Disk fault surface (the fsyncgate-adjacent one): make a node's
    WAL device slow — every fsync stalls mean±jitter ms — and set it
    back.  A correct durable SUT degrades gracefully (slower confirms,
    possibly timing out into indeterminate ops, which is always safe);
    nothing confirmed may be lost.  The node that IS fast under a slow
    disk is the one lying about fsync (``ack-before-fsync``)."""

    @abc.abstractmethod
    def slow(self, node: str, mean_ms: float, jitter_ms: float) -> None:
        """Inject fsync latency on ``node``'s WAL device."""

    @abc.abstractmethod
    def reset(self, node: str) -> None:
        """Restore ``node``'s WAL device to full speed."""


class TransportDisks(Disks):
    """Disk delay over the command transport as the device-mapper
    ``delay`` target an operator would use (``dmsetup``, suspending and
    reloading the WAL volume's table); the local process cluster maps
    the same command string onto its admin ``FSYNC_LAT``.  A failed
    injection raises — a run must never claim "tolerates slow disks"
    with no slow disk ever injected (the false-green-by-absent-fault
    class, same refusal as :class:`TransportClocks`)."""

    def __init__(self, transport, nodes):
        self.transport = transport
        self.nodes = list(nodes)

    def _run(self, node: str, cmd: str) -> None:
        r = self.transport.run(node, cmd)
        if r.rc != 0:
            raise RuntimeError(
                f"disk-delay injection on {node} failed (rc={r.rc}): "
                f"{(r.err or r.out).strip()[:200] or 'no output'} — "
                f"refusing to run a slow-disk test with no actual delay"
            )

    def slow(self, node, mean_ms, jitter_ms):
        self._run(
            node,
            f"sudo dmsetup message jt-wal-delay 0 "
            f"delay {mean_ms:g} {jitter_ms:g}",
        )

    def reset(self, node):
        self._run(node, "sudo dmsetup message jt-wal-delay 0 delay 0 0")


class Wire(abc.ABC):
    """Wire fault surface: netem-style frame corruption / duplication /
    delay-reordering on a node's outgoing peer links, and calm again.
    A correct SUT's transport drops corrupted frames on checksum
    (corruption degrades to loss, which consensus retries through) and
    tolerates duplicated/reordered protocol frames by idempotency."""

    @abc.abstractmethod
    def chaos(
        self, node: str, corrupt_p: float, duplicate_p: float,
        delay_p: float, delay_ms: float,
    ) -> None:
        """Install the fault rates on ``node``'s outgoing frames."""

    @abc.abstractmethod
    def calm(self, node: str) -> None:
        """Remove all wire faults from ``node``."""


class TransportWire(Wire):
    """Wire chaos over the command transport as the real ``tc qdisc``
    netem line an operator would run; the local process cluster maps it
    onto its admin ``WIRE`` (rates applied inside the node's RPC layer).
    Failure raises — same no-silent-no-op rule as the other surfaces."""

    def __init__(self, transport, nodes):
        self.transport = transport
        self.nodes = list(nodes)

    def _run(self, node: str, cmd: str) -> None:
        r = self.transport.run(node, cmd)
        if r.rc != 0:
            raise RuntimeError(
                f"wire-chaos injection on {node} failed (rc={r.rc}): "
                f"{(r.err or r.out).strip()[:200] or 'no output'} — "
                f"refusing to run a wire test with no actual faults"
            )

    def chaos(self, node, corrupt_p, duplicate_p, delay_p, delay_ms):
        self._run(
            node,
            f"sudo tc qdisc replace dev eth0 root netem "
            f"corrupt {corrupt_p * 100:g}% "
            f"duplicate {duplicate_p * 100:g}% "
            f"reorder {delay_p * 100:g}% delay {delay_ms:g}ms",
        )

    def calm(self, node):
        self._run(node, "sudo tc qdisc del dev eth0 root netem")


class Membership(abc.ABC):
    """Cluster-membership fault surface: remove a (stopped) node from
    the cluster and join a fresh one back — the ``rabbitmqctl
    forget_cluster_node`` / ``join_cluster`` pair, which is how real
    operators shrink and grow a RabbitMQ cluster."""

    @abc.abstractmethod
    def forget(self, via_node: str, target: str) -> bool:
        """On surviving ``via_node``: forget stopped ``target``."""

    @abc.abstractmethod
    def join(self, node: str, via_node: str) -> bool:
        """On freshly-booted ``node``: join ``via_node``'s cluster."""


class TransportMembership(Membership):
    """Membership changes as the rabbitmqctl command strings the DB
    choreography already uses (``db_rabbitmq.py`` — the archive-path
    ``CTL``, under ``su``, because the server is installed under /tmp
    and not on PATH), run over the transport — the local cluster maps
    them to real Raft Add/Remove Server commits."""

    def __init__(self, transport, nodes):
        self.transport = transport
        self.nodes = list(nodes)

    def _ctl(self, node: str, args: str) -> bool:
        from jepsen_tpu.control.db_rabbitmq import CTL  # lazy: no cycle
        from jepsen_tpu.control.ssh import Control, RemoteError

        try:
            Control(self.transport, node).su().exec(shell=f"{CTL} {args}")
            return True
        except RemoteError:
            return False

    def forget(self, via_node, target):
        return self._ctl(via_node, f"forget_cluster_node rabbit@{target}")

    def join(self, node, via_node):
        # the documented rejoin procedure for a node forgotten while
        # down: stop_app → reset (clear its old cluster metadata, or
        # real rabbitmqctl rejects the join) → join_cluster → start_app
        self._ctl(node, "stop_app")
        self._ctl(node, "reset")
        ok = self._ctl(node, f"join_cluster rabbit@{via_node}")
        self._ctl(node, "start_app")
        return ok


class SimProcs(Procs):
    """Drives the simulator's down-node set.  Kill and pause coincide in
    the sim (a down node is simply unreachable and votes in no quorum;
    durable state is cluster-global, so both come back intact)."""

    def __init__(self, cluster):
        self.cluster = cluster

    def kill(self, node):
        self.cluster.set_down(node)

    def restart(self, node):
        self.cluster.set_up(node)

    pause = kill
    resume = restart


def complete_grudges(groups: Sequence[Iterable[str]]) -> dict[str, set[str]]:
    """Block every cross-group link (jepsen ``complete-grudge``)."""
    groups = [list(g) for g in groups]
    out: dict[str, set[str]] = {}
    for i, g in enumerate(groups):
        others = {n for j, o in enumerate(groups) if j != i for n in o}
        for n in g:
            out[n] = set(others)
    return out
