"""RabbitMQ DB lifecycle over SSH.

Equivalent of the reference's ``db`` reify (``rabbitmq.clj:28-141``): per
node — kill stray Erlang VMs, install a pinned Erlang from the RabbitMQ apt
repo if absent, install the RabbitMQ generic-unix archive, push the config
templates (debug logging incl. Raft; ``net_ticktime``/aten failure-detector
settings), set the shared Erlang cookie — then the boot choreography:
primary boots first and enables the Khepri feature flag, a barrier
synchronizes all setup threads, and the remaining nodes boot, stop their
app, ``join_cluster`` the primary (with a randomized stagger), and start
the app.  Teardown dumps the Raft member status of the queue, its
dead-letter twin, and the dlx worker; ``log_files`` returns the broker and
crash logs for collection into the store.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Any, Mapping, Sequence

from jepsen_tpu.control.runner import DB
from jepsen_tpu.control.ssh import Control, RemoteError, Transport

logger = logging.getLogger("jepsen_tpu.db.rabbitmq")

ERLANG_VERSION = "1:27*"
SERVER_DIR = "/tmp/rabbitmq-server"
CTL = f"{SERVER_DIR}/sbin/rabbitmqctl"
COOKIE = "jepsen-rabbitmq"

# config templates (semantics of rabbitmq/resources/rabbitmq/*): debug file
# logging incl. Raft, open loopback users; tunable net_ticktime + aten
# poll_interval (Raft failure detector) + DLQ confirm timeout
RABBITMQ_CONF = """\
loopback_users = none
log.file.level = debug
log.ra.level = debug
log.connection.level = info
log.channel.level = info
log.queue.level = info
log.default.level = info
"""

ADVANCED_CONFIG = """\
[
  {kernel, [{net_ticktime, $NET_TICKTIME}]},
  {aten, [{poll_interval, 1000}]},
  {rabbit, [{dead_letter_worker_publisher_confirm_timeout, 15000}]}
].
"""

ERLANG_APT_PIN = """\
Package: erlang*
Pin: version $ERLANG_VERSION
Pin-Priority: 1000
"""

ERLANG_PACKAGES = (
    "socat xz-utils erlang-base erlang-asn1 erlang-crypto erlang-eldap "
    "erlang-ftp erlang-inets erlang-mnesia erlang-os-mon erlang-parsetools "
    "erlang-public-key erlang-runtime-tools erlang-snmp erlang-ssl "
    "erlang-syntax-tools erlang-tftp erlang-tools erlang-xmerl"
)


class RabbitMQDB(DB):
    def __init__(
        self,
        transport: Transport,
        nodes: Sequence[str],
        primary_wait_s: float = 15.0,
        secondary_wait_s: float = 20.0,
        join_stagger_max_s: float = 15.0,
        seed: int | None = None,
    ):
        self.transport = transport
        self.nodes = list(nodes)
        self.primary_wait_s = primary_wait_s
        self.secondary_wait_s = secondary_wait_s
        self.join_stagger_max_s = join_stagger_max_s
        self.barrier = threading.Barrier(len(self.nodes))
        self.rng = random.Random(seed)

    def primary(self) -> str:
        """The boot-order primary (= ``jepsen.core/primary``: first node)."""
        return self.nodes[0]

    SETUP_BARRIER_TIMEOUT_S = 900.0

    # ------------------------------------------------------------------
    def setup(self, test: Mapping[str, Any], node: str) -> None:
        try:
            self._setup_pre_barrier(test, node)
        except BaseException:
            # never leave peer setup threads blocked on the barrier
            self.barrier.abort()
            raise
        self.barrier.wait(self.SETUP_BARRIER_TIMEOUT_S)  # = core/synchronize
        self._setup_post_barrier(test, node)

    def _setup_pre_barrier(self, test: Mapping[str, Any], node: str) -> None:
        c = Control(self.transport, node).su()
        logger.info("[%s] cleaning previous install", node)
        c.exec(shell="killall -q -9 beam.smp epmd || true")
        c.exec("rm", "-rf", SERVER_DIR)

        self._ensure_erlang(c)

        archive_url = test.get("archive-url")
        if not archive_url:
            raise ValueError("test map needs an archive-url")
        logger.info("[%s] installing RabbitMQ from %s", node, archive_url)
        c.install_archive(archive_url, SERVER_DIR)

        c.exec("mkdir", "-p", f"{SERVER_DIR}/etc/rabbitmq")
        c.write_file(RABBITMQ_CONF, f"{SERVER_DIR}/etc/rabbitmq/rabbitmq.conf")
        c.write_file(
            ADVANCED_CONFIG,
            f"{SERVER_DIR}/etc/rabbitmq/advanced.config",
            substitutions={"NET_TICKTIME": test.get("net-ticktime", 15)},
        )
        c.write_file(COOKIE, "/root/.erlang.cookie")
        c.exec("chmod", "600", "/root/.erlang.cookie")

        primary = self.primary()
        if node == primary:
            logger.info("[%s] booting primary", node)
            c.exec(shell=f"{SERVER_DIR}/sbin/rabbitmq-server -detached")
            time.sleep(self.primary_wait_s)
            logger.info("[%s] enabling khepri_db", node)
            c.exec(shell=f"{CTL} enable_feature_flag --opt-in khepri_db")
        # secondaries just fall through to the barrier — it already
        # guarantees they don't boot before the primary is up

    def _setup_post_barrier(self, test: Mapping[str, Any], node: str) -> None:
        c = Control(self.transport, node).su()
        primary = self.primary()
        if node != primary:
            logger.info("[%s] booting secondary", node)
            c.exec(shell=f"{SERVER_DIR}/sbin/rabbitmq-server -detached")
            time.sleep(self.secondary_wait_s)
            c.exec(shell=f"{CTL} enable_feature_flag --opt-in khepri_db")
            c.exec(shell=f"{CTL} stop_app")
            time.sleep(self.rng.uniform(0, self.join_stagger_max_s))
            logger.info("[%s] join_cluster rabbit@%s", node, primary)
            c.exec(shell=f"{CTL} join_cluster rabbit@{primary}")
            c.exec(shell=f"{CTL} start_app")
            logger.info("[%s] joined", node)

    def _ensure_erlang(self, c: Control) -> None:
        """Install pinned Erlang from the RabbitMQ apt repo if absent
        (``rabbitmq.clj:41-57``)."""
        probe = (
            'erl -noshell -eval "\\$2 /= hd(erlang:system_info(otp_release))'
            ' andalso halt(2)." -run init stop'
        )
        try:
            c.exec(shell=probe)
            return
        except RemoteError:
            logger.info("[%s] Erlang not detected, installing", c.node)
        c.exec(
            shell="echo 'deb https://deb1.rabbitmq.com/rabbitmq-erlang/"
            "debian/bookworm bookworm main' >> "
            "/etc/apt/sources.list.d/rabbitmq-erlang.list"
        )
        c.exec(
            shell="echo 'deb https://deb2.rabbitmq.com/rabbitmq-erlang/"
            "debian/bookworm bookworm main' >> "
            "/etc/apt/sources.list.d/rabbitmq-erlang.list"
        )
        sig = c.wget(
            "https://keys.openpgp.org/vks/v1/by-fingerprint/"
            "0A9AF2115F4687BD29803A206B73A36E6026DFCA"
        )
        c.exec("apt-key", "add", sig)
        c.exec("mkdir", "-p", "/etc/apt/preferences.d/")
        c.write_file(
            ERLANG_APT_PIN,
            "/etc/apt/preferences.d/erlang",
            substitutions={"ERLANG_VERSION": ERLANG_VERSION},
        )
        c.exec(shell="apt-get update -y", timeout=600)
        c.exec(
            shell=f"DEBIAN_FRONTEND=noninteractive apt-get install -y "
            f"{ERLANG_PACKAGES}",
            timeout=1200,
        )

    # ------------------------------------------------------------------
    def teardown(self, test: Mapping[str, Any], node: str) -> None:
        c = Control(self.transport, node).su()
        if not c.exists(CTL):
            return
        # Raft member status dumps (rabbitmq.clj:124-135)
        for name, probe in (
            (
                "jepsen.queue",
                "case whereis('%2F_jepsen.queue') of undefined -> "
                "no_local_member; _ -> sys:get_status(whereis("
                "'%2F_jepsen.queue')) end.",
            ),
            (
                "jepsen.queue.dead.letter",
                "case whereis('%2F_jepsen.queue.dead.letter') of undefined "
                "-> no_local_member; _ -> sys:get_status(whereis("
                "'%2F_jepsen.queue.dead.letter')) end.",
            ),
            (
                "rabbit_fifo_dlx_worker",
                "try supervisor:which_children(rabbit_fifo_dlx_sup) of [] "
                "-> no_local_dlx_worker; [{undefined, Pid, worker, _}] -> "
                "sys:get_status(Pid) catch exit:{noproc, _} -> no_dlx_sup "
                "end.",
            ),
        ):
            try:
                status = c.exec(shell=f'{CTL} eval "{probe}"', timeout=30)
                logger.info("[%s] quorum status %s: %s", node, name, status)
            except RemoteError as e:
                logger.info("[%s] status dump %s failed: %s", node, name, e)
        logger.info("[%s] teardown complete", node)

    def log_files(self, test: Mapping[str, Any], node: str) -> list[str]:
        return [
            f"{SERVER_DIR}/var/log/rabbitmq/rabbit@{node}.log",
            f"{SERVER_DIR}/var/log/rabbitmq/log/crash.log",
        ]

    def collect_log(self, test, node, path, dest) -> bool:
        return self.transport.get(node, path, dest)

    # CI cross-check helper (ci/jepsen-test.sh:144-155)
    def queue_lengths_settled(
        self, node: str, settle_s: float = 3.0
    ) -> dict[str, int]:
        """``queue_lengths`` retried briefly while counts drain to zero.
        On a replicated cluster the final acks settle asynchronously
        (Raft apply lag on followers), so one instantaneous reading right
        after a drain can show phantom depth; the reference's own CI
        empty-check polls rabbitmqctl in a loop for the same reason
        (``ci/jepsen-test.sh:144-155``)."""
        deadline = time.monotonic() + settle_s
        while True:
            lengths = self.queue_lengths(node)
            if all(v == 0 for v in lengths.values()):
                return lengths
            if time.monotonic() >= deadline:
                return lengths
            time.sleep(0.15)

    def queue_lengths(self, node: str) -> dict[str, int]:
        c = Control(self.transport, node).su()
        out = c.exec(
            shell=f"{CTL} list_queues name messages --no-table-headers -q",
            timeout=30,
        )
        lengths: dict[str, int] = {}
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 2 and parts[-1].isdigit():
                lengths[" ".join(parts[:-1])] = int(parts[-1])
        return lengths


class RabbitMQProcs:
    """Process-fault surface for a live cluster (:class:`~jepsen_tpu.control.net.Procs`):
    SIGKILL/restart and SIGSTOP/SIGCONT of the broker's Erlang VM over
    SSH — the mechanism behind the kill/pause nemeses.  A killed node's
    durable Raft state survives under ``SERVER_DIR``; restart simply
    boots the server again and the node rejoins its cluster.  Pause
    freezes beam.smp in place (sockets held, zero progress) — the
    failure-detector stress the ``net_ticktime``/aten knobs exist for."""

    def __init__(self, transport: Transport, nodes: Sequence[str]):
        self._controls = {n: Control(transport, n).su() for n in nodes}

    def kill(self, node: str) -> None:
        self._controls[node].exec(
            shell="killall -q -9 beam.smp epmd || true"
        )

    def restart(self, node: str) -> None:
        self._controls[node].exec(
            shell=f"{SERVER_DIR}/sbin/rabbitmq-server -detached"
        )

    def pause(self, node: str) -> None:
        self._controls[node].exec(shell="killall -q -STOP beam.smp || true")

    def resume(self, node: str) -> None:
        self._controls[node].exec(shell="killall -q -CONT beam.smp || true")
