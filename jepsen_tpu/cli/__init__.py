"""CLI: test assembly and subcommand dispatch."""

from jepsen_tpu.cli.main import build_parser, main  # noqa: F401
