"""Results web server: browse the ``store/`` directory.

Equivalent of the web server ``jepsen.cli/serve-cmd`` runs on the
controller (the reference points at it in ``rabbitmq.clj:330-331``'s
docstring — "browse results over the web"): an index of recorded runs with
their verdicts, plus raw access to every run artifact (history, results,
``jepsen.log``, perf plots, timelines, node logs).

Stdlib-only (``http.server``); read-only; paths are resolved and checked
against the store root so the server can't be walked out of it.
"""

from __future__ import annotations

import html
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from urllib.parse import unquote

from jepsen_tpu.checkers.protocol import UNKNOWN
from jepsen_tpu.history.store import LIVE_FILE, RESULTS_FILE

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>{title}</title>
<style>
 body {{ font-family: monospace; margin: 2em; }}
 table {{ border-collapse: collapse; }}
 td, th {{ padding: .3em .8em; border: 1px solid #ccc; text-align: left; }}
 .valid {{ color: #0a0; }} .invalid {{ color: #c00; }}
 .unknown {{ color: #888; }}
 a {{ text-decoration: none; }}
</style></head><body><h1>{title}</h1>{body}</body></html>"""


def _read_json_dict(path: Path) -> dict | None:
    """Defensive artifact read: a truncated, rewritten, or non-object
    JSON file must read as absent, never 500 the index page."""
    if not path.is_file():
        return None
    try:
        data = json.loads(path.read_text())
    except (json.JSONDecodeError, OSError, UnicodeDecodeError):
        return None
    return data if isinstance(data, dict) else None


def _runs(root: Path) -> list[dict]:
    """Every run dir under ``root`` (test-name/timestamp layout), newest
    first, with its verdict when results.json exists."""
    runs = []
    if not root.is_dir():
        return runs
    for test_dir in sorted(root.iterdir()):
        if not test_dir.is_dir() or test_dir.is_symlink():
            continue
        for run_dir in sorted(test_dir.iterdir()):
            if not run_dir.is_dir() or run_dir.is_symlink():
                continue
            valid = None  # True | False | "unknown" | None (no results)
            data = _read_json_dict(run_dir / RESULTS_FILE)
            if data is not None and "valid?" in data:
                v = data["valid?"]
                valid = v if v == UNKNOWN else bool(v)
            live = None  # None = no monitor ran; else bool violation flag
            data = _read_json_dict(run_dir / LIVE_FILE)
            if data is not None and "violation-so-far" in data:
                live = bool(data["violation-so-far"])
            runs.append(
                {
                    "test": test_dir.name,
                    "run": run_dir.name,
                    "rel": f"{test_dir.name}/{run_dir.name}",
                    "valid": valid,
                    "live": live,
                }
            )
    runs.sort(key=lambda r: r["run"], reverse=True)
    return runs


def _index_page(root: Path) -> str:
    rows = []
    for r in _runs(root):
        cls, verdict = {
            True: ("valid", "valid"),
            False: ("invalid", "INVALID"),
            UNKNOWN: ("unknown", "unknown"),
            None: ("unknown", "?"),
        }[r["valid"]]
        live_cls, live_txt = {
            True: ("invalid", "flagged mid-run"),
            False: ("valid", "clean"),
            None: ("unknown", "&mdash;"),
        }[r["live"]]
        rows.append(
            f'<tr><td><a href="/files/{html.escape(r["rel"])}/">'
            f'{html.escape(r["test"])}</a></td>'
            f'<td>{html.escape(r["run"])}</td>'
            f'<td class="{cls}">{verdict}</td>'
            f'<td class="{live_cls}">{live_txt}</td></tr>'
        )
    body = (
        "<table><tr><th>test</th><th>run</th><th>verdict</th>"
        "<th>live monitor</th></tr>"
        + "".join(rows)
        + "</table>"
        if rows
        else "<p>no runs recorded yet</p>"
    )
    return _PAGE.format(title="jepsen-tpu store", body=body)


def _listing_page(root: Path, d: Path) -> str:
    rel = d.relative_to(root)
    entries = []
    for p in sorted(d.iterdir()):
        name = p.name + ("/" if p.is_dir() else "")
        entries.append(
            f'<li><a href="/files/{html.escape(str(rel / p.name))}'
            f'{"/" if p.is_dir() else ""}">{html.escape(name)}</a></li>'
        )
    body = f'<p><a href="/">&larr; index</a></p><ul>{"".join(entries)}</ul>'
    return _PAGE.format(title=f"store/{rel}", body=body)


_CONTENT_TYPES = {
    ".html": "text/html; charset=utf-8",
    ".json": "application/json",
    ".jsonl": "text/plain; charset=utf-8",
    ".log": "text/plain; charset=utf-8",
    ".txt": "text/plain; charset=utf-8",
    ".png": "image/png",
    ".svg": "image/svg+xml",
}


class StoreHandler(BaseHTTPRequestHandler):
    store_root: Path  # set by make_server

    def log_message(self, *args):  # quiet by default
        pass

    def _send_html(self, content: str, status: int = 200) -> None:
        data = content.encode()
        self.send_response(status)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 — http.server API
        root = self.store_root.resolve()
        path = unquote(self.path.split("?", 1)[0])
        if path in ("/", "/index.html"):
            self._send_html(_index_page(root))
            return
        if not path.startswith("/files/"):
            self._send_html(_PAGE.format(title="404", body="not found"), 404)
            return
        target = (root / path[len("/files/"):].lstrip("/")).resolve()
        if (
            target != root and not str(target).startswith(str(root) + "/")
        ) or not target.exists():
            self._send_html(_PAGE.format(title="404", body="not found"), 404)
            return
        if target.is_dir():
            self._send_html(_listing_page(root, target))
            return
        ctype = _CONTENT_TYPES.get(
            target.suffix, "application/octet-stream"
        )
        data = target.read_bytes()
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


def make_server(
    store_root: str | Path, host: str = "0.0.0.0", port: int = 8080
) -> ThreadingHTTPServer:
    handler = type(
        "BoundStoreHandler",
        (StoreHandler,),
        {"store_root": Path(store_root)},
    )
    return ThreadingHTTPServer((host, port), handler)


def serve_forever(
    store_root: str | Path, host: str = "0.0.0.0", port: int = 8080
) -> None:
    srv = make_server(store_root, host, port)
    print(f"serving {store_root} on http://{host}:{srv.server_address[1]}")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()


def start_background(
    store_root: str | Path, host: str = "127.0.0.1", port: int = 0
) -> tuple[ThreadingHTTPServer, int]:
    """Start the server on a daemon thread; returns (server, port)."""
    srv = make_server(store_root, host, port)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1]
