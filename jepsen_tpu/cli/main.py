"""Command-line interface.

Mirrors the surface the reference gets from ``jepsen.cli``
(``rabbitmq.clj:329-334``): subcommand dispatch, merged opt specs, and a
run/analysis lifecycle whose console output the CI triage greps —
``Analysis invalid`` marks a genuine consistency violation
(``ci/jepsen-test.sh:180-184``), and a valid run prints the reference's
"Everything looks good!" banner (``README.md:55``).

Subcommands:

- ``test``        — run a partition test for any of the four workload
                    families (all the reference's flags; ``--db sim`` for
                    the in-process cluster, ``--db local`` for the full
                    rabbitmq assembly over local broker OS processes,
                    ``--db rabbitmq`` for a real cluster over SSH).
- ``check``       — re-check a recorded history (``--checker tpu|cpu``);
                    the ``--checker`` dispatch point is the north-star seam.
- ``bench-check`` — batched replay: verify many stored/synthetic histories
                    at once on the device mesh, report histories/sec.
- ``synth``       — generate synthetic histories (with injectable
                    anomalies) into a store, for demos and differential
                    testing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

# JAX (and the jax-importing checker modules) are imported lazily, inside
# the subcommands that need them: subcommands that never touch a device
# (``synth``, ``serve``, ``matrix --print-configs``) must not initialize a
# JAX backend at all — a tunneled single-chip plugin can hang init for
# minutes when the tunnel does not answer, and e.g. the CI matrix
# introspection path is spawned as a subprocess by shell tooling that
# cannot afford that.

from jepsen_tpu.checkers.protocol import UNKNOWN
from jepsen_tpu.history.store import (
    HISTORY_FILE,
    Store,
    read_history,
    read_history_jsonl,
    save_results,
    _json_default,
)

GOOD_BANNER = "Everything looks good! ヽ('ー`)ノ"
INVALID_BANNER = "Analysis invalid! ಠ~ಠ"
UNKNOWN_BANNER = "Analysis result unknown ¯\\_(ツ)_/¯"


def _verdict_exit(verdict) -> int:
    """jepsen tri-state → banner + exit code.

    0 = valid, 1 = invalid (genuine violation), 3 = analysis undecided
    ("unknown", e.g. a capped search).  2 stays the usage/environment
    error code (missing history, bad config) so CI shells can tell an
    undecided analysis from a broken run."""
    if verdict is True:
        print(GOOD_BANNER)
        return 0
    if verdict == UNKNOWN:
        print(UNKNOWN_BANNER)
        return 3
    print(INVALID_BANNER)
    return 1


def _resolve_history_path(path: Path) -> Path:
    """Accept a history file (JSONL or jepsen EDN), a run dir, or a
    store root (→ latest run)."""
    if path.is_file():
        return path
    for name in (HISTORY_FILE, "history.edn"):
        if (path / name).is_file():
            return path / name
        latest = path / "latest"
        if latest.exists() and (latest / name).is_file():
            return (latest / name).resolve()
    raise FileNotFoundError(f"no {HISTORY_FILE} (or history.edn) under {path}")


def _workload_of(history) -> str:
    from jepsen_tpu.history.ops import workload_of

    return workload_of(history)


def _history_paths(root: str) -> list:
    """Every stored history under ``root`` — ``history.jsonl`` plus EDN
    files that are not just an exported twin of a JSONL in the same run
    dir (the same run must not load twice)."""
    from jepsen_tpu.history.store import EDN_FILE

    return sorted(Path(root).glob(f"**/{HISTORY_FILE}")) + [
        p
        for p in sorted(Path(root).glob(f"**/{EDN_FILE}"))
        if not (p.parent / HISTORY_FILE).exists()
    ]


def _pipelined_checkers(args, workload: str, hpath) -> dict | None:
    """Family checkers routed through the bytes-to-verdict pipeline
    executor (``parallel/pipeline.py``): cache-first native substrate,
    device check — instead of re-packing the already-parsed Op objects.
    Only for the pipelined families on the tpu backend with a real
    history file; ``--serial`` is the triage escape hatch.  One shared
    run serves every sub-checker of the family (queue surfaces as two
    result keys)."""
    if (
        args.checker != "tpu"
        or getattr(args, "serial", False)
        or hpath is None
        or workload not in ("queue", "stream", "elle", "mutex")
    ):
        return None
    from jepsen_tpu.parallel.pipeline import PipelinedChecker

    shared: dict = {}
    if workload == "mutex":
        if getattr(args, "no_pcomp", False):
            return None  # --no-pcomp: the monolithic MutexWgl path
        return {
            "mutex": PipelinedChecker("mutex", hpath, "mutex", shared=shared)
        }
    if workload == "queue":
        opts = {"delivery": getattr(args, "delivery", None) or "exactly-once"}
        return {
            sub: PipelinedChecker(
                "queue", hpath, sub, shared=shared, **opts
            )
            for sub in ("queue", "linear")
        }
    if workload == "stream":
        opts = {
            "append_fail": getattr(args, "append_fail", None) or "definite"
        }
        return {
            "stream": PipelinedChecker(
                "stream", hpath, "stream", shared=shared, **opts
            )
        }
    opts = {
        "model": getattr(args, "consistency_model", None) or "serializable"
    }
    return {
        "elle": PipelinedChecker("elle", hpath, "elle", shared=shared, **opts)
    }


def _checker_for(args, out_dir=None, history=None, hpath=None):
    from jepsen_tpu.checkers.perf import Perf
    from jepsen_tpu.checkers.protocol import compose
    from jepsen_tpu.checkers.queue_lin import QueueLinearizability
    from jepsen_tpu.checkers.total_queue import TotalQueue

    backend = args.checker
    workload = getattr(args, "workload", "auto")
    if workload == "auto":
        workload = _workload_of(history) if history is not None else "queue"
    pipelined = _pipelined_checkers(args, workload, hpath)
    if pipelined is not None:
        checkers = {"perf": Perf(out_dir=out_dir), **pipelined}
        if workload == "queue" and getattr(args, "wgl", False):
            from jepsen_tpu.checkers.wgl import QueueWgl

            checkers["wgl"] = QueueWgl(
                backend=backend,
                pcomp=not getattr(args, "no_pcomp", False),
            )
        return compose(checkers)
    if workload == "stream":
        from jepsen_tpu.checkers.stream_lin import StreamLinearizability

        return compose(
            {
                "perf": Perf(out_dir=out_dir),
                "stream": StreamLinearizability(
                    backend=backend,
                    append_fail=getattr(args, "append_fail", None)
                    or "definite",
                ),
            }
        )
    if workload == "elle":
        from jepsen_tpu.checkers.elle import ElleListAppend

        return compose(
            {
                "perf": Perf(out_dir=out_dir),
                "elle": ElleListAppend(
                    backend=backend,
                    model=getattr(args, "consistency_model", None)
                    or "serializable",
                ),
            }
        )
    if workload == "mutex":
        from jepsen_tpu.checkers.wgl import MutexWgl

        return compose(
            {
                "perf": Perf(out_dir=out_dir),
                "mutex": MutexWgl(
                    backend=backend,
                    pcomp=not getattr(args, "no_pcomp", False),
                ),
            }
        )
    checkers = {
        "perf": Perf(out_dir=out_dir),
        "queue": TotalQueue(backend=backend),
        "linear": QueueLinearizability(
            backend=backend,
            delivery=getattr(args, "delivery", None) or "exactly-once",
        ),
    }
    if getattr(args, "wgl", False):
        from jepsen_tpu.checkers.wgl import QueueWgl

        checkers["wgl"] = QueueWgl(
            backend=backend, pcomp=not getattr(args, "no_pcomp", False)
        )
    return compose(checkers)


def _cmd_check_procs(args, paths, workload: str, prev: dict) -> int:
    """``check --procs N`` over SEVERAL stored histories: the
    multi-process checker harness (``parallel/distributed.py``) — N
    worker processes (CPU workers: a local chip is exclusive to one
    process, so the host cores are the multi-process resource),
    deterministic size-striped file assignment, per-process multi-lane
    pipelines, one merged verdict set.  Elastic by default: dead
    workers degrade the run (requeue + quarantine + provenance)
    instead of aborting it; ``--fail-fast`` restores the loud
    no-partial-verdicts abort verbatim."""
    import os as _os

    from jepsen_tpu.checkers.protocol import VALID, merge_valid
    from jepsen_tpu.parallel.distributed import run_multiprocess_check

    opts: dict = {}
    if workload == "queue":
        opts["delivery"] = (
            getattr(args, "delivery", None)
            or prev.get("linear", {}).get("delivery")
            or "exactly-once"
        )
    elif workload == "stream":
        opts["append_fail"] = (
            getattr(args, "append_fail", None)
            or prev.get("stream", {}).get("append-fail")
            or "definite"
        )
    elif workload == "elle":
        opts["model"] = (
            getattr(args, "consistency_model", None)
            or prev.get("elle", {}).get("consistency-model")
            or "serializable"
        )
    avail = len(_os.sched_getaffinity(0))
    if getattr(args, "global_mesh", False):
        # one jax.distributed fleet, one global (hist, seq) mesh, the
        # shard_map verdict programs with cross-host collectives; the
        # verdict arrives reduced to two scalars (PIPELINE.md §Global
        # mesh) rather than per-history result sets
        t0 = time.perf_counter()
        verdict, info = run_multiprocess_check(
            workload,
            paths,
            args.procs,
            devices_per_proc=max(1, avail // args.procs),
            reduce=True,
            global_mesh=True,
            seq=max(1, getattr(args, "gm_seq", 1) or 1),
            **opts,
        )
        dt = time.perf_counter() - t0
        from jepsen_tpu.parallel.distributed import degraded_active

        deg = info.get("degraded")
        doc = {
            "valid?": verdict["invalid"] == 0
            and verdict["quarantined"] == 0,
            "verdict": verdict,
            "global_mesh": {
                "procs": info["n_procs"],
                "devices_per_proc": info["devices_per_proc"],
                "seq": info["seq"],
            },
        }
        if degraded_active(deg):
            doc["degraded"] = deg
            print(
                f"# DEGRADED check: {len(deg['dead_workers'])} dead "
                f"worker(s), {len(deg['requeued_stripes'])} requeued "
                f"stripe(s), {deg['quarantined_histories']} quarantined "
                "histories",
                file=sys.stderr,
            )
        print(json.dumps(doc, indent=1, default=_json_default))
        print(
            f"# checked {verdict['histories']} histories on one global "
            f"mesh ({info['n_procs']} processes x "
            f"{info['devices_per_proc']} devices, seq={info['seq']}) "
            f"in {dt:.2f} s",
            file=sys.stderr,
        )
        return _verdict_exit(doc["valid?"])
    t0 = time.perf_counter()
    results, info = run_multiprocess_check(
        workload,
        paths,
        args.procs,
        devices_per_proc=max(1, avail // args.procs),
        mesh=True,
        fail_fast=getattr(args, "fail_fast", False),
        **opts,
    )
    dt = time.perf_counter() - t0
    from jepsen_tpu.parallel.distributed import degraded_active

    degraded = info.get("degraded")
    if not degraded_active(degraded):
        degraded = None
    if degraded is not None:
        # the per-history copy stays machine-readable but drops each
        # dead worker's log tail — replicating the same multi-KB text
        # into every history's results.json adds nothing the pid/rc
        # fields don't already identify
        degraded = dict(degraded)
        degraded["dead_workers"] = [
            {k: v for k, v in d.items() if k != "log_tail"}
            for d in degraded.get("dead_workers", ())
        ]
    composed = []
    for p, row in zip(paths, results):
        result = dict(row)
        result[VALID] = merge_valid(
            r.get(VALID, False)
            for r in result.values()
            if isinstance(r, dict)
        )
        if degraded is not None:
            # machine-readable batch provenance beside the verdict: the
            # report's degraded row and any later triage read it from
            # results.json (attached AFTER the merge — it carries no
            # "valid?" and must never vote)
            result["degraded"] = degraded
        save_results(Path(p).parent, result)
        composed.append(result)
    if degraded is not None:
        print(
            f"# DEGRADED check: {len(degraded['dead_workers'])} dead "
            f"worker(s), {len(degraded['requeued_stripes'])} requeued "
            f"stripe(s), {degraded['quarantined_histories']} quarantined "
            f"histories (verdicts at those positions are explicit "
            f"unknowns; provenance saved in results.json)",
            file=sys.stderr,
        )
    if getattr(args, "report", False):
        # per-run artifacts for the whole tree; `jepsen-tpu report`
        # builds the cross-run index over the same pages
        from jepsen_tpu.report.render import render_run_report

        for p in paths:
            try:
                render_run_report(Path(p).parent)
            except Exception as e:  # noqa: BLE001 — verdicts already saved
                print(f"# report rendering failed for {p}: {e}",
                      file=sys.stderr)
    if len(composed) == 1:
        print(json.dumps(composed[0], indent=1, default=_json_default))
    else:
        print(
            json.dumps(
                [
                    {"history": str(p), "valid?": r[VALID]}
                    for p, r in zip(paths, composed)
                ],
                indent=1,
                default=_json_default,
            )
        )
    print(
        f"# checked {len(paths)} histories through {info['n_procs']} "
        f"processes in {dt:.2f} s",
        file=sys.stderr,
    )
    return _verdict_exit(merge_valid(r[VALID] for r in composed))


def cmd_check(args) -> int:
    from jepsen_tpu.checkers.protocol import VALID

    hpath = _resolve_history_path(Path(args.history)).resolve()
    out_dir = hpath.parent
    # inherit the contract levels the run was judged at: a live run is
    # valid at its SUT's contractual level (read-committed for AMQP tx;
    # at-least-once delivery for the queue), and a bare re-check must not
    # silently tighten the verdict
    try:
        prev = json.loads((out_dir / "results.json").read_text())
    except (OSError, ValueError):
        prev = {}
    if getattr(args, "consistency_model", None) is None:
        args.consistency_model = prev.get("elle", {}).get(
            "consistency-model"
        )
    if getattr(args, "delivery", None) is None:
        args.delivery = prev.get("linear", {}).get("delivery")
    if getattr(args, "append_fail", None) is None:
        args.append_fail = prev.get("stream", {}).get("append-fail")
    if getattr(args, "segment_ops", None):
        # the segmented engine streams the file — the whole-history
        # parse below is exactly what bounded memory must avoid
        return _cmd_check_segmented(args, hpath, out_dir)
    history = read_history(hpath)
    if getattr(args, "procs", 0) and args.procs > 1:
        workload = getattr(args, "workload", "auto")
        if workload == "auto":
            workload = _workload_of(history)
        if workload not in ("queue", "stream", "elle"):
            print(
                f"# --procs applies to the pipelined families "
                f"(queue/stream/elle); {workload} runs in-process",
                file=sys.stderr,
            )
        else:
            root = Path(args.history)
            paths = (
                _history_paths(str(root)) if root.is_dir() else [hpath]
            )
            if len(paths) > 1:
                # every history in the tree, checked as one family
                # (the resolved history's) — a mixed-family store
                # should use bench-check --pipeline per family
                return _cmd_check_procs(args, paths, workload, prev)
            print(
                "# --procs: a single history gives the worker fleet "
                "nothing to divide — running in-process (point --procs "
                "at a store tree to fan N histories across processes)",
                file=sys.stderr,
            )
    checker = _checker_for(args, out_dir=out_dir, history=history, hpath=hpath)
    log_pat = getattr(args, "log_file_pattern", None) or prev.get(
        "log-file-pattern", {}
    ).get("pattern")
    if log_pat:
        # same no-silent-loosening rule as the levels above: a run the
        # log scan invalidated must not re-check back to valid just
        # because the bare re-check forgot the pattern
        from jepsen_tpu.checkers.logpattern import LogFilePattern

        checker.checkers["log-file-pattern"] = LogFilePattern(
            log_pat, out_dir=str(out_dir)
        )
    t0 = time.perf_counter()
    result = checker.check({}, history)
    dt = time.perf_counter() - t0
    print(json.dumps(result, indent=1, default=_json_default))
    print(
        f"# checked {len(history)} ops with backend={args.checker} "
        f"in {dt * 1e3:.1f} ms",
        file=sys.stderr,
    )
    save_results(out_dir, result)
    if getattr(args, "report", False):
        from jepsen_tpu.report.render import render_run_report

        paths = render_run_report(out_dir, history=history, results=result)
        print(
            "# report: " + " ".join(sorted(paths.values())),
            file=sys.stderr,
        )
    return _verdict_exit(result[VALID])


def _cmd_check_segmented(args, hpath: Path, out_dir: Path) -> int:
    """``check --segment-ops N [--resume]``: stream the history through
    the segmented carry engine (``checkers/segmented.py``) — bounded
    memory in history length, a durable checkpoint after every
    segment, verdicts equal to the monolithic engine wherever both can
    run (SEGMENTED.md)."""
    from jepsen_tpu.checkers.protocol import VALID
    from jepsen_tpu.obs.metrics import REGISTRY
    from jepsen_tpu.parallel.pipeline import check_source_segmented

    workload = getattr(args, "workload", "auto")
    opts: dict = {}
    if getattr(args, "delivery", None):
        opts["delivery"] = args.delivery
    if getattr(args, "append_fail", None):
        opts["append_fail"] = args.append_fail
    if getattr(args, "consistency_model", None):
        opts["model"] = args.consistency_model
    t0 = time.perf_counter()
    result, stats = check_source_segmented(
        None if workload == "auto" else workload,
        hpath,
        segment_ops=args.segment_ops,
        resume=getattr(args, "resume", False),
        carry_cap=getattr(args, "carry_cap", None),
        device=args.checker == "tpu",
        prefix_index=getattr(args, "prefix_index", None),
        **opts,
    )
    dt = time.perf_counter() - t0
    print(json.dumps(result, indent=1, default=_json_default))
    meta = result["segmented"]
    sk = REGISTRY.sketch("segmented.segment_check_s")
    resumed = (
        f", resumed from segment {meta['resumed_from']}"
        if meta.get("resumed")
        else ""
    )
    pfx = meta.get("resumed_from_prefix")
    if pfx:
        print(
            f"# fleet memory: resumed from prefix anchor @ segment "
            f"{pfx['segment_idx']} (offset {pfx['offset']}, "
            f"{pfx['substrate']})",
            file=sys.stderr,
        )
    print(
        f"# segmented check: {meta['ops']} ops in {meta['segments']} "
        f"segments of {meta['segment_ops']} in {dt:.2f} s "
        f"(segment p50 {sk.quantile(0.5) * 1e3:.1f} ms / "
        f"p99 {sk.quantile(0.99) * 1e3:.1f} ms{resumed})",
        file=sys.stderr,
    )
    if meta.get("quarantined-segments"):
        print(
            f"# QUARANTINED: {meta['quarantined-segments']} poisoned "
            f"segment(s) — verdict capped at unknown with evidence",
            file=sys.stderr,
        )
    save_results(out_dir, result)
    return _verdict_exit(result[VALID])


def _valid_regex(s: str) -> str:
    """argparse type for user-supplied patterns: a clean usage error
    instead of a raw re.error traceback mid-run."""
    import re as _re

    try:
        _re.compile(s)
    except _re.error as e:
        raise argparse.ArgumentTypeError(f"invalid regex {s!r}: {e}")
    return s


def _parse_bool_flag(s: str) -> bool:
    import argparse as _argparse

    v = s.strip().lower()
    if v in ("true", "1", "yes"):
        return True
    if v in ("false", "0", "no"):
        return False
    raise _argparse.ArgumentTypeError(
        f"expected true/false, got {s!r}"
    )


def _select_family(pairs, workload: str, src: str):
    """Filter ``(kind, item)`` pairs to one family, with the mixed-store
    note; None (after an error message) when nothing remains.  One
    implementation for the worker/serial/queue/non-queue paths so the
    skip message and exit contract cannot drift apart."""
    keep = [item for kind, item in pairs if kind == workload]
    if len(keep) != len(pairs):
        print(
            f"# mixed store: benching {len(keep)} {workload} histories, "
            f"skipping {len(pairs) - len(keep)} of other families",
            file=sys.stderr,
        )
    if not keep:
        print(f"no {workload} histories under {src}", file=sys.stderr)
        return None
    return keep


def _cmd_bench_check_pipeline(args) -> int:
    """``bench-check --pipeline``: bytes-to-verdict over a stored history
    tree through the overlapped executor (``parallel/pipeline.py``) —
    native thread-pool packing on the producer thread, async H2D
    staging, device checking — with the executor's utilization evidence
    in the output JSON.  ``--serial`` runs the identical stages strictly
    serially (the triage twin: byte-identical results, no overlap)."""
    import jax

    from jepsen_tpu.parallel.pipeline import check_sources

    paths = _history_paths(args.histories)
    if not paths:
        print(f"no histories under {args.histories}", file=sys.stderr)
        return 2
    # classify each file (native tag, cache, or parse) — same majority
    # rule on auto as the serial path
    from jepsen_tpu.history.fastpack import pack_file as _fastpack
    from jepsen_tpu.history.rows import load_rows_cache, save_rows_cache

    kinds = []
    for p in paths:
        got = load_rows_cache(p)
        if got is not None:
            kinds.append(got[0])
            continue
        fast = _fastpack(p)
        if fast is not None:
            save_rows_cache(p, fast[0], fast[1])
            kinds.append(fast[0])
        else:
            kinds.append(_workload_of(read_history(p)))
    workload = getattr(args, "workload", "auto")
    if workload == "auto":
        workload = max(sorted(set(kinds)), key=kinds.count)
    if workload == "mutex" and getattr(args, "engine", "pcomp") != "pcomp":
        # an explicit --engine classic/tensor must be HONORED, not
        # silently swapped for pcomp (the engine field exists so
        # classic-vs-tensor-vs-pcomp numbers can never be conflated) —
        # those engines run through the standard batched path
        print(
            f"# --pipeline runs the mutex family's pcomp engine; "
            f"--engine {args.engine} requested — running the standard "
            f"path instead",
            file=sys.stderr,
        )
        return cmd_bench_check(args, _pipeline=False)
    keep = _select_family(list(zip(kinds, paths)), workload, args.histories)
    if keep is None:
        return 2
    opts: dict = {}
    if workload == "mutex" and getattr(args, "reduce", False):
        print(
            "error: the mutex family has no reduce mode (its device "
            "batch axis is the sub-history axis, not the history axis)",
            file=sys.stderr,
        )
        return 2
    if workload == "queue":
        opts["delivery"] = getattr(args, "delivery", None) or "exactly-once"
    elif workload == "stream":
        opts["append_fail"] = (
            getattr(args, "append_fail", None) or "definite"
        )
    elif workload == "elle":
        opts["model"] = (
            getattr(args, "consistency_model", None) or "serializable"
        )
    if getattr(args, "mesh", False):
        from jepsen_tpu.parallel.mesh import checker_mesh

        opts["mesh"] = checker_mesh()
    reduce = getattr(args, "reduce", False)
    if reduce and "mesh" not in opts:
        print(
            "error: --reduce needs --mesh (the collective reduction "
            "runs on the device mesh)",
            file=sys.stderr,
        )
        return 2
    results, stats = check_sources(
        workload,
        keep,
        chunk=getattr(args, "chunk", None) or 64,
        serial=getattr(args, "serial", False),
        lanes=getattr(args, "lanes", None),
        reduce=reduce,
        fail_fast=getattr(args, "fail_fast", False),
        **opts,
    )
    if reduce:
        n_invalid = results["invalid"]
        extra = {
            "reduce": True,
            "first_invalid": results["first_invalid"],
        }
    else:
        extra = {}
        if workload == "queue":
            n_invalid = sum(
                1
                for r in results
                if not (
                    r["queue"]["valid?"] is True
                    and r["linear"]["valid?"] is True
                )
            )
        else:
            key = workload  # stream / elle / mutex: one sub-verdict key
            n_invalid = sum(
                1 for r in results if r[key]["valid?"] is not True
            )
    print(
        json.dumps(
            {
                "histories": stats.histories,
                "batches": stats.batches,
                "mode": "serial" if getattr(args, "serial", False)
                else "pipeline",
                "lanes": stats.lanes,
                "dropped": stats.dropped,
                **extra,
                "wall_s": round(stats.wall_s, 3),
                "pipeline_e2e_histories_per_sec": round(
                    stats.histories / max(stats.wall_s, 1e-9), 1
                ),
                "stage_overlap_frac": round(stats.stage_overlap_frac, 3),
                "device_idle_frac": round(stats.device_idle_frac, 3),
                "invalid": n_invalid,
                "quarantined": stats.quarantined,
                "backend": jax.default_backend(),
            }
        )
    )
    return 0


def cmd_bench_check(args, _pipeline: bool | None = None) -> int:
    if _pipeline is None:
        _pipeline = getattr(args, "pipeline", False)
    if _pipeline and args.histories:
        return _cmd_bench_check_pipeline(args)
    from jepsen_tpu.checkers.queue_lin import queue_lin_tensor_check
    from jepsen_tpu.checkers.total_queue import total_queue_tensor_check
    from jepsen_tpu.history.encode import pack_histories, pack_row_matrices
    import jax

    workload = getattr(args, "workload", "auto")
    workers = getattr(args, "workers", 0)
    if workers < 0:
        print(f"error: --workers must be >= 0, got {workers}", file=sys.stderr)
        return 2
    if workers:
        import os as _os

        avail = len(_os.sched_getaffinity(0))
        if workers > avail:
            # on a core-starved host extra workers are pure spawn/pickle
            # overhead (measured 120 s vs 68 s serial on a 1-core box)
            print(
                f"# --workers {workers} capped to {avail} available "
                f"core(s){' — running serially' if avail <= 1 else ''}",
                file=sys.stderr,
            )
            workers = avail if avail > 1 else 0
    mats = None  # pre-exploded row matrices from parallel pack workers
    t_produce = None  # worker phase wall clock (reported as produce_s)
    packed_pre = None  # store-level packed cache hit (no assembly at all)
    store_cache_dst = None  # (root, paths) to save after a fresh pack
    pre_paths = None  # one store walk, reused by every branch below
    elle_mops = None  # (src, cell matrix, meta) triples (device inference)
    stream_mats = None  # native-exploded stream columns (file path)
    if args.histories and workload in ("auto", "queue"):
        # store-level packed cache: one file holding the ASSEMBLED
        # columns for the exact (stat-stamped) file set — a hit skips
        # per-file cache reads AND assembly (history/storecache.py)
        from jepsen_tpu.history.storecache import load_packed_store_cache

        pre_paths = _history_paths(args.histories)
        if pre_paths:
            t0 = time.perf_counter()
            packed_pre = load_packed_store_cache(args.histories, pre_paths)
            if packed_pre is not None:
                workload = "queue"
                print(
                    f"# store cache hit: {packed_pre.batch} packed "
                    f"histories in {time.perf_counter() - t0:.2f}s "
                    f"(no per-file reads, no assembly)",
                    file=sys.stderr,
                )
    if packed_pre is not None:
        pass  # nothing to produce
    elif workers and workload in ("auto", "queue") and not args.histories:
        workload = "queue"  # the synthetic default family
        # parallel host packing (the north-star wall clock): workers
        # synthesize their seed ranges and explode rows; only compact
        # row matrices cross the process boundary.  Queue-family only —
        # the other families' packers are already sub-dominant.
        from jepsen_tpu.history.parpack import synth_queue_rows_parallel

        t0 = time.perf_counter()
        mats = synth_queue_rows_parallel(
            args.count, args.ops, lost=1, workers=workers
        )
        t_produce = time.perf_counter() - t0
        print(
            f"# {workers} workers synthesized+exploded {len(mats)} "
            f"histories in {t_produce:.1f}s",
            file=sys.stderr,
        )
    elif workers and args.histories and workload in ("auto", "queue"):
        from jepsen_tpu.history.parpack import read_rows_parallel

        paths = (
            pre_paths
            if pre_paths is not None
            else _history_paths(args.histories)
        )
        if not paths:
            print(f"no histories under {args.histories}", file=sys.stderr)
            return 2
        t0 = time.perf_counter()
        tagged = read_rows_parallel(paths, workers)
        t_produce = time.perf_counter() - t0
        if workload == "auto":
            # the workers already classified each history — resolve auto
            # from their tags instead of silently dropping to the serial
            # path (advisor r3 #3); same majority rule as the serial path
            kinds = [kind for kind, _m in tagged]
            workload = max(sorted(set(kinds)), key=kinds.count)
        if workload == "queue":
            # the same family filter the serial path applies — a mixed
            # store must not have its other families checked as queue
            mats = _select_family(tagged, workload, args.histories)
            if mats is None:
                return 2
            if len(mats) == len(paths):
                # same pure-queue condition as the serial path: a first
                # check with --workers must also leave the store-level
                # packed cache behind
                store_cache_dst = (args.histories, paths)
            print(
                f"# {workers} workers read+exploded {len(tagged)} stored "
                f"histories in {t_produce:.1f}s",
                file=sys.stderr,
            )
        else:
            print(
                f"# stored histories are {workload}; --workers applies "
                f"to the queue family only — running serially",
                file=sys.stderr,
            )
    elif workers:
        print(
            f"# --workers applies to the queue workload only; running "
            f"{workload} serially",
            file=sys.stderr,
        )
    if mats is not None or packed_pre is not None:
        pass  # skip serial production entirely
    elif args.histories:
        from jepsen_tpu.history.rows import load_rows_cache, rows_with_cache

        paths = (
            pre_paths
            if pre_paths is not None
            else _history_paths(args.histories)
        )
        if not paths:
            print(f"no histories under {args.histories}", file=sys.stderr)
            return 2
        # packed-row store cache (VERDICT r3 #3; PR 7 backing): the
        # loader consults each history's `.jtc` columnar substrate
        # first (mmap'd column blocks, zero parse — COLUMNAR.md), then
        # the legacy rows.npz for pre-format stores, read ONCE per
        # file; files without a fresh cache are parsed once and the
        # ops reused (queue misses reuse them for the explode,
        # non-queue families pack from them).
        from jepsen_tpu.history.fastpack import pack_file as _fastpack
        from jepsen_tpu.history.rows import save_rows_cache

        t0 = time.perf_counter()
        kinds, parsed, rowcache = [], {}, {}
        n_fast = 0
        for p in paths:
            got = load_rows_cache(p)
            if got is not None:
                kinds.append(got[0])
                rowcache[p] = got[1]
                continue
            fast = _fastpack(p)  # native parse+classify+explode
            if fast is not None and fast[0] == "queue":
                kind, rows = fast
                save_rows_cache(p, kind, rows)  # first check cuts the cache
                kinds.append(kind)
                rowcache[p] = rows
                n_fast += 1
            elif fast is not None:
                # non-queue family: the native pass classified it; the
                # family-specific native substrates (elle_graph_file /
                # stream_rows_file) build the checker inputs below
                # without ever materializing Python Op objects.  Persist
                # the rows cache so re-checks classify from it instead
                # of re-parsing (the substrate pass is then this store's
                # only native parse)
                save_rows_cache(p, fast[0], fast[1])
                kinds.append(fast[0])
            else:
                parsed[p] = read_history(p)
                kinds.append(_workload_of(parsed[p]))
        # a store may hold several families; bench the majority on auto
        # (sorted → deterministic tie-break, favoring "elle" < "queue"
        # < "stream" alphabetically on equal counts)
        if workload == "auto":
            workload = max(sorted(set(kinds)), key=kinds.count)
        print(
            f"# loaded {len(paths)} stored histories in "
            f"{time.perf_counter() - t0:.1f}s "
            f"({len(rowcache) - n_fast} from the packed-row cache, "
            f"{n_fast} native-packed)",
            file=sys.stderr,
        )
        if workload == "queue":
            tagged = [
                (
                    kind,
                    rowcache.get(p)
                    if p in rowcache
                    else rows_with_cache(p, history=parsed.get(p))[1],
                )
                if kind == workload
                else (kind, None)
                for p, kind in zip(paths, kinds)
            ]
            mats = _select_family(tagged, workload, args.histories)
            if mats is None:
                return 2
            if len(mats) == len(paths):
                # pure-queue store: leave the assembled columns behind so
                # the next re-check skips per-file reads and assembly
                # (a mixed store stays per-file — a cached pack of a
                # subset would be ambiguous under --workload auto)
                store_cache_dst = (args.histories, paths)
        elif workload == "elle":
            # cached / native micro-op cell emission per file
            # (elle_mops.npz -> jt_elle_mops_file -> Python twin): the
            # fresh-pack path never materializes Op objects, a re-check
            # loads cells straight from the digest-keyed cache, and the
            # edge inference itself runs ON DEVICE (checkers/elle.py)
            from jepsen_tpu.history.storecache import elle_mops_with_cache

            n_hit = 0
            triples = []
            for p, kind in zip(paths, kinds):
                if kind != workload:
                    triples.append((kind, None))
                    continue
                mat, meta, hit = elle_mops_with_cache(
                    p, history=parsed.get(p)
                )
                n_hit += hit
                triples.append((kind, (p, mat, meta)))
            elle_mops = _select_family(triples, workload, args.histories)
            if elle_mops is None:
                return 2
            print(
                f"# elle cells: {n_hit} of {len(elle_mops)} histories "
                f"from the packed-cell cache",
                file=sys.stderr,
            )
        elif workload == "stream":
            # digest-cached native row explosion per file
            # (stream_rows.npz -> jt_stream_rows_file -> Python twin):
            # a re-check loads the exploded columns straight from the
            # cache, same scheme as elle_mops.npz (history/storecache)
            from jepsen_tpu.history.storecache import (
                stream_rows_with_cache,
            )

            n_hit = 0

            def _srows(p, hist):
                nonlocal n_hit
                cols, full, hit = stream_rows_with_cache(p, history=hist)
                n_hit += hit
                return cols, full

            pairs = [
                (kind, _srows(p, parsed.get(p)))
                if kind == workload
                else (kind, None)
                for p, kind in zip(paths, kinds)
            ]
            print(
                f"# stream rows: {n_hit} of "
                f"{sum(1 for k in kinds if k == workload)} histories "
                f"from the exploded-row cache",
                file=sys.stderr,
            )
            stream_mats = _select_family(pairs, workload, args.histories)
            if stream_mats is None:
                return 2
        else:
            # the mutex family packs from Op lists
            pairs = [
                (kind, parsed.get(p) or read_history(p))
                if kind == workload
                else (kind, None)
                for p, kind in zip(paths, kinds)
            ]
            histories = _select_family(pairs, workload, args.histories)
            if histories is None:
                return 2
    else:
        if workload == "stream":
            from jepsen_tpu.history.synth import (
                StreamSynthSpec,
                synth_stream_batch,
            )

            histories = [
                sh.ops
                for sh in synth_stream_batch(
                    args.count, StreamSynthSpec(n_ops=args.ops), lost=1
                )
            ]
        elif workload == "elle":
            from jepsen_tpu.history.synth import (
                ElleSynthSpec,
                synth_elle_batch,
            )

            histories = [
                sh.ops
                for sh in synth_elle_batch(
                    args.count,
                    ElleSynthSpec(n_txns=max(args.ops // 2, 8)),
                    g2_cycle=1,
                )
            ]
        elif workload == "mutex":
            from jepsen_tpu.history.synth import (
                MutexSynthSpec,
                synth_mutex_batch,
            )

            histories = [
                sh.ops
                for sh in synth_mutex_batch(
                    args.count,
                    MutexSynthSpec(n_ops=args.ops),
                    double_grant=1,
                )
            ]
        else:
            workload = "queue"
            from jepsen_tpu.history.synth import SynthSpec, synth_batch

            histories = [
                sh.ops
                for sh in synth_batch(
                    args.count, SynthSpec(n_ops=args.ops), lost=1
                )
            ]
        print(f"# generated {len(histories)} synthetic histories", file=sys.stderr)

    if getattr(args, "profile", None):
        # device + host trace of the pack/compile/check phases, viewable
        # in XProf/TensorBoard (the checker's own tracing story — the
        # analog of the reference's gnuplot perf artifacts, SURVEY.md §5)
        jax.profiler.start_trace(args.profile)

    if workload == "stream":
        from jepsen_tpu.checkers.stream_lin import (
            pack_stream_histories,
            pack_stream_rows,
            stream_lin_tensor_check,
        )

        t0 = time.perf_counter()
        packed = (
            pack_stream_rows(stream_mats)
            if stream_mats is not None
            else pack_stream_histories(histories)
        )
        t_pack = time.perf_counter() - t0
        jax.block_until_ready(stream_lin_tensor_check(packed))  # compile
        t1 = time.perf_counter()
        sl = stream_lin_tensor_check(packed)
        jax.block_until_ready(sl)
        t_check = time.perf_counter() - t1
        n_invalid = int((~sl.valid).sum())
    elif workload == "mutex":
        from jepsen_tpu.checkers.wgl import (
            check_wgl_cpu,
            fenced_mutex_wgl_ops,
            mutex_history_is_fenced,
            mutex_wgl_ops,
            pack_wgl_batch,
            wgl_tensor_check,
        )
        from jepsen_tpu.models.core import FencedMutex, OwnedMutex

        t0 = time.perf_counter()
        # per-history model selection, like the standard checker pipeline:
        # fenced histories (token-valued acquires) check token order
        pairs = [
            (fenced_mutex_wgl_ops(h), FencedMutex)
            if mutex_history_is_fenced(h)
            else (mutex_wgl_ops(h), OwnedMutex)
            for h in histories
        ]
        engine = getattr(args, "engine", "pcomp")
        if engine == "pcomp":
            # the default: P-compositional decomposition — every
            # history's per-class sub-histories pool into shape buckets
            # and check as thousands of narrow vmapped frontiers
            # (WGL_BENCH.md round 6: the measured fast path on hard
            # histories, on BOTH backends)
            from jepsen_tpu.checkers.wgl_pcomp import (
                decompose,
                pcomp_tensor_check,
            )

            decomps = [
                decompose(ops, (model, ())) for ops, model in pairs
            ]
            t_pack = time.perf_counter() - t0
            pcomp_tensor_check(decomps)  # compile
            t1 = time.perf_counter()
            ok, unknown, _info = pcomp_tensor_check(decomps)
            n_invalid = int((~ok & ~unknown).sum())
            n_unknown = int(unknown.sum())
            t_check = time.perf_counter() - t1
        elif engine == "tensor":
            # opt-in ONLY: the batched frontier-bitset device search —
            # measured ~650x slower per history than the classic host
            # search on this family (WGL_BENCH.md re-scope); it exists
            # for general-model correctness, not throughput.  One packed
            # batch per model — a compiled program is model-specific.
            by_model: dict = {}
            for ops, model in pairs:
                by_model.setdefault(model, []).append(ops)
            packs = {
                m: pack_wgl_batch(opss) for m, opss in by_model.items()
            }
            t_pack = time.perf_counter() - t0
            for m, packed in packs.items():
                wgl_tensor_check(packed, (m, ()))  # compile
            t1 = time.perf_counter()
            n_invalid = n_unknown = 0
            for m, packed in packs.items():
                ok, unknown = wgl_tensor_check(packed, (m, ()))
                n_invalid += int((~ok & ~unknown).sum())
                n_unknown += int(unknown.sum())
            t_check = time.perf_counter() - t1
        else:
            # the classic Wing-Gong host search — still the fastest
            # single-history engine on easy histories (WGL_BENCH.md)
            t_pack = time.perf_counter() - t0
            t1 = time.perf_counter()
            results = [
                check_wgl_cpu(ops, model()) for ops, model in pairs
            ]
            t_check = time.perf_counter() - t1
            # tri-state: "valid?" is True / False / the truthy string
            # "unknown" (config-cap overflow) — an undecided history is
            # neither passing nor a violation
            n_invalid = sum(1 for r in results if r["valid?"] is False)
            n_unknown = sum(
                1 for r in results if r["valid?"] not in (True, False)
            )
    elif workload == "elle":
        import numpy as np

        from jepsen_tpu.checkers.elle import (
            elle_mops_check,
            elle_mops_for,
            elle_tensor_check,
            infer_txn_graph,
            pack_txn_graphs,
        )

        from jepsen_tpu.checkers.elle import split_elle_mops

        t0 = time.perf_counter()
        if elle_mops is None:  # synthetic histories: pack in-process
            elle_mops = [(h, *elle_mops_for(h)) for h in histories]
        live_ix, packed_mops, degen_ix = split_elle_mops(
            [(m, g) for _, m, g in elle_mops]
        )
        degen = [elle_mops[i] for i in degen_ix]
        degen_batch = None
        if degen:
            # tensor-unrepresentable histories (see elle_mops_for): the
            # host inference twin stays their source of truth
            from jepsen_tpu.history.fastpack import elle_graph_file

            def _graph(src):
                if isinstance(src, list):  # synthetic ops, no file
                    return infer_txn_graph(src)
                g = elle_graph_file(src)
                return g if g is not None else infer_txn_graph(
                    read_history(src)
                )

            degen_batch = pack_txn_graphs(
                [_graph(src) for src, _, _ in degen]
            )
            print(
                f"# {len(degen)} histories fell back to host inference "
                f"(tensor-degenerate)",
                file=sys.stderr,
            )
        t_pack = time.perf_counter() - t0
        if packed_mops is not None:  # compile
            jax.block_until_ready(elle_mops_check(packed_mops))
        if degen_batch is not None:
            jax.block_until_ready(elle_tensor_check(degen_batch))
        t1 = time.perf_counter()
        n_invalid = 0
        if packed_mops is not None:
            el, _ = elle_mops_check(packed_mops)
            jax.block_until_ready(el)
            # ElleTensors.valid folds cycle + device-inferred anomalies
            n_invalid += int((~np.asarray(el.valid)).sum())
        if degen_batch is not None:
            el = elle_tensor_check(degen_batch)
            jax.block_until_ready(el)
            n_invalid += int((~np.asarray(el.valid)).sum())
        t_check = time.perf_counter() - t1
    else:
        t0 = time.perf_counter()
        if packed_pre is not None:
            packed = packed_pre
        else:
            packed = (
                pack_row_matrices(mats)
                if mats is not None
                else pack_histories(histories)
            )
        t_pack = time.perf_counter() - t0
        if packed_pre is None and store_cache_dst is not None:
            from jepsen_tpu.history.storecache import (
                save_packed_store_cache,
            )

            save_packed_store_cache(*store_cache_dst, packed)

        jax.block_until_ready(
            (total_queue_tensor_check(packed), queue_lin_tensor_check(packed))
        )  # compile
        t1 = time.perf_counter()
        tq, ql = total_queue_tensor_check(packed), queue_lin_tensor_check(packed)
        jax.block_until_ready((tq, ql))
        t_check = time.perf_counter() - t1
        n_invalid = int((~(tq.valid & ql.valid)).sum())

    if getattr(args, "profile", None):
        jax.profiler.stop_trace()
        print(f"# wrote profiler trace under {args.profile}", file=sys.stderr)
    # elle packs txn *graphs*, where .length is padded txn slots, not op
    # rows — report recorded op rows for every workload so the stat is
    # comparable across families
    if workload == "elle" and elle_mops is not None and not isinstance(
        elle_mops[0][0], list
    ):
        # store path: Op lists were never materialized — count ops as
        # non-blank JSONL lines so the stat matches the Python path's
        # max(len(history)) exactly (same store, same number either way)
        def _op_count(p):
            with open(p, "rb") as fh:
                return sum(1 for line in fh if line.strip())

        ops_per_history = max(
            _op_count(src) for src, _, _ in elle_mops
        )
    elif workload in ("elle", "mutex"):
        ops_per_history = max(len(h) for h in histories)
    else:
        ops_per_history = packed.length
    n_hist = (
        packed.batch
        if packed_pre is not None
        else len(mats)
        if mats is not None
        else len(elle_mops)
        if elle_mops is not None
        else len(stream_mats)
        if stream_mats is not None
        else len(histories)
    )
    stats_extra = {}
    if workload == "mutex":
        # tri-state honesty: a frontier overflow is undecided, which is
        # neither a pass nor a violation — surface it.  The engine field
        # keeps classic-vs-tensor numbers from ever being conflated.
        stats_extra["unknown"] = n_unknown
        stats_extra["engine"] = getattr(args, "engine", "pcomp")
    print(
        json.dumps(
            {
                "histories": n_hist,
                **stats_extra,
                "ops_per_history": ops_per_history,
                # produce_s: the parallel workers' synth/read + row
                # explosion — work that the SERIAL path counts inside
                # pack_s; reported so machine-readable stats never make
                # --workers look like packing itself got cheaper
                **(
                    {"produce_s": round(t_produce, 3)}
                    if t_produce is not None
                    else {}
                ),
                "pack_s": round(t_pack, 3),
                "check_s": round(t_check, 5),
                "histories_per_sec": round(n_hist / max(t_check, 1e-9), 1),
                "invalid": n_invalid,
                "backend": jax.default_backend(),
            }
        )
    )
    return 0


def cmd_test(args) -> int:
    import logging

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    from jepsen_tpu.control.runner import run_test
    from jepsen_tpu.suite import build_rabbitmq_test, build_sim_test

    opts = {
        "rate": args.rate,
        "time-limit": args.time_limit,
        "time-before-partition": args.time_before_partition,
        "partition-duration": args.partition_duration,
        "network-partition": args.network_partition,
        "nemesis": args.nemesis,
        "publish-confirm-timeout": args.publish_confirm_timeout / 1000.0,
        "read-timeout": args.read_timeout / 1000.0,
        "full-read-confirm-empties": args.full_read_confirm_empties,
        "recovery-sleep": args.recovery_sleep,
        "consumer-type": args.consumer_type,
        "net-ticktime": args.net_ticktime,
        "quorum-initial-group-size": args.quorum_initial_group_size,
        "dead-letter": args.dead_letter,
        "fenced": args.fenced,
        "durable": args.durable,
        "seed": args.seed,
        "mixed-extended": args.mixed_extended,
        "slow-disk-mean-ms": args.slow_disk_mean_ms,
        "slow-disk-jitter-ms": args.slow_disk_jitter_ms,
        "wire-corrupt": args.wire_corrupt,
        "wire-duplicate": args.wire_duplicate,
        "wire-delay": args.wire_delay,
        "wire-delay-ms": args.wire_delay_ms,
    }
    if args.archive_url:
        opts["archive-url"] = args.archive_url
    if args.consistency_model:
        opts["consistency-model"] = args.consistency_model
    local_cluster = None
    if args.db == "rabbitmq":
        try:
            test = build_rabbitmq_test(
                opts=opts,
                nodes=args.nodes.split(","),
                concurrency=args.concurrency,
                checker_backend=args.checker,
                store_root=args.store,
                ssh_user=args.ssh_user,
                ssh_private_key=args.ssh_private_key,
                workload=args.workload,
            )
        except (NotImplementedError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    elif args.db == "local":
        # the dress rehearsal: the full --db rabbitmq assembly (real
        # runner, native TCP clients, RabbitMQDB choreography, nemesis)
        # against local mini-broker OS processes (harness/localcluster.py)
        from jepsen_tpu.client import native as native_mod
        from jepsen_tpu.harness.localcluster import build_local_test

        # the drain once-latch (and client registry) is process-global in
        # the native driver: an earlier native run in this process would
        # otherwise make this run's drain return instantly empty
        native_mod.reset()

        # every family is multi-node-meaningful on the replicated
        # cluster: queue/mutex ops and stream/elle reads all route
        # through the Raft leader (stream reads commit through the log —
        # linearizable even from lagging followers)
        n = len(args.nodes.split(",")) if args.nodes else 3
        try:
            test, local_cluster = build_local_test(
                opts,
                n_nodes=n,
                concurrency=args.concurrency,
                checker_backend=args.checker,
                store_root=args.store,
                workload=args.workload,
                seed_bug=args.seed_bug,
                durable=args.durable,
            )
        except (NotImplementedError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    else:
        try:
            test, _cluster = build_sim_test(
                opts=opts,
                nodes=args.nodes.split(","),
                concurrency=args.concurrency,
                checker_backend=args.checker,
                store_root=args.store,
                workload=args.workload,
            )
        except (NotImplementedError, ValueError) as e:
            # e.g. an asymmetric one-way partition on the sim's
            # symmetrizing net, or a refused nemesis/surface combo —
            # a clean usage error, not a traceback
            print(f"error: {e}", file=sys.stderr)
            return 2
    if getattr(args, "log_file_pattern", None):
        # jepsen.checker/log-file-pattern: scan the collected node logs
        # for SUT-crash indicators; a match invalidates the run even
        # when the history itself looks consistent
        from jepsen_tpu.checkers.logpattern import LogFilePattern

        test.checker.checkers["log-file-pattern"] = LogFilePattern(
            args.log_file_pattern
        )
    test.report = not getattr(args, "no_report", False)
    test.cluster_telemetry = not getattr(
        args, "no_cluster_telemetry", False
    )
    monitor = None
    if args.live_check:
        from jepsen_tpu.checkers.live import attach_live_monitor_for

        monitor_key = args.workload
        if args.workload == "mutex" and args.fenced:
            # fenced runs tolerate overlapping revoked/current holds —
            # LiveMutex's double-grant rule would false-positive; the
            # fenced monitor watches token reuse instead
            monitor_key = "fenced-mutex"
        monitor = attach_live_monitor_for(test, monitor_key)
        if monitor is None:
            print(
                f"warning: --live-check has no monitor for "
                f"{args.workload!r}",
                file=sys.stderr,
            )
    try:
        run = run_test(test)
    finally:
        if local_cluster is not None:
            local_cluster.close()
    if monitor is not None:
        snap = monitor.snapshot()
        counts = ", ".join(
            f"{v} {k}" for k, v in snap["anomalies"].items()
        )
        print(
            f"# live monitor ({monitor.name}): {counts} "
            f"(of {snap['observations']} observations); "
            f"violation-so-far={snap['violation-so-far']}",
            file=sys.stderr,
        )
        if run.run_dir is not None:  # a store artifact, like results.json
            from jepsen_tpu.history.store import LIVE_FILE

            (run.run_dir / LIVE_FILE).write_text(
                json.dumps({"monitor": monitor.name, **snap}, indent=1)
            )
    print(json.dumps(run.results, indent=1, default=_json_default))
    return _verdict_exit(run.verdict)


def cmd_matrix(args) -> int:
    if args.print_configs:
        # one line of `test` CLI flags per config — the CI shell layer and
        # any external driver consume the matrix from this single source
        # of truth instead of duplicating it.  Introspection only: no
        # logging setup, no runner/suite (and hence no JAX) imports.
        from jepsen_tpu.harness.matrix import (
            CI_MATRIX,
            EXTENDED_MATRIX,
            LOCAL_EXTENDED_MATRIX,
            matrix_cli_flags,
        )

        rows = CI_MATRIX + (EXTENDED_MATRIX if args.extended else [])
        if args.extended and args.db in ("local", "rabbitmq"):
            rows += LOCAL_EXTENDED_MATRIX
        for line in matrix_cli_flags(rows):
            print(line)
        return 0

    import logging

    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
    from jepsen_tpu.control.runner import run_test
    from jepsen_tpu.harness.matrix import (
        CI_MATRIX,
        EXTENDED_MATRIX,
        LOCAL_EXTENDED_MATRIX,
        MatrixRunner,
    )
    from jepsen_tpu.suite import (
        DEFAULT_OPTS,
        build_rabbitmq_test,
        build_sim_test,
    )

    scale = args.time_scale

    def _collect_queue_lengths(db, nodes):
        # out-of-band queue-empty cross-check straight from the brokers
        # (= the reference's rabbitmqctl loop, ci/jepsen-test.sh:144-155)
        lengths: dict[str, int] = {}
        read = getattr(db, "queue_lengths_settled", None) or db.queue_lengths
        for node in nodes:
            try:
                for q, n in read(node).items():
                    lengths[f"{q}@{node}"] = n
            except Exception as e:  # noqa: BLE001 — node may be down
                logging.warning(
                    "queue-length check failed on %s: %s", node, e
                )
        return lengths

    def run_fn(opts):
        scaled = dict(opts)
        for k in ("time-limit", "time-before-partition", "partition-duration"):
            scaled[k] = opts[k] * scale
        scaled["recovery-sleep"] = DEFAULT_OPTS["recovery-sleep"] * scale
        # the dead-letter TTL must shrink with the run, or scaled-down
        # smoke runs never see an expiry and the two dead-letter configs
        # degenerate into the plain ones
        scaled["message-ttl"] = DEFAULT_OPTS["message-ttl"] * scale
        scaled["rate"] = args.rate
        if args.db == "rabbitmq":
            if args.archive_url:
                scaled["archive-url"] = args.archive_url
            nodes = args.nodes.split(",")
            test = build_rabbitmq_test(
                opts=scaled,
                nodes=nodes,
                checker_backend=args.checker,
                store_root=args.store,
                ssh_user=args.ssh_user,
                ssh_private_key=args.ssh_private_key,
            )
            run = run_test(test)
            return run.results, _collect_queue_lengths(test.db, nodes)
        if args.db == "local":
            # the dress-rehearsal cluster: every config gets a FRESH set
            # of broker OS processes (like CI's per-run clusters) and a
            # driver-registry reset (the drain once-latch is per-run)
            from jepsen_tpu.client import native as native_mod
            from jepsen_tpu.harness.localcluster import build_local_test

            native_mod.reset(drain_wait_ms=200)
            test, t = build_local_test(
                scaled,
                checker_backend=args.checker,
                store_root=args.store,
                durable=bool(scaled.get("durable")),
            )
            try:
                run = run_test(test)
                return run.results, _collect_queue_lengths(
                    test.db, test.nodes
                )
            finally:
                t.close()
        test, cluster = build_sim_test(
            opts=scaled, checker_backend=args.checker, store_root=args.store
        )
        run = run_test(test)
        return run.results, {"jepsen.queue": cluster.queue_length()}

    matrix = CI_MATRIX + (EXTENDED_MATRIX if args.extended else [])
    if args.extended and args.db in ("local", "rabbitmq"):
        # clock-skew / membership-churn need fault surfaces the sim
        # cannot honestly provide (matrix.py LOCAL_EXTENDED_MATRIX)
        matrix = matrix + LOCAL_EXTENDED_MATRIX
    if args.limit:
        matrix = matrix[: args.limit]
    outcomes = MatrixRunner(run_fn, matrix).run()
    summary = [
        {
            "config": o.config_index + 1,
            "status": o.status,
            "attempts": o.attempts,
            "nemesis": o.opts.get("nemesis", "partition"),
            "partition": o.opts.get("network-partition"),
            "notes": o.notes,
        }
        for o in outcomes
    ]
    ok = all(o.status == "valid" for o in outcomes)
    if args.pins:
        # auto-grown regression rows: replay every pinned red the
        # fuzzer/campaign minted and hold it to its recorded
        # expectation — a pin flipping green is a LOUD failure here
        # (delete the row once the fix is confirmed deliberate)
        from jepsen_tpu.fuzz.pins import replay_pins

        pin_results = replay_pins(
            args.pins, store_root=args.store,
            log=lambda s: print(s, file=sys.stderr, flush=True),
        )
        summary.append({"pins": pin_results})
        ok = ok and all(
            r.get("matched", True) for r in pin_results
        )
    # stdout is exactly the JSON summary (the CI driver tees it into
    # matrix-summary.json); the banner goes to stderr
    print(json.dumps(summary, indent=1))
    print(GOOD_BANNER if ok else INVALID_BANNER, file=sys.stderr)
    return 0 if ok else 1


def cmd_serve(args) -> int:
    from jepsen_tpu.cli.serve import serve_forever

    serve_forever(args.store, host=args.host, port=args.port)
    return 0


def cmd_serve_checker(args) -> int:
    from jepsen_tpu.service.server import serve_forever

    buckets = []
    for part in str(args.warmup_buckets).split(","):
        part = part.strip()
        if not part:
            continue
        length, space = part.split(":", 1)
        buckets.append((int(length), int(space)))
    serve_forever(
        host=args.host, port=args.port, seq=args.seq, store=args.store,
        metrics_port=args.metrics_port, workers=args.workers,
        max_streams=args.max_streams, ingress_cap=args.ingress_cap,
        stream_deadline_s=args.stream_deadline,
        batch=args.batch, target_batch=args.target_batch,
        max_batch_wait_ms=args.max_batch_wait_ms,
        warmup=args.warmup, warmup_buckets=tuple(buckets),
    )
    return 0


def cmd_campaign(args) -> int:
    """``jepsen-tpu campaign``: the continuous-campaign supervisor
    (trial plan, live services, oracle comparison, durable ledger);
    stdout is the JSON summary, the banner goes to stderr."""
    from jepsen_tpu.campaign.supervisor import CampaignSupervisor

    sup = CampaignSupervisor(
        args.out,
        seed=args.seed,
        trials=args.trials,
        n_base=args.base,
        n_ops=args.ops,
        faults=tuple(
            f.strip() for f in args.faults.split(",") if f.strip()
        ),
        pins_dir=args.pins_dir,
        resume=args.resume,
        log=lambda s: print(s, file=sys.stderr, flush=True),
    )
    summary = sup.run()
    print(json.dumps(summary, indent=1))
    complete = summary["completed"] == summary["planned"]
    if args.expect_red:
        ok = complete and summary["reds"] > 0
    else:
        ok = complete and summary["reds"] == 0
    print(GOOD_BANNER if ok else INVALID_BANNER, file=sys.stderr)
    return 0 if ok else 1


def cmd_report(args) -> int:
    """``jepsen-tpu report <store-dir>``: render any missing per-run
    reports under the tree and (re)build the cross-run ``index.html``
    (verdict/latency-headline rows + trend sparkline).  Pointed at a
    single run dir, it renders just that run's artifacts."""
    from jepsen_tpu.history.store import RESULTS_FILE

    root = Path(args.store)
    if not root.is_dir():
        print(f"error: no such store dir {root}", file=sys.stderr)
        return 2
    if (root / HISTORY_FILE).is_file() or (root / RESULTS_FILE).is_file():
        from jepsen_tpu.report.render import render_run_report

        paths = render_run_report(root)
        for name in sorted(paths):
            print(f"{name}: {paths[name]}")
        return 0
    from jepsen_tpu.report.index import build_store_index

    idx = build_store_index(root, render_missing=not args.no_render)
    if idx is None:
        print(f"no runs under {root}", file=sys.stderr)
        return 2
    print(str(idx))
    # fleet memory: the index pass refreshed <store>/baselines.json —
    # surface its regression flags here so a terminal-only consumer
    # sees the drift without opening index.html
    try:
        doc = json.loads((root / "baselines.json").read_text())
        for f in doc.get("flags") or []:
            print(
                f"# REGRESSION: {f['series']} last={f.get('last')} "
                f"baseline={f.get('baseline')} "
                f"delta={f.get('delta_pct')}%",
                file=sys.stderr,
            )
    except (OSError, ValueError):
        pass
    return 0


def cmd_trace(args) -> int:
    """Record any CLI run through the flight recorder and export a
    Perfetto/Chrome trace: ``jepsen-tpu trace [--out F] -- check ...``.

    The wrapped command re-enters :func:`main` (so backend pinning,
    compile-cache wiring, and the harvest hook behave exactly as in a
    bare invocation) with the obs tracer enabled; the artifact is
    written ONLY when the wrapped command exits 0 — the soak/fuzz
    fail-loud capture discipline (a crashed run leaves no trace file
    pretending to be evidence)."""
    rest = list(args.rest)
    if rest and rest[0] == "--":
        rest = rest[1:]
    if not rest:
        print("error: trace needs a command to record, e.g. "
              "`jepsen-tpu trace -- check --store store`",
              file=sys.stderr)
        return 2
    if rest[0] == "trace":
        print("error: trace cannot wrap itself", file=sys.stderr)
        return 2

    from jepsen_tpu.obs import export as obs_export
    from jepsen_tpu.obs import trace as obs_trace

    out = args.out
    if out is None:
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        out = os.path.join("store", f"trace_{rest[0]}_{stamp}.json")

    profile_dir = args.jax_profile
    if profile_dir:
        import jax

        jax.profiler.start_trace(profile_dir)
    obs_trace.enable(capacity=args.capacity)
    try:
        rc = main(rest)
    finally:
        obs_trace.disable()
        if profile_dir:
            import jax

            try:
                jax.profiler.stop_trace()
            except RuntimeError:
                pass  # trace never started (early arg error)
    if rc != 0:
        if getattr(args, "keep_on_failure", False):
            # failing runs are exactly the ones whose traces matter for
            # triage — keep the recording, but NEVER at the artifact
            # path: `<out>.failed` cannot be mistaken for committed
            # evidence (the soak/fuzz capture discipline)
            summary = obs_export.write_trace(
                f"{out}.failed", merge_jax_profile_dir=profile_dir or None
            )
            print(
                f"# wrapped command exited {rc}; trace kept at "
                f"{summary['path']} (--keep-on-failure; NOT evidence)",
                file=sys.stderr,
            )
        else:
            print(
                f"# trace NOT written: wrapped command exited {rc} (an "
                f"artifact only lands on a completed run; "
                f"--keep-on-failure writes {out}.failed instead)",
                file=sys.stderr,
            )
        return rc
    summary = obs_export.write_trace(
        out, merge_jax_profile_dir=profile_dir or None
    )
    if profile_dir and summary["jax_events"] == 0:
        print(
            "# note: the jax.profiler capture held no Trace-Event JSON "
            "(XSpace-only profiler build) — the trace carries host "
            "spans only",
            file=sys.stderr,
        )
    print(f"# trace: {json.dumps(summary)}")
    print(
        "# open it at https://ui.perfetto.dev (or chrome://tracing): "
        f"load {summary['path']}",
        file=sys.stderr,
    )
    return 0


def cmd_synth(args) -> int:
    store = Store(args.store)
    if getattr(args, "workload", "queue") == "stream":
        from jepsen_tpu.history.synth import StreamSynthSpec, synth_stream_batch

        shs = synth_stream_batch(
            args.count,
            StreamSynthSpec(n_ops=args.ops),
            lost=args.lost,
            duplicated=args.duplicated,
            divergent=args.divergent,
            reorder=args.reorder,
            recovered=getattr(args, "recovered", 0),
        )
    elif getattr(args, "workload", "queue") == "elle":
        from jepsen_tpu.history.synth import ElleSynthSpec, synth_elle_batch

        shs = synth_elle_batch(
            args.count,
            ElleSynthSpec(n_txns=max(args.ops // 2, 8)),
            g1a=args.g1a,
            g1b=args.g1b,
            g0_cycle=args.g0_cycle,
            g1c_cycle=args.g1c_cycle,
            g2_cycle=args.g2_cycle,
        )
    elif getattr(args, "workload", "queue") == "mutex":
        from jepsen_tpu.history.synth import MutexSynthSpec, synth_mutex_batch

        shs = synth_mutex_batch(
            args.count,
            MutexSynthSpec(n_ops=args.ops),
            double_grant=args.double_grant,
        )
    else:
        from jepsen_tpu.history.synth import SynthSpec, synth_batch

        shs = synth_batch(
            args.count,
            SynthSpec(n_ops=args.ops),
            lost=args.lost,
            duplicated=args.duplicated,
            unexpected=args.unexpected,
        )
    for i, sh in enumerate(shs):
        d = store.run_dir("synth", f"{time.strftime('%Y%m%dT%H%M%S')}-{i:04d}")
        if getattr(args, "format", "jsonl") == "edn":
            # jepsen's own on-disk layout: fixtures for its ecosystem
            store.save_history_edn(d, sh.ops)
        else:
            store.save_history(d, sh.ops)
    print(f"wrote {len(shs)} histories under {args.store}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="jepsen_tpu",
        description="TPU-native distributed-systems correctness testing",
    )
    sub = p.add_subparsers(dest="command", required=True)

    c = sub.add_parser("check", help="re-check a recorded history")
    c.add_argument("history", help="history.jsonl, run dir, or store root")
    c.add_argument(
        "--checker",
        choices=("tpu", "cpu"),
        default="tpu",
        help="analysis backend (the north-star dispatch seam)",
    )
    c.add_argument(
        "--consistency-model",
        choices=("serializable", "read-committed"),
        default=None,
        help="elle histories: isolation level to check against "
        "(default: the level recorded with the run's results, else "
        "serializable — so re-checking a live run that passed at its "
        "SUT's contractual level doesn't silently tighten it)",
    )
    c.add_argument(
        "--log-file-pattern",
        default=None,
        type=_valid_regex,
        metavar="REGEX",
        help="re-scan the run's collected node logs for this pattern "
        "(default: the pattern recorded with the run's results, if "
        "any — a log-invalidated run must not re-check back to valid)",
    )
    c.add_argument(
        "--delivery",
        choices=("exactly-once", "at-least-once"),
        default=None,
        help="queue histories: the SUT's delivery contract (default: the "
        "contract recorded with the run's results, else exactly-once — "
        "same no-silent-tightening rule as --consistency-model)",
    )
    c.add_argument(
        "--append-fail",
        dest="append_fail",
        choices=("definite", "indeterminate"),
        default=None,
        help="stream histories: whether a fail-typed append is "
        "authoritative (sim: definite, a read of it is a phantom) or "
        "the client's verdict only (real sockets: indeterminate, a "
        "materialized one is `recovered`); default: the contract "
        "recorded with the run's results, else definite",
    )
    c.add_argument(
        "--wgl",
        action="store_true",
        help="also run the full Wing-Gong linearizability search "
        "(in addition to the per-value decomposition)",
    )
    c.add_argument(
        "--serial",
        action="store_true",
        help="triage escape hatch: check from re-packed Op objects on "
        "the calling thread instead of the bytes-to-verdict pipeline "
        "executor (--checker tpu routes queue/stream/elle/mutex through "
        "parallel/pipeline.py by default; results are identical)",
    )
    c.add_argument(
        "--no-pcomp",
        dest="no_pcomp",
        action="store_true",
        help="mutex/queue WGL: disable the P-compositional decomposition "
        "(checkers/wgl_pcomp.py — thousands of narrow per-class "
        "frontiers, the measured fast path) and run the monolithic "
        "engine instead; verdicts are identical on single-lock "
        "histories (differential gate in tests/test_wgl_pcomp.py)",
    )
    c.add_argument(
        "--workload",
        choices=("auto", "queue", "stream", "elle", "mutex"),
        default="auto",
        help="checker family; auto-detected from the history's op kinds",
    )
    c.add_argument(
        "--report",
        action="store_true",
        help="after the check, render the per-run report artifacts into "
        "the run dir (report.html latency/throughput panels with "
        "nemesis windows shaded, timeline.html per-process op "
        "timeline, forensics.html on an invalid verdict — "
        "jepsen_tpu/report/)",
    )
    c.add_argument(
        "--procs",
        type=int,
        default=0,
        help="multi-process checking of a STORE TREE: spawn N checker "
        "worker processes (parallel/distributed.py) — deterministic "
        "size-striped assignment of every history under the tree, "
        "per-process multi-lane pipelines (CPU workers: a chip is "
        "exclusive to one process, so host cores are the multi-process "
        "resource), one merged verdict set.  ELASTIC by default: a "
        "dead/wedged worker's stripes requeue onto the survivors with "
        "bounded retry, exhausted stripes quarantine as explicit "
        "unknowns, and the merged verdict carries machine-readable "
        "degraded provenance.  A single history falls back to the "
        "in-process pipeline",
    )
    c.add_argument(
        "--fail-fast",
        dest="fail_fast",
        action="store_true",
        help="disable elastic degradation: any stage/worker failure "
        "aborts the whole run loudly with no partial verdicts (the "
        "pre-PR-13 PipelineError / DistributedCheckError contract, "
        "preserved verbatim — the triage escape hatch)",
    )
    c.add_argument(
        "--global-mesh",
        dest="global_mesh",
        action="store_true",
        help="with --procs N: the workers join ONE jax.distributed "
        "fleet and run the shard_map verdict programs over one global "
        "(hist, seq) mesh — collectives cross the host boundary (gloo "
        "on CPU) and each process feeds its own input lane; the "
        "verdict arrives device-reduced (two scalars), host deaths "
        "degrade by generation restart (queue/elle workloads)",
    )
    c.add_argument(
        "--gm-seq",
        dest="gm_seq",
        type=int,
        default=1,
        help="with --global-mesh: seq-axis extent of the global mesh "
        "(must be a multiple of --procs; >1 shards the packed "
        "transitive-closure plane axis ACROSS hosts)",
    )
    c.add_argument(
        "--segment-ops",
        dest="segment_ops",
        type=int,
        default=0,
        metavar="N",
        help="segmented online checking (SEGMENTED.md): stream the "
        "history N ops at a time through the carry engine — bounded "
        "memory in history length, a CRC'd checkpoint after every "
        "segment (tmp→fsync→rename beside the history), verdicts "
        "equal to the monolithic engine wherever both can run; a "
        "poisoned segment quarantines the verdict as unknown WITH "
        "evidence, never silently",
    )
    c.add_argument(
        "--resume",
        action="store_true",
        help="with --segment-ops: continue from the newest valid "
        "checkpoint (torn/corrupt ones are refused loudly and fall "
        "back to the previous, then to a from-scratch run); the "
        "resumed check reaches the identical verdict — proof harness "
        "in tools/chaos_check.py --segmented",
    )
    c.add_argument(
        "--carry-cap",
        dest="carry_cap",
        type=int,
        default=None,
        metavar="OPS",
        help="with --segment-ops on the mutex family: bound the "
        "open-class carry; a class that outgrows the cap escalates "
        "the verdict to unknown with the class named (the PR-8 "
        "honesty rule — never a silent truncation)",
    )
    c.add_argument(
        "--prefix-index",
        dest="prefix_index",
        default=None,
        metavar="DIR",
        help="with --segment-ops: fleet memory (SEGMENTED.md §Prefix "
        "resume) — publish every full-segment checkpoint into a "
        "content-hash-keyed index under DIR, and resume a "
        "re-submitted history from the deepest anchor whose "
        "(prefix sha256, offset) matches its bytes; the verdict is "
        "identical to from-zero, with resumed_from_prefix provenance "
        "in the result",
    )
    c.set_defaults(fn=cmd_check)

    b = sub.add_parser(
        "bench-check", help="batched replay of stored/synthetic histories"
    )
    b.add_argument("--histories", help="dir tree containing history.jsonl files")
    b.add_argument("--count", type=int, default=256, help="synthetic histories")
    b.add_argument("--ops", type=int, default=470, help="invocations per history")
    b.add_argument(
        "--workload",
        choices=("auto", "queue", "stream", "elle", "mutex"),
        default="auto",
    )
    b.add_argument(
        "--engine",
        choices=("classic", "tensor", "pcomp"),
        default="pcomp",
        help="mutex workload only: 'pcomp' (default) decomposes each "
        "history into per-class sub-histories and vmaps narrow frontier "
        "searches over them (checkers/wgl_pcomp.py — the measured fast "
        "path, WGL_BENCH.md round 6); 'classic' is the monolithic "
        "Wing-Gong host search; 'tensor' the monolithic batched device "
        "frontier search (kept for general-model correctness)",
    )
    b.add_argument(
        "--profile",
        help="write a jax.profiler (XProf) trace of the check to this dir",
    )
    b.add_argument(
        "--workers",
        type=int,
        default=0,
        help="parallel host-packing worker processes (queue workload "
        "only): workers synthesize their seed ranges / read their file "
        "chunks and explode rows; the device check is unchanged",
    )
    b.add_argument(
        "--pipeline",
        action="store_true",
        help="route stored-history checking (--histories; queue/stream/"
        "elle) through the overlapped bytes-to-verdict executor "
        "(parallel/pipeline.py): native thread-pool packing on a "
        "producer thread, async H2D staging, device checking — reports "
        "pipeline_e2e_histories_per_sec / stage_overlap_frac / "
        "device_idle_frac",
    )
    b.add_argument(
        "--serial",
        action="store_true",
        help="with --pipeline: run the identical stages strictly "
        "serially on the calling thread (triage twin — byte-identical "
        "results, no overlap)",
    )
    b.add_argument(
        "--fail-fast",
        dest="fail_fast",
        action="store_true",
        help="with --pipeline: disable the elastic per-chunk "
        "quarantine — any stage failure aborts the whole batch with "
        "PipelineError (the pre-PR-13 contract; also the baseline the "
        "bench's elastic_overhead section compares against)",
    )
    b.add_argument(
        "--chunk",
        type=int,
        default=64,
        help="with --pipeline: histories per pipeline chunk",
    )
    b.add_argument(
        "--mesh",
        action="store_true",
        help="with --pipeline: stage batches through the device mesh "
        "(parallel/mesh.py sharded dispatch over all devices)",
    )
    b.add_argument(
        "--lanes",
        type=int,
        default=None,
        metavar="N",
        help="with --pipeline: per-device input lanes — one producer "
        "thread + staging slot per device, size-aware largest-first "
        "unit balancing with steal-on-idle (0 = one lane per local "
        "device); unreadable/zero-length files are dropped loudly and "
        "counted in the stats",
    )
    b.add_argument(
        "--reduce",
        action="store_true",
        help="with --pipeline --mesh: collective verdict reduction — "
        "per-shard verdicts psum/index-pmin'ed ON DEVICE, the host "
        "receives one {invalid, first_invalid} pair per batch instead "
        "of per-history gathers",
    )
    b.add_argument(
        "--delivery",
        choices=("exactly-once", "at-least-once"),
        default=None,
        help="queue histories: delivery contract for the "
        "linearizability sub-checker (--pipeline path)",
    )
    b.add_argument(
        "--append-fail",
        dest="append_fail",
        choices=("definite", "indeterminate"),
        default=None,
        help="stream histories: fail-typed append contract "
        "(--pipeline path)",
    )
    b.add_argument(
        "--consistency-model",
        choices=("serializable", "read-committed"),
        default=None,
        help="elle histories: isolation level (--pipeline path)",
    )
    b.set_defaults(fn=cmd_bench_check)

    t = sub.add_parser(
        "test", help="run a quorum-queue partition test (reference flags)"
    )
    t.add_argument("--nodes", default="n1,n2,n3", help="comma-separated nodes")
    t.add_argument("--concurrency", type=int, default=5)
    t.add_argument("--db", choices=("sim", "local", "rabbitmq"), default="sim")
    t.add_argument(
        "--workload",
        choices=("queue", "stream", "elle", "mutex"),
        default="queue",
        help="test program: quorum-queue (reference), stream append/read, "
        "elle list-append transactions, or the legacy mutex variant "
        "(sim, or live as a single-token quorum-queue lock)",
    )
    t.add_argument("--store", default="store")
    t.add_argument("--checker", choices=("tpu", "cpu"), default="tpu")
    t.add_argument(
        "--seed-bug",
        choices=(
            "confirm-before-quorum",
            "drop-unacked-on-close",
            "ack-before-fsync",
            "no-wire-checksum",
        ),
        default=None,
        help="(--db local) inject a replication bug into every broker "
        "node: confirm-before-quorum acknowledges publishes on leader-"
        "local append (a partition+heal truncates confirmed writes); "
        "drop-unacked-on-close discards a dying connection's un-acked "
        "deliveries instead of requeueing them (the delivery plane's "
        "loss mode); ack-before-fsync commits against the in-memory log "
        "while the WAL falls behind (needs --durable + --nemesis "
        "crash-restart-cluster to surface); no-wire-checksum sends peer "
        "RPC frames without the integrity CRC, so wire corruption "
        "(--nemesis wire-chaos) is PROCESSED instead of dropped and the "
        "replicas diverge — either way the checker must go red",
    )
    t.add_argument(
        "--durable",
        action="store_true",
        help="(--db local) persist each broker node's Raft log + "
        "term/vote to a per-node data dir that survives SIGKILL — the "
        "real quorum-queue durability contract; enables the "
        "crash-restart-cluster power-failure nemesis to run green",
    )
    # the reference's cli-opts (rabbitmq.clj:288-327)
    t.add_argument(
        "-r", "--rate", type=float, default=50.0, help="ops/sec"
    )
    t.add_argument("--time-limit", type=float, default=30.0)
    t.add_argument("--time-before-partition", type=float, default=10.0)
    t.add_argument("--partition-duration", type=float, default=10.0)
    t.add_argument(
        "--network-partition",
        default="partition-random-halves",
        choices=(
            "partition-random-halves",
            "random-partition-halves",  # the reference's spelling (same)
            "partition-halves",
            "partition-majorities-ring",
            "partition-random-node",
            "partition-leader",
            "partition-one-way-in",
            "partition-one-way-out",
        ),
        help="the reference's four topologies (random-partition-halves "
        "is the reference's spelling of partition-random-halves; both "
        "parse), the targeted partition-leader (isolate the current "
        "Raft leader; --db local), plus the ASYMMETRIC pair: "
        "partition-one-way-in (a victim hears nobody, everyone hears "
        "it) and partition-one-way-out (nobody hears a victim, it "
        "hears everyone) — one-way drops need a direction-honoring "
        "net (--db local / rabbitmq; the sim symmetrizes and refuses)",
    )
    t.add_argument(
        "--log-file-pattern",
        default=None,
        type=_valid_regex,
        metavar="REGEX",
        help="scan the node logs collected into the store for this "
        "pattern (e.g. 'CRASH REPORT|Segmentation fault') and "
        "invalidate the run on any match — jepsen.checker/"
        "log-file-pattern; the SUT can be broken even when the "
        "history looks consistent",
    )
    t.add_argument(
        "--no-report",
        dest="no_report",
        action="store_true",
        help="skip the default-on per-run report artifacts "
        "(report.html/timeline.html — jepsen writes store/report for "
        "every run; this framework now does too)",
    )
    t.add_argument(
        "--no-cluster-telemetry",
        dest="no_cluster_telemetry",
        action="store_true",
        help="skip the default-on ~1 Hz cluster telemetry poller "
        "(per-node Raft/broker internals sampled over the admin STATS "
        "command into cluster.json + the report's cluster panel; "
        "jepsen_tpu/obs/cluster.py)",
    )
    t.add_argument(
        "--live-check",
        action="store_true",
        help="attach the mid-run anomaly monitor (all workloads: flags "
        "monotone anomalies — unexpected/duplicated deliveries, "
        "divergent/phantom/non-monotone stream reads, contradictory or "
        "failed-write txn reads, mutex double grants — the moment they "
        "are recorded, instead of only post-hoc)",
    )
    t.add_argument(
        "--nemesis",
        default="partition",
        choices=(
            "partition",
            "kill-random-node",
            "pause-random-node",
            "crash-restart-cluster",
            "clock-skew",
            "membership-churn",
            "slow-disk",
            "wire-chaos",
            "mixed",
        ),
        help="fault family: the reference's network partitions (shaped by "
        "--network-partition), process kill/pause of a random node, "
        "the whole-cluster power failure (SIGKILL every node, restart — "
        "pair with --durable or the checker will rightly flag loss), "
        "clock-skew (bump a random node's wall clock ±0.1-3s; not --db "
        "sim), membership-churn (kill a node, forget_cluster_node it - "
        "a real RemoveServer commit - then fresh rejoin on heal; needs "
        ">=3 nodes), slow-disk (fsync latency on a random node's WAL; "
        "needs --durable), wire-chaos (corrupt/duplicate/reorder a "
        "random node's peer frames; --db local/rabbitmq), or mixed "
        "(the jepsen.nemesis/compose soak: each cycle randomly picks "
        "partition/kill/pause/clock-skew/membership-churn, plus "
        "crash-restart when --durable; --mixed-extended adds the two "
        "new families to the draw)",
    )
    t.add_argument(
        "--mixed-extended",
        action="store_true",
        help="--nemesis mixed: add slow-disk (when --durable) and "
        "wire-chaos to the family draw (kept opt-in so default mixed "
        "schedules stay comparable with committed soak evidence)",
    )
    t.add_argument(
        "--slow-disk-mean-ms", type=float, default=120.0,
        help="slow-disk nemesis: mean injected fsync latency",
    )
    t.add_argument(
        "--slow-disk-jitter-ms", type=float, default=80.0,
        help="slow-disk nemesis: uniform +/- jitter on each fsync",
    )
    t.add_argument(
        "--wire-corrupt", type=float, default=0.25,
        help="wire-chaos: per-frame corruption probability [0,1]",
    )
    t.add_argument(
        "--wire-duplicate", type=float, default=0.15,
        help="wire-chaos: per-frame duplication probability "
        "(idempotent protocol RPCs only)",
    )
    t.add_argument(
        "--wire-delay", type=float, default=0.15,
        help="wire-chaos: per-frame delay/reorder probability",
    )
    t.add_argument(
        "--wire-delay-ms", type=float, default=40.0,
        help="wire-chaos: held-frame delay (concurrent frames overtake)",
    )
    t.add_argument(
        "--publish-confirm-timeout", type=float, default=5000.0, help="ms"
    )
    t.add_argument(
        "--consistency-model",
        choices=("serializable", "read-committed"),
        default=None,
        help="elle workload: the isolation level to check the SUT "
        "against (default: serializable for --db sim, read-committed "
        "for live brokers — AMQP tx promises atomic commit visibility, "
        "not read isolation, so G2 cycles are its contract)",
    )
    t.add_argument(
        "--read-timeout",
        type=float,
        default=5000.0,
        help="ms; stream workload: how long a cursor read waits for "
        "records (a live AMQP read at the log tail holds its consumer "
        "open this long when nothing arrives)",
    )
    t.add_argument(
        "--full-read-confirm-empties",
        type=int,
        default=1,
        help="stream workload: extra empty read batches required to "
        "conclude end-of-log on the final read when no offset proof is "
        "available (the x-stream-offset=last probe is tried first)",
    )
    t.add_argument("--recovery-sleep", type=float, default=20.0)
    t.add_argument(
        "--consumer-type",
        # the reference's default (rabbitmq.clj:253); was "polling" here
        # through round 3 — see MIGRATION.md's renames/defaults table
        default="asynchronous",
        choices=("asynchronous", "polling", "mixed"),
    )
    t.add_argument("--net-ticktime", type=int, default=15)
    t.add_argument(
        "--seed",
        type=int,
        default=0,
        help="workload-generator seed (elle micro-op mix; distinct "
        "trials should not replay identical txn programs)",
    )
    t.add_argument("--quorum-initial-group-size", type=int, default=0)
    t.add_argument(
        "--fenced",
        action="store_true",
        help="mutex workload: fencing-token mode — acquire returns a "
        "monotonically increasing token (the Raft log index of the "
        "grant commit), releases/protected operations carry it, and the "
        "broker REJECTS operations bearing a superseded token.  The "
        "same revocation schedules that double-grant the unfenced lock "
        "(kill/pause past the dead-owner window) then soak green: the "
        "checker verifies token order (FencedMutex model) instead of "
        "hold exclusivity, and a revoked holder degrades to a failed "
        "release + acquire-retry instead of split-brain",
    )
    t.add_argument(
        "--dead-letter",
        # the reference CI passes a VALUE ("--dead-letter true",
        # ci/jepsen-test.sh:105-107); bare --dead-letter also works.
        # Unrecognized values ERROR rather than silently meaning False —
        # a typo must not run the suite without the config it names.
        nargs="?",
        const=True,
        default=False,
        type=_parse_bool_flag,
    )
    t.add_argument(
        "--archive-url",
        default=None,
        help="RabbitMQ generic-unix archive (--db rabbitmq)",
    )
    t.add_argument("--ssh-user", default="root")
    t.add_argument("--ssh-private-key", default=None)
    t.set_defaults(fn=cmd_test)

    m = sub.add_parser(
        "matrix",
        help="run the CI test matrix (the reference's 14 configs; 18 with "
        "--extended, 25 with --extended --db local) against sim or "
        "rabbitmq — or generate configs beyond any static list with "
        "tools/fuzz_matrix.py",
    )
    m.add_argument("--limit", type=int, default=0, help="first N configs only")
    m.add_argument(
        "--time-scale",
        type=float,
        default=1.0,
        help="scale factor on all durations (smoke runs: ~0.01)",
    )
    m.add_argument("--rate", type=float, default=50.0)
    m.add_argument("--checker", choices=("tpu", "cpu"), default="tpu")
    m.add_argument("--store", default="store")
    m.add_argument("--db", choices=("sim", "local", "rabbitmq"), default="sim")
    m.add_argument("--nodes", default="n1,n2,n3")
    m.add_argument("--archive-url", default=None)
    m.add_argument("--ssh-user", default="root")
    m.add_argument("--ssh-private-key", default=None)
    m.add_argument(
        "--print-configs",
        action="store_true",
        help="print each matrix config as `test` CLI flags and exit",
    )
    m.add_argument(
        "--extended",
        action="store_true",
        help="append the extended configs (process-fault nemeses) to the "
        "reference's 14",
    )
    m.add_argument(
        "--pins",
        default=None,
        metavar="DIR",
        help="also replay the auto-grown regression corpus "
        "(fuzz_pins.json in DIR — rows minted by tools/fuzz_matrix.py "
        "and the campaign supervisor) and hold each pin to its "
        "recorded expectation",
    )
    m.set_defaults(fn=cmd_matrix)

    w = sub.add_parser("serve", help="browse recorded runs over the web")
    w.add_argument("--store", default="store")
    w.add_argument("--host", default="0.0.0.0")
    w.add_argument("--port", type=int, default=8080)
    w.set_defaults(fn=cmd_serve)

    sc = sub.add_parser(
        "serve-checker",
        help="run the TPU checker sidecar (RPC over packed int32 tensors)",
    )
    sc.add_argument("--host", default="0.0.0.0")
    sc.add_argument("--port", type=int, default=8640)
    sc.add_argument(
        "--seq",
        type=int,
        default=1,
        help="seq-parallel shards per history on the device mesh "
        "(multi-device runtimes shard batches across all devices)",
    )
    sc.add_argument(
        "--store",
        default="store",
        help="store root (the persistent XLA compile cache lives under "
        "<store>/xla_cache, shared with the CLI)",
    )
    sc.add_argument(
        "--metrics-port",
        type=int,
        default=9640,
        help="Prometheus-style text /metrics endpoint (p50/p99 check "
        "latency from the shared obs registry); 0 = ephemeral port, "
        "-1 = off",
    )
    sc.add_argument(
        "--workers",
        type=int,
        default=2,
        help="streaming-ingest checker workers (each runs segmented "
        "carry engines; a dead worker's streams requeue onto survivors)",
    )
    sc.add_argument(
        "--max-streams",
        type=int,
        default=256,
        help="admission cap on concurrently open streams — opens past "
        "it are rejected SATURATED, never queued silently",
    )
    sc.add_argument(
        "--ingress-cap",
        type=int,
        default=1024,
        help="total buffered-but-unchecked blocks across all streams; "
        "feeds past it are rejected SATURATED (backpressure, not drop)",
    )
    sc.add_argument(
        "--stream-deadline",
        type=float,
        default=120.0,
        help="seconds an open stream may sit idle before it is "
        "quarantined as overdue (unknown-with-evidence, slot freed)",
    )
    sc.add_argument(
        "--batch",
        action="store_true",
        help="continuous batching: coalesce ready segments across ALL "
        "admitted streams into full shape-bucketed super-batches "
        "(carries never mix — batching crosses streams only on the "
        "history axis), dispatched at target size or the latency "
        "budget, whichever first",
    )
    sc.add_argument(
        "--target-batch",
        type=int,
        default=32,
        help="--batch: segments per coalesced super-batch (the device "
        "batch width is the next pow2)",
    )
    sc.add_argument(
        "--max-batch-wait-ms",
        type=float,
        default=25.0,
        help="--batch: latency budget — a bucket's oldest parked "
        "segment never waits longer than this before dispatch, even "
        "in a partial batch (deadline-aware, never starvation)",
    )
    sc.add_argument(
        "--warmup",
        action="store_true",
        help="--batch: AOT-precompile the configured bucket set at "
        "service start (into the persistent XLA compile cache where "
        "enabled) so a cold bucket's first super-batch pays no "
        "compile on the latency path; hits/misses on /metrics",
    )
    sc.add_argument(
        "--warmup-buckets",
        default="128:128,256:256",
        help="--warmup: comma-separated L:V shape buckets to "
        "precompile (pow2 row/value size classes)",
    )
    sc.set_defaults(fn=cmd_serve_checker)

    cp = sub.add_parser(
        "campaign",
        help="run a crash-recoverable continuous campaign: service "
        "trials over {stream rate x admission pressure x checker-side "
        "fault}, every verdict held to a serial oracle, journaled to a "
        "durable ledger so SIGKILL -> --resume lands on the identical "
        "verdict set",
    )
    cp.add_argument("--out", required=True,
                    help="campaign dir (ledger + per-service stores)")
    cp.add_argument("--seed", type=int, default=17)
    cp.add_argument("--trials", type=int, default=8)
    cp.add_argument("--base", type=int, default=4,
                    help="distinct corpus histories (one carries a "
                    "known loss)")
    cp.add_argument("--ops", type=int, default=160,
                    help="ops per corpus history")
    cp.add_argument(
        "--faults",
        default=",".join(
            ("none", "kill-worker", "service-restart",
             "torn-subscription")
        ),
        help="comma list of checker-side faults the plan samples "
        "(drop service-restart for subprocess-free smoke runs)",
    )
    cp.add_argument("--pins-dir", default=None,
                    help="pin any minimized red into this dir's "
                    "fuzz_pins.json (the matrix replays it)")
    cp.add_argument("--resume", action="store_true",
                    help="resume from the ledger in --out (skips the "
                    "journaled prefix; refuses a foreign campaign)")
    cp.add_argument("--expect-red", action="store_true",
                    help="exit non-zero unless a red was found and "
                    "pinned (pair with the force-red chaos hook)")
    cp.set_defaults(fn=cmd_campaign)

    rp = sub.add_parser(
        "report",
        help="render run reports + the cross-run index.html for a "
        "store tree (jepsen_tpu/report/; runs a single run dir too)",
    )
    rp.add_argument(
        "store",
        help="store root (index + any missing per-run reports) or a "
        "single run dir (that run's artifacts only)",
    )
    rp.add_argument(
        "--no-render",
        action="store_true",
        help="index only what already has a report.json; render "
        "nothing new",
    )
    rp.set_defaults(fn=cmd_report)

    tr = sub.add_parser(
        "trace",
        help="record any CLI run through the flight recorder and "
        "export a Perfetto trace (obs/OBSERVABILITY.md)",
    )
    tr.add_argument(
        "--out",
        default=None,
        help="trace artifact path (default: "
        "store/trace_<cmd>_<utc-stamp>.json); written only when the "
        "wrapped command exits 0",
    )
    tr.add_argument(
        "--capacity",
        type=int,
        default=1 << 16,
        help="span ring capacity (oldest records drop past it)",
    )
    tr.add_argument(
        "--keep-on-failure",
        dest="keep_on_failure",
        action="store_true",
        help="when the wrapped command exits non-zero, still export the "
        "recording — to <out>.failed, never the artifact path "
        "(failing runs are the ones whose traces matter; the .failed "
        "suffix keeps the capture discipline honest)",
    )
    tr.add_argument(
        "--jax-profile",
        default=None,
        metavar="DIR",
        help="also run jax.profiler over the wrapped command and merge "
        "any Trace-Event JSON it leaves under DIR (profiler builds that "
        "emit only XSpace protobufs merge 0 events, reported honestly)",
    )
    tr.add_argument(
        "rest",
        nargs=argparse.REMAINDER,
        help="the command to record (prefix with -- to end trace's own "
        "flags), e.g. `trace -- check --store store --checker tpu`",
    )
    tr.set_defaults(fn=cmd_trace)

    s = sub.add_parser("synth", help="generate synthetic histories into a store")
    s.add_argument(
        "--format",
        choices=("jsonl", "edn"),
        default="jsonl",
        help="history file format (edn = jepsen's own layout, e.g. for "
        "feeding jepsen-ecosystem tooling)",
    )
    s.add_argument("--store", default="store", help="store root dir")
    s.add_argument(
        "--workload",
        choices=("queue", "stream", "elle", "mutex"),
        default="queue",
    )
    s.add_argument("--count", type=int, default=16)
    s.add_argument("--ops", type=int, default=470)
    s.add_argument("--lost", type=int, default=0)
    s.add_argument("--duplicated", type=int, default=0)
    s.add_argument("--unexpected", type=int, default=0, help="queue workload")
    s.add_argument("--divergent", type=int, default=0, help="stream workload")
    s.add_argument(
        "--recovered", type=int, default=0,
        help="stream workload: appends completed FAIL whose value is in "
        "the log anyway (phantom under --append-fail definite, recovered "
        "under indeterminate)",
    )
    s.add_argument("--reorder", type=int, default=0, help="stream workload")
    s.add_argument("--g1a", type=int, default=0, help="elle workload")
    s.add_argument("--g1b", type=int, default=0, help="elle workload")
    s.add_argument("--g0-cycle", type=int, default=0, help="elle workload")
    s.add_argument("--g1c-cycle", type=int, default=0, help="elle workload")
    s.add_argument("--g2-cycle", type=int, default=0, help="elle workload")
    s.add_argument(
        "--double-grant", type=int, default=0, help="mutex workload"
    )
    s.set_defaults(fn=cmd_synth)

    return p


def _wants_device_backend(args) -> bool:
    """True when the subcommand benefits from the real default backend
    (a TPU if the environment has one)."""
    if args.command in ("synth", "serve", "report"):
        # host-only work (report's windowed-stats kernel is a tiny CPU
        # dispatch; rendering must never hang on a wedged chip tunnel)
        return False
    if args.command in ("bench-check", "serve-checker"):
        return True  # device-throughput measurement / checker sidecar
    if args.command == "trace":
        return True  # the WRAPPED command decides on re-entry; pinning
        # here would override its choice before it parses
    if getattr(args, "print_configs", False):
        return False  # matrix introspection runs no checks
    return getattr(args, "checker", None) == "tpu"


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from jepsen_tpu.utils.jaxenv import (
        enable_compilation_cache,
        ensure_backend,
        pin_cpu_platform,
    )

    cache_dir = os.path.join(
        getattr(args, "store", None) or "store", "xla_cache"
    )
    if not _wants_device_backend(args):
        # no device compute on these paths — never touch a chip plugin
        pin_cpu_platform()
    elif args.command not in ("serve-checker", "trace"):
        # the sidecar guards its own init; trace defers to the wrapped
        # command's own main() pass
        try:
            backend = ensure_backend()
            # persistent XLA compile cache under the store
            # (env-overridable via JEPSEN_TPU_COMPILE_CACHE): the WGL
            # engine's 20–66 s per-bucket compiles must be paid once per
            # store, not once per process (VERDICT r4 weak #4).  Non-TPU
            # backends cache too, in a machine-fingerprinted subdir —
            # the CPU AOT loader rejects entries over machine-feature
            # drift, so the fingerprint keys them (jaxenv docstring)
            enable_compilation_cache(cache_dir, backend=backend)
            if backend == "tpu":
                # the tunnel answers RIGHT NOW — the moment a chip bench
                # capture must not be missed (VERDICT r3 #1)
                from jepsen_tpu.utils.harvest import opportunistic

                opportunistic()
        except TimeoutError as e:
            print(
                f"# warning: {e}; falling back to the CPU backend",
                file=sys.stderr,
            )
            pin_cpu_platform()
            enable_compilation_cache(cache_dir, backend="cpu")
    try:
        return args.fn(args)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
