"""Generator algebra: the workload program.

Replicates the ``jepsen.generator`` combinators the reference composes
(``/root/reference/rabbitmq/src/main/clojure/jepsen/rabbitmq.clj:267-284``):
``mix``, ``delay`` (rate limiting), ``nemesis`` (op routing), ``phases``,
``time-limit``, ``once``, ``log``, ``sleep``, ``clients``, ``each-thread``,
plus ``cycle`` (used for the partition start/stop loop).

Execution model: worker threads (one per logical process, plus the nemesis)
ask a shared :class:`Scheduler` for their next op.  The scheduler serializes
access to the generator tree with one lock and hands each thread either an
invoke op, a wake-up deadline (rate limit / sleep), or exhaustion.  This
mirrors Jepsen's pure-generator interpreter semantics at the points the
reference exercises:

- ``mix`` draws each op from a random sub-generator;
- ``delay 1/rate`` spaces *global* op emission, giving ``rate`` ops/sec
  across all client threads combined per ``gen/delay``'s contract;
- ``phases`` advances when the current phase generator is exhausted
  (in-flight ops from the previous phase may still be completing);
- ``each-thread`` gives every client thread its own copy and exhausts when
  all copies do;
- ``nemesis``/``clients`` route by thread class.

Generators are stateful objects mutated only under the scheduler lock.
"""

from __future__ import annotations

import abc
import logging
import random
import threading
import time as _time
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

from jepsen_tpu.history.ops import NEMESIS_PROCESS, Op, OpF, OpType

logger = logging.getLogger("jepsen_tpu.generator")


@dataclass
class Ctx:
    """What a generator may consult when asked for an op."""

    time: int  # ns since test start
    thread: int  # worker thread id (NEMESIS_PROCESS for the nemesis)
    process: int  # current logical process of that thread
    n_threads: int  # number of client threads


@dataclass
class Pending:
    """No op yet — ask again at ``wake`` (ns since test start)."""

    wake: int


EXHAUSTED = None


class Generator(abc.ABC):
    @abc.abstractmethod
    def next_for(self, ctx: Ctx) -> Op | Pending | None:
        """An invoke op for this thread, a wake-up time, or EXHAUSTED."""


class FnGen(Generator):
    """Wraps an ``(ctx) -> Op`` function; never exhausts (bound it with
    ``TimeLimit``).  The reference's ``enqueue``/``dequeue`` fns."""

    def __init__(self, fn: Callable[[Ctx], Op]):
        self.fn = fn

    def next_for(self, ctx):
        return self.fn(ctx)


class OpGen(Generator):
    """A bare op map used directly as a generator (emitted indefinitely)."""

    def __init__(self, f: OpF, type: OpType = OpType.INVOKE, value: Any = None):
        self.f, self.type, self.value = f, type, value

    def next_for(self, ctx):
        return Op(self.type, self.f, ctx.process, self.value)


class Once(Generator):
    """``gen/once`` — emit a single op then exhaust."""

    def __init__(self, gen: Generator | Op):
        self.gen = gen
        self.done = False

    def next_for(self, ctx):
        if self.done:
            return EXHAUSTED
        got = (
            self.gen.next_for(ctx)
            if isinstance(self.gen, Generator)
            else Op(self.gen.type, self.gen.f, ctx.process, self.gen.value)
        )
        if isinstance(got, (Pending, type(None))):
            return got
        self.done = True
        return got


class Mix(Generator):
    """``gen/mix`` — each op from a uniformly random sub-generator."""

    def __init__(self, gens: Sequence[Generator], seed: int | None = None):
        self.gens = list(gens)
        self.rng = random.Random(seed)

    def next_for(self, ctx):
        order = list(range(len(self.gens)))
        self.rng.shuffle(order)
        soonest: Pending | None = None
        dead: set[int] = set()
        for i in order:
            got = self.gens[i].next_for(ctx)
            if isinstance(got, Op):
                return got
            if isinstance(got, Pending):
                if soonest is None or got.wake < soonest.wake:
                    soonest = got
            else:
                dead.add(i)
        if len(dead) == len(self.gens):
            return EXHAUSTED
        if dead:
            self.gens = [g for i, g in enumerate(self.gens) if i not in dead]
        return soonest


class Delay(Generator):
    """``gen/delay dt`` — at most one op per ``dt`` seconds globally."""

    def __init__(self, gen: Generator, dt_s: float):
        self.gen = gen
        self.dt_ns = int(dt_s * 1e9)
        self.next_at = 0

    def next_for(self, ctx):
        if ctx.time < self.next_at:
            return Pending(self.next_at)
        got = self.gen.next_for(ctx)
        if isinstance(got, Op):
            self.next_at = max(self.next_at + self.dt_ns, ctx.time)
        return got


class TimeLimit(Generator):
    """``gen/time-limit t`` — exhausted once ``t`` seconds have elapsed."""

    def __init__(self, gen: Generator, limit_s: float):
        self.gen = gen
        self.deadline_ns = int(limit_s * 1e9)

    def next_for(self, ctx):
        if ctx.time >= self.deadline_ns:
            return EXHAUSTED
        got = self.gen.next_for(ctx)
        if isinstance(got, Pending) and got.wake > self.deadline_ns:
            # don't let a thread oversleep the deadline (e.g. a nemesis
            # mid-cycle Sleep): wake it at the limit so it sees exhaustion
            # and the next phase (the final heal) can start on time
            return Pending(self.deadline_ns)
        return got


class Sleep(Generator):
    """``gen/sleep t`` — emit nothing for ``t`` seconds, then exhaust."""

    def __init__(self, dt_s: float):
        self.dt_ns = int(dt_s * 1e9)
        self.until: int | None = None

    def next_for(self, ctx):
        if self.until is None:
            self.until = ctx.time + self.dt_ns
        if ctx.time < self.until:
            return Pending(self.until)
        return EXHAUSTED


class Log(Generator):
    """``gen/log`` — log a message once, exhaust immediately."""

    def __init__(self, message: str):
        self.message = message
        self.done = False

    def next_for(self, ctx):
        if not self.done:
            logger.info(self.message)
            self.done = True
        return EXHAUSTED


class Seq(Generator):
    """Run sub-generators in order (building block for ``cycle``)."""

    def __init__(self, gens: Sequence[Generator]):
        self.gens = list(gens)
        self.i = 0

    def next_for(self, ctx):
        while self.i < len(self.gens):
            got = self.gens[self.i].next_for(ctx)
            if got is not EXHAUSTED:
                return got
            self.i += 1
        return EXHAUSTED


class Cycle(Generator):
    """``(cycle [...])`` — endlessly instantiate a sequence of generators
    from a factory.  Bound it with ``TimeLimit``."""

    def __init__(self, factory: Callable[[], Sequence[Generator]]):
        self.factory = factory
        self.current = Seq(list(factory()))

    def next_for(self, ctx):
        got = self.current.next_for(ctx)
        if got is not EXHAUSTED:
            return got
        self.current = Seq(list(self.factory()))
        return self.current.next_for(ctx)


class Phases(Generator):
    """``gen/phases`` — run each phase to exhaustion, in order."""

    def __init__(self, phases: Sequence[Generator]):
        self.phases = list(phases)
        self.i = 0

    def next_for(self, ctx):
        while self.i < len(self.phases):
            got = self.phases[self.i].next_for(ctx)
            if got is not EXHAUSTED:
                return got
            self.i += 1
        return EXHAUSTED


class Nothing(Generator):
    """Immediately exhausted."""

    def next_for(self, ctx):
        return EXHAUSTED


_POLL_NS = 20_000_000  # 20 ms — how often an idle thread re-asks a
# generator that is waiting on *other* threads to finish


class NemesisRoute(Generator):
    """``gen/nemesis`` — clients draw from ``client_gen``, the nemesis
    thread from ``nemesis_gen``.  The combined generator is exhausted only
    when BOTH sides are: a thread whose side finished idles (Pending) until
    the other side finishes too, so phase advancement stays global (a
    nemesis-only phase blocks clients from skipping ahead, and vice versa)."""

    def __init__(self, nemesis_gen: Generator, client_gen: Generator):
        self.nemesis_gen = nemesis_gen
        self.client_gen = client_gen
        self.nemesis_done = False
        self.client_done = False

    def next_for(self, ctx):
        mine = ctx.thread == NEMESIS_PROCESS
        if (self.nemesis_done if mine else self.client_done):
            got = EXHAUSTED
        else:
            got = (self.nemesis_gen if mine else self.client_gen).next_for(ctx)
        if got is EXHAUSTED:
            if mine:
                self.nemesis_done = True
            else:
                self.client_done = True
            if self.nemesis_done and self.client_done:
                return EXHAUSTED
            return Pending(ctx.time + _POLL_NS)
        return got


def Clients(gen: Generator) -> Generator:
    """``gen/clients`` — only client threads draw ops; the nemesis waits."""
    return NemesisRoute(Nothing(), gen)


def NemesisOnly(gen: Generator) -> Generator:
    """``(gen/nemesis g)`` with no client generator."""
    return NemesisRoute(gen, Nothing())


class EachThread(Generator):
    """``gen/each-thread`` — every client thread gets its own copy;
    exhausted only when all ``ctx.n_threads`` copies are."""

    def __init__(self, factory: Callable[[], Generator]):
        self.factory = factory
        self.per_thread: dict[int, Generator] = {}
        self.done: set[int] = set()

    def next_for(self, ctx):
        if ctx.thread not in self.per_thread:
            self.per_thread[ctx.thread] = self.factory()
        got = self.per_thread[ctx.thread].next_for(ctx)
        if got is EXHAUSTED:
            self.done.add(ctx.thread)
            if len(self.done) >= ctx.n_threads:
                return EXHAUSTED
            return Pending(ctx.time + _POLL_NS)
        return got


class Scheduler:
    """Hands ops from one generator tree to many worker threads.

    The single lock is the concurrency-correctness boundary: generator state
    only changes inside ``next_op``.  ``abort()`` poisons the scheduler so
    every thread sees exhaustion and exits — the escape hatch when a worker
    hits an unrecoverable error (otherwise combinators like ``EachThread``
    would wait forever for the dead thread)."""

    def __init__(self, gen: Generator, n_threads: int, start_ns: int | None = None):
        self.gen = gen
        self.n_threads = n_threads
        self.lock = threading.Lock()
        self.start_ns = start_ns if start_ns is not None else _time.monotonic_ns()
        self.aborted = False

    def now(self) -> int:
        return _time.monotonic_ns() - self.start_ns

    def abort(self) -> None:
        with self.lock:
            self.aborted = True

    def next_op(self, thread: int, process: int) -> Op | Pending | None:
        with self.lock:
            if self.aborted:
                return EXHAUSTED
            ctx = Ctx(
                time=self.now(),
                thread=thread,
                process=process,
                n_threads=self.n_threads,
            )
            return self.gen.next_for(ctx)
