"""Compatibility shim: the mini broker moved to ``jepsen_tpu.harness``
(it is product infrastructure — the local dev cluster's node processes —
not a test double; see harness/broker.py)."""

from jepsen_tpu.harness.broker import MiniAmqpBroker  # noqa: F401
