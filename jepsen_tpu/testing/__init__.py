"""Test doubles: the in-memory AMQP mini-broker."""

from jepsen_tpu.testing.broker import MiniAmqpBroker  # noqa: F401
