"""Compatibility shim — moved to :mod:`jepsen_tpu.harness.broker`."""

from jepsen_tpu.harness.broker import *  # noqa: F401,F403
from jepsen_tpu.harness.broker import MiniAmqpBroker  # noqa: F401
