"""Length-prefixed binary framing for the checker sidecar.

One frame = magic ``JTQ1`` + uint32 header length + JSON header + raw array
payload.  The header describes the op and every array (name, dtype, shape,
in order); the payload is the arrays' bytes concatenated.  Arrays travel as
little-endian numpy buffers — the packed ``int32`` history columns go over
the wire exactly as they'll sit in HBM, no per-op serialization.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Mapping

import numpy as np

MAGIC = b"JTQ1"
_HDR = struct.Struct(">4sI")  # magic, header-json length

#: hard cap on a single frame's payload (1 GiB) — a corrupt length prefix
#: must not make the receiver try to allocate arbitrary memory
MAX_PAYLOAD = 1 << 30


class ProtocolError(RuntimeError):
    pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ProtocolError(f"connection closed mid-frame ({got}/{n})")
        got += r
    return bytes(buf)


def send_frame(
    sock: socket.socket,
    header: Mapping[str, Any],
    arrays: Mapping[str, np.ndarray] | None = None,
) -> None:
    arrays = arrays or {}
    specs = []
    chunks = []
    for name, arr in arrays.items():
        a = np.ascontiguousarray(arr)
        if a.dtype == bool:
            a = a.astype(np.uint8)
        a = a.astype(a.dtype.newbyteorder("<"), copy=False)
        specs.append(
            {"name": name, "dtype": str(a.dtype), "shape": list(a.shape)}
        )
        chunks.append(a.tobytes())
    hdr = dict(header)
    hdr["arrays"] = specs
    hdr_bytes = json.dumps(hdr).encode()
    sock.sendall(_HDR.pack(MAGIC, len(hdr_bytes)))
    sock.sendall(hdr_bytes)
    for c in chunks:
        sock.sendall(c)


def recv_frame(
    sock: socket.socket,
) -> tuple[dict[str, Any], dict[str, np.ndarray]]:
    magic, hdr_len = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if magic != MAGIC:
        raise ProtocolError(f"bad magic {magic!r}")
    if hdr_len > MAX_PAYLOAD:
        raise ProtocolError(f"oversized header ({hdr_len} bytes)")
    header = json.loads(_recv_exact(sock, hdr_len))
    arrays: dict[str, np.ndarray] = {}
    total = 0
    for spec in header.get("arrays", []):
        dtype = np.dtype(spec["dtype"])
        count = int(np.prod(spec["shape"], dtype=np.int64)) if spec["shape"] else 1
        nbytes = dtype.itemsize * count
        total += nbytes
        if total > MAX_PAYLOAD:
            raise ProtocolError(f"oversized payload (> {MAX_PAYLOAD} bytes)")
        buf = _recv_exact(sock, nbytes)
        arrays[spec["name"]] = np.frombuffer(buf, dtype=dtype).reshape(
            spec["shape"]
        )
    return header, arrays
