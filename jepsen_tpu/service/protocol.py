"""Length-prefixed binary framing for the checker sidecar.

One frame = magic ``JTQ1`` + uint32 header length + JSON header + raw array
payload.  The header describes the op and every array (name, dtype, shape,
in order); the payload is the arrays' bytes concatenated.  Arrays travel as
little-endian numpy buffers — the packed ``int32`` history columns go over
the wire exactly as they'll sit in HBM, no per-op serialization.

Streaming ops additionally ship a per-array ``crc32`` in the spec
(``send_frame(..., crc=True)``): a torn or bit-flipped block is then
detected at the RECEIVER as :class:`TornPayloadError` — raised only
after the whole payload has been consumed, so the connection stays in
frame-sync and the server can quarantine exactly the poisoned stream
while continuing to serve every other one (the PR-13 precedence rule on
the wire: unknown-with-evidence, never folded into a verdict, never a
gapped carry).

The framing is symmetric, which is what makes subscription push
possible without a second wire format: after a ``stream-subscribe``
request the server INVERTS the rhythm on that connection and sends
:data:`PUSH_OPS` frames (``verdict-window`` deltas, then terminal
``subscribe-done`` / ``subscribe-timeout`` markers) until the stream's
final window — every push frame is an ordinary ``send_frame`` the
client reads with an ordinary ``recv_frame``.
"""

from __future__ import annotations

import json
import socket
import struct
import zlib
from typing import Any, Mapping

import numpy as np

MAGIC = b"JTQ1"
_HDR = struct.Struct(">4sI")  # magic, header-json length

#: hard cap on a single frame's payload (1 GiB) — a corrupt length prefix
#: must not make the receiver try to allocate arbitrary memory
MAX_PAYLOAD = 1 << 30

#: the op that flips a connection into push mode (server → client frames)
SUBSCRIBE_OP = "stream-subscribe"

#: frames the SERVER originates on a subscribed connection; everything
#: else on the wire stays strict request → reply
PUSH_OPS = frozenset({
    "verdict-window", "subscribe-done", "subscribe-timeout",
})


class ProtocolError(RuntimeError):
    pass


class TornPayloadError(ProtocolError):
    """An array's bytes failed their declared crc32.

    The frame was fully consumed (the connection is still usable); the
    parsed ``header`` identifies which op/stream the torn bytes belonged
    to, so the receiver can quarantine that stream instead of dropping
    the connection."""

    def __init__(self, msg: str, header: dict[str, Any], torn: list[str]):
        super().__init__(msg)
        self.header = header
        self.torn = torn


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ProtocolError(f"connection closed mid-frame ({got}/{n})")
        got += r
    return bytes(buf)


def send_frame(
    sock: socket.socket,
    header: Mapping[str, Any],
    arrays: Mapping[str, np.ndarray] | None = None,
    crc: bool = False,
) -> None:
    arrays = arrays or {}
    specs = []
    chunks = []
    for name, arr in arrays.items():
        a = np.ascontiguousarray(arr)
        if a.dtype == bool:
            a = a.astype(np.uint8)
        a = a.astype(a.dtype.newbyteorder("<"), copy=False)
        raw = a.tobytes()
        spec = {"name": name, "dtype": str(a.dtype), "shape": list(a.shape)}
        if crc:
            spec["crc32"] = zlib.crc32(raw)
        specs.append(spec)
        chunks.append(raw)
    hdr = dict(header)
    hdr["arrays"] = specs
    hdr_bytes = json.dumps(hdr).encode()
    sock.sendall(_HDR.pack(MAGIC, len(hdr_bytes)))
    sock.sendall(hdr_bytes)
    for c in chunks:
        sock.sendall(c)


def recv_frame(
    sock: socket.socket,
) -> tuple[dict[str, Any], dict[str, np.ndarray]]:
    magic, hdr_len = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if magic != MAGIC:
        raise ProtocolError(f"bad magic {magic!r}")
    if hdr_len > MAX_PAYLOAD:
        raise ProtocolError(f"oversized header ({hdr_len} bytes)")
    header = json.loads(_recv_exact(sock, hdr_len))
    arrays: dict[str, np.ndarray] = {}
    torn: list[str] = []
    total = 0
    for spec in header.get("arrays", []):
        dtype = np.dtype(spec["dtype"])
        count = int(np.prod(spec["shape"], dtype=np.int64)) if spec["shape"] else 1
        nbytes = dtype.itemsize * count
        total += nbytes
        if total > MAX_PAYLOAD:
            raise ProtocolError(f"oversized payload (> {MAX_PAYLOAD} bytes)")
        buf = _recv_exact(sock, nbytes)
        # verify-but-keep-reading: the whole frame must be consumed
        # before raising, or the next recv would misparse payload bytes
        # as a frame header (losing the connection, not just the block)
        if "crc32" in spec and zlib.crc32(buf) != spec["crc32"]:
            torn.append(spec["name"])
            continue
        arrays[spec["name"]] = np.frombuffer(buf, dtype=dtype).reshape(
            spec["shape"]
        )
    if torn:
        raise TornPayloadError(
            f"torn payload: crc32 mismatch on array(s) {torn} "
            f"(op {header.get('op')!r})",
            header=header,
            torn=torn,
        )
    return header, arrays
