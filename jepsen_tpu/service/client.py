"""Client side of the checker sidecar: pack host-side, ship tensors."""

from __future__ import annotations

import socket
from typing import Any, Sequence

import numpy as np

from jepsen_tpu.history.encode import PackedHistories, pack_histories
from jepsen_tpu.history.ops import Op
from jepsen_tpu.service.protocol import recv_frame, send_frame


#: result-map keys that are value *sets* locally and travel as sorted lists
_SET_KEYS = frozenset(
    {
        "lost",
        "unexpected",
        "duplicated",
        "recovered",
        "duplicate",
        "phantom",
        "causality",
        # stream family
        "divergent",
        "reorder",
        # elle family
        "G0",
        "G1c",
        "G2",
        "G1a",
        "G1b",
        "incompatible-order",
    }
)


def _desetted(result: dict[str, Any]) -> dict[str, Any]:
    """Restore the local checkers' result shape (lists → value sets)."""
    out: dict[str, Any] = {}
    for k, v in result.items():
        if isinstance(v, dict):
            out[k] = _desetted(v)
        elif k in _SET_KEYS and isinstance(v, list):
            out[k] = set(v)
        else:
            out[k] = v
    return out


class CheckerClient:
    """One TCP connection to a checker sidecar; reusable across calls."""

    def __init__(
        self, host: str = "127.0.0.1", port: int = 8640, timeout: float = 120.0
    ):
        self.sock = socket.create_connection((host, port), timeout=timeout)

    def close(self) -> None:
        self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _call(
        self, header: dict[str, Any], arrays=None
    ) -> tuple[dict[str, Any], dict[str, np.ndarray]]:
        send_frame(self.sock, header, arrays)
        reply, reply_arrays = recv_frame(self.sock)
        if reply.get("op") == "error":
            raise RuntimeError(f"sidecar error: {reply.get('error')}")
        return reply, reply_arrays

    def ping(self) -> dict[str, Any]:
        reply, _ = self._call({"op": "ping"})
        return reply

    def check_packed(self, packed: PackedHistories) -> list[dict[str, Any]]:
        arrays = {
            "f": np.asarray(packed.f),
            "type": np.asarray(packed.type),
            "value": np.asarray(packed.value),
            "mask": np.asarray(packed.mask),
        }
        reply, _ = self._call(
            {"op": "check", "value_space": packed.value_space}, arrays
        )
        return [_desetted(r) for r in reply["results"]]

    def check_histories(
        self,
        histories: Sequence[Sequence[Op]],
        length: int | None = None,
        value_space: int | None = None,
    ) -> list[dict[str, Any]]:
        packed = pack_histories(
            histories, length=length, value_space=value_space
        )
        return self.check_packed(packed)

    def check_stream_histories(
        self,
        histories: Sequence[Sequence[Op]],
        length: int | None = None,
        space: int | None = None,
        append_fail: str = "definite",
    ) -> list[dict[str, Any]]:
        from jepsen_tpu.checkers.stream_lin import (
            STREAM_ARRAYS,
            pack_stream_histories,
        )

        batch = pack_stream_histories(histories, length=length, space=space)
        arrays = {k: np.asarray(getattr(batch, k)) for k in STREAM_ARRAYS}
        reply, _ = self._call(
            {
                "op": "check-stream",
                "space": batch.space,
                "append-fail": append_fail,
            },
            arrays,
        )
        return [_desetted(r) for r in reply["results"]]

    def check_elle_histories(
        self, histories: Sequence[Sequence[Op]]
    ) -> list[dict[str, Any]]:
        header = {
            "op": "check-elle",
            "histories": [[op.to_json() for op in h] for h in histories],
        }
        reply, _ = self._call(header)
        return [_desetted(r) for r in reply["results"]]
