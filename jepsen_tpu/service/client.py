"""Client side of the checker sidecar: pack host-side, ship tensors.

Streaming methods (``stream_open`` / ``stream_feed_rows`` / ... /
``submit_batch_rows``) speak the always-on ingestion surface.  Pass a
:class:`RetryPolicy` to make transient faults the CLIENT's problem, not
the caller's: a connection reset reconnects and resends (safe — block
feeds are idempotent by sequence number, the server dup-acks), and a
loud ``SATURATED`` reject backs off with exponential delay + jitter and
re-offers.  When the budget runs out the caller gets
:class:`ServiceUnavailable` whose ``.reason`` is machine-readable —
never a raw socket exception, never a silently dropped block.
"""

from __future__ import annotations

import random
import socket
import time
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

from jepsen_tpu.history.encode import PackedHistories, pack_histories
from jepsen_tpu.history.ops import Op
from jepsen_tpu.service.protocol import ProtocolError, recv_frame, send_frame


#: result-map keys that are value *sets* locally and travel as sorted lists
_SET_KEYS = frozenset(
    {
        "lost",
        "unexpected",
        "duplicated",
        "recovered",
        "duplicate",
        "phantom",
        "causality",
        # stream family
        "divergent",
        "reorder",
        # elle family
        "G0",
        "G1c",
        "G2",
        "G1a",
        "G1b",
        "incompatible-order",
    }
)


def _desetted(result: dict[str, Any]) -> dict[str, Any]:
    """Restore the local checkers' result shape (lists → value sets)."""
    out: dict[str, Any] = {}
    for k, v in result.items():
        if isinstance(v, dict):
            out[k] = _desetted(v)
        elif k in _SET_KEYS and isinstance(v, list):
            out[k] = set(v)
        else:
            out[k] = v
    return out


@dataclass
class RetryPolicy:
    """Bounded retry with exponential backoff + full jitter.

    ``attempts`` bounds the TOTAL tries (first offer included); delays
    grow ``base_s * 2**k`` capped at ``cap_s``, each multiplied by a
    uniform jitter draw so a saturated server isn't re-hit by every
    client on the same beat.  ``seed`` pins the draw for tests."""

    attempts: int = 6
    base_s: float = 0.05
    cap_s: float = 2.0
    jitter: float = 0.5  # delay is scaled by uniform(jitter, 1.0)
    seed: int | None = None

    def delay_s(self, attempt: int, rng: random.Random) -> float:
        d = min(self.base_s * (2.0 ** attempt), self.cap_s)
        return d * rng.uniform(min(self.jitter, 1.0), 1.0)


class ServiceUnavailable(RuntimeError):
    """The retry budget is spent.  ``reason`` is machine-readable:

    ``{"reason": "SATURATED"|"connection", "attempts": n,
    "last": <final reject dict or repr of the final exception>}``"""

    def __init__(self, msg: str, reason: dict[str, Any]):
        super().__init__(msg)
        self.reason = reason


class SubscriptionGap(RuntimeError):
    """A subscription cannot be made whole.  ``gap`` is machine-readable:
    either the server's retained window log no longer reaches back to
    the requested window (``{"requested": k, "floor": f,
    "missed_windows": n}``) or the push sequence itself skipped
    (``{"expected": k, "got": g}``).  The subscriber KNOWS exactly which
    windows it can never see — a silent resume would fabricate a
    contiguous verdict history around a hole."""

    def __init__(self, msg: str, gap: dict[str, Any]):
        super().__init__(msg)
        self.gap = gap


class CheckerClient:
    """One TCP connection to a checker sidecar; reusable across calls."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8640,
        timeout: float = 120.0,
        retry: RetryPolicy | None = None,
    ):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.retry = retry
        self._rng = random.Random(retry.seed if retry else None)
        self.sock = socket.create_connection((host, port), timeout=timeout)

    def close(self) -> None:
        self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _reconnect(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
        self.sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )

    def _call(
        self, header: dict[str, Any], arrays=None, crc: bool = False
    ) -> tuple[dict[str, Any], dict[str, np.ndarray]]:
        send_frame(self.sock, header, arrays, crc=crc)
        reply, reply_arrays = recv_frame(self.sock)
        if reply.get("op") == "error":
            raise RuntimeError(f"sidecar error: {reply.get('error')}")
        return reply, reply_arrays

    def _call_robust(
        self, header: dict[str, Any], arrays=None, crc: bool = False
    ) -> dict[str, Any]:
        """One streaming-surface call under the retry policy: resend on
        connection faults (block feeds are seq-idempotent), back off and
        re-offer on ``SATURATED``.  Without a policy, single-shot."""
        attempts = self.retry.attempts if self.retry else 1
        last: Any = None
        saturated = False
        for attempt in range(attempts):
            if attempt:
                time.sleep(self.retry.delay_s(attempt - 1, self._rng))
            try:
                reply, _ = self._call(header, arrays, crc=crc)
            except (ConnectionError, ProtocolError, OSError) as e:
                last, saturated = repr(e), False
                if self.retry is None or attempt + 1 >= attempts:
                    break
                try:
                    self._reconnect()
                except OSError as e2:
                    last = repr(e2)
                continue
            if (
                reply.get("op") == "rejected"
                and reply.get("reason") == "SATURATED"
            ):
                last, saturated = reply, True
                continue
            return reply
        reason = {
            "reason": "SATURATED" if saturated else "connection",
            "attempts": attempts,
            "last": last,
        }
        raise ServiceUnavailable(
            f"service unavailable after {attempts} attempt(s): "
            f"{reason['reason']}",
            reason,
        )

    def ping(self) -> dict[str, Any]:
        reply, _ = self._call({"op": "ping"})
        return reply

    def check_packed(self, packed: PackedHistories) -> list[dict[str, Any]]:
        arrays = {
            "f": np.asarray(packed.f),
            "type": np.asarray(packed.type),
            "value": np.asarray(packed.value),
            "mask": np.asarray(packed.mask),
        }
        reply, _ = self._call(
            {"op": "check", "value_space": packed.value_space}, arrays
        )
        return [_desetted(r) for r in reply["results"]]

    def check_histories(
        self,
        histories: Sequence[Sequence[Op]],
        length: int | None = None,
        value_space: int | None = None,
    ) -> list[dict[str, Any]]:
        packed = pack_histories(
            histories, length=length, value_space=value_space
        )
        return self.check_packed(packed)

    def check_stream_histories(
        self,
        histories: Sequence[Sequence[Op]],
        length: int | None = None,
        space: int | None = None,
        append_fail: str = "definite",
    ) -> list[dict[str, Any]]:
        from jepsen_tpu.checkers.stream_lin import (
            STREAM_ARRAYS,
            pack_stream_histories,
        )

        batch = pack_stream_histories(histories, length=length, space=space)
        arrays = {k: np.asarray(getattr(batch, k)) for k in STREAM_ARRAYS}
        reply, _ = self._call(
            {
                "op": "check-stream",
                "space": batch.space,
                "append-fail": append_fail,
            },
            arrays,
        )
        return [_desetted(r) for r in reply["results"]]

    def check_elle_histories(
        self, histories: Sequence[Sequence[Op]]
    ) -> list[dict[str, Any]]:
        header = {
            "op": "check-elle",
            "histories": [[op.to_json() for op in h] for h in histories],
        }
        reply, _ = self._call(header)
        return [_desetted(r) for r in reply["results"]]

    # -- streaming surface ------------------------------------------------

    def stream_open(
        self,
        workload: str,
        opts: dict | None = None,
        content_key: str | None = None,
        deadline_s: float | None = None,
    ) -> dict[str, Any]:
        """Open a stream: ``{"op": "opened", "stream": sid}``, a cached
        verdict (when ``content_key`` hits), or raises
        :class:`ServiceUnavailable` after the retry budget."""
        header: dict[str, Any] = {
            "op": "stream-open", "workload": workload, "opts": opts or {},
        }
        if content_key is not None:
            header["content_key"] = content_key
        if deadline_s is not None:
            header["deadline_s"] = deadline_s
        return self._call_robust(header)

    def stream_feed_rows(
        self, sid: str, seq: int, rows: np.ndarray, n_ops: int
    ) -> dict[str, Any]:
        """Feed one ``[n, 8]`` row block (queue family), CRC-protected
        on the wire; seq-idempotent, so resend-after-reset is safe."""
        return self._call_robust(
            {"op": "stream-feed", "stream": sid, "seq": seq,
             "n_ops": n_ops},
            {"rows": np.ascontiguousarray(rows, np.int32)},
            crc=True,
        )

    def stream_feed_ops(
        self, sid: str, seq: int, ops_json: list, n_ops: int | None = None
    ) -> dict[str, Any]:
        """Feed one op-JSON block (stream/elle/mutex families)."""
        return self._call_robust({
            "op": "stream-feed", "stream": sid, "seq": seq,
            "ops_block": ops_json,
            "n_ops": len(ops_json) if n_ops is None else n_ops,
        })

    def stream_finish(
        self, sid: str, timeout: float | None = None
    ) -> dict[str, Any]:
        header: dict[str, Any] = {"op": "stream-finish", "stream": sid}
        if timeout is not None:
            header["timeout"] = timeout
        return _desetted(self._call_robust(header))

    def stream_abort(self, sid: str) -> dict[str, Any]:
        return self._call_robust({"op": "stream-abort", "stream": sid})

    def submit_batch_rows(
        self,
        workload: str,
        blocks: Sequence[np.ndarray],
        n_ops: Sequence[int],
        opts: dict | None = None,
        content_keys: Sequence[str] | None = None,
    ) -> dict[str, Any]:
        """One frame, many one-shot histories (the fleet path):
        concatenated rows + offsets; per-history admission replies in
        order (``accepted`` with an id, ``cached``, or ``rejected``)."""
        if not blocks:
            return {"op": "submitted", "replies": []}
        mats = [np.ascontiguousarray(b, np.int32) for b in blocks]
        offsets = np.zeros(len(mats) + 1, np.int64)
        np.cumsum([m.shape[0] for m in mats], out=offsets[1:])
        header: dict[str, Any] = {
            "op": "submit-batch", "workload": workload,
            "opts": opts or {}, "n_ops": [int(n) for n in n_ops],
        }
        if content_keys is not None:
            header["content_keys"] = list(content_keys)
        return self._call_robust(
            header,
            {"rows": np.concatenate(mats, axis=0), "offsets": offsets},
            crc=True,
        )

    def collect(
        self, ids: Sequence[str], timeout: float = 0.0
    ) -> dict[str, Any]:
        reply = self._call_robust(
            {"op": "collect", "ids": list(ids), "timeout": timeout}
        )
        if isinstance(reply.get("done"), dict):
            reply["done"] = {
                k: _desetted(v) if isinstance(v, dict) else v
                for k, v in reply["done"].items()
            }
        return reply

    def cache_get(
        self, content_key: str, workload: str, opts: dict | None = None
    ) -> dict[str, Any]:
        return self._call_robust({
            "op": "cache-get", "content_key": content_key,
            "workload": workload, "opts": opts or {},
        })

    def service_stats(self) -> dict[str, Any]:
        return self._call_robust({"op": "service-stats"})

    def subscribe_windows(
        self, sid: str, from_window: int = 0,
        timeout: float | None = None,
    ):
        """Generator over a stream's PUSHED verdict windows (the
        poll-free path): yields contiguous ``verdict-window`` dicts from
        ``from_window`` until the terminal ``final`` window.

        Runs on a DEDICATED connection (push frames must not interleave
        with this client's request→reply calls).  A torn push connection
        reconnects under the retry policy and re-subscribes from the
        first window not yet yielded — the server replays the missed
        windows from its retained log, and duplicates below the resume
        point are dropped here, so the caller sees each window exactly
        once.  When the story cannot be made whole (the server's
        retained floor moved past the resume point, or the push sequence
        itself skipped), raises :class:`SubscriptionGap` with the
        machine-readable hole; when the budget is spent, raises
        :class:`ServiceUnavailable`."""
        next_window = from_window
        attempts = self.retry.attempts if self.retry else 1
        failures = 0
        last: Any = None
        sock: socket.socket | None = None

        def _drop(s):
            try:
                s.close()
            except OSError:
                pass

        try:
            while True:
                if sock is None:
                    if failures:
                        time.sleep(
                            self.retry.delay_s(failures - 1, self._rng)
                        )
                    try:
                        sock = socket.create_connection(
                            (self.host, self.port),
                            timeout=(timeout if timeout is not None
                                     else self.timeout),
                        )
                        send_frame(sock, {
                            "op": "stream-subscribe", "stream": sid,
                            "from_window": next_window,
                        })
                        ack, _ = recv_frame(sock)
                    except (ConnectionError, ProtocolError, OSError) as e:
                        if sock is not None:
                            _drop(sock)
                            sock = None
                        last = repr(e)
                        failures += 1
                        if failures >= attempts:
                            raise ServiceUnavailable(
                                f"subscription unavailable after "
                                f"{failures} attempt(s)",
                                {"reason": "connection",
                                 "attempts": failures, "last": last},
                            ) from e
                        continue
                    if ack.get("op") == "error":
                        raise RuntimeError(
                            f"sidecar error: {ack.get('error')}"
                        )
                    if "gap" in ack:
                        g = ack["gap"]
                        raise SubscriptionGap(
                            f"window(s) "
                            f"[{g['requested']}, {g['floor']}) fell off "
                            f"the server's retained log",
                            gap=g,
                        )
                try:
                    frame, _ = recv_frame(sock)
                except (ConnectionError, ProtocolError, OSError) as e:
                    _drop(sock)
                    sock = None
                    last = repr(e)
                    failures += 1
                    if failures >= attempts:
                        raise ServiceUnavailable(
                            f"subscription torn and not recoverable "
                            f"after {failures} attempt(s)",
                            {"reason": "connection",
                             "attempts": failures, "last": last},
                        ) from e
                    continue
                failures = 0  # progress renews the budget
                op = frame.get("op")
                if op in ("subscribe-done", "subscribe-timeout"):
                    return
                if op != "verdict-window":
                    raise ProtocolError(
                        f"unexpected push frame {op!r} on subscription"
                    )
                w = int(frame.get("window", -1))
                if w < next_window:
                    continue  # replayed duplicate: already yielded
                if w > next_window:
                    raise SubscriptionGap(
                        f"push sequence skipped: expected window "
                        f"{next_window}, got {w}",
                        gap={"expected": next_window, "got": w},
                    )
                next_window = w + 1
                if isinstance(frame.get("verdict"), dict):
                    frame["verdict"] = _desetted(frame["verdict"])
                yield frame
                if frame.get("final"):
                    return
        finally:
            if sock is not None:
                _drop(sock)

    def check_jtc(
        self,
        path,
        block_rows: int = 512,
        opts: dict | None = None,
        timeout: float | None = None,
    ) -> dict[str, Any]:
        """Stream one ``.jtc`` substrate end-to-end: content-key lookup
        first (a cached verdict costs a hash, not a device dispatch),
        else open + feed row blocks + finish.  Queue-family substrates
        only (the zero-parse wire path)."""
        from jepsen_tpu.history.columnar import iter_row_blocks, read_jtc

        jtc, _stamp = read_jtc(path)
        rows = jtc.rows()
        if rows is None:
            raise ValueError(f"{path}: no row section to stream")
        workload = jtc.workload or "queue"
        if workload != "queue":
            raise ValueError(
                f"{path}: {workload} histories stream as op blocks "
                f"(stream_feed_ops), not row blocks"
            )
        opened = self.stream_open(
            workload, opts=opts, content_key=jtc.content_key()
        )
        if opened["op"] == "cached":
            return opened
        if opened["op"] != "opened":
            return opened
        sid = opened["stream"]
        for seq, (blk, n) in enumerate(iter_row_blocks(rows, block_rows)):
            fed = self.stream_feed_rows(sid, seq, blk, n)
            if fed["op"] not in ("accepted",):
                return fed
        return self.stream_finish(sid, timeout=timeout)
