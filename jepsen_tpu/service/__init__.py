"""Checker sidecar: RPC service carrying packed int32 history tensors.

The reference's analysis phase runs in-process on the controller (SURVEY.md
§2.4 "checker-plane communication: none").  The TPU build externalizes it:
the run controller (or a fleet of them — the CI matrix, batched replay)
ships packed histories to a long-lived checker process that owns the TPU,
amortizing backend init and compilation across runs (north star,
BASELINE.json: "Clojure/Python boundary via a sidecar RPC").
"""

from jepsen_tpu.service.client import (  # noqa: F401
    CheckerClient,
    RetryPolicy,
    ServiceUnavailable,
)
from jepsen_tpu.service.server import CheckerServer  # noqa: F401
