"""Content-addressed verdict cache for the always-on service.

At fleet scale many submitted histories are identical — a re-checked
run, a fuzz shrink candidate re-confirmed, the same soak replayed by
two controllers.  Checking is a pure function of (history bytes, model,
contract), so a verdict can be served by hash lookup instead of a
device dispatch: the cache key is

    sha256( content_digest || workload || canonical-JSON(opts) )

where ``content_digest`` is the sha256 of the history's substrate bytes
(``columnar.payload_sha256`` for a ``.jtc``; the running digest of the
streamed block payloads for a wire stream — the server computes its OWN
digest over what it actually received, so a client-declared key can
never poison the cache with a verdict for different bytes).

Invalidation is structural, not temporal: the key embeds the content
digest, so changed bytes are a different key — stale entries are never
*wrong*, only unreachable, and the LRU bound evicts them.  Only CLEAN
verdicts are cached: a quarantined or ``degraded`` verdict reflects
this run's worker deaths / poison, not the history, and must be
recomputed, never replayed (SERVICE.md §Cache).

Entries may carry a ``report_ref`` (a store-relative run directory):
cache hits for histories that already have a recorded run serve the
PR-11 report route (``/report/<run>``) alongside the verdict —
:func:`seed_from_store` builds those entries off the committed store.
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
from collections import OrderedDict
from pathlib import Path
from typing import Any

log = logging.getLogger("jepsen_tpu.service.cache")


def contract_key(workload: str, opts: dict | None) -> str:
    """Canonical (model, contract) half of the cache key: the checker
    options that change verdict semantics, JSON-canonicalized."""
    return json.dumps(
        [workload, dict(opts or {})], sort_keys=True, separators=(",", ":")
    )


def cache_key(content_digest: str, workload: str, opts: dict | None) -> str:
    """The full content-addressed key: (substrate sha256, model,
    contract) → one hex digest."""
    h = hashlib.sha256()
    h.update(content_digest.encode())
    h.update(b"\x00")
    h.update(contract_key(workload, opts).encode())
    return h.hexdigest()


class VerdictCache:
    """Thread-safe LRU of verdicts keyed by :func:`cache_key`.

    ``get``/``put`` maintain the shared obs counters
    (``service.cache_hits`` / ``service.cache_misses``) so ``/metrics``
    answers the hit rate live."""

    def __init__(self, capacity: int = 4096, registry=None):
        if capacity <= 0:
            raise ValueError("cache capacity must be positive")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, dict] = OrderedDict()
        if registry is None:
            from jepsen_tpu.obs.metrics import REGISTRY as registry  # noqa: N813
        self._hits = registry.counter("service.cache_hits")
        self._misses = registry.counter("service.cache_misses")

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, key: str) -> dict | None:
        """The cached entry ``{"verdict": ..., "report_ref": ...?}`` or
        None; counts a hit/miss either way."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
        if entry is None:
            self._misses.inc()
            return None
        self._hits.inc()
        return entry

    def peek(self, key: str) -> dict | None:
        """Read-only lookup: no LRU reorder, no hit/miss accounting.
        The observability surface (``/report/by-key/<key>``) uses this
        so browsing NEVER changes cache state or skews the hit rate."""
        with self._lock:
            return self._entries.get(key)

    def put(
        self,
        key: str,
        verdict: dict[str, Any],
        report_ref: str | None = None,
    ) -> None:
        entry = {"verdict": verdict}
        with self._lock:
            if report_ref is None:
                # a live-stream re-verification of a seeded history
                # must not orphan its recorded run: the refreshed entry
                # keeps serving the PR-11 report route for hits
                prev = self._entries.get(key)
                if prev is not None:
                    report_ref = prev.get("report_ref")
            if report_ref is not None:
                entry["report_ref"] = report_ref
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def stats(self) -> dict[str, int]:
        with self._lock:
            n = len(self._entries)
        return {
            "entries": n,
            "capacity": self.capacity,
            "hits": int(self._hits.value),
            "misses": int(self._misses.value),
        }

    def seed_from_store(
        self, store_root: str | Path, limit: int | None = None
    ) -> int:
        """Seed entries from recorded runs: every run directory with a
        ``results.json`` verdict and a fresh ``.jtc`` substrate becomes
        a cache entry whose ``report_ref`` points the hit at the PR-11
        report route.  Returns the number of entries seeded; malformed
        runs are skipped (a cache seed must never refuse to serve)."""
        from jepsen_tpu.report.index import run_content_refs

        seeded = 0
        for digest, workload, opts, verdict, rel in run_content_refs(
            Path(store_root)
        ):
            self.put(
                cache_key(digest, workload, opts), verdict, report_ref=rel
            )
            seeded += 1
            if limit is not None and seeded >= limit:
                break
        return seeded
