"""The checker sidecar server.

A long-lived process owning the JAX backend (one TPU chip, or a mesh via
``use_mesh``).  Controllers connect over TCP, send packed histories, and
get reference-shaped verdicts back.  The jitted check program is cached per
``(B, L, V)`` shape, so a fleet of runs with bucketed shapes pays one
compile each.

Ops:

- ``ping``  → backend info (devices, platform)
- ``check`` → arrays ``f``/``type``/``value``/``mask`` of shape ``[B, L]``
  + ``value_space`` → per-history ``total-queue`` and queue-linearizability
  verdicts
- ``check-stream`` → the packed stream columns + ``space`` → per-history
  stream-log linearizability verdicts
- ``check-elle`` → histories as op JSON in the header (edge inference is
  a host-side parse; the server runs it next to the device) → per-history
  Elle serializability verdicts
"""

from __future__ import annotations

import logging
import socketserver
import threading
from typing import Any

import numpy as np

from jepsen_tpu.service.protocol import (
    ProtocolError,
    recv_frame,
    send_frame,
)

logger = logging.getLogger("jepsen_tpu.service")

REQUIRED_ARRAYS = ("f", "type", "value", "mask")


def _check_arrays(
    arrays: dict[str, np.ndarray], value_space: int
) -> dict[str, Any]:
    import jax.numpy as jnp

    from jepsen_tpu.checkers.queue_lin import queue_lin_tensors_to_results
    from jepsen_tpu.checkers.total_queue import _tensors_to_results

    missing = [k for k in REQUIRED_ARRAYS if k not in arrays]
    if missing:
        raise ProtocolError(f"missing arrays: {missing}")
    f = jnp.asarray(arrays["f"], jnp.int32)
    type_ = jnp.asarray(arrays["type"], jnp.int32)
    value = jnp.asarray(arrays["value"], jnp.int32)
    mask = jnp.asarray(arrays["mask"].astype(bool))
    from jepsen_tpu.checkers.fused import _combined_batch

    # the canonical single-program combined check (checkers/fused.py)
    tq, ql = _combined_batch(f, type_, value, mask, value_space)
    tq_results = _tensors_to_results(tq)
    ql_results = queue_lin_tensors_to_results(ql)
    out = []
    for q, l in zip(tq_results, ql_results):
        out.append(
            {
                "queue": _jsonable(q),
                "linear": _jsonable(l),
                "valid?": bool(q["valid?"] and l["valid?"]),
            }
        )
    return {"op": "result", "results": out}


def _jsonable(d: dict[str, Any]) -> dict[str, Any]:
    """Result maps hold value sets; the wire header is JSON."""
    return {
        k: sorted(v) if isinstance(v, (set, frozenset)) else v
        for k, v in d.items()
    }


def _prepare_stream_batch(arrays: dict[str, np.ndarray], space: int):
    """Host-side reconstruction of a StreamBatch (no device lock needed)."""
    import jax.numpy as jnp

    from jepsen_tpu.checkers.stream_lin import STREAM_ARRAYS, StreamBatch

    missing = [k for k in STREAM_ARRAYS if k not in arrays]
    if missing:
        raise ProtocolError(f"missing arrays: {missing}")
    full_read = arrays["full_read"].astype(bool)
    batch = StreamBatch(
        type=jnp.asarray(arrays["type"], jnp.int32),
        f=jnp.asarray(arrays["f"], jnp.int32),
        value=jnp.asarray(arrays["value"], jnp.int32),
        offset=jnp.asarray(arrays["offset"], jnp.int32),
        pos=jnp.asarray(arrays["pos"], jnp.int32),
        mask=jnp.asarray(arrays["mask"].astype(bool)),
        first=jnp.asarray(arrays["first"].astype(bool)),
        full_read=jnp.asarray(full_read),
        space=space,
    )
    return batch, full_read


def _stream_results(t, full_read) -> dict[str, Any]:
    from jepsen_tpu.checkers.stream_lin import stream_lin_tensors_to_results

    results = stream_lin_tensors_to_results(t, full_read.tolist())
    return {
        "op": "result",
        "results": [
            {"stream": _jsonable(r), "valid?": bool(r["valid?"])}
            for r in results
        ],
    }


def _prepare_elle_batch(histories_json: list):
    """Host-side parse + edge inference + packing (the O(total ops) part —
    runs outside the device lock)."""
    from jepsen_tpu.checkers.elle import infer_txn_graph, pack_txn_graphs
    from jepsen_tpu.history.ops import Op

    if not isinstance(histories_json, list) or not histories_json:
        raise ProtocolError("histories must be a non-empty list")
    graphs = [
        infer_txn_graph([Op.from_json(d) for d in history])
        for history in histories_json
    ]
    return graphs, pack_txn_graphs(graphs)


def _elle_results(graphs, t) -> dict[str, Any]:
    from jepsen_tpu.checkers.elle import _classify

    g0 = np.asarray(t.g0)
    g1c = np.asarray(t.g1c)
    g2 = np.asarray(t.g2)
    results = [
        _classify(
            g,
            set(np.nonzero(g0[b])[0].tolist()),
            set(np.nonzero(g1c[b])[0].tolist()),
            set(np.nonzero(g2[b])[0].tolist()),
        )
        for b, g in enumerate(graphs)
    ]
    return {
        "op": "result",
        "results": [
            {"elle": _jsonable(r), "valid?": bool(r["valid?"])}
            for r in results
        ],
    }


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        server: CheckerServer = self.server  # type: ignore[assignment]
        while True:
            try:
                header, arrays = recv_frame(self.request)
            except (ProtocolError, ConnectionError, OSError):
                return
            try:
                reply = server.dispatch(header, arrays)
                send_frame(self.request, reply)
            except ProtocolError as e:
                send_frame(self.request, {"op": "error", "error": str(e)})
            except Exception as e:  # noqa: BLE001 — report, keep serving
                logger.exception("check failed")
                send_frame(self.request, {"op": "error", "error": repr(e)})


class CheckerServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, host: str = "0.0.0.0", port: int = 8640):
        super().__init__((host, port), _Handler)
        # one device-compute at a time: connections multiplex onto the
        # accelerator serially, which is also the fastest way to use it
        self._device_lock = threading.Lock()

    @property
    def port(self) -> int:
        return self.server_address[1]

    def dispatch(
        self, header: dict[str, Any], arrays: dict[str, np.ndarray]
    ) -> dict[str, Any]:
        op = header.get("op")
        if op == "ping":
            import jax

            return {
                "op": "pong",
                "backend": jax.default_backend(),
                "device_count": jax.device_count(),
            }
        if op == "check":
            value_space = int(header.get("value_space", 0))
            if value_space <= 0:
                raise ProtocolError("value_space must be positive")
            with self._device_lock:
                return _check_arrays(arrays, value_space)
        if op == "check-stream":
            space = int(header.get("space", 0))
            if space <= 0:
                raise ProtocolError("space must be positive")
            from jepsen_tpu.checkers.stream_lin import stream_lin_tensor_check

            batch, full_read = _prepare_stream_batch(arrays, space)
            with self._device_lock:
                t = stream_lin_tensor_check(batch)
            return _stream_results(t, full_read)
        if op == "check-elle":
            from jepsen_tpu.checkers.elle import elle_tensor_check

            graphs, batch = _prepare_elle_batch(header.get("histories"))
            with self._device_lock:
                t = elle_tensor_check(batch)
            return _elle_results(graphs, t)
        raise ProtocolError(f"unknown op {op!r}")

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t


def serve_forever(host: str = "0.0.0.0", port: int = 8640) -> None:
    import jax

    from jepsen_tpu.utils.jaxenv import ensure_backend, pin_cpu_platform

    try:
        backend = ensure_backend()
    except TimeoutError as e:
        # a hanging chip-plugin init must not take the sidecar down —
        # serve on CPU and say so, rather than blocking forever (safe
        # because ensure_backend probes in a subprocess: this process has
        # not touched the hanging plugin)
        print(f"warning: {e}; serving on the CPU backend")
        pin_cpu_platform()
        backend = jax.default_backend()
    srv = CheckerServer(host, port)
    print(f"checker sidecar on {host}:{srv.port} (backend={backend})")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
