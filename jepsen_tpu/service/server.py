"""The checker sidecar server.

A long-lived process owning the JAX backend — one chip, or every device
the runtime can see sharded through a ``(hist, seq)`` mesh (pass
``mesh=`` / ``serve_forever(seq=...)``; multi-device runtimes build the
global mesh automatically, including pod-wide after ``init_multihost``).
Controllers connect over TCP, send packed histories, and get
reference-shaped verdicts back.  The jitted check program is cached per
``(B, L, V)`` shape, so a fleet of runs with bucketed shapes pays one
compile each.  Batches whose size doesn't divide the ``hist`` axis are
padded with fully-masked histories and sliced back on reply.

Ops:

- ``ping``  → backend info (devices, platform)
- ``check`` → arrays ``f``/``type``/``value``/``mask`` of shape ``[B, L]``
  + ``value_space`` → per-history ``total-queue`` and queue-linearizability
  verdicts
- ``check-stream`` → the packed stream columns + ``space`` → per-history
  stream-log linearizability verdicts
- ``check-elle`` → histories as op JSON in the header (edge inference is
  a host-side parse; the server runs it next to the device) → per-history
  Elle serializability verdicts
"""

from __future__ import annotations

import logging
import os
import socketserver
import threading
from typing import Any

import numpy as np

from jepsen_tpu.service.protocol import (
    ProtocolError,
    TornPayloadError,
    recv_frame,
    send_frame,
)

logger = logging.getLogger("jepsen_tpu.service")

REQUIRED_ARRAYS = ("f", "type", "value", "mask")

#: the streaming ingestion surface (service/stream.py); everything else
#: is the original batch sidecar
_STREAM_OPS = frozenset({
    "stream-open", "stream-feed", "stream-finish", "stream-abort",
    "submit-batch", "collect", "cache-get", "service-stats",
})

#: chaos hook (tools/chaos_check.py vocabulary): ``"<n>"`` — the FIRST
#: subscription on this server is torn (socket closed abruptly) after
#: pushing n verdict-window frames; consumed once, so the client's
#: reconnect-with-replay lands on a healthy push loop
SUB_DROP_ENV = "JEPSEN_TPU_SERVE_SUB_DROP_AFTER"

#: bound on how long a push loop waits for the NEXT window before
#: answering with a machine-readable timeout frame (never a silent hang)
SUBSCRIBE_IDLE_TIMEOUT_S = 120.0


def _pad_batch_axis(tree, multiple: int):
    """Zero/False-pad every leaf's axis 0 to a multiple (padded histories
    are fully masked → ignored); returns ``(padded, original_B)``."""
    import jax
    import jax.numpy as jnp

    B = jax.tree.leaves(tree)[0].shape[0]
    pad = (-B) % multiple
    if pad == 0:
        return tree, B

    def p(x):
        return jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))

    return jax.tree.map(p, tree), B


def _check_arrays(
    arrays: dict[str, np.ndarray], value_space: int, mesh=None
) -> dict[str, Any]:
    import jax.numpy as jnp

    from jepsen_tpu.checkers.queue_lin import queue_lin_tensors_to_results
    from jepsen_tpu.checkers.total_queue import _tensors_to_results

    missing = [k for k in REQUIRED_ARRAYS if k not in arrays]
    if missing:
        raise ProtocolError(f"missing arrays: {missing}")
    f = jnp.asarray(arrays["f"], jnp.int32)
    type_ = jnp.asarray(arrays["type"], jnp.int32)
    value = jnp.asarray(arrays["value"], jnp.int32)
    mask = jnp.asarray(arrays["mask"].astype(bool))

    if mesh is not None:
        # mesh-wide check: the same sharded programs the driver dryruns
        import jax
        from jax.sharding import NamedSharding

        from jepsen_tpu.parallel.mesh import (
            HIST_AXIS,
            SEQ_AXIS,
            _queue_lin_program,
            _row_spec,
            _total_queue_program,
        )

        (f, type_, value, mask), B = _pad_batch_axis(
            (f, type_, value, mask), mesh.shape[HIST_AXIS]
        )
        # the op axis must divide the seq shards too: pad with masked rows
        # (appended at the end, so real row positions are unchanged)
        pad_l = (-f.shape[1]) % mesh.shape[SEQ_AXIS]
        if pad_l:
            widths = ((0, 0), (0, pad_l))
            f = jnp.pad(f, widths)
            type_ = jnp.pad(type_, widths)
            value = jnp.pad(value, widths)
            mask = jnp.pad(mask, widths)
        # place once; both programs then consume the committed arrays
        sh = NamedSharding(mesh, _row_spec())
        f, type_, value, mask = (
            jax.device_put(x, sh) for x in (f, type_, value, mask)
        )
        tq = _total_queue_program(mesh, value_space)(f, type_, value, mask)
        ql = _queue_lin_program(mesh, value_space)(f, type_, value, mask)
    else:
        from jepsen_tpu.checkers.fused import _combined_batch

        # the canonical single-program combined check (checkers/fused.py)
        tq, ql = _combined_batch(f, type_, value, mask, value_space)
        B = f.shape[0]
    tq_results = _tensors_to_results(tq)[:B]
    ql_results = queue_lin_tensors_to_results(ql)[:B]
    out = []
    for q, l in zip(tq_results, ql_results):
        out.append(
            {
                "queue": _jsonable(q),
                "linear": _jsonable(l),
                "valid?": bool(q["valid?"] and l["valid?"]),
            }
        )
    return {"op": "result", "results": out}


def _jsonable(d: dict[str, Any]) -> dict[str, Any]:
    """Result maps hold value sets; the wire header is JSON."""
    return {
        k: sorted(v) if isinstance(v, (set, frozenset)) else v
        for k, v in d.items()
    }


def _prepare_stream_batch(arrays: dict[str, np.ndarray], space: int):
    """Host-side reconstruction of a StreamBatch (no device lock needed)."""
    import jax.numpy as jnp

    from jepsen_tpu.checkers.stream_lin import STREAM_ARRAYS, StreamBatch

    missing = [k for k in STREAM_ARRAYS if k not in arrays]
    if missing:
        raise ProtocolError(f"missing arrays: {missing}")
    full_read = arrays["full_read"].astype(bool)
    batch = StreamBatch(
        type=jnp.asarray(arrays["type"], jnp.int32),
        f=jnp.asarray(arrays["f"], jnp.int32),
        value=jnp.asarray(arrays["value"], jnp.int32),
        offset=jnp.asarray(arrays["offset"], jnp.int32),
        pos=jnp.asarray(arrays["pos"], jnp.int32),
        mask=jnp.asarray(arrays["mask"].astype(bool)),
        first=jnp.asarray(arrays["first"].astype(bool)),
        full_read=jnp.asarray(full_read),
        space=space,
    )
    return batch, full_read


def _stream_results(t, full_read) -> dict[str, Any]:
    from jepsen_tpu.checkers.stream_lin import stream_lin_tensors_to_results

    results = stream_lin_tensors_to_results(t, full_read.tolist())
    return {
        "op": "result",
        "results": [
            {"stream": _jsonable(r), "valid?": bool(r["valid?"])}
            for r in results
        ],
    }


def _prepare_elle_batch(histories_json: list):
    """Host-side parse + edge inference + packing (the O(total ops) part —
    runs outside the device lock)."""
    from jepsen_tpu.checkers.elle import infer_txn_graph, pack_txn_graphs
    from jepsen_tpu.history.ops import Op

    if not isinstance(histories_json, list) or not histories_json:
        raise ProtocolError("histories must be a non-empty list")
    graphs = [
        infer_txn_graph([Op.from_json(d) for d in history])
        for history in histories_json
    ]
    return graphs, pack_txn_graphs(graphs)


def _elle_results(graphs, t) -> dict[str, Any]:
    from jepsen_tpu.checkers.elle import _classify

    g0 = np.asarray(t.g0)
    g1c = np.asarray(t.g1c)
    g2 = np.asarray(t.g2)
    results = [
        _classify(
            g,
            set(np.nonzero(g0[b])[0].tolist()),
            set(np.nonzero(g1c[b])[0].tolist()),
            set(np.nonzero(g2[b])[0].tolist()),
        )
        for b, g in enumerate(graphs)
    ]
    return {
        "op": "result",
        "results": [
            {"elle": _jsonable(r), "valid?": bool(r["valid?"])}
            for r in results
        ],
    }


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        server: CheckerServer = self.server  # type: ignore[assignment]
        while True:
            try:
                header, arrays = recv_frame(self.request)
            except TornPayloadError as e:
                # the frame was fully consumed (connection still in
                # sync): quarantine exactly the poisoned stream, reply,
                # keep serving this connection
                try:
                    send_frame(self.request, server.torn_reply(e))
                except (ProtocolError, ConnectionError, OSError):
                    return
                continue
            except (ProtocolError, ConnectionError, OSError):
                return
            if header.get("op") == "stream-subscribe":
                # push mode: the reply rhythm inverts — the server sends
                # verdict-window frames as segments close, until the
                # terminal window (or the chaos tear) ends the loop
                try:
                    if not self._handle_subscribe(server, header):
                        return
                    continue
                except (ProtocolError, ConnectionError, OSError):
                    return
            try:
                reply = server.dispatch(header, arrays)
                send_frame(self.request, reply)
            except ProtocolError as e:
                send_frame(self.request, {"op": "error", "error": str(e)})
            except Exception as e:  # noqa: BLE001 — report, keep serving
                logger.exception("check failed")
                send_frame(self.request, {"op": "error", "error": repr(e)})

    def _handle_subscribe(self, server: "CheckerServer", header) -> bool:
        """Run one subscription push loop.  Returns True to keep the
        connection (back to the request rhythm after the terminal
        window), False to close it (chaos tear / dead subscriber)."""
        import queue as queue_mod

        server.metrics.counter(
            "service.requests", op="stream-subscribe"
        ).inc()
        svc = server.ingest_service()
        sid = str(header.get("stream"))
        if header.get("stream") is None:
            raise ProtocolError("stream-subscribe requires stream")
        from_window = int(header.get("from_window", 0))
        ack, replay, q = svc.subscribe(sid, from_window)
        if ack.get("op") != "subscribed":
            send_frame(self.request, ack)
            return True
        drop_after = server.take_sub_drop()
        pushed = 0
        final_seen = False
        try:
            send_frame(self.request, ack)
            for w in replay:
                send_frame(self.request, w)
                pushed += 1
                final_seen = final_seen or bool(w.get("final"))
                if drop_after is not None and pushed >= drop_after:
                    logger.error(
                        "%s hook: tearing subscription on %s after %d "
                        "window(s)", SUB_DROP_ENV, sid, pushed,
                    )
                    return False
            if final_seen or q is None:
                if not final_seen:
                    # stream already done but the terminal window fell
                    # outside the replay range: say so, never hang
                    send_frame(self.request, {
                        "op": "subscribe-done", "stream": sid,
                        "pushed": pushed,
                    })
                return True
            deadline = None
            while True:
                try:
                    w = q.get(timeout=0.5)
                except queue_mod.Empty:
                    import time as _time

                    if deadline is None:
                        deadline = (
                            _time.monotonic() + SUBSCRIBE_IDLE_TIMEOUT_S
                        )
                    elif _time.monotonic() > deadline:
                        send_frame(self.request, {
                            "op": "subscribe-timeout", "stream": sid,
                            "idle_s": SUBSCRIBE_IDLE_TIMEOUT_S,
                            "pushed": pushed,
                        })
                        return True
                    continue
                deadline = None
                send_frame(self.request, w)
                pushed += 1
                if drop_after is not None and pushed >= drop_after:
                    logger.error(
                        "%s hook: tearing subscription on %s after %d "
                        "window(s)", SUB_DROP_ENV, sid, pushed,
                    )
                    return False
                if w.get("final"):
                    return True
        finally:
            svc.unsubscribe(sid, q)


class CheckerServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 8640,
        mesh=None,
        metrics_registry=None,
        ingest_opts: dict | None = None,
        cache_capacity: int = 4096,
        store: str | None = None,
    ):
        super().__init__((host, port), _Handler)
        # streaming ingestion (stream-open/feed/finish, submit/collect):
        # built lazily on first streaming op so batch-only deployments
        # never pay the worker pool; constructor knobs flow through
        self._ingest = None
        self._ingest_lock = threading.Lock()
        self._ingest_opts = dict(ingest_opts or {})
        self._cache_capacity = cache_capacity
        self._store = store
        # one device-compute at a time: connections multiplex onto the
        # accelerator serially, which is also the fastest way to use it
        self._device_lock = threading.Lock()
        # optional (hist, seq) mesh: batches shard across every device the
        # runtime can see (a slice, or a pod via jax.distributed)
        self._mesh = mesh
        # the shared obs metrics registry (default: the process-global
        # one): every check op lands its wall latency in a mergeable
        # quantile sketch, which the /metrics endpoint renders as
        # p50/p90/p99 — the ROADMAP direction-1 latency-SLO substrate
        from jepsen_tpu.obs import metrics as obs_metrics

        self.metrics = (
            obs_metrics.REGISTRY
            if metrics_registry is None
            else metrics_registry
        )
        self._metrics_srv = None
        # chaos: arm the one-shot subscription tear from the env
        self._sub_drop: int | None = None
        spec = os.environ.get(SUB_DROP_ENV)
        if spec:
            try:
                self._sub_drop = int(spec)
            except ValueError:
                logger.error("%s=%r malformed (want int); ignoring",
                             SUB_DROP_ENV, spec)

    def take_sub_drop(self) -> int | None:
        """Consume the one-shot torn-subscription chaos hook (the first
        subscriber gets torn; its reconnect must find a healthy loop)."""
        with self._ingest_lock:
            n, self._sub_drop = self._sub_drop, None
            return n

    @property
    def port(self) -> int:
        return self.server_address[1]

    def start_metrics(
        self,
        host: str = "0.0.0.0",
        port: int = 9640,
        store: str | None = None,
    ):
        """Serve the shared registry as Prometheus text on
        ``GET http://host:port/metrics`` — and, when ``store`` is
        given, per-run reports on ``GET /report/<run>`` (rendered on
        demand from the store tree) plus ``GET /report/by-key/<key>``
        (content-addressed verdict-cache lookup, 302 to the recorded
        run); returns the HTTP server (``.server_address[1]`` carries
        the bound port)."""
        from jepsen_tpu.obs import metrics as obs_metrics

        self._metrics_srv = obs_metrics.serve_metrics(
            host, port, self.metrics, store=store,
            # lazy: the ingest core (and with it the cache) may not be
            # built yet when the metrics endpoint comes up
            cache=lambda: (
                self._ingest.cache if self._ingest is not None else None
            ),
        )
        self._metrics_srv.start_background()
        return self._metrics_srv

    def server_close(self):
        if self._metrics_srv is not None:
            self._metrics_srv.shutdown()
            self._metrics_srv.server_close()
            self._metrics_srv = None
        if self._ingest is not None:
            self._ingest.close()
            self._ingest = None
        super().server_close()

    def ingest_service(self):
        """The lazily-built streaming ingestion core (thread-safe)."""
        if self._ingest is None:
            with self._ingest_lock:
                if self._ingest is None:
                    from jepsen_tpu.service.cache import VerdictCache
                    from jepsen_tpu.service.stream import IngestService

                    cache = VerdictCache(
                        capacity=self._cache_capacity,
                        registry=self.metrics,
                    )
                    if self._store:
                        try:
                            n = cache.seed_from_store(self._store)
                            if n:
                                logger.info(
                                    "verdict cache seeded with %d "
                                    "recorded run(s) from %s",
                                    n, self._store,
                                )
                        except Exception:  # noqa: BLE001 — serve anyway
                            logger.exception(
                                "cache seed from %s failed", self._store
                            )
                        self._export_fleet_gauges()
                    self._ingest = IngestService(
                        cache=cache,
                        registry=self.metrics,
                        **self._ingest_opts,
                    )
        return self._ingest

    def _export_fleet_gauges(self) -> None:
        """Fleet-memory state of the backing store, as gauges on the
        service registry (visible on ``/metrics``): CAS dedup ratio,
        prefix-checkpoint index size, and per-config regression flags
        (``jepsen_tpu/report/baselines.py``).  Pure telemetry — any
        failure here costs a gauge, never the service."""
        try:
            from jepsen_tpu.history.cas import dedup_stats

            ds = dedup_stats(self._store)
            self.metrics.gauge("fleet.cas_dedup_ratio").set(ds["ratio"])
            self.metrics.gauge("fleet.cas_objects").set(
                ds.get("unique_objects", 0)
            )
        except Exception:  # noqa: BLE001 — telemetry only
            logger.debug("cas dedup gauge skipped", exc_info=True)
        try:
            import os as _os

            from jepsen_tpu.history.prefix_index import (
                DEFAULT_INDEX_DIR,
                PrefixCheckpointIndex,
            )

            st = PrefixCheckpointIndex(
                _os.path.join(self._store, DEFAULT_INDEX_DIR)
            ).stats()
            self.metrics.gauge("fleet.prefix_index_entries").set(
                st["entries"]
            )
        except Exception:  # noqa: BLE001 — telemetry only
            logger.debug("prefix index gauge skipped", exc_info=True)
        try:
            from jepsen_tpu.report.baselines import collect_baselines

            collect_baselines(self._store, registry=self.metrics)
        except Exception:  # noqa: BLE001 — telemetry only
            logger.debug("baseline gauges skipped", exc_info=True)

    def torn_reply(self, e: TornPayloadError) -> dict[str, Any]:
        """Map a torn frame to its stream: poison evidence quarantines
        exactly that stream (never folded into a verdict); torn frames
        outside a stream are a plain error reply."""
        hdr = e.header
        sid = hdr.get("stream")
        if hdr.get("op") == "stream-feed" and sid is not None:
            self.metrics.counter(
                "service.torn_blocks", op="stream-feed"
            ).inc()
            return self.ingest_service().quarantine_stream(
                str(sid),
                f"torn block on the wire (seq {hdr.get('seq')}): {e}",
            )
        return {"op": "error", "error": str(e), "torn": e.torn}

    def dispatch(
        self, header: dict[str, Any], arrays: dict[str, np.ndarray]
    ) -> dict[str, Any]:
        import time as _time

        from jepsen_tpu.obs import trace as obs_trace

        op = header.get("op")
        if op in ("check", "check-stream", "check-elle"):
            t0 = _time.perf_counter()
            try:
                reply = self._dispatch(op, header, arrays)
            except Exception:
                self.metrics.counter("service.errors", op=op).inc()
                raise
            dt = _time.perf_counter() - t0
            self.metrics.counter("service.requests", op=op).inc()
            self.metrics.counter("service.histories", op=op).inc(
                len(reply.get("results", ()))
            )
            self.metrics.sketch("service.check_latency_s", op=op).add(dt)
            # per-thread track (the handler thread's name), NOT one
            # shared "service" track: concurrent requests overlap in
            # time (t0 is taken before the device lock), and overlapping
            # spans on one tid would render as bogus nesting
            obs_trace.complete(f"service.{op}", t0, t0 + dt)
            return reply
        if op in _STREAM_OPS:
            self.metrics.counter("service.requests", op=op).inc()
        return self._dispatch(op, header, arrays)

    def _dispatch(
        self, op, header: dict[str, Any], arrays: dict[str, np.ndarray]
    ) -> dict[str, Any]:
        if op in _STREAM_OPS:
            return self._dispatch_stream(op, header, arrays)
        if op == "ping":
            import jax

            return {
                "op": "pong",
                "backend": jax.default_backend(),
                "device_count": jax.device_count(),
            }
        if op == "check":
            value_space = int(header.get("value_space", 0))
            if value_space <= 0:
                raise ProtocolError("value_space must be positive")
            with self._device_lock:
                return _check_arrays(arrays, value_space, mesh=self._mesh)
        if op == "check-stream":
            space = int(header.get("space", 0))
            if space <= 0:
                raise ProtocolError("space must be positive")
            append_fail = header.get("append-fail", "definite")
            if append_fail not in ("definite", "indeterminate"):
                raise ProtocolError(f"unknown append-fail {append_fail!r}")
            batch, full_read = _prepare_stream_batch(arrays, space)
            with self._device_lock:
                if self._mesh is not None:
                    from jepsen_tpu.parallel.mesh import (
                        HIST_AXIS,
                        sharded_stream_lin,
                    )

                    batch, nb = _pad_batch_axis(
                        batch, self._mesh.shape[HIST_AXIS]
                    )
                    t = sharded_stream_lin(
                        batch, self._mesh, append_fail=append_fail
                    )
                    full_read = np.pad(full_read, (0, batch.batch - nb))
                else:
                    from jepsen_tpu.checkers.stream_lin import (
                        stream_lin_tensor_check,
                    )

                    nb = len(full_read)
                    t = stream_lin_tensor_check(
                        batch, append_fail=append_fail
                    )
            reply = _stream_results(t, full_read)
            for r in reply["results"]:
                r["stream"]["append-fail"] = append_fail
            reply["results"] = reply["results"][:nb]
            return reply
        if op == "check-elle":
            graphs, batch = _prepare_elle_batch(header.get("histories"))
            with self._device_lock:
                if self._mesh is not None:
                    from jepsen_tpu.parallel.mesh import (
                        HIST_AXIS,
                        sharded_elle,
                    )

                    batch, _nb = _pad_batch_axis(
                        batch, self._mesh.shape[HIST_AXIS]
                    )
                    t = sharded_elle(batch, self._mesh)
                else:
                    from jepsen_tpu.checkers.elle import elle_tensor_check

                    t = elle_tensor_check(batch)
            # _elle_results iterates the (unpadded) graphs, so padded rows
            # drop out naturally
            return _elle_results(graphs, t)
        raise ProtocolError(f"unknown op {op!r}")

    def _dispatch_stream(
        self, op, header: dict[str, Any], arrays: dict[str, np.ndarray]
    ) -> dict[str, Any]:
        """The always-on streaming surface: every reply is a plain
        machine-readable dict (``opened`` / ``accepted`` / ``rejected``
        with ``SATURATED`` / ``quarantined`` / a verdict) — admission
        decisions are data, not exceptions."""
        svc = self.ingest_service()
        if op == "stream-open":
            workload = header.get("workload")
            if not workload:
                raise ProtocolError("stream-open requires workload")
            return svc.open(
                str(workload),
                opts=header.get("opts") or {},
                content_key=header.get("content_key"),
                deadline_s=header.get("deadline_s"),
            )
        if op == "stream-feed":
            sid = header.get("stream")
            seq = header.get("seq")
            if sid is None or seq is None:
                raise ProtocolError("stream-feed requires stream and seq")
            if "rows" in arrays:
                payload = arrays["rows"]
                bkind = "rows"
                n_ops = int(header.get("n_ops", payload.shape[0]))
            elif "ops_block" in header:
                payload = header["ops_block"]
                bkind = "ops"
                n_ops = int(header.get("n_ops", len(payload)))
            else:
                raise ProtocolError(
                    "stream-feed requires a rows array or an ops_block"
                )
            return svc.feed(str(sid), int(seq), bkind, payload, n_ops)
        if op == "stream-finish":
            sid = header.get("stream")
            if sid is None:
                raise ProtocolError("stream-finish requires stream")
            verdict = svc.finish(str(sid), timeout=header.get("timeout"))
            if "op" not in verdict:
                verdict = dict(verdict)
                verdict["op"] = "verdict"
            return verdict
        if op == "stream-abort":
            sid = header.get("stream")
            if sid is None:
                raise ProtocolError("stream-abort requires stream")
            return svc.abort(str(sid))
        if op == "submit-batch":
            # the fleet path: one frame = many histories (concatenated
            # rows + offsets), one admission decision each
            workload = header.get("workload")
            if not workload:
                raise ProtocolError("submit-batch requires workload")
            if "rows" not in arrays or "offsets" not in arrays:
                raise ProtocolError(
                    "submit-batch requires rows and offsets arrays"
                )
            rows = arrays["rows"]
            offsets = np.asarray(arrays["offsets"], np.int64)
            n_ops = header.get("n_ops") or []
            keys = header.get("content_keys") or []
            opts = header.get("opts") or {}
            replies = []
            for i in range(len(offsets) - 1):
                blk = rows[int(offsets[i]) : int(offsets[i + 1])]
                replies.append(svc.submit(
                    str(workload), opts, "rows", blk,
                    int(n_ops[i]) if i < len(n_ops) else blk.shape[0],
                    content_key=keys[i] if i < len(keys) else None,
                ))
            return {"op": "submitted", "replies": replies}
        if op == "collect":
            ids = header.get("ids") or []
            return svc.collect(
                [str(i) for i in ids],
                timeout=float(header.get("timeout", 0.0)),
            )
        if op == "cache-get":
            key = header.get("content_key")
            if not key:
                raise ProtocolError("cache-get requires content_key")
            if svc.cache is None:
                return {"op": "miss"}
            from jepsen_tpu.service.cache import cache_key

            entry = svc.cache.get(cache_key(
                str(key), str(header.get("workload", "queue")),
                header.get("opts") or {},
            ))
            if entry is None:
                return {"op": "miss"}
            out = {"op": "cached", "verdict": entry["verdict"]}
            if "report_ref" in entry:
                out["report_ref"] = entry["report_ref"]
            return out
        if op == "service-stats":
            stats = svc.stats()
            stats["op"] = "stats"
            return stats
        raise ProtocolError(f"unknown stream op {op!r}")

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t


def serve_forever(
    host: str = "0.0.0.0",
    port: int = 8640,
    seq: int = 1,
    store: str = "store",
    metrics_port: int = 9640,
    workers: int = 2,
    max_streams: int = 256,
    ingress_cap: int = 1024,
    stream_deadline_s: float = 120.0,
    batch: bool = False,
    target_batch: int = 32,
    max_batch_wait_ms: float = 25.0,
    warmup: bool = False,
    warmup_buckets=((128, 128), (256, 256)),
) -> None:
    import jax

    from jepsen_tpu.utils.jaxenv import (
        enable_compilation_cache,
        ensure_backend,
        pin_cpu_platform,
    )

    # NOTE: no opportunistic harvest here, deliberately — the sidecar
    # never exits, so a spawned harvest child could never take the
    # exclusive chip; it would only hold the single-flight lock and
    # starve real capture windows (see utils/harvest.opportunistic).
    try:
        backend = ensure_backend()
        if backend == "tpu":
            # TPU-only (CPU AOT-loader feature drift, jaxenv docstring);
            # same store-derived dir as the CLI so the two share compiles
            enable_compilation_cache(os.path.join(store, "xla_cache"))
    except TimeoutError as e:
        # a hanging chip-plugin init must not take the sidecar down —
        # serve on CPU and say so, rather than blocking forever (safe
        # because ensure_backend probes in a subprocess: this process has
        # not touched the hanging plugin)
        print(f"warning: {e}; serving on the CPU backend")
        pin_cpu_platform()
        backend = jax.default_backend()
    mesh = None
    if jax.device_count() > 1:
        from jepsen_tpu.parallel.distributed import global_checker_mesh

        mesh = global_checker_mesh(seq=seq)
    srv = CheckerServer(
        host, port, mesh=mesh, store=store,
        ingest_opts={
            "workers": workers,
            "max_streams": max_streams,
            "ingress_cap": ingress_cap,
            "stream_deadline_s": stream_deadline_s,
            # continuous batching (ISSUE 20): cross-stream coalescing
            # with AOT bucket warmup off the latency path
            "batch": batch,
            "target_batch": target_batch,
            "max_batch_wait_ms": max_batch_wait_ms,
            "warmup": warmup,
            "warmup_buckets": tuple(warmup_buckets),
        },
    )
    if batch and warmup:
        # the batcher (and its AOT warmup) is built lazily with the
        # ingest core — force it NOW so the compile happens at service
        # start, not on the first admitted stream's latency path
        srv.ingest_service()
    metrics_note = "off"
    if metrics_port >= 0:
        try:
            msrv = srv.start_metrics(host, metrics_port, store=store)
            metrics_note = (
                f"http://{host}:{msrv.server_address[1]}/metrics "
                f"(+ /report/<run> over {store})"
            )
        except OSError as e:
            # a busy metrics port must not take the checker down — the
            # sidecar's job is verdicts; scraping is best-effort
            print(f"warning: /metrics endpoint unavailable ({e}); "
                  f"serving checks without it")
    print(
        f"checker sidecar on {host}:{srv.port} (backend={backend}, "
        f"mesh={dict(mesh.shape) if mesh else None}, "
        f"metrics={metrics_note})"
    )
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
