"""Streaming ingestion: the always-on verification service's core.

The sidecar's batch ops (``check`` / ``check-stream`` / ``check-elle``)
answer one request at a time; under live traffic that shape is a full
outage waiting for one slow stream.  This module is the robustness
contract made first-class:

- **Streams, not requests.**  A client opens a stream, feeds ``.jtc``
  column blocks (queue family: the zero-parse ``[n, 8]`` row slices) or
  op-JSON blocks (stream/elle/mutex families) in sequence order, and
  finishes for a verdict.  Each stream owns a PR-15
  :class:`~jepsen_tpu.checkers.segmented.SegmentedChecker` carry
  engine, so verdicts are ≡ the batch ``check`` oracle by construction.

- **Admission control + backpressure.**  Both bounds are explicit and
  LOUD: more open streams than ``max_streams``, or more queued blocks
  than ``ingress_cap``, and the offer is rejected with a
  machine-readable ``SATURATED`` — never a silent drop (the block stays
  with the client; nothing was consumed), never a fabricated gapped
  carry (the PR-15 bounded live-check hand-off, generalized to the
  wire).

- **Degraded-but-honest under worker death.**  Checker workers claim
  streams off a shared token queue (shape-bucketed so same-shape
  streams coalesce onto the worker that just ran that compiled shape —
  the lane pipeline's ``_pow2_bucket`` discipline).  The carry state is
  snapshotted after every fed block; a worker dying MID-FEED loses
  nothing — the claim is requeued onto a survivor, the engine restored
  from the snapshot, the block re-fed, and the stream's verdict carries
  machine-readable ``degraded`` provenance (the PR-13 spool/requeue
  protocol under live traffic).  A block that kills workers past the
  retry budget quarantines ITS stream as unknown-with-evidence; zero
  survivors quarantine every open stream rather than hang their
  clients.

- **Sequencing is part of the contract.**  Blocks carry a sequence
  number; a duplicate is acked idempotently (safe client retry after a
  connection reset), a GAP quarantines the stream — a carry fed around
  a hole would fabricate a verdict for ops it never saw.

- **Content-addressed verdict cache.**  The server runs its own sha256
  over every block payload it accepts; a clean finished verdict is
  cached under (digest, workload, contract) so a repeat submission
  costs a hash lookup, not a device dispatch (``service/cache.py``).

Everything here is transport-free — ``service/server.py`` maps wire
ops onto :class:`IngestService`, and the tests drive it directly.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import queue as queue_mod
import threading
import time
from collections import deque
from typing import Sequence

import numpy as np

from jepsen_tpu.checkers.protocol import UNKNOWN, VALID

logger = logging.getLogger("jepsen_tpu.service.stream")

#: chaos hook (tools/chaos_check.py vocabulary, the PR-13/15 die-after
#: pattern): ``"<worker_idx>:<n_blocks>"`` — that checker worker raises
#: :class:`WorkerDeath` MID-FEED of its n-th block (after the engine
#: mutation, before the snapshot/ack), the worst-case kill point
DIE_AFTER_ENV = "JEPSEN_TPU_SERVE_DIE_AFTER"

#: a block that sees this many worker deaths is poison: quarantine the
#: stream (PR-13 precedence — never foldable into valid), stop killing
MAX_BLOCK_RETRIES = 2

#: per-stream window-log retention for subscribers (ISSUE 17): a push
#: connection that drops and reconnects with ``from_window`` gets the
#: missed windows REPLAYED from this log; asking below the retained
#: floor is answered with a machine-readable gap, never a silent skip
WINDOW_LOG_CAP = 64

SATURATED = "SATURATED"


class WorkerDeath(BaseException):
    """Chaos-injected checker-worker death (BaseException so ordinary
    ``except Exception`` recovery paths cannot swallow the kill)."""


def _parse_die_after(spec: str | None) -> tuple[int, int] | None:
    if not spec:
        return None
    try:
        idx, blocks = spec.split(":", 1)
        return int(idx), int(blocks)
    except ValueError:
        logger.error("%s=%r malformed (want idx:blocks); ignoring",
                     DIE_AFTER_ENV, spec)
        return None


class _Stream:
    """One admitted history stream and its carry engine."""

    __slots__ = (
        "sid", "workload", "opts", "engine", "kind", "shape",
        "pending", "next_seq", "blocks_fed", "ops_fed", "snapshot",
        "retries", "requeues", "quarantined", "finish_requested",
        "busy", "scheduled", "verdict", "done", "done_at",
        "created", "t0", "deadline", "digest", "content_key",
        "dead_workers", "carry_nbytes",
        "windows", "window_base", "subscribers",
        "batch_inflight", "batch_next_merge", "batch_results",
    )

    def __init__(self, sid, workload, opts, engine, kind, deadline_s):
        self.sid = sid
        self.workload = workload
        self.opts = opts
        self.engine = engine
        self.kind = kind  # "stream" (multi-block) | "submit" (one-shot)
        self.shape: tuple | None = None
        self.pending: deque = deque()  # (seq, block_kind, payload, n_ops)
        self.next_seq = 0
        self.blocks_fed = 0
        self.ops_fed = 0
        self.snapshot: dict | None = None
        self.retries = 0
        self.requeues: list[dict] = []
        self.dead_workers: list[str] = []
        self.quarantined = False
        self.finish_requested = False
        self.busy = False
        self.scheduled = False
        self.verdict: dict | None = None
        self.done = threading.Event()
        self.done_at: float | None = None
        self.created = time.monotonic()
        self.t0 = time.perf_counter()
        self.deadline = self.created + deadline_s
        self.digest = hashlib.sha256()
        self.content_key: str | None = None
        self.carry_nbytes = 0  # last snapshot's footprint (gauge share)
        # -- subscription push (ISSUE 17) --
        self.windows: deque = deque()  # retained verdict windows (replay)
        self.window_base = 0  # window index of windows[0] (the floor)
        self.subscribers: list = []  # live SimpleQueue sinks
        # -- continuous batching (ISSUE 20) --
        self.batch_inflight = 0  # accepted, not yet merged/evicted
        self.batch_next_merge = 0  # next seq the demux may fold in
        self.batch_results: dict = {}  # seq -> landed entry (reorder)


def _wire_safe(v):
    """Verdicts leave here over JSON (wire replies, the verdict cache):
    value sets become sorted lists (the batch ops' ``_jsonable``
    convention, deep), numpy scalars become Python ones."""
    if isinstance(v, dict):
        return {k: _wire_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_wire_safe(x) for x in v]
    if isinstance(v, (set, frozenset)):
        return sorted(v)
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    if isinstance(v, np.bool_):
        return bool(v)
    return v


def _block_shape(workload: str, block) -> tuple:
    from jepsen_tpu.parallel.pipeline import _pow2_bucket

    _seq, bkind, payload, _n = block
    n = len(payload) if bkind == "ops" else int(payload.shape[0])
    return (workload, _pow2_bucket(max(n, 1)))


class IngestService:
    """The long-lived ingestion core: admission, bounded ingress,
    shape-coalescing checker workers, degraded-but-honest recovery.

    All limits are constructor-explicit so tests and the bench can pin
    tiny bounds; the CLI exposes them on ``serve-checker``."""

    def __init__(
        self,
        workers: int = 2,
        max_streams: int = 256,
        ingress_cap: int = 1024,
        stream_deadline_s: float = 120.0,
        cache=None,
        device: bool | None = None,
        registry=None,
        block_delay_s: float = 0.0,
        die_after: tuple[int, int] | None = None,
        done_ttl_s: float = 300.0,
        batch: bool = False,
        target_batch: int = 32,
        max_batch_wait_ms: float = 25.0,
        dispatch_depth: int = 2,
        park_max_s: float = 5.0,
        warmup: bool = False,
        warmup_buckets: Sequence[tuple[int, int]] = (
            (128, 128), (256, 256),
        ),
    ):
        if workers < 1:
            raise ValueError("need at least one checker worker")
        if registry is None:
            from jepsen_tpu.obs.metrics import REGISTRY as registry  # noqa: N813
        self.metrics = registry
        self.max_streams = max_streams
        self.ingress_cap = ingress_cap
        self.stream_deadline_s = stream_deadline_s
        self.cache = cache
        self.block_delay_s = block_delay_s
        self.done_ttl_s = done_ttl_s
        self._device = device
        self._die_after = (
            die_after
            if die_after is not None
            else _parse_die_after(os.environ.get(DIE_AFTER_ENV))
        )

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._streams: dict[str, _Stream] = {}
        self._tokens: deque[tuple[str, tuple]] = deque()
        self._active = 0  # undone streams (the admission bound)
        self._queued_blocks = 0  # blocks awaiting a worker (ingress bound)
        self._next_sid = 0
        self._running = True
        self._dead_workers: list[str] = []
        self._coalesced = 0

        self._g_depth = registry.gauge("service.ingress_depth")
        self._g_active = registry.gauge("service.streams_active")
        self._g_quar = registry.gauge("service.streams_quarantined")
        self._g_alive = registry.gauge("service.workers_alive")
        self._g_carry = registry.gauge("service.carry_bytes")
        self._carry_total = 0
        self._c_blocks = registry.counter("service.blocks")
        self._c_deaths = registry.counter("service.worker_deaths")
        self._c_requeues = registry.counter("service.block_requeues")
        self._c_windows = registry.counter("service.verdict_windows")
        self._g_subs = registry.gauge("service.subscribers")
        self._subs_total = 0
        self._s_verdict = registry.sketch("service.submit_to_verdict_s")
        self._s_block = registry.sketch("service.block_check_s")

        self._workers: list[threading.Thread] = []
        for i in range(workers):
            t = threading.Thread(
                target=self._worker, args=(i,),
                name=f"svcworker{i}", daemon=True,
            )
            self._workers.append(t)
            t.start()
        self._g_alive.set(workers)
        # continuous batching (ISSUE 20): opt-in cross-stream
        # coalescing of queue-family rows blocks into full
        # shape-bucketed super-batches, bounded by a latency budget
        self._batcher = None
        if batch:
            from jepsen_tpu.service.batcher import ContinuousBatcher

            self._batcher = ContinuousBatcher(
                self,
                target_batch=target_batch,
                max_wait_ms=max_batch_wait_ms,
                dispatch_depth=dispatch_depth,
                park_max_s=park_max_s,
                registry=registry,
            )
            if warmup:
                self._batcher.warmup(warmup_buckets)
        self._reaper = threading.Thread(
            target=self._reap, name="svc-reaper", daemon=True
        )
        self._reaper.start()

    # -- admission --------------------------------------------------------

    def _reject(self, reason: str, **detail) -> dict:
        self.metrics.counter(
            "service.admission_rejects", reason=reason
        ).inc()
        out = {"op": "rejected", "reason": SATURATED, "saturated": reason}
        out.update(detail)
        return out

    def _engine_device(self) -> bool:
        if self._device is None:
            # per-block dispatch of tiny segments on the CPU backend
            # loses to the numpy twin; real accelerators win (and share
            # the shape-bucketed compiled programs across streams)
            import jax

            self._device = jax.default_backend() != "cpu"
        return self._device

    def _new_engine(self, workload: str, opts: dict):
        from jepsen_tpu.checkers.segmented import SegmentedChecker

        return SegmentedChecker(
            workload, opts=opts, device=self._engine_device()
        )

    def open(
        self,
        workload: str,
        opts: dict | None = None,
        content_key: str | None = None,
        deadline_s: float | None = None,
        kind: str = "stream",
    ) -> dict:
        """Admit one stream (or serve it straight off the verdict
        cache).  Returns ``{"op": "opened", "stream": sid}``, a cached
        verdict, or a loud ``SATURATED`` reject."""
        opts = dict(opts or {})
        if content_key is not None and self.cache is not None:
            from jepsen_tpu.service.cache import cache_key

            entry = self.cache.get(cache_key(content_key, workload, opts))
            if entry is not None:
                out = {"op": "cached", "verdict": entry["verdict"]}
                if "report_ref" in entry:
                    out["report_ref"] = entry["report_ref"]
                return out
        with self._lock:
            if not self._running:
                return self._reject("shutdown")
            if len(self._dead_workers) >= len(self._workers):
                # a dead pool must refuse loudly, not enqueue forever
                return self._reject(
                    "no-live-workers",
                    dead_workers=list(self._dead_workers),
                )
            if self._active >= self.max_streams:
                return self._reject(
                    "streams", active=self._active,
                    max_streams=self.max_streams,
                )
            try:
                engine = self._new_engine(workload, opts)
            except ValueError as e:
                return {"op": "error", "error": str(e),
                        "reason": "bad-workload"}
            sid = f"s{self._next_sid}"
            self._next_sid += 1
            st = _Stream(
                sid, workload, opts, engine, kind,
                deadline_s if deadline_s is not None
                else self.stream_deadline_s,
            )
            st.content_key = content_key
            self._streams[sid] = st
            self._active += 1
            self._g_active.set(self._active)
        return {"op": "opened", "stream": sid}

    def feed(self, sid: str, seq: int, block_kind: str, payload,
             n_ops: int) -> dict:
        """Offer one block.  ``block_kind`` is ``"rows"`` (an ``[n, 8]``
        int32 matrix, queue family) or ``"ops"`` (a list of op-JSON
        dicts).  The reply is always machine-readable: ``accepted``
        (with the ingress depth), idempotent ``accepted dup`` for an
        already-fed seq, ``SATURATED`` (block NOT consumed — retry), or
        ``quarantined`` (gap / poisoned stream)."""
        with self._lock:
            st = self._streams.get(sid)
            if st is None:
                return {"op": "error", "error": f"unknown stream {sid!r}",
                        "reason": "unknown-stream"}
            if st.done.is_set() or st.quarantined:
                return {
                    "op": "quarantined", "stream": sid,
                    "error": "stream already closed or quarantined",
                }
            if seq < st.next_seq:
                # client retry after a reset: already consumed — ack,
                # never double-feed
                return {"op": "accepted", "stream": sid, "seq": seq,
                        "dup": True}
            if seq > st.next_seq:
                expected = st.next_seq
                self._quarantine_locked(
                    st,
                    f"gap in block sequence: expected seq {expected}, "
                    f"got {seq} — a carry fed around a hole would "
                    f"fabricate a verdict",
                )
                return {"op": "quarantined", "stream": sid,
                        "error": "sequence gap", "expected": expected,
                        "got": seq}
            if self._queued_blocks >= self.ingress_cap:
                return self._reject(
                    "ingress", queue_depth=self._queued_blocks,
                    ingress_cap=self.ingress_cap,
                )
            st.next_seq = seq + 1
            block = (seq, block_kind, payload, n_ops)
            if st.shape is None:
                st.shape = _block_shape(st.workload, block)
            batched = (
                self._batcher is not None and st.workload == "queue"
            )
            self._queued_blocks += 1
            self._g_depth.set(self._queued_blocks)
            if batched:
                # the coalescing path: parked entries stay counted in
                # the ingress bound above, so a full coalescing queue
                # counts against admission — never unbounded buffering
                st.batch_inflight += 1
            else:
                st.pending.append(block)
                self._schedule_locked(st)
            depth = self._queued_blocks
        if batched:
            # host prep + parking run on THIS connection's thread (the
            # lock is released): prep parallelizes across clients
            self._batcher.offer(st, seq, block_kind, payload, n_ops)
        if self.cache is not None:
            # content digest feeds ONLY the verdict cache key — with no
            # cache attached it is pure submit-path overhead (measured
            # >50% of a small submit's cost)
            if block_kind == "rows":
                st.digest.update(np.ascontiguousarray(payload).tobytes())
            else:
                st.digest.update(
                    json.dumps(payload, sort_keys=True,
                               separators=(",", ":")).encode()
                )
        return {"op": "accepted", "stream": sid, "seq": seq,
                "queue_depth": depth}

    def quarantine_stream(self, sid: str, error: str) -> dict:
        """External poison evidence (e.g. a torn block on the wire):
        quarantine THAT stream as unknown-with-evidence."""
        with self._lock:
            st = self._streams.get(sid)
            if st is None:
                return {"op": "error", "error": f"unknown stream {sid!r}"}
            self._quarantine_locked(st, error)
        return {"op": "quarantined", "stream": sid, "error": error}

    def abort(self, sid: str) -> dict:
        """Client abandons the stream: free its admission slot and any
        queued blocks without producing a verdict (nothing was promised
        — accounting-wise the stream never completed)."""
        with self._lock:
            st = self._streams.pop(sid, None)
            if st is None:
                return {"op": "error", "error": f"unknown stream {sid!r}"}
            if st.pending:
                self._queued_blocks -= len(st.pending)
                st.pending.clear()
                self._g_depth.set(self._queued_blocks)
            if self._batcher is not None:
                self._batcher.purge_stream_locked(st, "aborted")
            if not st.done.is_set():
                self._active -= 1
                self._g_active.set(self._active)
                self._carry_total -= st.carry_nbytes
                st.carry_nbytes = 0
                self._g_carry.set(self._carry_total)
                st.quarantined = True  # a racing worker drops the claim
                st.done.set()
                # subscribers must see a terminal window, never hang
                self._emit_window_locked(st, "aborted", final=True)
        return {"op": "aborted", "stream": sid}

    def finish(self, sid: str, timeout: float | None = None) -> dict:
        """Close the stream: drain its pending blocks, run the carry
        engine's ``finish()``, attach provenance, cache a clean
        verdict.  Returns the verdict dict (quarantined streams report
        ``unknown`` with the evidence attached, never an exception)."""
        with self._lock:
            st = self._streams.get(sid)
            if st is None:
                return {"op": "error", "error": f"unknown stream {sid!r}"}
            st.finish_requested = True
            self._schedule_locked(st)
            if self._batcher is not None:
                # drain: parked entries of a closing stream dispatch
                # now instead of riding out the coalescing deadline
                self._batcher.hurry_locked()
        limit = timeout if timeout is not None else max(
            0.0, st.deadline - time.monotonic()
        ) + 1.0
        if not st.done.wait(limit):
            with self._lock:
                if not st.done.is_set() and not st.busy:
                    self._quarantine_locked(
                        st,
                        f"finish deadline exceeded with "
                        f"{len(st.pending)} block(s) pending "
                        f"({limit:.1f}s)",
                        finalize_if_free=True,
                    )
            if not st.done.wait(1.0):
                # a worker is wedged holding the engine: answer without
                # it — unknown WITH evidence, never a hang
                return self._synthetic_verdict(
                    st, "checker worker wedged past the stream deadline"
                )
        assert st.verdict is not None
        return st.verdict

    def submit(
        self,
        workload: str,
        opts: dict | None,
        block_kind: str,
        payload,
        n_ops: int,
        content_key: str | None = None,
    ) -> dict:
        """One-shot admission: open + single block + finish-when-fed,
        without waiting for the verdict (fetch it with
        :meth:`collect`).  The 10k-histories/s fleet path."""
        opened = self.open(
            workload, opts, content_key=content_key, kind="submit"
        )
        if opened["op"] != "opened":
            return opened
        sid = opened["stream"]
        fed = self.feed(sid, 0, block_kind, payload, n_ops)
        if fed["op"] != "accepted":
            # ingress refused the block: nothing was consumed, so the
            # admission slot must not leak — abort; the client retries
            # the whole submit (zero silent drops: this is counted as a
            # reject, not a verdict)
            self.abort(sid)
            return fed
        with self._lock:
            st = self._streams.get(sid)
            if st is not None:
                st.finish_requested = True
                self._schedule_locked(st)
                if self._batcher is not None:
                    self._batcher.hurry_locked()
        return {"op": "accepted", "id": sid}

    def collect(self, ids: Sequence[str], timeout: float = 0.0) -> dict:
        """Fetch finished submit verdicts; waits up to ``timeout`` for
        stragglers.  Collected verdicts are released from memory."""
        deadline = time.monotonic() + timeout
        done: dict[str, dict] = {}
        pending = list(ids)
        while True:
            still = []
            for sid in pending:
                with self._lock:
                    st = self._streams.get(sid)
                if st is None:
                    done[sid] = {"op": "error",
                                 "error": f"unknown stream {sid!r}"}
                elif st.done.is_set():
                    done[sid] = st.verdict
                    with self._lock:
                        self._streams.pop(sid, None)
                else:
                    still.append(sid)
            pending = still
            if not pending or time.monotonic() >= deadline:
                break
            time.sleep(0.002)
        return {"op": "collected", "done": done, "pending": pending}

    # -- subscription push (ISSUE 17) --------------------------------------

    def subscribe(self, sid: str, from_window: int = 0):
        """Register a push subscriber on a stream's verdict windows.

        Returns ``(ack, replay, queue)``: the machine-readable ack (with
        a ``gap`` entry when the retained window floor has moved past
        ``from_window`` — the subscriber KNOWS which windows it can never
        see, instead of silently resuming), the retained windows at or
        above ``from_window`` to replay, and a live queue for windows
        emitted after this call (``None`` when the stream is already
        done — the replay then already ends in the terminal window)."""
        with self._lock:
            st = self._streams.get(sid)
            if st is None:
                return (
                    {"op": "error", "error": f"unknown stream {sid!r}",
                     "reason": "unknown-stream"},
                    [], None,
                )
            floor = st.window_base
            replay = [w for w in st.windows if w["window"] >= from_window]
            ack = {
                "op": "subscribed",
                "stream": sid,
                "from_window": from_window,
                "window_floor": floor,
                "next_window": st.window_base + len(st.windows),
                "replay": len(replay),
                "done": st.done.is_set(),
            }
            if from_window < floor:
                ack["gap"] = {
                    "requested": from_window,
                    "floor": floor,
                    "missed_windows": floor - from_window,
                }
            q = None
            if not st.done.is_set():
                q = queue_mod.SimpleQueue()
                st.subscribers.append(q)
                self._subs_total += 1
                self._g_subs.set(self._subs_total)
        return ack, replay, q

    def unsubscribe(self, sid: str, q) -> None:
        if q is None:
            return
        with self._lock:
            st = self._streams.get(sid)
            if st is not None and q in st.subscribers:
                st.subscribers.remove(q)
            self._subs_total = max(0, self._subs_total - 1)
            self._g_subs.set(self._subs_total)

    def _valid_so_far(self, st: _Stream):
        """Per-window partial verdict, O(carry) only (the PR-15
        ``_partial_summary`` rule): queue and mutex carries answer per
        window; elle/stream would re-run their finish-time graph
        analysis per WINDOW, so they say ``"deferred"`` and deliver
        their one real verdict in the final window."""
        if st.workload not in ("queue", "mutex"):
            return "deferred"
        try:
            return st.engine.verdict_so_far().get(VALID)
        except Exception as e:  # noqa: BLE001 — must not sink the drain
            return f"error: {type(e).__name__}: {e}"

    def _emit_window_locked(
        self, st: _Stream, valid_so_far, final: bool = False,
        verdict: dict | None = None,
    ) -> None:
        """Append one verdict window to the stream's bounded retained
        log and push it to live subscribers (caller holds the lock).
        The newest ``WINDOW_LOG_CAP`` windows are replayable; trimming
        advances ``window_base`` so a reconnect below the floor gets a
        machine-readable gap, never a silent skip."""
        w = {
            "op": "verdict-window",
            "stream": st.sid,
            "window": st.window_base + len(st.windows),
            "blocks": st.blocks_fed,
            "ops": st.ops_fed,
            "quarantined": st.quarantined,
            "final": final,
            "valid_so_far": _wire_safe(valid_so_far),
        }
        if verdict is not None:
            w["verdict"] = verdict  # already wire-safe on this path
        st.windows.append(w)
        while len(st.windows) > WINDOW_LOG_CAP:
            st.windows.popleft()
            st.window_base += 1
        self._c_windows.inc()
        for q in st.subscribers:
            q.put(w)

    def stats(self) -> dict:
        with self._lock:
            out = {
                "streams_active": self._active,
                "streams_held": len(self._streams),
                "queued_blocks": self._queued_blocks,
                "workers": len(self._workers),
                "workers_alive": len(self._workers)
                - len(self._dead_workers),
                "dead_workers": list(self._dead_workers),
                "coalesced_claims": self._coalesced,
                "carry_bytes": self._carry_total,
            }
            if self._batcher is not None:
                out["batcher"] = {
                    "parked": self._batcher.parked_locked(),
                    "target_batch": self._batcher.target,
                    "batch": self._batcher.batch,
                    "max_wait_ms": self._batcher.wait_s * 1000.0,
                    "warmed_buckets": sorted(self._batcher._warmed),
                }
        out["blocks"] = int(self._c_blocks.value)
        out["worker_deaths"] = int(self._c_deaths.value)
        out["block_requeues"] = int(self._c_requeues.value)
        out["verdict_windows"] = int(self._c_windows.value)
        out["subscribers"] = self._subs_total
        rejects = {}
        evictions = {}
        for name, labels, metric in self.metrics.items():
            if name == "service.admission_rejects":
                rejects[dict(labels).get("reason", "")] = int(metric.value)
            elif name == "service.batcher_evictions":
                evictions[dict(labels).get("reason", "")] = int(
                    metric.value
                )
        out["admission_rejects"] = rejects
        if self._batcher is not None:
            out["batcher"]["launches"] = int(
                self._batcher._c_batches.value
            )
            out["batcher"]["batched_blocks"] = int(
                self._batcher._c_blocks.value
            )
            out["batcher"]["salvages"] = int(
                self._batcher._c_salvage.value
            )
            out["batcher"]["warmup_hits"] = int(
                self._batcher._c_whit.value
            )
            out["batcher"]["warmup_misses"] = int(
                self._batcher._c_wmiss.value
            )
            out["batcher"]["evictions"] = evictions
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        return out

    def close(self) -> None:
        with self._lock:
            self._running = False
            self._cond.notify_all()
            if self._batcher is not None:
                self._batcher.close_locked()
        for t in self._workers:
            t.join(timeout=2.0)
        if self._batcher is not None:
            self._batcher.join(timeout=2.0)

    # -- internals --------------------------------------------------------

    def _schedule_locked(self, st: _Stream) -> None:
        """Make the stream claimable (caller holds the lock): one token
        per idle stream with work — the ≤1-claimer discipline that
        keeps block order per stream while workers roam streams."""
        if st.scheduled or st.busy or st.done.is_set():
            return
        if not st.pending and not st.finish_requested:
            return
        if st.batch_inflight > 0:
            # batched blocks still in flight: the finish claim waits
            # until the demux drains them (it re-schedules at zero) —
            # a finish over unmerged blocks would fabricate a verdict
            return
        st.scheduled = True
        self._tokens.append((st.sid, st.shape or (st.workload, 0)))
        self._cond.notify()

    def _claim(self, pref_shape: tuple | None):
        """Pop a token, preferring one in the caller's last shape
        bucket (bounded scan) — same-shape streams coalesce onto the
        worker that just compiled/ran that shape."""
        with self._cond:
            while True:
                if not self._running:
                    return None
                if self._tokens:
                    idx = 0
                    if pref_shape is not None:
                        for i, (_sid, shape) in enumerate(self._tokens):
                            if i >= 32:
                                break
                            if shape == pref_shape:
                                idx = i
                                break
                    sid, _shape = self._tokens[idx]
                    del self._tokens[idx]
                    if idx > 0:
                        self._coalesced += 1
                    st = self._streams.get(sid)
                    if st is None:
                        continue
                    st.scheduled = False
                    if st.busy or st.done.is_set():
                        continue
                    st.busy = True
                    return st
                self._cond.wait(timeout=0.5)

    def _worker(self, idx: int) -> None:
        name = threading.current_thread().name
        fed_here = 0
        last_shape: tuple | None = None
        while True:
            st = self._claim(last_shape)
            if st is None:
                return
            last_shape = st.shape
            try:
                fed_here = self._drain(st, idx, fed_here)
            except WorkerDeath:
                self._on_worker_death(name, st)
                return
            except Exception as e:  # noqa: BLE001 — honest, not fatal
                # a bug in the drain path must not wedge the stream or
                # kill the worker: quarantine with evidence, keep going
                logger.exception("service: drain of %s failed", st.sid)
                with self._lock:
                    if st.pending:
                        self._queued_blocks -= len(st.pending)
                        st.pending.clear()
                        self._g_depth.set(self._queued_blocks)
                    st.busy = False
                    self._quarantine_locked(
                        st,
                        f"checker worker error: {type(e).__name__}: {e}",
                        finalize_if_free=st.finish_requested,
                    )
                continue
            with self._lock:
                st.busy = False
                self._schedule_locked(st)

    def _drain(self, st: _Stream, idx: int, fed_here: int) -> int:
        while True:
            with self._lock:
                if st.quarantined and st.pending:
                    # poisoned: drop the backlog from accounting (the
                    # verdict already says unknown-with-evidence)
                    self._queued_blocks -= len(st.pending)
                    st.pending.clear()
                    self._g_depth.set(self._queued_blocks)
                block = st.pending[0] if st.pending else None
            if block is None:
                break
            seq, bkind, payload, n_ops = block
            if self.block_delay_s:
                time.sleep(self.block_delay_s)
            t0 = time.perf_counter()
            self._feed_engine(st, bkind, payload, n_ops)
            fed_here += 1
            if (
                self._die_after is not None
                and idx == self._die_after[0]
                and fed_here >= self._die_after[1]
            ):
                # mid-feed kill: the engine was mutated, the block not
                # yet acked — the worst case the snapshot protocol must
                # survive exactly
                raise WorkerDeath(
                    f"{DIE_AFTER_ENV} hook: worker {idx} dying mid-feed "
                    f"of {st.sid} seq {seq}"
                )
            nb = st.carry_nbytes
            if st.kind == "stream":
                st.snapshot = st.engine.state()
                nb = st.engine.state_nbytes(st.snapshot)
            st.blocks_fed += 1
            st.ops_fed += n_ops
            dt = time.perf_counter() - t0
            self._s_block.add(dt)
            self._c_blocks.inc()
            vsf = self._valid_so_far(st)
            with self._lock:
                if st.pending:  # a racing abort() may have cleared it
                    st.pending.popleft()
                    self._queued_blocks -= 1
                    self._g_depth.set(self._queued_blocks)
                if not st.done.is_set():
                    self._carry_total += nb - st.carry_nbytes
                    st.carry_nbytes = nb
                    self._g_carry.set(self._carry_total)
                    # one verdict window per closed segment, pushed to
                    # subscribers the moment the block lands (ISSUE 17)
                    self._emit_window_locked(st, vsf)
        if st.finish_requested and not st.done.is_set():
            # the engine belongs to this worker (single-claimer): run
            # the heavy finish outside the service lock
            verdict = st.engine.finish()
            with self._lock:
                if not st.done.is_set():
                    self._complete_locked(st, verdict)
        return fed_here

    def _feed_engine(self, st: _Stream, bkind: str, payload,
                     n_ops: int) -> None:
        """Feed one block; engine-level failures (poison payloads)
        quarantine inside the engine itself (PR-15 contract)."""
        if bkind == "rows":
            rows = np.asarray(payload, np.int32)
            if rows.ndim != 2 or rows.shape[1] != 8:
                st.engine.quarantine(
                    st.engine.segments,
                    f"malformed rows block: shape {rows.shape}",
                )
                st.quarantined = True
                return
            st.engine.feed_rows(rows, n_ops)
        else:
            from jepsen_tpu.history.ops import Op

            try:
                ops = [Op.from_json(d) for d in payload]
            except Exception as e:  # noqa: BLE001 — poison, not fatal
                st.engine.quarantine(
                    st.engine.segments,
                    f"undecodable ops block: {type(e).__name__}: {e}",
                )
                st.quarantined = True
                return
            st.engine.feed(ops, start_op=st.ops_fed)
        if st.engine.quarantines:
            st.quarantined = True

    def _quarantine_locked(
        self, st: _Stream, error: str, finalize_if_free: bool = False
    ) -> None:
        """Mark the stream poisoned (caller holds the lock).  The
        engine is only finalized when no worker holds it; a busy
        worker observes ``quarantined`` and finalizes after its
        current block."""
        st.quarantined = True
        if self._batcher is not None:
            # parked coalescing entries of a poisoned stream evict
            # (service.batcher_evictions) — batch-mates are untouched
            self._batcher.purge_stream_locked(st, "quarantined")
        if not st.engine.quarantines:
            # appending evidence is safe concurrently (list append);
            # the carry itself is never touched here
            st.engine.quarantine(st.engine.segments, error)
        if not st.busy and (finalize_if_free or st.finish_requested):
            self._finalize_locked(st)

    def _provenance(self, st: _Stream) -> dict:
        out = {
            "stream": st.sid,
            "workload": st.workload,
            "blocks": st.blocks_fed,
            "ops": st.ops_fed,
        }
        if self.cache is not None:
            # digests are only accumulated when a cache wants the key
            out["content_sha256"] = st.digest.hexdigest()
        return out

    def _degraded(self, st: _Stream) -> dict | None:
        if not (st.dead_workers or st.requeues):
            return None
        return {
            "dead_workers": list(st.dead_workers),
            "requeued_blocks": list(st.requeues),
            "worker_deaths": len(st.dead_workers),
        }

    def _finalize_locked(self, st: _Stream) -> None:
        """Finish the engine under the lock — only for the cold paths
        (quarantine, deadline, fail-all) where the engine is free."""
        if st.done.is_set():
            return
        self._complete_locked(st, st.engine.finish())

    def _complete_locked(self, st: _Stream, verdict: dict) -> None:
        from jepsen_tpu.obs import trace as obs_trace

        verdict = _wire_safe(verdict)
        verdict["provenance"] = self._provenance(st)
        deg = self._degraded(st)
        if deg is not None:
            verdict["degraded"] = deg
        st.verdict = verdict
        st.done_at = time.monotonic()
        st.done.set()
        self._active -= 1
        self._g_active.set(self._active)
        self._carry_total -= st.carry_nbytes
        st.carry_nbytes = 0
        self._g_carry.set(self._carry_total)
        if st.quarantined:
            self._g_quar.inc()
        # terminal window: carries the FULL verdict so a subscriber
        # needs no follow-up poll to learn how the stream ended
        self._emit_window_locked(
            st, verdict.get(VALID), final=True, verdict=verdict
        )
        now = time.perf_counter()
        self._s_verdict.add(now - st.t0)
        obs_trace.complete(
            "service.stream", st.t0, now, track="service",
            args=(
                {"stream": st.sid, "blocks": st.blocks_fed,
                 "quarantined": st.quarantined}
                if obs_trace.is_enabled()
                else None
            ),
        )
        if (
            self.cache is not None
            and not st.quarantined
            and deg is None
            and st.blocks_fed > 0
        ):
            # clean verdicts only: a degraded/quarantined verdict
            # reflects THIS run's faults, not the history — replaying
            # it from cache would make transient damage permanent
            from jepsen_tpu.service.cache import cache_key

            self.cache.put(
                cache_key(st.digest.hexdigest(), st.workload, st.opts),
                verdict,
            )

    def _synthetic_verdict(self, st: _Stream, error: str) -> dict:
        """A verdict without the engine (it is wedged under a worker):
        unknown WITH evidence — the degraded-but-honest floor."""
        out = {
            VALID: UNKNOWN,
            "quarantined": {"segments": [{"segment": st.blocks_fed,
                                          "error": error}]},
            "provenance": self._provenance(st),
        }
        deg = self._degraded(st) or {"dead_workers": [],
                                     "requeued_blocks": [],
                                     "worker_deaths": 0}
        deg["wedged"] = True
        out["degraded"] = deg
        return out

    def _on_worker_death(self, name: str, st: _Stream) -> None:
        """The PR-13 requeue protocol at block granularity: restore the
        stream's engine from its last snapshot, put the claim back for
        a survivor, name the dead worker in the provenance."""
        self._c_deaths.inc()
        logger.error(
            "service: checker worker %s died mid-feed of %s "
            "(block retries so far: %d)", name, st.sid, st.retries,
        )
        with self._lock:
            self._dead_workers.append(name)
            alive = len(self._workers) - len(self._dead_workers)
            self._g_alive.set(alive)
            st.dead_workers.append(name)
            st.retries += 1
            if st.snapshot is not None:
                from jepsen_tpu.checkers.segmented import SegmentedChecker

                st.engine = SegmentedChecker.from_state(
                    st.snapshot, device=self._engine_device()
                )
            else:
                st.engine = self._new_engine(st.workload, st.opts)
            head_seq = st.pending[0][0] if st.pending else None
            st.busy = False
            if st.retries > MAX_BLOCK_RETRIES:
                if st.pending:
                    self._queued_blocks -= len(st.pending)
                    st.pending.clear()
                    self._g_depth.set(self._queued_blocks)
                self._quarantine_locked(
                    st,
                    f"block seq {head_seq} killed {st.retries} checker "
                    f"worker(s) — treating as poison (dead: "
                    f"{st.dead_workers})",
                    finalize_if_free=True,
                )
            else:
                self._c_requeues.inc()
                st.requeues.append({
                    "seq": head_seq,
                    "dead_worker": name,
                    "retries": st.retries,
                })
                self._schedule_locked(st)
            if alive <= 0:
                self._fail_all_locked(
                    f"no surviving checker workers (dead: "
                    f"{self._dead_workers})"
                )

    def _fail_all_locked(self, error: str) -> None:
        """Zero survivors: every undone stream quarantines loudly
        (unknown-with-evidence) instead of hanging its client."""
        for st in self._streams.values():
            if st.done.is_set():
                continue
            if st.pending:
                self._queued_blocks -= len(st.pending)
                st.pending.clear()
            if self._batcher is not None:
                self._batcher.purge_stream_locked(st, "failed")
            st.quarantined = True
            if not st.engine.quarantines:
                st.engine.quarantine(st.engine.segments, error)
            if st.busy and st.batch_inflight > 0:
                # the batch collector is mid-merge on this engine:
                # leave finalization to its pass (or the finish-path
                # wedge fallback) rather than racing the merge
                continue
            st.busy = False
            self._finalize_locked(st)
        self._g_depth.set(self._queued_blocks)

    def _reap(self) -> None:
        """Deadline sweep: expire overdue idle streams as quarantined
        (freeing their admission slots), release stale done records."""
        while True:
            time.sleep(0.25)
            with self._lock:
                if not self._running:
                    return
                now = time.monotonic()
                for st in list(self._streams.values()):
                    if st.done.is_set():
                        if (
                            st.done_at is not None
                            and now - st.done_at > self.done_ttl_s
                        ):
                            self._streams.pop(st.sid, None)
                        continue
                    if now > st.deadline and not st.busy:
                        if st.pending:
                            self._queued_blocks -= len(st.pending)
                            st.pending.clear()
                            self._g_depth.set(self._queued_blocks)
                        self._quarantine_locked(
                            st,
                            f"stream deadline exceeded "
                            f"({self.stream_deadline_s:.1f}s) with "
                            f"pending work",
                            finalize_if_free=True,
                        )
