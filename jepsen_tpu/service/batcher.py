"""Continuous batching for the verification service (ISSUE 20).

The PR-16 service dispatches device work at CLAIM granularity: a
worker drains whatever its claimed stream has ready and launches the
verdict program on that fragment.  Under many concurrent small
streams the device runs short, padding-heavy batches at low occupancy
— the under-batching failure mode inference servers solve with
continuous batching.  This module is that scheduler, sitting between
stream ingestion (:class:`~jepsen_tpu.service.stream.IngestService`)
and the PR-15 segmented carry engines:

- **Cross-stream coalescing.**  Every accepted queue-family rows
  block is host-prepared (:func:`queue_prepare_rows`) on the feeding
  connection's thread and parked in a per-shape-bucket queue keyed
  ``(L, V)`` — the same pow2 size classes the per-segment program
  compiles at.  A bucket launches when it reaches the target batch
  size OR its oldest entry exceeds the latency budget
  (``max_batch_wait_ms``) — size-or-deadline, never starvation.

- **Carry isolation.**  Batching crosses streams ONLY on the history
  axis: the batched program
  (:func:`~jepsen_tpu.checkers.segmented.seg_queue_batch_program`) is
  pure per-segment stats — no carry state ever enters a launch.
  Results demux back to each stream through a per-stream reorder
  buffer and merge into that stream's residue strictly in seq order
  (``QueueCarry.merge_stats`` is NOT order-independent: settling
  forgets ``(s, t)`` and a reopen pins ``causal=False``), so every
  verdict and every carry is ≡ the per-stream serial oracle.

- **Donation-aware staging ring.**  Each bucket owns a
  :class:`~jepsen_tpu.parallel.pipeline.StagingRing` of
  ``dispatch_depth`` recycled host slots at the one compiled
  ``[batch, L]`` shape; steady-state dispatch allocates nothing, and
  the staged device copies are donated on backends where donation is
  usable.

- **Backpressure.**  Parked entries stay counted in the service's
  ``_queued_blocks`` ingress bound, so a full coalescing queue counts
  against admission — the batcher can never buffer unboundedly behind
  a loud ``SATURATED`` front door.  Entries whose stream dies
  (abort / quarantine / deadline reap) are evicted and surfaced as
  ``service.batcher_evictions{reason}``; a parked-age bound
  (``park_max_s``) force-dispatches anything the size-or-deadline
  loop could not move (e.g. behind a wedged ring), so a ``SATURATED``
  reject mid-coalesce never strands a stream's partial segments.

Locking: the batcher shares the service's lock (one lock, two
condition variables) — every queue mutation happens under it, so the
service's abort/quarantine/reap paths purge parked entries without
lock-order hazards.  The engine itself is only ever touched by the
collector thread (under ``st.busy``, the same single-claimer
discipline workers use), or by a worker running ``finish()`` after
the in-flight count drains to zero.
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
from collections import deque

import numpy as np

logger = logging.getLogger("jepsen_tpu.service.batcher")

#: bucket pseudo-keys for entries that never reach the device program
EMPTY_BUCKET = ("empty",)  # rows with no queue-relevant ops
PASS_BUCKET = ("pass",)  # ops-JSON blocks on a queue stream


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


class ContinuousBatcher:
    """The admission-to-dispatch scheduler.  Constructed by
    :class:`IngestService` when batching is enabled; all knobs are
    constructor-explicit so tests and the bench pin tiny bounds."""

    def __init__(
        self,
        service,
        target_batch: int = 32,
        max_wait_ms: float = 25.0,
        dispatch_depth: int = 2,
        park_max_s: float = 5.0,
        donate: bool | None = None,
        registry=None,
    ):
        from jepsen_tpu.parallel.pipeline import _default_donate

        self.svc = service
        self.target = max(1, int(target_batch))
        self.batch = _pow2(self.target)  # the ONE compiled batch width
        self.wait_s = max(0.0, float(max_wait_ms) / 1000.0)
        self.depth = max(1, int(dispatch_depth))
        # the stranding backstop is ABSOLUTE: it must fire even when
        # the coalescing deadline is configured far beyond it
        self.park_max_s = max(0.05, float(park_max_s))
        self.donate = _default_donate() if donate is None else bool(donate)

        self._lock = service._lock  # ONE lock with the service
        self._cond = threading.Condition(self._lock)
        self._buckets: dict[tuple, deque] = {}
        self._rings: dict[tuple, object] = {}
        self._warmed: set[tuple] = set()
        self._seen: set[tuple] = set()  # buckets that already dispatched
        self._closing = False
        self._collect_q: queue_mod.Queue = queue_mod.Queue(
            maxsize=self.depth
        )
        self._idle_since = time.perf_counter()

        if registry is None:
            registry = service.metrics
        self.metrics = registry
        self._c_batches = registry.counter("service.batches")
        self._c_blocks = registry.counter("service.batched_blocks")
        self._c_salvage = registry.counter("service.batch_salvages")
        self._c_whit = registry.counter("service.warmup_hits")
        self._c_wmiss = registry.counter("service.warmup_misses")
        self._s_fill = registry.sketch("service.batch_fill")
        self._s_waste = registry.sketch("service.batch_pad_waste")
        self._s_coalesce = registry.sketch("service.batch_coalesce_s")
        self._s_dispatch = registry.sketch("service.batch_dispatch_s")
        self._s_occupancy = registry.sketch("service.batch_occupancy")

        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="svc-batcher", daemon=True
        )
        self._collector = threading.Thread(
            target=self._collect_loop, name="svc-batch-collect", daemon=True
        )
        self._dispatcher.start()
        self._collector.start()

    # -- warmup ------------------------------------------------------------

    def warmup(self, buckets) -> int:
        """AOT-precompile the batched program for each ``(L, V)``
        bucket at this batcher's batch width (``serve-checker
        --warmup``): the first super-batch of a warmed bucket pays no
        compile on the latency path, counted as ``service.warmup_hits``
        when it lands."""
        from jepsen_tpu.checkers.segmented import warmup_queue_buckets

        keys = [(int(L), int(V)) for L, V in buckets]
        n = warmup_queue_buckets(keys, batch=self.batch, donate=self.donate)
        self._warmed.update(keys)
        logger.info(
            "batcher warmup: %d bucket program(s) compiled at batch %d",
            n, self.batch,
        )
        return n

    # -- ingestion side ----------------------------------------------------

    def offer(self, st, seq: int, block_kind: str, payload,
              n_ops: int) -> None:
        """Park one accepted block (called WITHOUT the service lock —
        host prep runs on the feeding connection's thread, so prep
        parallelizes across clients instead of serializing the
        dispatcher).  The service already counted the block against
        the ingress bound and the stream's in-flight count."""
        entry = {
            "sid": st.sid, "seq": int(seq), "n_ops": int(n_ops),
            "t_enq": time.monotonic(), "prep": None, "payload": None,
            "err": None, "stats": None,
        }
        if block_kind == "rows":
            from jepsen_tpu.checkers.segmented import (
                EMPTY_QUEUE_STATS,
                queue_prepare_rows,
            )

            rows = np.asarray(payload, np.int32)
            if rows.ndim != 2 or rows.shape[1] != 8:
                entry["err"] = f"malformed rows block: shape {rows.shape}"
                key = EMPTY_BUCKET
            else:
                prep = queue_prepare_rows(
                    rows, rows[:, 0].astype(np.int64)
                )
                if prep is None:
                    entry["stats"] = EMPTY_QUEUE_STATS
                    key = EMPTY_BUCKET
                else:
                    entry["prep"] = prep
                    key = (prep["L"], prep["V"])
        else:
            entry["payload"] = (block_kind, payload)
            key = PASS_BUCKET
        with self._lock:
            cur = self.svc._streams.get(st.sid)
            if cur is not st or st.done.is_set() or st.quarantined:
                # the stream died between accept and park: the block
                # was counted — release it loudly, never strand it
                self._evict_locked(st, 1, "dead-stream")
                return
            self._buckets.setdefault(key, deque()).append(entry)
            self._cond.notify()

    def purge_stream_locked(self, st, reason: str) -> None:
        """Drop every parked entry and pending demux result of one
        stream (caller holds the lock) — the abort / quarantine /
        deadline-reap hook.  In-flight launches containing the stream
        are unaffected; the collector drops their rows on landing.
        Batch-mates are untouched either way."""
        dropped = 0
        for dq in self._buckets.values():
            if not dq:
                continue
            keep = [e for e in dq if e["sid"] != st.sid]
            if len(keep) != len(dq):
                dropped += len(dq) - len(keep)
                dq.clear()
                dq.extend(keep)
        dropped += len(st.batch_results)
        st.batch_results.clear()
        if dropped:
            self._evict_locked(st, dropped, reason)

    def _evict_locked(self, st, n: int, reason: str) -> None:
        svc = self.svc
        svc._queued_blocks = max(0, svc._queued_blocks - n)
        svc._g_depth.set(svc._queued_blocks)
        st.batch_inflight = max(0, st.batch_inflight - n)
        self.metrics.counter(
            "service.batcher_evictions", reason=reason
        ).inc(n)

    def parked_locked(self) -> int:
        return sum(len(dq) for dq in self._buckets.values())

    def close_locked(self) -> None:
        self._closing = True
        self._cond.notify_all()

    def join(self, timeout: float = 2.0) -> None:
        self._dispatcher.join(timeout=timeout)
        self._collector.join(timeout=timeout)

    # -- dispatch loop -----------------------------------------------------

    def _ready_key_locked(self, now: float):
        """Size-or-deadline: a bucket at target size dispatches NOW; a
        bucket whose oldest entry exceeded the budget dispatches
        partial (never starvation).  Overdue-past-park-bound buckets
        trump everything (the stranded-segment backstop).  A bucket
        holding a finish-requested stream's entries is drained
        immediately — close must not ride out the coalescing deadline."""
        best, best_age = None, -1.0
        streams = self.svc._streams
        for key, dq in self._buckets.items():
            if not dq:
                continue
            age = now - dq[0]["t_enq"]
            if age >= self.park_max_s:
                return key
            ready = len(dq) >= self.target or age >= self.wait_s
            if not ready:
                ready = any(
                    (s := streams.get(e["sid"])) is not None
                    and s.finish_requested
                    for e in dq
                )
            if ready and age > best_age:
                best, best_age = key, age
        return best

    def hurry_locked(self) -> None:
        """Wake the dispatcher out of its deadline sleep (caller holds
        the lock) — the finish() drain hook."""
        self._cond.notify()

    def _next_deadline_locked(self, now: float) -> float:
        dt = 0.25
        for dq in self._buckets.values():
            if dq:
                dt = min(dt, max(0.0, self.wait_s
                                 - (now - dq[0]["t_enq"])))
        return dt

    def _dispatch_loop(self) -> None:
        while True:
            key = entries = None
            with self._cond:
                while True:
                    if self._closing or not self.svc._running:
                        break
                    now = time.monotonic()
                    key = self._ready_key_locked(now)
                    if key is not None:
                        dq = self._buckets[key]
                        entries = []
                        while dq and len(entries) < self.target:
                            e = dq.popleft()
                            st = self.svc._streams.get(e["sid"])
                            if (st is None or st.done.is_set()
                                    or st.quarantined):
                                if st is not None:
                                    self._evict_locked(
                                        st, 1, "dead-stream"
                                    )
                                else:
                                    self.svc._queued_blocks = max(
                                        0, self.svc._queued_blocks - 1
                                    )
                                    self.svc._g_depth.set(
                                        self.svc._queued_blocks
                                    )
                                    self.metrics.counter(
                                        "service.batcher_evictions",
                                        reason="dead-stream",
                                    ).inc()
                                continue
                            entries.append(e)
                        if entries:
                            break
                        entries = None
                        continue  # bucket drained by evictions: rescan
                    self._cond.wait(
                        timeout=self._next_deadline_locked(now)
                    )
            if entries is None:
                # closing: sentinel goes out OUTSIDE the lock (the
                # bounded collect queue must never block a lock holder)
                self._collect_q.put(None)
                return
            t0 = time.perf_counter()
            try:
                self._launch(key, entries)
            except Exception:  # noqa: BLE001 — salvage already tried
                logger.exception("batcher: launch of %s failed", key)
                for e in entries:
                    e["err"] = e["err"] or "batched dispatch failed"
                self._collect_q.put((None, None, entries, None, t0))
            t1 = time.perf_counter()
            idle = max(0.0, t0 - self._idle_since)
            busy = t1 - t0
            if busy + idle > 0:
                self._s_occupancy.add(busy / (busy + idle))
            self._idle_since = t1

    def _ring(self, key):
        ring = self._rings.get(key)
        if ring is None:
            from jepsen_tpu.parallel.pipeline import StagingRing

            L, _V = key
            ring = self._rings[key] = StagingRing(
                self.batch, L, depth=self.depth
            )
        return ring

    def _launch(self, key, entries) -> None:
        now = time.monotonic()
        for e in entries:
            self._s_coalesce.add(now - e["t_enq"])
        self._c_batches.inc()
        self._c_blocks.inc(len(entries))
        if key in (EMPTY_BUCKET, PASS_BUCKET):
            # nothing for the device: straight to the demux, keeping
            # the per-stream seq order the reorder buffer enforces
            self._collect_q.put(
                (None, None, entries, None, time.perf_counter())
            )
            return
        L, V = key
        if key not in self._seen:
            self._seen.add(key)
            (self._c_whit if key in self._warmed
             else self._c_wmiss).inc()
        self._s_fill.add(len(entries) / self.batch)
        used = sum(e["prep"]["n_rel"] for e in entries)
        self._s_waste.add(1.0 - used / float(self.batch * L))
        ring = self._ring(key)
        while True:
            slot = ring.acquire(timeout=0.5)
            if slot is not None:
                break
            if self._closing or not self.svc._running:
                raise RuntimeError("batcher closing with ring busy")
        t0 = time.perf_counter()
        try:
            from jepsen_tpu.parallel.pipeline import dispatch_coalesced

            ring.fill(slot, [e["prep"] for e in entries])
            dev = dispatch_coalesced(slot, V, donate=self.donate)
        except Exception as err:  # noqa: BLE001 — salvage per entry
            ring.release(slot)
            logger.warning(
                "batcher: coalesced dispatch %s failed (%s); "
                "salvaging per entry", key, err,
            )
            self._salvage(entries)
            self._collect_q.put((None, None, entries, None, t0))
            return
        self._collect_q.put((key, slot, entries, dev, t0))

    def _salvage(self, entries) -> None:
        """Per-entry serial retry after a failed coalesced launch: one
        poison segment quarantines ONE stream, not its batch-mates."""
        from jepsen_tpu.checkers.segmented import queue_stats_from_prepared

        self._c_salvage.inc()
        for e in entries:
            try:
                e["stats"] = queue_stats_from_prepared(e["prep"])
            except Exception as err:  # noqa: BLE001 — that entry only
                e["err"] = (
                    f"segment failed batched AND solo dispatch: "
                    f"{type(err).__name__}: {err}"
                )

    # -- collect / demux ---------------------------------------------------

    def _collect_loop(self) -> None:
        from jepsen_tpu.obs import trace as obs_trace

        while True:
            item = self._collect_q.get()
            if item is None:
                return
            key, slot, entries, dev, t0 = item
            if dev is not None:
                from jepsen_tpu.checkers.segmented import _trim_queue_stats

                planes = [np.asarray(p) for p in dev]  # blocks on device
                for i, e in enumerate(entries):
                    e["stats"] = _trim_queue_stats(
                        e["prep"]["u"], *(p[i] for p in planes)
                    )
                ring = self._rings[key]
                ring.release(slot)
            t1 = time.perf_counter()
            self._s_dispatch.add(t1 - t0)
            if obs_trace.is_enabled():
                obs_trace.complete(
                    "service.batch", t0, t1, track="service",
                    args={
                        "bucket": "x".join(str(k) for k in (key or ())),
                        "entries": len(entries),
                    },
                )
            try:
                self._demux(entries)
            except Exception:  # noqa: BLE001 — must not kill the loop
                logger.exception("batcher: demux failed")

    def _demux(self, entries) -> None:
        """Hand every landed entry to its stream's reorder buffer and
        merge each stream's contiguous run IN SEQ ORDER — the other
        half of the carry-isolation invariant."""
        svc = self.svc
        runs: dict[str, tuple] = {}  # sid -> (st, [entry, ...])
        with self._lock:
            for e in entries:
                st = svc._streams.get(e["sid"])
                if st is None or st.done.is_set() or st.quarantined:
                    if st is not None:
                        self._evict_locked(st, 1, "dead-stream")
                    else:
                        svc._queued_blocks = max(
                            0, svc._queued_blocks - 1
                        )
                        svc._g_depth.set(svc._queued_blocks)
                        self.metrics.counter(
                            "service.batcher_evictions",
                            reason="dead-stream",
                        ).inc()
                    continue
                st.batch_results[e["seq"]] = e
                if e["sid"] not in runs:
                    runs[e["sid"]] = (st, [])
            for sid, (st, run) in list(runs.items()):
                while st.batch_next_merge in st.batch_results:
                    run.append(st.batch_results.pop(st.batch_next_merge))
                    st.batch_next_merge += 1
                if not run:
                    del runs[sid]
                else:
                    # single-claimer: workers cannot hold a stream with
                    # in-flight batched blocks (finish is gated), so
                    # busy is free to take here
                    st.busy = True
        for st, run in runs.values():
            try:
                self._merge_run(st, run)
            except Exception as err:  # noqa: BLE001 — that stream only
                logger.exception(
                    "batcher: merge into %s failed", st.sid
                )
                with self._lock:
                    self._evict_locked(st, len(run), "demux-error")
                    st.busy = False
                    svc._quarantine_locked(
                        st,
                        f"batched demux error: {type(err).__name__}: "
                        f"{err}",
                        finalize_if_free=st.finish_requested,
                    )

    def _merge_run(self, st, run) -> None:
        """Fold one stream's contiguous landed run into its engine
        (outside the lock — single-claimer via ``st.busy``), then book
        the blocks, emit verdict windows, and release the claim."""
        svc = self.svc
        merged = []
        error = None
        for e in run:
            if e["err"] is not None:
                st.engine.quarantine(st.engine.segments, e["err"])
                error = e["err"]
            elif e["payload"] is not None:
                bkind, payload = e["payload"]
                svc._feed_engine(st, bkind, payload, e["n_ops"])
            else:
                st.engine.merge_queue_stats(e["stats"], e["n_ops"])
            if st.engine.quarantines:
                st.quarantined = True
            merged.append((e, svc._valid_so_far(st)))
        nb = st.carry_nbytes
        if st.kind == "stream" and not st.quarantined:
            # one footprint refresh per landed run (amortized over
            # the batch, vs the worker path's per-block snapshot)
            nb = st.engine.state_nbytes()
        with self._lock:
            for e, vsf in merged:
                st.blocks_fed += 1
                st.ops_fed += e["n_ops"]
                svc._queued_blocks = max(0, svc._queued_blocks - 1)
                st.batch_inflight = max(0, st.batch_inflight - 1)
                svc._c_blocks.inc()
                if not st.done.is_set():
                    svc._emit_window_locked(st, vsf)
            svc._g_depth.set(svc._queued_blocks)
            if not st.done.is_set():
                svc._carry_total += nb - st.carry_nbytes
                st.carry_nbytes = nb
                svc._g_carry.set(svc._carry_total)
            st.busy = False
            if st.quarantined:
                svc._quarantine_locked(
                    st,
                    error or "segment quarantined in batched merge",
                    finalize_if_free=st.finish_requested,
                )
            elif st.finish_requested and st.batch_inflight == 0:
                svc._schedule_locked(st)
