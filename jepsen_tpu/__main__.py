"""``python -m jepsen_tpu`` entry point."""

import sys

from jepsen_tpu.cli.main import main

sys.exit(main())
