#!/bin/bash
# Derive the short RabbitMQ branch tag ("41", "42", …) from a
# server-packages binary URL — same contract as the reference's
# ci/extract-rabbitmq-branch-from-binary-url.sh: the tag keys the AWS
# resource names, S3 archive prefixes, and the CI rate-limit artifact.
#
# e.g. …/rabbitmq-server-generic-unix-4.1.0-alpha.047cc5a0.tar.xz → 41
set -euo pipefail

BINARY_URL=${1:?usage: $0 <binary-url>}
FILENAME=$(basename "$BINARY_URL")
VERSION=${FILENAME#rabbitmq-server-generic-unix-}
VERSION=${VERSION%.tar.xz}
MAJOR=${VERSION%%.*}
REST=${VERSION#*.}
MINOR=${REST%%.*}
echo "${MAJOR}${MINOR}"
