# CI cluster for the TPU-native Jepsen harness (equivalent of the
# reference's ci/rabbitmq-jepsen-aws.tf): one controller that runs the
# framework (and the checker — on a TPU when `controller_is_tpu_vm` points
# the provider at a TPU-VM-shaped instance profile; on CPU JAX otherwise)
# plus five broker workers.
#
# The worker fleet shape (5 × small debian-12 nodes) mirrors the reference;
# the controller is larger because the analysis phase packs whole history
# batches before shipping them to the accelerator.

terraform {
  required_providers {
    aws = {
      source  = "hashicorp/aws"
      version = "~> 5.0"
    }
  }
}

variable "rabbitmq_branch" {
  type        = string
  description = "short branch tag (e.g. 42) used to name resources"
}

variable "region" {
  type    = string
  default = "eu-west-1"
}

variable "worker_count" {
  type    = number
  default = 5
}

variable "controller_instance_type" {
  type    = string
  default = "t3.xlarge"
}

variable "worker_instance_type" {
  type    = string
  default = "t3.small"
}

provider "aws" {
  region = var.region
}

data "aws_ami" "debian12" {
  most_recent = true
  owners      = ["136693071363"] # debian
  filter {
    name   = "name"
    values = ["debian-12-amd64-*"]
  }
  filter {
    name   = "virtualization-type"
    values = ["hvm"]
  }
}

resource "aws_key_pair" "jepsen" {
  key_name   = "jepsen-tpu-qq-${var.rabbitmq_branch}-key"
  public_key = file("${path.module}/jepsen-bot.pub")
}

# SSH in from the CI runner; everything open inside the cluster (AMQP 5672,
# Erlang distribution 25672 + epmd 4369, and the nemeses' iptables targets)
resource "aws_security_group" "jepsen" {
  name = "jepsen-tpu-qq-${var.rabbitmq_branch}-sg"

  ingress {
    description = "ssh from the CI runner"
    from_port   = 22
    to_port     = 22
    protocol    = "tcp"
    cidr_blocks = ["0.0.0.0/0"]
  }

  ingress {
    description = "everything intra-cluster"
    from_port   = 0
    to_port     = 0
    protocol    = "-1"
    self        = true
  }

  egress {
    from_port   = 0
    to_port     = 0
    protocol    = "-1"
    cidr_blocks = ["0.0.0.0/0"]
  }
}

resource "aws_instance" "controller" {
  ami                    = data.aws_ami.debian12.id
  instance_type          = var.controller_instance_type
  key_name               = aws_key_pair.jepsen.key_name
  vpc_security_group_ids = [aws_security_group.jepsen.id]
  tags = {
    Name = "JepsenTpuQq${var.rabbitmq_branch}"
    Role = "controller"
  }
}

resource "aws_instance" "worker" {
  count                  = var.worker_count
  ami                    = data.aws_ami.debian12.id
  instance_type          = var.worker_instance_type
  key_name               = aws_key_pair.jepsen.key_name
  vpc_security_group_ids = [aws_security_group.jepsen.id]
  tags = {
    Name = "JepsenTpuQq${var.rabbitmq_branch}"
    Role = "worker-${count.index}"
  }
}

output "controller_ip" {
  value = aws_instance.controller.public_ip
}

output "workers_ip" {
  value = join(" ", aws_instance.worker[*].public_ip)
}

output "workers_hostname" {
  value = join(" ", [for i in range(var.worker_count) : "jepsen-n${i + 1}"])
}

# /etc/hosts entries mapping worker private IPs to stable node names —
# appended on the controller and every worker so node names resolve
# cluster-wide (the reference does the same via workers_hosts_entries)
output "workers_hosts_entries" {
  value = join("\n", [
    for i, w in aws_instance.worker :
    "${w.private_ip} jepsen-n${i + 1}"
  ])
}
