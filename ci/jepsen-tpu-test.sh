#!/bin/bash
# CI driver (equivalent of the reference's ci/jepsen-test.sh): provision an
# AWS cluster with terraform, provision the controller, distribute the
# RabbitMQ binary under test, run the 14-config matrix, archive artifacts,
# and report a verdict.
#
# Where the reference drives 14 `lein run test …` invocations from bash and
# triages failures by grepping jepsen.log ("Analysis invalid" = genuine
# violation, "Set was never read" = retry ≤3), this framework keeps all of
# that logic in `python -m jepsen_tpu matrix` (jepsen_tpu/harness/matrix.py
# — same matrix, same retry/triage rules, same rabbitmqctl queue-empty
# cross-check), so the shell layer only provisions and collects.
set -exo pipefail

: "${BINARY_URL:?BINARY_URL must point at a rabbitmq-server-generic-unix tarball}"

RABBITMQ_BRANCH=$(ci/extract-rabbitmq-branch-from-binary-url.sh "$BINARY_URL")
ARCHIVE=$(basename "$BINARY_URL")
JEPSEN_USER=${JEPSEN_USER:-admin}
S3_BUCKET=${S3_BUCKET:-s3://jepsen-tests-logs}
SSH="ssh -o StrictHostKeyChecking=no -i jepsen-bot"

# fresh SSH keypair for the cluster
ssh-keygen -t ed25519 -m pem -f jepsen-bot -C jepsen-bot -N ''

set +x
mkdir -p ~/.aws
echo "$AWS_CONFIG" > ~/.aws/config
echo "$AWS_CREDENTIALS" > ~/.aws/credentials
set -x

# tear down leftovers from a previous aborted run, then bring up the cluster
AWS_TAG="JepsenTpuQq$RABBITMQ_BRANCH"
AWS_KEY_NAME="jepsen-tpu-qq-$RABBITMQ_BRANCH-key"
set +e
aws ec2 terminate-instances --no-cli-pager --instance-ids \
    "$(aws ec2 describe-instances \
        --query 'Reservations[].Instances[].InstanceId' \
        --filters "Name=tag:Name,Values=$AWS_TAG" --output text)"
aws ec2 delete-key-pair --no-cli-pager --key-name "$AWS_KEY_NAME"
set -e

cp ./ci/jepsen-tpu-aws.tf .
terraform init
terraform apply -auto-approve -var="rabbitmq_branch=$RABBITMQ_BRANCH"

# keep state around so the workflow's always() step can destroy the cluster
mkdir -p terraform-state
cp -r jepsen-bot jepsen-bot.pub .terraform terraform.tfstate \
    jepsen-tpu-aws.tf terraform-state/

CONTROLLER_IP=$(terraform output -raw controller_ip)
WORKERS=( $(terraform output -raw workers_hostname) )
WORKERS_IP=( $(terraform output -raw workers_ip) )
WORKERS_HOSTS_ENTRIES=$(terraform output -raw workers_hosts_entries)

# controller: framework + venv + native driver; node names into /etc/hosts
$SSH "$JEPSEN_USER@$CONTROLLER_IP" 'bash -s' < ci/provision-jepsen-tpu-controller.sh
$SSH "$JEPSEN_USER@$CONTROLLER_IP" \
    "echo '$WORKERS_HOSTS_ENTRIES' | sudo tee --append /etc/hosts"
scp -o StrictHostKeyChecking=no -i jepsen-bot jepsen-bot \
    "$JEPSEN_USER@$CONTROLLER_IP:~/jepsen-bot"

# binary under test onto the controller, then fan out to every worker
$SSH "$JEPSEN_USER@$CONTROLLER_IP" "wget -q '$BINARY_URL'"
for worker in "${WORKERS[@]}"; do
  $SSH "$JEPSEN_USER@$CONTROLLER_IP" \
    "scp -o StrictHostKeyChecking=no -i ~/jepsen-bot ~/${ARCHIVE} $JEPSEN_USER@$worker:/tmp/${ARCHIVE}"
done
for worker_ip in "${WORKERS_IP[@]}"; do
  $SSH "$JEPSEN_USER@$worker_ip" "sudo apt-get update -q"
  $SSH "$JEPSEN_USER@$worker_ip" \
    "echo '$WORKERS_HOSTS_ENTRIES' | sudo tee --append /etc/hosts"
done

NODES=$(IFS=, ; echo "${WORKERS[*]}")

# the matrix: retries, triage, and the queue-empty cross-check all happen
# inside the runner; matrix-summary.json is the machine-readable verdict
set +e
$SSH "$JEPSEN_USER@$CONTROLLER_IP" "source ~/.profile ; cd ~/jepsen-tpu ; \
  python -m jepsen_tpu matrix --db rabbitmq \
    --nodes '$NODES' \
    --ssh-user $JEPSEN_USER --ssh-private-key ~/jepsen-bot \
    --archive-url 'file:///tmp/${ARCHIVE}' \
    --store store | tee matrix-summary.json"
matrix_exit=$?
set -e

# archive the store (histories, results, perf plots, timelines, node logs)
the_date=$(date '+%Y%m%d-%H%M%S')
archive_name="qq-jepsen-tpu-$RABBITMQ_BRANCH-$the_date-logs"
$SSH "$JEPSEN_USER@$CONTROLLER_IP" "cd ~/jepsen-tpu ; \
  tar -zcf - store matrix-summary.json --transform='s/^/${archive_name}\//'" \
  > "$archive_name.tar.gz"
aws s3 cp "$archive_name.tar.gz" "$S3_BUCKET/" --quiet

echo "Download logs: aws s3 cp $S3_BUCKET/$archive_name.tar.gz ."
exit $matrix_exit
