#!/bin/bash
# Provision the CI controller (equivalent of the reference's
# ci/provision-jepsen-controller.sh, which installs JDK + lein + gnuplot):
# the TPU framework needs python + jax + a C++ toolchain for the native
# AMQP driver, and matplotlib instead of gnuplot for the perf artifacts.
set -euo pipefail

REPO_URL=${REPO_URL:-https://github.com/rabbitmq/jepsen-tpu.git}
JAX_EXTRA=${JAX_EXTRA:-jax[tpu]}   # set to plain "jax" for a CPU controller

sudo apt-get update
sudo apt-get install -y --no-install-recommends \
    python3 python3-venv python3-pip \
    g++ make git graphviz openssh-client

git clone "$REPO_URL" "$HOME/jepsen-tpu" || (cd "$HOME/jepsen-tpu" && git pull)

python3 -m venv "$HOME/jepsen-tpu-venv"
# shellcheck disable=SC1091
source "$HOME/jepsen-tpu-venv/bin/activate"
pip install --upgrade pip
pip install "$JAX_EXTRA" numpy matplotlib
pip install -e "$HOME/jepsen-tpu"

# native AMQP driver (C++): built on the controller, used by every test run
make -C "$HOME/jepsen-tpu/native"

# the venv activates for subsequent ssh commands via ~/.profile
grep -q jepsen-tpu-venv "$HOME/.profile" 2>/dev/null || \
    echo "source \$HOME/jepsen-tpu-venv/bin/activate" >> "$HOME/.profile"

cd "$HOME/jepsen-tpu"
python -m jepsen_tpu test --help > /dev/null
echo "controller provisioned"
