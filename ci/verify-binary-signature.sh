#!/bin/bash
# Gate: GPG-verify the RabbitMQ generic-unix tarball named by $BINARY_URL
# against the RabbitMQ release signing key before any cluster is built.
# (Same check the reference performs inline in its workflow,
# /root/reference/.github/workflows/jepsen.yml:53-60 — here it is a
# standalone, locally runnable script.)
set -euo pipefail

: "${BINARY_URL:?BINARY_URL must be set}"
SIGNING_KEY_URL=${SIGNING_KEY_URL:-https://github.com/rabbitmq/signing-keys/releases/download/3.0/rabbitmq-release-signing-key.asc}

workdir=$(mktemp -d)
trap 'rm -rf "$workdir"' EXIT
cd "$workdir"

curl -fsSL "$SIGNING_KEY_URL" -o signing-key.asc
gpg --import signing-key.asc

tarball=$(basename "$BINARY_URL")
curl -fsSL -O "$BINARY_URL"
curl -fsSL -O "$BINARY_URL.asc"
gpg --verify "$tarball.asc" "$tarball"
echo "signature OK: $tarball"
