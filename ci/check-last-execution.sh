#!/bin/bash
# CI rate limiter: allow at most one run per branch per 24 h, tracked via a
# GitHub Actions artifact holding the last-execution epoch (same contract
# as the reference's ci/check-last-execution.sh; SKIP_CHECK=true forces a
# run).  Emits `allow_execution=<bool>` to $GITHUB_OUTPUT and exports the
# artifact name via $GITHUB_ENV for the upload step.
set -uo pipefail

LIMIT_SECONDS=${LIMIT_SECONDS:-86400}
CURRENT_TIME=$(date '+%s')
RABBITMQ_BRANCH=$(ci/extract-rabbitmq-branch-from-binary-url.sh "$BINARY_URL")
LAST_EXECUTION_ARTIFACT="last-execution-jepsen-tpu-rabbitmq-$RABBITMQ_BRANCH"

echo "UTC is $(date --utc --rfc-3339=seconds --date=@"$CURRENT_TIME")"

gh run --repo "${GITHUB_REPOSITORY:-rabbitmq/jepsen-tpu}" download \
    --name "$LAST_EXECUTION_ARTIFACT" 2>/dev/null

ALLOW_EXECUTION=true
if [ -e last-execution.txt ]; then
    LAST_EXECUTION=$(cat last-execution.txt)
    DIFF=$((CURRENT_TIME - LAST_EXECUTION))
    echo "Last execution was ${DIFF}s ago (limit ${LIMIT_SECONDS}s)"
    if [ "$DIFF" -le "$LIMIT_SECONDS" ]; then
        ALLOW_EXECUTION=false
    fi
fi

if [ "${SKIP_CHECK:-false}" = true ]; then
    echo "SKIP_CHECK set, forcing execution"
    ALLOW_EXECUTION=true
fi

if [ "$ALLOW_EXECUTION" = true ]; then
    echo "$CURRENT_TIME" > last-execution.txt
fi

echo "Allow execution? $ALLOW_EXECUTION"
[ -n "${GITHUB_OUTPUT:-}" ] && echo "allow_execution=$ALLOW_EXECUTION" >> "$GITHUB_OUTPUT"
[ -n "${GITHUB_ENV:-}" ] && echo "LAST_EXECUTION_ARTIFACT=$LAST_EXECUTION_ARTIFACT" >> "$GITHUB_ENV"
exit 0
