#!/bin/bash
# Teardown: destroy the AWS cluster and scrub credentials. Runs under the
# workflow's `if: always()` so an aborted matrix never leaks EC2 instances
# (the always-destroy guarantee of the reference pipeline). Uses the
# terraform state that ci/jepsen-tpu-test.sh snapshots into
# terraform-state/ right after `apply`.
set -uo pipefail

branch=""
if [ -n "${BINARY_URL:-}" ]; then
    branch=$(ci/extract-rabbitmq-branch-from-binary-url.sh "$BINARY_URL")
fi

destroy_ok=true
if [ -d terraform-state ]; then
    (
        cd terraform-state &&
        terraform init &&
        terraform destroy -auto-approve -var="rabbitmq_branch=$branch"
    ) || {
        echo "terraform destroy failed — instances may need manual cleanup"
        destroy_ok=false
    }
fi
if [ -n "$branch" ]; then
    aws ec2 delete-key-pair --no-cli-pager \
        --key-name "jepsen-tpu-qq-$branch-key" || true
fi

# credentials never survive the runner; the terraform state survives a
# FAILED destroy — it is the only handle the advertised manual cleanup
# has on the orphaned instances
rm -rf ~/.aws
if [ "$destroy_ok" = true ]; then
    rm -rf terraform-state terraform.tfstate
else
    echo "keeping terraform-state/ for a manual terraform destroy"
fi
