#!/bin/bash
# Teardown: destroy the AWS cluster and scrub credentials. Runs under the
# workflow's `if: always()` so an aborted matrix never leaks EC2 instances
# (the always-destroy guarantee of the reference pipeline). Uses the
# terraform state that ci/jepsen-tpu-test.sh snapshots into
# terraform-state/ right after `apply`.
set -uo pipefail

branch=""
if [ -n "${BINARY_URL:-}" ]; then
    branch=$(ci/extract-rabbitmq-branch-from-binary-url.sh "$BINARY_URL")
fi

if [ -d terraform-state ]; then
    (
        cd terraform-state &&
        terraform init &&
        terraform destroy -auto-approve -var="rabbitmq_branch=$branch"
    ) || echo "terraform destroy failed — instances may need manual cleanup"
fi
if [ -n "$branch" ]; then
    aws ec2 delete-key-pair --no-cli-pager \
        --key-name "jepsen-tpu-qq-$branch-key" || true
fi

rm -rf ~/.aws terraform-state terraform.tfstate
