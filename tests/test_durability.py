"""Durable replication: Raft WAL + term/vote persistence and crash recovery.

The reference SUT's quorum queues are durable — RabbitMQ's Ra log fsyncs
before acking, and Jepsen's classic power-failure test (kill -9 every
node, restart, drain) is exactly what `x-queue-type=quorum` exists to
survive.  Round-4's replicated mini cluster was in-memory by design
(killed nodes rejoin amnesiac); ``durable=True`` closes that last
fidelity gap: per-node WAL + meta under a data dir that survives
SIGKILL, recovery on boot, and a ``crash-restart-cluster`` nemesis that
power-fails the whole cluster mid-run.

The red-run proof is ``ack-before-fsync``: commits/confirms proceed on
the in-memory log while the WAL silently falls behind — undetectable by
any partition (the in-memory majority stays correct), caught only by a
full-cluster crash.  total-queue must flag the vanished confirmed
writes end-to-end.
"""

import json
import os
import tempfile
import time

from _load import scaled

import pytest

from jepsen_tpu.harness.replication import RaftNode, ReplicatedBackend


def _one_node_backend(data_dir, seed_bug=None):
    return ReplicatedBackend(
        "a",
        {"a": ("127.0.0.1", 0)},
        election_timeout=(0.05, 0.1),
        heartbeat_s=0.02,
        seed_bug=seed_bug,
        data_dir=data_dir,
    )


def _wait_leader(backend, timeout_s=5.0):
    deadline = time.monotonic() + scaled(timeout_s)
    while time.monotonic() < deadline:
        if backend.raft.is_leader():
            return
        time.sleep(0.01)
    raise AssertionError("no leader elected")


def test_wal_recover_roundtrip():
    """Committed ops survive stop + recreate: the WAL replays into the
    log and the state machine rebuilds exactly on recovery."""
    with tempfile.TemporaryDirectory() as d:
        b = _one_node_backend(d)
        try:
            _wait_leader(b)
            b.declare("q")
            for v in (7, 8, 9):
                assert b.enqueue("q", str(v).encode(), b"") is True
            msg = b.dequeue("q", owner="a|c1")  # 7 goes inflight
            assert msg is not None and msg.body == b"7"
        finally:
            b.stop()

        b2 = _one_node_backend(d)
        try:
            _wait_leader(b2)
            # the leader's no-op commits the recovered tail
            deadline = time.monotonic() + scaled(5.0)
            while time.monotonic() < deadline:
                if b2.counts().get("q") == 3:  # 2 ready + 1 inflight
                    break
                time.sleep(0.02)
            assert b2.counts().get("q") == 3, b2.counts()
            # ready order preserved; the pre-crash inflight entry is
            # still owned (its requeue is the broker layer's job)
            m = b2.dequeue("q", owner="a|c2")
            assert m is not None and m.body == b"8"
        finally:
            b2.stop()


def test_recover_truncation_and_torn_tail():
    """WAL replay honors truncation markers and drops a torn final line
    (a crash mid-append must not poison recovery)."""
    with tempfile.TemporaryDirectory() as d:
        recs = [
            {"t": 1, "op": {"k": "declare", "q": "q"}},
            {"t": 1, "op": {"k": "enq", "q": "q", "body": "MQ==",
                            "props": "", "ts": 0.0}},
            {"t": 2, "op": {"k": "enq", "q": "q", "body": "Mg==",
                            "props": "", "ts": 0.0}},
            {"trunc": 3},
            {"t": 3, "op": {"k": "noop"}},
        ]
        with open(os.path.join(d, "wal.jsonl"), "w") as fh:
            for r in recs:
                fh.write(json.dumps(r) + "\n")
            fh.write('{"t": 3, "op": {"k"')  # torn tail
        with open(os.path.join(d, "meta.json"), "w") as fh:
            json.dump({"term": 3, "voted_for": "b"}, fh)
        n = RaftNode(
            "a", {"a": ("127.0.0.1", 0)}, lambda i, op: None,
            election_timeout=(5.0, 9.0),  # never fires during the test
            data_dir=d,
        )
        try:
            assert n.term == 3
            assert n.voted_for == "b"
            assert [t for t, _ in n.log] == [1, 1, 3]
            assert n.log[2][1] == {"k": "noop"}
        finally:
            n.stop()


def test_append_after_torn_tail_recovery_survives_next_crash():
    """Code-review r4 find: recovery must TRUNCATE the torn tail, not
    just skip it — otherwise records appended (and fsync'd!) after a
    torn-tail recovery land glued to the corrupt line and the *next*
    recovery silently discards them (confirmed writes lost on a
    bug-free cluster — the power-failure green run would go red)."""
    with tempfile.TemporaryDirectory() as d:
        b = _one_node_backend(d)
        try:
            _wait_leader(b)
            b.declare("q")
            assert b.enqueue("q", b"A", b"") is True
        finally:
            b.stop()
        # crash #1 landed mid-write: a partial record with no newline
        with open(os.path.join(d, "wal.jsonl"), "a") as fh:
            fh.write('{"t": 1, "op": {"k"')
        b2 = _one_node_backend(d)
        try:
            _wait_leader(b2)
            deadline = time.monotonic() + scaled(5.0)
            while time.monotonic() < deadline:
                if b2.counts().get("q") == 1:
                    break
                time.sleep(0.02)
            assert b2.counts().get("q") == 1  # A recovered, tail dropped
            assert b2.enqueue("q", b"B", b"") is True  # confirmed + fsync'd
        finally:
            b2.stop()
        b3 = _one_node_backend(d)  # crash #2: B must still be there
        try:
            _wait_leader(b3)
            deadline = time.monotonic() + scaled(5.0)
            while time.monotonic() < deadline:
                if b3.counts().get("q") == 2:
                    break
                time.sleep(0.02)
            assert b3.counts().get("q") == 2, b3.counts()
        finally:
            b3.stop()


def test_ack_before_fsync_bug_loses_the_wal():
    """The seeded bug's mechanics in isolation: confirms succeed, but
    nothing reaches the WAL — a recovered node has an empty log."""
    with tempfile.TemporaryDirectory() as d:
        b = _one_node_backend(d, seed_bug="ack-before-fsync")
        try:
            _wait_leader(b)
            b.declare("q")
            assert b.enqueue("q", b"1", b"") is True  # confirmed!
            assert b.counts().get("q") == 1  # and served, in memory
        finally:
            b.stop()
        assert not os.path.exists(os.path.join(d, "wal.jsonl"))
        b2 = _one_node_backend(d)  # recovery: honest from here on
        try:
            _wait_leader(b2)
            assert b2.counts().get("q") is None  # the confirm was a lie
        finally:
            b2.stop()


def test_wal_failure_fail_stops_the_node(monkeypatch):
    """Review r4 find: a WAL write failure must FAIL-STOP the node
    (fsyncgate semantics).  Acking would lie — and a leader retry of the
    same entries would find them already in the in-memory log and ack
    without ever persisting them, a silent durability hole."""
    import jepsen_tpu.harness.replication as repl

    with tempfile.TemporaryDirectory() as d:
        b = _one_node_backend(d)
        try:
            _wait_leader(b)
            b.declare("q")
            monkeypatch.setattr(
                repl.os, "fsync",
                lambda fd: (_ for _ in ()).throw(OSError("EIO: injected")),
            )
            with pytest.raises(OSError, match="fail-stop"):
                b.raft.submit(
                    {"k": "enq", "q": "q", "body": "WA==", "props": "",
                     "ts": 0.0},
                    timeout_s=1.0,
                )
            assert not b.raft._running  # stopped, not limping
            monkeypatch.undo()
            # the disk came back — the node must STAY dead (restart is
            # the only way back; a half-alive node could still ack)
            ok, _ = b.raft.submit({"k": "noop"}, timeout_s=0.3)
            assert ok is False
        finally:
            b.stop()


# ---------------------------------------------------------------------------
# Process-level: kill -9 the broker, restart, state survives
# ---------------------------------------------------------------------------


# native_lib / _reset fixtures come from conftest.py


def test_kill_restart_durable_single_node(_reset, native_lib):
    """The durable counterpart of
    ``test_kill_is_genuinely_nondurable``: same SIGKILL, but the
    confirmed value is on disk and the restarted broker serves it."""
    from jepsen_tpu.harness.localcluster import LocalProcTransport

    t = LocalProcTransport(n_nodes=1, replicated=True, durable=True)
    try:
        node = t.nodes[0]
        t.run(node, "/tmp/rabbitmq-server/sbin/rabbitmq-server -detached")
        d = native_lib.NativeQueueDriver([node], node, connect_retry_ms=5000)
        d.setup()
        assert d.enqueue(7, 5.0) is True
        d.close()
        t.run(node, "killall -q -9 beam.smp epmd || true")
        assert not t.alive(node)
        t.run(node, "/tmp/rabbitmq-server/sbin/rabbitmq-server -detached")
        d2 = native_lib.NativeQueueDriver([node], node, connect_retry_ms=5000)
        d2.setup()
        got = d2.dequeue(10.0)
        assert got == 7, f"durable broker lost the acked value: {got!r}"
        d2.close()
    finally:
        t.close()


# ---------------------------------------------------------------------------
# Full assembly: the power-failure nemesis through the live suite
# ---------------------------------------------------------------------------


def _crash_restart_build(seed_bug):
    """Builder for one durable replicated 3-node cluster with the
    whole-cluster crash-restart nemesis (fresh per triage attempt)."""
    from jepsen_tpu.harness.localcluster import build_local_test
    from jepsen_tpu.suite import DEFAULT_OPTS

    opts = {
        **DEFAULT_OPTS,
        "rate": 120.0,
        "time-limit": 4.0,
        "time-before-partition": 1.0,
        "partition-duration": 1.0,
        "recovery-sleep": 1.5,
        "publish-confirm-timeout": 2.5,
        "nemesis": "crash-restart-cluster",
    }
    return build_local_test(
        opts,
        n_nodes=3,
        concurrency=4,
        checker_backend="cpu",
        store_root=tempfile.mkdtemp(),
        workload="queue",
        seed_bug=seed_bug,
        durable=True,
    )


def test_cluster_power_failure_green_when_durable(_reset):
    """Jepsen's classic power-failure test: SIGKILL every node mid-run,
    restart, drain.  A durable cluster loses nothing confirmed — valid
    verdict, zero lost.  Triage-retried (tests/_live.py)."""
    from _live import run_live_with_triage

    def checks(run):
        assert run.results["queue"]["lost-count"] == 0
        # the crash actually happened: a nemesis START recorded the kill
        from jepsen_tpu.history.ops import NEMESIS_PROCESS, OpF, OpType

        crashes = [
            op for op in run.history
            if op.process == NEMESIS_PROCESS
            and op.f == OpF.START
            and op.type == OpType.INFO
            and "crashed" in str(op.value)
        ]
        assert crashes, "crash-restart nemesis never fired"

    run_live_with_triage(
        lambda: _crash_restart_build(None), expect="valid", checks=checks
    )


def test_mixed_fault_soak_on_durable_cluster(_reset):
    """The jepsen.nemesis/compose soak: partitions, kills, pauses, AND
    whole-cluster power failures randomly interleaved over one run
    against a durable replicated cluster — recovery paths no
    single-family run reaches (e.g. a kill landing mid-heal).  A correct
    durable cluster survives all of it: valid verdict, nothing lost.
    Triage-retried (tests/_live.py)."""
    from jepsen_tpu.harness.localcluster import build_local_test
    from jepsen_tpu.history.ops import NEMESIS_PROCESS, OpF, OpType
    from jepsen_tpu.suite import DEFAULT_OPTS

    opts = {
        **DEFAULT_OPTS,
        "rate": 120.0,
        "time-limit": 8.0,
        "time-before-partition": 0.7,
        "partition-duration": 1.0,
        "recovery-sleep": 1.5,
        "publish-confirm-timeout": 2.5,
        "nemesis": "mixed",
        "durable": True,
        "seed": 1,  # family prefix: kill, crash-restart, partition, …
    }
    from _live import run_live_with_triage

    def build():
        return build_local_test(
            opts, n_nodes=3, concurrency=4, checker_backend="cpu",
            store_root=tempfile.mkdtemp(), workload="queue", durable=True,
        )

    def checks(run):
        assert run.results["queue"]["lost-count"] == 0
        fired = [
            str(op.value).split(":")[0]
            for op in run.history
            if op.process == NEMESIS_PROCESS
            and op.f == OpF.START
            and op.type == OpType.INFO
            and op.value is not None  # completions only (invocations pair)
        ]
        # the seeded family sequence is deterministic; how many cycles
        # fit the window is wall-clock — so assert the PREFIX, not a
        # count (review r4: a loaded host may fit a single cycle)
        import random as _random

        rng = _random.Random(1)
        fams = sorted([
            "partition", "kill", "pause", "clock-skew", "membership",
            "crash-restart",
        ])
        expected = [rng.choice(fams) for _ in fired]
        assert fired and fired == expected, (fired, expected)

    run_live_with_triage(build, expect="valid", checks=checks)


def test_seeded_ack_before_fsync_caught_end_to_end(_reset):
    """The durability red run: every node confirms against its in-memory
    log while the WAL silently falls behind (ack-before-fsync).  No
    partition can expose this; the whole-cluster crash does — confirmed
    writes vanish on recovery and total-queue must flag them LOST,
    through the full live assembly."""
    from _live import run_live_with_triage

    def checks(run):
        assert run.results["queue"]["lost-count"] > 0, run.results["queue"]

    run_live_with_triage(
        lambda: _crash_restart_build("ack-before-fsync"),
        expect="invalid",
        checks=checks,
    )
