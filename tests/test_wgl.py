"""Wing-Gong search: models, CPU engine, TPU frontier BFS, differential."""

import pytest

from jepsen_tpu.checkers.wgl import (
    INF,
    Call,
    QueueWgl,
    WglOp,
    check_wgl_cpu,
    pack_wgl_batch,
    queue_wgl_ops,
    wgl_tensor_check,
)
from jepsen_tpu.history.ops import Op, OpF, OpType, reindex
from jepsen_tpu.history.synth import SynthSpec, synth_history
from jepsen_tpu.models.core import CasRegister, Mutex, UnorderedQueue

Q = UnorderedQueue
ENQ, DEQ = Q.ENQUEUE, Q.DEQUEUE


def both(ops, model_args=(64,)):
    cpu = check_wgl_cpu(ops, UnorderedQueue(*model_args))
    batch = pack_wgl_batch([ops])
    ok, unknown = wgl_tensor_check(batch, (UnorderedQueue, model_args))
    assert not unknown[0], "TPU search overflowed on a tiny history"
    assert bool(ok[0]) == cpu["valid?"], f"cpu={cpu} tpu={bool(ok[0])}"
    return cpu["valid?"]


# ---- hand-built interval histories ---------------------------------------


def test_sequential_enq_deq_linearizable():
    ops = [
        WglOp(Call(ENQ, 1), 0, 1),
        WglOp(Call(DEQ, 1), 2, 3),
    ]
    assert both(ops)


def test_deq_before_enq_not_linearizable():
    ops = [
        WglOp(Call(DEQ, 1), 0, 1),
        WglOp(Call(ENQ, 1), 2, 3),
    ]
    assert not both(ops)


def test_overlapping_enq_deq_linearizable():
    ops = [
        WglOp(Call(ENQ, 1), 0, 3),
        WglOp(Call(DEQ, 1), 1, 2),
    ]
    assert both(ops)


def test_double_dequeue_not_linearizable():
    ops = [
        WglOp(Call(ENQ, 1), 0, 1),
        WglOp(Call(DEQ, 1), 2, 3),
        WglOp(Call(DEQ, 1), 4, 5),
    ]
    assert not both(ops)


def test_indeterminate_enqueue_allows_later_read():
    ops = [
        WglOp(Call(ENQ, 1), 0, INF),  # confirm timed out
        WglOp(Call(DEQ, 1), 5, 6),
    ]
    assert both(ops)


def test_indeterminate_enqueue_requires_invocation_order():
    # the read completes before the enqueue was even invoked
    ops = [
        WglOp(Call(DEQ, 1), 0, 1),
        WglOp(Call(ENQ, 1), 2, INF),
    ]
    assert not both(ops)


def test_concurrent_swap_linearizable():
    # two enqueues concurrent with two dequeues reading them crosswise
    ops = [
        WglOp(Call(ENQ, 1), 0, 10),
        WglOp(Call(ENQ, 2), 0, 10),
        WglOp(Call(DEQ, 2), 1, 9),
        WglOp(Call(DEQ, 1), 1, 9),
    ]
    assert both(ops)


# ---- other models (CPU engine) -------------------------------------------


def test_cas_register():
    m = CasRegister(0)
    W, R, C = CasRegister.WRITE, CasRegister.READ, CasRegister.CAS
    good = [
        WglOp(Call(W, 5), 0, 1),
        WglOp(Call(C, 5, 7), 2, 3),
        WglOp(Call(R, 7), 4, 5),
    ]
    assert check_wgl_cpu(good, m)["valid?"]
    bad = [
        WglOp(Call(W, 5), 0, 1),
        WglOp(Call(R, 9), 2, 3),  # reads a value never written
    ]
    assert not check_wgl_cpu(bad, m)["valid?"]


def test_cas_register_tensor_matches_cpu():
    W, R, C = CasRegister.WRITE, CasRegister.READ, CasRegister.CAS
    cases = [
        [WglOp(Call(W, 5), 0, 1), WglOp(Call(R, 5), 2, 3)],
        [WglOp(Call(W, 5), 0, 1), WglOp(Call(R, 9), 2, 3)],
        [WglOp(Call(W, 1), 0, 5), WglOp(Call(W, 2), 0, 5),
         WglOp(Call(R, 1), 6, 7)],
        [WglOp(Call(C, 0, 3), 0, 1), WglOp(Call(R, 3), 2, 3)],
    ]
    batch = pack_wgl_batch(cases)
    ok, unknown = wgl_tensor_check(batch, (CasRegister, (0,)))
    assert not unknown.any()
    for i, ops in enumerate(cases):
        assert bool(ok[i]) == check_wgl_cpu(ops, CasRegister(0))["valid?"]


def test_mutex():
    m = Mutex()
    A, R = Mutex.ACQUIRE, Mutex.RELEASE
    good = [
        WglOp(Call(A), 0, 1),
        WglOp(Call(R), 2, 3),
        WglOp(Call(A), 4, 5),
    ]
    assert check_wgl_cpu(good, m)["valid?"]
    # two non-overlapping acquires with no release: impossible
    bad = [
        WglOp(Call(A), 0, 1),
        WglOp(Call(A), 2, 3),
    ]
    assert not check_wgl_cpu(bad, m)["valid?"]
    batch = pack_wgl_batch([good, bad])
    ok, unknown = wgl_tensor_check(batch, (Mutex, ()))
    assert not unknown.any()
    assert bool(ok[0]) and not bool(ok[1])


# ---- full histories through the checker wrapper ---------------------------


def test_checker_on_clean_synth_history():
    sh = synth_history(SynthSpec(n_ops=120, seed=41))
    r = QueueWgl(backend="tpu").check({}, sh.ops)
    assert r["valid?"], r
    r2 = QueueWgl(backend="cpu").check({}, sh.ops)
    assert r2["valid?"]


def test_checker_flags_duplicate_delivery():
    sh = synth_history(SynthSpec(n_ops=120, seed=42, duplicated=1))
    assert not QueueWgl(backend="tpu").check({}, sh.ops)["valid?"]
    assert not QueueWgl(backend="cpu").check({}, sh.ops)["valid?"]


def test_checker_flags_phantom_read():
    sh = synth_history(SynthSpec(n_ops=120, seed=43, unexpected=1))
    assert not QueueWgl(backend="tpu").check({}, sh.ops)["valid?"]


def test_checker_accepts_lost_messages():
    # loss is not a linearizability violation (total-queue's concern)
    sh = synth_history(SynthSpec(n_ops=120, seed=44, lost=2))
    assert QueueWgl(backend="tpu").check({}, sh.ops)["valid?"]


@pytest.mark.parametrize("seed", range(4))
def test_differential_wgl_vs_per_value_on_synth(seed):
    from jepsen_tpu.checkers.queue_lin import check_queue_lin_cpu

    sh = synth_history(
        SynthSpec(
            n_ops=100,
            seed=300 + seed,
            duplicated=seed % 2,
            unexpected=(seed // 2) % 2,
        )
    )
    wgl = QueueWgl(backend="tpu").check({}, sh.ops)
    per_value = check_queue_lin_cpu(sh.ops)
    # P-compositionality: the decomposed check and the full search agree
    assert wgl["valid?"] == per_value["valid?"]


def test_queue_wgl_ops_mapping():
    ops = reindex(
        [
            Op.invoke(OpF.ENQUEUE, 0, 3, time=0),
            Op(OpType.INFO, OpF.ENQUEUE, 0, 3, time=1),
            Op.invoke(OpF.ENQUEUE, 1, 4, time=2),
            Op(OpType.FAIL, OpF.ENQUEUE, 1, 4, time=3),
            Op.invoke(OpF.DRAIN, 2, time=4),
            Op(OpType.OK, OpF.DRAIN, 2, [3], time=5),
        ]
    )
    w = queue_wgl_ops(ops)
    # failed enqueue dropped; info enqueue open forever; drain value = DEQ
    assert len(w) == 2
    assert w[0].call == Call(ENQ, 3) and w[0].ret == INF
    assert w[1].call == Call(DEQ, 3) and w[1].ret == 5


def test_owned_mutex():
    from jepsen_tpu.models.core import OwnedMutex

    m = OwnedMutex()
    A, R = OwnedMutex.ACQUIRE, OwnedMutex.RELEASE
    good = [
        WglOp(Call(A, a0=1), 0, 1),
        WglOp(Call(R, a0=1), 2, 3),
        WglOp(Call(A, a0=2), 4, 5),
    ]
    assert check_wgl_cpu(good, m)["valid?"]
    # only the holder can release: p2 releasing p1's lock is illegal
    bad = [
        WglOp(Call(A, a0=1), 0, 1),
        WglOp(Call(R, a0=2), 2, 3),
    ]
    assert not check_wgl_cpu(bad, m)["valid?"]
    # a pending (indeterminate) release by a non-holder never linearizes,
    # so it cannot rescue a double grant
    double = [
        WglOp(Call(A, a0=1), 0, 1),
        WglOp(Call(R, a0=2), 2, INF),
        WglOp(Call(A, a0=3), 4, 5),
    ]
    assert not check_wgl_cpu(double, m)["valid?"]
    batch = pack_wgl_batch([good, bad])
    ok, unknown = wgl_tensor_check(batch, (OwnedMutex, ()))
    assert not unknown.any()
    assert bool(ok[0]) and not bool(ok[1])


def test_mutex_wgl_ops_mapping():
    from jepsen_tpu.checkers.wgl import mutex_wgl_ops
    from jepsen_tpu.history.ops import Op, OpF, OpType, reindex

    a1 = Op.invoke(OpF.ACQUIRE, 1)
    r1 = Op.invoke(OpF.RELEASE, 1)
    a2 = Op.invoke(OpF.ACQUIRE, 2)
    h = reindex(
        [
            a1, a1.complete(OpType.OK),
            a2, a2.complete(OpType.FAIL, error="held"),  # never happened
            r1, r1.complete(OpType.INFO, error="timeout"),  # maybe freed
        ]
    )
    ops = mutex_wgl_ops(h)
    assert len(ops) == 2  # the failed acquire is dropped
    assert ops[0].call.a0 == 1 and ops[1].ret == INF


def test_capped_search_reports_unknown_not_invalid():
    """A search that hits the config cap is undecided — jepsen's :unknown
    verdict — and must not propagate as a violation through compose."""
    from jepsen_tpu.checkers.protocol import merge_valid
    from jepsen_tpu.models.core import OwnedMutex

    # many forever-pending acquires from distinct processes explode the
    # config space; a tiny cap forces the unknown path deterministically
    ops = [WglOp(Call(OwnedMutex.ACQUIRE, a0=p), 0, INF) for p in range(12)]
    ops.append(WglOp(Call(OwnedMutex.ACQUIRE, a0=99), 1, 2))
    r = check_wgl_cpu(ops, OwnedMutex(), max_configs=8)
    assert r["valid?"] == "unknown" and r["unknown"]
    assert merge_valid([True, "unknown", True]) == "unknown"
    assert merge_valid([True, "unknown", False]) is False
    assert merge_valid([True, True]) is True


def test_fifo_queue_tensor_matches_cpu():
    """FIFO model: ordered dequeue enforced by both engines (the tensor
    ring encoding is canonical — head at slot 0, empty slots zero)."""
    from jepsen_tpu.models.core import FifoQueue

    F_ENQ, F_DEQ = FifoQueue.ENQUEUE, FifoQueue.DEQUEUE
    cases = [
        # in-order: enq 1, enq 2, deq 1, deq 2 — linearizable
        [WglOp(Call(F_ENQ, 1), 0, 1), WglOp(Call(F_ENQ, 2), 2, 3),
         WglOp(Call(F_DEQ, 1), 4, 5), WglOp(Call(F_DEQ, 2), 6, 7)],
        # out-of-order dequeue with sequential intervals — NOT fifo
        [WglOp(Call(F_ENQ, 1), 0, 1), WglOp(Call(F_ENQ, 2), 2, 3),
         WglOp(Call(F_DEQ, 2), 4, 5), WglOp(Call(F_DEQ, 1), 6, 7)],
        # concurrent enqueues: either order works, deq 2 then deq 1 ok
        [WglOp(Call(F_ENQ, 1), 0, 3), WglOp(Call(F_ENQ, 2), 0, 3),
         WglOp(Call(F_DEQ, 2), 4, 5), WglOp(Call(F_DEQ, 1), 6, 7)],
        # dequeue of a value never enqueued
        [WglOp(Call(F_ENQ, 1), 0, 1), WglOp(Call(F_DEQ, 9), 2, 3)],
    ]
    expected = [True, False, True, False]
    batch = pack_wgl_batch(cases)
    ok, unknown = wgl_tensor_check(batch, (FifoQueue, (8,)))
    assert not unknown.any()
    for i, ops in enumerate(cases):
        cpu = check_wgl_cpu(ops, FifoQueue(8))["valid?"]
        assert cpu is expected[i], (i, cpu)
        assert bool(ok[i]) == cpu, (i, bool(ok[i]), cpu)


def test_fifo_vs_unordered_divergence():
    """The one history family where the models must disagree: unordered
    admits out-of-order dequeues, FIFO refutes them."""
    from jepsen_tpu.models.core import FifoQueue

    ops = [
        WglOp(Call(0, 1), 0, 1), WglOp(Call(0, 2), 2, 3),
        WglOp(Call(1, 2), 4, 5), WglOp(Call(1, 1), 6, 7),
    ]
    assert both(ops)  # unordered-queue: fine
    batch = pack_wgl_batch([ops])
    ok, unknown = wgl_tensor_check(batch, (FifoQueue, (8,)))
    assert not unknown[0] and not bool(ok[0])
    assert not check_wgl_cpu(ops, FifoQueue(8))["valid?"]


def test_fifo_capacity_bound_is_engine_equivalent():
    """A fixed capacity is bounded-queue (reject-publish) SPEC, not a
    resource cap: enqueue beyond it is illegal in BOTH engines, verdicts
    stay equivalent, and the unbounded intent goes through FifoWgl's
    auto-sizing instead."""
    from jepsen_tpu.models.core import FifoQueue

    ops = [WglOp(Call(0, v), 2 * v, 2 * v + 1) for v in range(4)]
    assert check_wgl_cpu(ops, FifoQueue(2))["valid?"] is False
    batch = pack_wgl_batch([ops])
    ok, unknown = wgl_tensor_check(batch, (FifoQueue, (2,)))
    assert not unknown[0] and not bool(ok[0])
    # and with room, the same history is fine
    assert check_wgl_cpu(ops, FifoQueue(8))["valid?"] is True
    ok8, unknown8 = wgl_tensor_check(batch, (FifoQueue, (8,)))
    assert not unknown8[0] and bool(ok8[0])


def test_fifo_wgl_autosizes_capacity():
    """FifoWgl sizes the model's capacity from the history, so deep
    pending backlogs can never produce a bounded-queue refutation."""
    from jepsen_tpu.checkers.wgl import FifoWgl
    from jepsen_tpu.history.ops import Op, OpF, OpType, reindex

    # 40 enqueues all pending, then in-order dequeues — far deeper than
    # any plausible fixed default would allow
    hist = []
    for v in range(40):
        inv = Op.invoke(OpF.ENQUEUE, 0, v)
        hist.append(inv)
        hist.append(inv.complete(OpType.OK))
    for v in range(40):
        inv = Op.invoke(OpF.DEQUEUE, 0)
        hist.append(inv)
        hist.append(inv.complete(OpType.OK, value=v))
    h = reindex(hist)
    for backend in ("cpu", "tpu"):
        r = FifoWgl(backend=backend).check({}, h)
        assert r["valid?"] is True, (backend, r)
    # and a swapped dequeue pair is a genuine FIFO violation
    bad = list(h)
    iv1 = Op.invoke(OpF.DEQUEUE, 0)
    bad[-3:] = [iv1, iv1.complete(OpType.OK, value=40)]  # value never enqueued
    r = FifoWgl(backend="cpu").check({}, reindex(bad))
    assert r["valid?"] is False


def test_synth_mutex_differential():
    """Mutex synth ground truth matches both WGL engines: clean batches
    are linearizable, injected double grants are refuted."""
    from jepsen_tpu.checkers.wgl import (
        MutexWgl,
        mutex_wgl_ops,
        pack_wgl_batch,
        wgl_tensor_check,
    )
    from jepsen_tpu.history.synth import MutexSynthSpec, synth_mutex_batch
    from jepsen_tpu.models.core import OwnedMutex

    clean = synth_mutex_batch(4, MutexSynthSpec(n_ops=80))
    bad = synth_mutex_batch(4, MutexSynthSpec(n_ops=80), double_grant=1)
    assert all(s.clean for s in clean)
    assert all(s.double_grant == 1 for s in bad)
    batch = pack_wgl_batch(
        [mutex_wgl_ops(s.ops) for s in clean + bad]
    )
    ok, unknown = wgl_tensor_check(batch, (OwnedMutex, ()))
    for i, s in enumerate(clean + bad):
        cpu = MutexWgl(backend="cpu").check({}, s.ops)
        assert cpu["valid?"] is s.clean, (i, cpu)
        if not unknown[i]:
            assert bool(ok[i]) is s.clean, i


# ---- fenced mutex (fencing-token mode) ------------------------------------


def _fenced_hist(events):
    """events: (f, proc, type, token_or_None) in completion order, each
    op invoked immediately before its completion."""
    hist = []
    for f, proc, typ, token in events:
        inv = Op.invoke(f, proc)
        hist.append(inv)
        hist.append(inv.complete(typ, value=token))
    return reindex(hist)


def _both_fenced(ops):
    from jepsen_tpu.models.core import FencedMutex

    cpu = check_wgl_cpu(ops, FencedMutex())
    batch = pack_wgl_batch([ops])
    ok, unknown = wgl_tensor_check(batch, (FencedMutex, ()))
    assert not unknown[0], "tensor search overflowed on a tiny history"
    assert bool(ok[0]) == cpu["valid?"], f"cpu={cpu} tpu={bool(ok[0])}"
    return cpu["valid?"]


def test_fenced_model_overlapping_holds_with_increasing_tokens_legal():
    """The revocation shape that REDS the unfenced model: two grants with
    no release between them.  Fenced, it is the tolerated hazard — tokens
    increased, the old holder's release FAILED — so the history is legal."""
    from jepsen_tpu.checkers.wgl import fenced_mutex_wgl_ops
    from jepsen_tpu.models.core import FencedMutex

    h = _fenced_hist(
        [
            (OpF.ACQUIRE, 0, OpType.OK, 5),
            (OpF.ACQUIRE, 1, OpType.OK, 9),   # revocation re-grant
            (OpF.RELEASE, 0, OpType.FAIL, None),  # stale: rejected
            (OpF.RELEASE, 1, OpType.OK, 9),
        ]
    )
    ops = fenced_mutex_wgl_ops(h)
    assert [o.call.a1 for o in ops] == [5, 9, 9]
    assert _both_fenced(ops)
    # the SAME shape without tokens refutes against OwnedMutex
    from jepsen_tpu.checkers.wgl import MutexWgl

    unfenced = _fenced_hist(
        [
            (OpF.ACQUIRE, 0, OpType.OK, None),
            (OpF.ACQUIRE, 1, OpType.OK, None),
        ]
    )
    assert MutexWgl(backend="cpu").check({}, unfenced)["valid?"] is False


def test_fenced_model_token_reuse_refuted():
    """One token granted twice admits no legal order: the second grant
    can never be strictly greater."""
    from jepsen_tpu.checkers.wgl import fenced_mutex_wgl_ops

    h = _fenced_hist(
        [
            (OpF.ACQUIRE, 0, OpType.OK, 5),
            (OpF.ACQUIRE, 1, OpType.OK, 5),  # THE BUG: token reuse
        ]
    )
    assert not _both_fenced(fenced_mutex_wgl_ops(h))


def test_fenced_model_stale_release_success_refuted():
    """A stale-token release that SUCCEEDED after the superseding grant
    completed is exactly what fencing forbids."""
    from jepsen_tpu.checkers.wgl import fenced_mutex_wgl_ops

    h = _fenced_hist(
        [
            (OpF.ACQUIRE, 0, OpType.OK, 5),
            (OpF.ACQUIRE, 1, OpType.OK, 9),
            (OpF.RELEASE, 0, OpType.OK, 5),  # broker failed to fence
        ]
    )
    assert not _both_fenced(fenced_mutex_wgl_ops(h))


def test_fenced_release_concurrent_with_regrant_is_ambiguous_hence_legal():
    """A release overlapping the superseding grant may have linearized
    first — the checker must find that order, not cry wolf."""
    from jepsen_tpu.checkers.wgl import fenced_mutex_wgl_ops

    hist = []
    inv_a = Op.invoke(OpF.ACQUIRE, 0)
    hist.append(inv_a)
    hist.append(inv_a.complete(OpType.OK, value=5))
    inv_r = Op.invoke(OpF.RELEASE, 0)       # release invoked...
    hist.append(inv_r)
    inv_b = Op.invoke(OpF.ACQUIRE, 1)       # ...concurrent with the grant
    hist.append(inv_b)
    hist.append(inv_b.complete(OpType.OK, value=9))
    hist.append(inv_r.complete(OpType.OK, value=5))
    assert _both_fenced(fenced_mutex_wgl_ops(reindex(hist)))


def test_fenced_info_ops_are_dropped_soundly():
    """Indeterminate ops carry no token and are dropped from the fenced
    mapping — a correct history with timeouts sprinkled in stays green."""
    from jepsen_tpu.checkers.wgl import fenced_mutex_wgl_ops

    h = _fenced_hist(
        [
            (OpF.ACQUIRE, 0, OpType.OK, 3),
            (OpF.ACQUIRE, 1, OpType.INFO, None),  # timed out: unknown
            (OpF.RELEASE, 0, OpType.INFO, None),
            (OpF.ACQUIRE, 2, OpType.OK, 7),
            (OpF.RELEASE, 2, OpType.OK, 7),
        ]
    )
    ops = fenced_mutex_wgl_ops(h)
    assert len(ops) == 3  # the two info ops vanished
    assert _both_fenced(ops)


def test_mutex_wgl_autodetects_fenced_histories():
    """The standard pipeline (check / bench-check re-runs) picks the
    model from the history itself: token-valued acquires -> FencedMutex,
    bare acquires -> OwnedMutex."""
    from jepsen_tpu.checkers.wgl import MutexWgl, mutex_history_is_fenced

    fenced = _fenced_hist(
        [
            (OpF.ACQUIRE, 0, OpType.OK, 5),
            (OpF.ACQUIRE, 1, OpType.OK, 9),
        ]
    )
    unfenced = _fenced_hist(
        [
            (OpF.ACQUIRE, 0, OpType.OK, None),
            (OpF.RELEASE, 0, OpType.OK, None),
        ]
    )
    assert mutex_history_is_fenced(fenced)
    assert not mutex_history_is_fenced(unfenced)
    r_f = MutexWgl(backend="cpu").check({}, fenced)
    assert r_f["model"] == "fenced-mutex" and r_f["valid?"] is True
    r_u = MutexWgl(backend="cpu").check({}, unfenced)
    assert r_u["model"] == "owned-mutex" and r_u["valid?"] is True
    # pinning the model explicitly overrides detection: the fenced
    # history judged as an unfenced one shows its overlapping holds
    r_pin = MutexWgl(backend="cpu", fenced=False).check({}, fenced)
    assert r_pin["model"] == "owned-mutex" and r_pin["valid?"] is False
